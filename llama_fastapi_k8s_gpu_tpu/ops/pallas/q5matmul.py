"""Fused Q5_K dequant-matmul (Pallas): completes the K-quant family.

Q5_K_M files (the other common llama.cpp artifact besides the reference's
Q4_K_M, reference api.py:14) store most linears as Q5_K.  Q5_K is Q4_K plus
one high bit per weight (gguf/quants.py: ``q5 = nibble + 16·hibit`` ∈
[0,32), same 8×32 sub-block scale/min structure, ``w = sc·q5 − mn``), so
this kernel is the v2 Q4_K design (ops/pallas/qmatmul.py — float nibble
split, lane-tiled scales, corrections folded into 128 extra K columns)
with one addition: a packed hi-bit plane, eight bits per byte, split by a
7-step ``floor`` chain (~1.9 VPU ops/weight extra) and folded into the
dequant as ``hibit·(16·sc)``.  ≈ 0.75 B/weight in HBM vs int8's 1.0.

Layout contract (:func:`prep_q5k`):

- ``q5s`` (N, K/2) int8 — re-biased nibble bytes, EXACTLY the Q4_K
  ``qs`` layout (column ``c = e·64 + s``, sub-block ``s = c % 64``).
- ``q5h`` (N, K/8) int8 — hi-bit bytes: tile-local byte ``b`` ∈ [0,256)
  holds bit ``j`` of columns ``b + 256·j``, stored biased (value − 128).
- ``sm5`` (K/2048, N, 128) bf16 — [64 scales | 64 mins], identical to the
  Q4_K ``sm``.

Activation prep (permute + xsum augmentation) is byte-for-byte the Q4_K
one, so the same prepared ``xpa`` could feed either kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ...gguf.constants import GGML_BLOCK_SIZES, GGMLType, QK_K
from ...obs.devtime import register_program
from ...gguf.quants import _garbage_tolerant
from ...gguf.quants import unpack_scale_min_k4
from .qmatmul import (
    augment_x,
    batched_rows,
    def_partition_compat,
    _env_variant,
    _interpret,
    _lane_repeat,
    permute_x,
    _pick_tn,
    plain_pallas_call,
    q4k_compatible,
    rows_vmappable,
    _spec_axis,
    stacked_pallas_call,
    stacked_partitioned,
    _SUBS,
    TK,
    TKA,
    _tn_prefs_for,
)

# `pre` is a LAYOUT variant in the Q6_K mold (q6matmul.py): prep stores one
# pre-combined int8 plane ``q5p = q5 ∈ [0,32)`` (N, K) at 1 B/weight
# instead of the nibble+hi-bit split at 0.625 B/weight.  The kernel then
# pays ~3 VPU ops/weight (convert, ·sc, bf16 cast) instead of the split
# path's nibble reconstruction + 8-step hi-bit extraction — attacking the
# measured ~205 vs ~145 µs per-op gap to the Q4_K kernel
# (kernel_microbench_q5k_2026-08-01; a Q5_K_M file carries ~2/3 of its
# weights in Q5_K, so unlike the q6k case the gap composes end-to-end:
# q5km 52.3 vs q4km 72.3 tok/s).  Numerics: ``q5·sc`` is an exact f32
# product (5-bit int × bf16 ≤ 13 mantissa bits) equal to the split path's
# summed exact terms; only the +8 hi-nibble bias moves from a separately
# bf16-rounded corr column into the exact plane — same deviation class as
# the gate-passing q6k `pre` (~1e-3), gated on chip.
# `pre` is the DEFAULT (tuple head): the 2026-08-01 chip A/B measured
# 63.09 vs 52.27 tok/s on the q5km grid (+21%, the per-op −15% composing
# at a Q5_K_M file's ~2/3 Q5_K weight share), and vs the f32 oracle the
# pre plane rounds strictly fewer terms than the split path (equal or
# better accuracy; dev vs `cur` ~3.5e-3 is two-roundings distance, inside
# the 5e-3 parity gate).  Cost: value planes go 0.625 → 1 B/weight (the
# sm5 scale plane, ~0.125 B/weight, is unchanged — totals 0.75 → 1.125),
# ≈ +2 GB on an 8B Q5_K_M's ~5.5G Q5_K weights — flip
# LFKT_Q5K_KERNEL=cur to trade the speed back for capacity.
Q5K_VARIANTS = ("pre", "cur", "parfloor")

q5k_compatible = q4k_compatible  # same divisibility classes


# ---------------------------------------------------------------------------
# host-side weight prep
# ---------------------------------------------------------------------------

def _combine_q5p(q5s: np.ndarray, q5h: np.ndarray, n_out: int,
                 k_in: int) -> np.ndarray:
    """Split planes → the `pre` layout's combined plane ``q5p`` (N, K) int8,
    true ``q5 = nibble + 16·hibit`` ∈ [0, 32) in the activation's permuted
    column order (lo-half columns [0, TK/2), hi-half [TK/2, TK) per tile;
    hi-bit byte ``b`` holds bit ``j`` of tile column ``b + 256·j``).  Pure
    integer numpy over the packers' output — the C++ layout contract is
    untouched."""
    kt = k_in // TK
    v4 = q5s.reshape(n_out, kt, TK // 2).astype(np.int16)
    h = v4 >> 4                                       # hi nibble − 8
    l = v4 - (h << 4)                                 # (arith shift floors)
    u = (q5h.reshape(n_out, kt, TK // 8).astype(np.int16)
         + 128)                                       # ∈ [0,256)
    # bit j of byte b belongs to tile column b + 256·j: emit the 8 bit
    # planes as contiguous 256-column slices (a single fancy-indexed
    # (N, kt, TK) gather here cost ~3 min of load time at 8B scale)
    out = np.empty((n_out, kt, TK), dtype=np.int8)
    half = TK // 2
    for j in range(8):
        hb_j = ((u >> j) & 1).astype(np.int8) << 4    # (N, kt, 256)
        lo, hi = j * 256, j * 256 + 256
        if hi <= half:                                # lo-half columns
            out[:, :, lo:hi] = (l[:, :, lo:hi] + hb_j).astype(np.int8)
        else:                                         # hi-half columns
            out[:, :, lo:hi] = ((h[:, :, lo - half:hi - half] + 8)
                                + hb_j).astype(np.int8)
    return out.reshape(n_out, k_in)


@_garbage_tolerant
def prep_q5k(raw: np.ndarray, n_out: int, k_in: int) -> dict:
    """Raw Q5_K block bytes (row-major, ``n_out`` rows of ``k_in`` elements)
    → the kernel layout dict: {"q5s", "q5h", "sm5"} (split layout) or
    {"q5p", "sm5"} under ``LFKT_Q5K_KERNEL=pre`` (see Q5K_VARIANTS)."""
    if not q5k_compatible(n_out, k_in):
        raise ValueError(f"({n_out}, {k_in}) not fused-Q5_K compatible "
                         f"(need K%{TK}==0, N%128==0)")
    from ...native import native_prep_q5k

    pre = _env_variant("LFKT_Q5K_KERNEL", Q5K_VARIANTS) == "pre"
    nat = native_prep_q5k(raw, n_out, k_in)
    if nat is not None:
        if pre:
            return {"q5p": jnp.asarray(_combine_q5p(
                        np.asarray(nat["q5s"]), np.asarray(nat["q5h"]),
                        n_out, k_in)),
                    "sm5": jnp.asarray(nat["sm5"])}
        return {"q5s": jnp.asarray(nat["q5s"]), "q5h": jnp.asarray(nat["q5h"]),
                "sm5": jnp.asarray(nat["sm5"])}
    bs = GGML_BLOCK_SIZES[GGMLType.Q5_K][1]           # 176
    nb = k_in // QK_K
    kt = k_in // TK
    blocks = np.ascontiguousarray(raw, dtype=np.uint8)[: n_out * nb * bs]
    blocks = blocks.reshape(n_out, nb, bs)
    d = blocks[..., 0:2].copy().view(np.float16).astype(np.float32)[..., 0]
    dmin = blocks[..., 2:4].copy().view(np.float16).astype(np.float32)[..., 0]
    sc, mn = unpack_scale_min_k4(blocks[..., 4:16])   # (N, nb, 8) uint8
    sm = np.concatenate([
        (d[..., None] * sc.astype(np.float32)).reshape(n_out, kt, _SUBS),
        (dmin[..., None] * mn.astype(np.float32)).reshape(n_out, kt, _SUBS),
    ], axis=-1).transpose(1, 0, 2)                    # (kt, N, 128)

    # 5-bit values per (sub-block, element): nibble file layout is Q4_K's
    # (byte g*32+i: sub 2g lo, sub 2g+1 hi); qh bit j = sub-block j's hi bit
    fqs = blocks[..., 48:].reshape(n_out, nb, 4, 32)
    q5 = np.empty((n_out, nb, 8, 32), dtype=np.uint8)
    q5[:, :, 0::2, :] = fqs & 0x0F
    q5[:, :, 1::2, :] = (fqs >> 4) & 0x0F
    qh = blocks[..., 16:48].reshape(n_out, nb, 1, 32)
    shifts = np.arange(8, dtype=np.uint8).reshape(1, 1, 8, 1)
    q5 |= (((qh >> shifts) & 1) << 4)

    # element-major tile columns (same map as Q4_K): Q[..., e, s]
    Q = q5.reshape(n_out, kt, 8, 8, 32).transpose(0, 1, 4, 2, 3)
    Q = np.ascontiguousarray(Q).reshape(n_out, kt, 32, 64)
    nib = Q & 0x0F
    hb = Q >> 4                                       # ∈ {0, 1}
    lo = nib[:, :, :16, :].reshape(n_out, kt, TK // 2)
    hi = nib[:, :, 16:, :].reshape(n_out, kt, TK // 2)
    v4 = ((hi.astype(np.int16) - 8) << 4) + lo
    q5s = v4.astype(np.int8).reshape(n_out, k_in // 2)

    hbc = hb.reshape(n_out, kt, TK)                   # column-major bits
    hbj = hbc.reshape(n_out, kt, 8, 256).astype(np.int16)  # [j, byte]
    v1 = (hbj << np.arange(8, dtype=np.int16).reshape(1, 1, 8, 1)).sum(2) - 128
    q5h = v1.astype(np.int8).reshape(n_out, k_in // 8)
    if pre:
        return {"q5p": jnp.asarray(_combine_q5p(q5s, q5h, n_out, k_in)),
                "sm5": jnp.asarray(np.ascontiguousarray(sm),
                                   dtype=jnp.bfloat16)}
    return {
        "q5s": jnp.asarray(q5s),
        "q5h": jnp.asarray(q5h),
        "sm5": jnp.asarray(np.ascontiguousarray(sm), dtype=jnp.bfloat16),
    }


def dequant_ref5(w: dict) -> jax.Array:
    """(N, K) f32 dequantized weights in **permuted** column order.
    Handles both layouts: the split {q5s, q5h} planes and the `pre`
    combined {q5p} plane."""
    sm_t = jnp.transpose(w["sm5"], (1, 0, 2)).astype(jnp.float32)
    if "q5p" in w:
        N, K = w["q5p"].shape
        kt = K // TK
        q5 = w["q5p"].astype(jnp.float32).reshape(N, kt, TK)
        sc = jnp.tile(sm_t[..., :_SUBS], (1, 1, TK // _SUBS))
        mn = jnp.tile(sm_t[..., _SUBS:], (1, 1, TK // _SUBS))
        return (q5 * sc - mn).reshape(N, kt * TK)
    N, half = w["q5s"].shape
    kt = half // (TK // 2)
    v4 = w["q5s"].astype(jnp.float32).reshape(N, kt, TK // 2)
    h = jnp.floor(v4 / 16.0)
    nib = jnp.concatenate([v4 - 16.0 * h, h + 8.0], axis=2)   # (N, kt, TK)
    u = w["q5h"].astype(jnp.float32).reshape(N, kt, 1, 256) + 128.0
    bits = []
    for j in range(7, -1, -1):
        bj = jnp.floor(u / float(1 << j))
        u = u - bj * float(1 << j)
        bits.append(bj)
    hb = jnp.concatenate(list(reversed(bits)), axis=2).reshape(N, kt, TK)
    q5 = nib + 16.0 * hb
    sc = jnp.tile(sm_t[..., :_SUBS], (1, 1, TK // _SUBS))
    mn = jnp.tile(sm_t[..., _SUBS:], (1, 1, TK // _SUBS))
    return (q5 * sc - mn).reshape(N, kt * TK)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _q5k_matmul_kernel(xpa_ref, q5s_ref, q5h_ref, sm_ref, o_ref, *, interpret,
                       variant="cur"):
    TN = q5s_ref.shape[0]
    v4 = q5s_ref[...].astype(jnp.float32)             # (TN, TK/2)
    h = jnp.floor(v4 * 0.0625)
    l = v4 - h * 16.0

    u = q5h_ref[...].astype(jnp.float32) + 128.0      # (TN, TK/8)
    if variant == "parfloor":
        # bit_j = floor(u/2^j) − 2·floor(u/2^(j+1)): independent floors
        # (depth-2 graph, same exact f32 integers → bit-identical) instead
        # of the serial remainder chain (depth-14).  Endpoints need no
        # floor: floor(u/1) = u and floor(u/256) = 0 for u ∈ [0,255].
        fl = [None] + [jnp.floor(u * (1.0 / (1 << j))) for j in range(1, 8)]
        bits = ([u - 2.0 * fl[1]]
                + [fl[j] - 2.0 * fl[j + 1] for j in range(1, 7)]
                + [fl[7]])
        hb = jnp.concatenate(bits, axis=1)            # (TN, TK) col-major
    else:
        bits = []
        for j in range(7, -1, -1):                    # bit7 .. bit0
            bj = jnp.floor(u * (1.0 / (1 << j)))
            u = u - bj * float(1 << j)
            bits.append(bj)
        hb = jnp.concatenate(list(reversed(bits)), axis=1)  # (TN, TK)

    sm = sm_ref[...].reshape(TN, 128)
    sc, mn = sm[:, :_SUBS], sm[:, _SUBS:]
    sc2 = jnp.concatenate([sc, sc], axis=1)           # (TN, 128)
    sc_exp = _lane_repeat(sc2, TK // 256, interpret)
    sc16 = sc_exp * 16.0
    a_lo = (l * sc_exp + hb[:, : TK // 2] * sc16).astype(jnp.bfloat16)
    a_hi = (h * sc_exp + hb[:, TK // 2:] * sc16).astype(jnp.bfloat16)
    corr = jnp.concatenate([-mn, sc * 8.0], axis=1).astype(jnp.bfloat16)

    xpa = xpa_ref[...]
    part = jax.lax.dot_general(
        xpa[:, : TK // 2], a_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK // 2: TK], a_hi, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += part


def _q5k_pre_kernel(xpa_ref, q5p_ref, sm_ref, o_ref, *, interpret):
    """`pre` layout body: one combined int8 plane, ~3 VPU ops/weight.

    ``y = Σ x·q5·sc − Σ_s mn_s·xsum_s`` — the +8 hi-nibble bias lives
    inside the exact plane, so corr's second half (the split layout's
    ``sc·8`` against xsum_hi) is zeros; keeping the shared Q4_K-family
    activation layout costs 64 dead corr columns."""
    TN = q5p_ref.shape[0]
    sm = sm_ref[...].reshape(TN, 128)
    sc, mn = sm[:, :_SUBS], sm[:, _SUBS:]
    sc2 = jnp.concatenate([sc, sc], axis=1)           # (TN, 128)
    eff = _lane_repeat(sc2, TK // 128, interpret)     # col c → sc[c % 64]
    a = (q5p_ref[...].astype(jnp.float32) * eff).astype(jnp.bfloat16)
    corr = jnp.concatenate([-mn, jnp.zeros_like(mn)],
                           axis=1).astype(jnp.bfloat16)

    xpa = xpa_ref[...]
    part = jax.lax.dot_general(
        xpa[:, :TK], a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += part


def _q5k_pre_specs(B: int, TN: int):
    """(in_specs, out_spec) for the `pre` layout: one (TN, TK) int8 plane
    plus the shared sm5 scale plane."""
    return (
        [
            ((B, TKA), lambda n, k: (0, k)),
            ((TN, TK), lambda n, k: (n, k)),
            ((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        ((B, TN), lambda n, k: (0, n)),
    )


def _q5k_pre_2d_raw(xpa: jax.Array, q5p: jax.Array, sm: jax.Array,
                    interpret: bool) -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA) * TK
    N = q5p.shape[0]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q5K))
    in_specs, out_spec = _q5k_pre_specs(B, TN)
    return plain_pallas_call(
        functools.partial(_q5k_pre_kernel, interpret=interpret),
        (N // TN, K // TK), in_specs, out_spec,
        jax.ShapeDtypeStruct((B, N), jnp.float32), interpret,
    )(xpa, q5p, sm)


@functools.lru_cache(maxsize=4)
def _q5k_pre_2d_partitioned(interpret: bool):
    """GSPMD rule for the `pre` layout (same contract: partition N/rows,
    never K)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xpa, q5p, sm):
        return _q5k_pre_2d_raw(xpa, q5p, sm, interpret)

    def partition(mesh, arg_shapes, result_shape):
        rows = _spec_axis(arg_shapes[0].sharding, 0)
        n_ax = _spec_axis(arg_shapes[1].sharding, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )

        def lower(xpa, q5p, sm):
            return _q5k_pre_2d_raw(xpa, q5p, sm, interpret)

        return (mesh, lower, NamedSharding(mesh, P(rows, n_ax)),
                arg_shardings)

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule="b k, n j, t n l -> b n",
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=0))


def _q5k_pre_2d_stacked_raw(idx: jax.Array, xpa: jax.Array, q5p: jax.Array,
                            sm: jax.Array, interpret: bool) -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA) * TK
    N = q5p.shape[1]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q5K))
    in_specs, out_spec = _q5k_pre_specs(B, TN)
    call = stacked_pallas_call(
        functools.partial(_q5k_pre_kernel, interpret=interpret),
        grid=(N // TN, K // TK),
        in_specs=in_specs,
        out_spec=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )
    return call(idx, xpa, q5p, sm)


@functools.lru_cache(maxsize=4)
def _q5k_pre_2d_stacked_partitioned(interpret: bool):
    return stacked_partitioned(
        _q5k_pre_2d_stacked_raw, "i, b k, l n j, l t n m -> b n", interpret)


_TN_PREFS_Q5K = (256, 128)


def _q5k_specs(B: int, TN: int):
    """Single tiling definition for both the unstacked and stacked calls
    (see qmatmul._q4k_specs)."""
    return (
        [
            ((B, TKA), lambda n, k: (0, k)),
            ((TN, TK // 2), lambda n, k: (n, k)),
            ((TN, TK // 8), lambda n, k: (n, k)),
            ((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        ((B, TN), lambda n, k: (0, n)),
    )


def _q5k_2d_raw(xpa: jax.Array, q5s: jax.Array, q5h: jax.Array,
                sm: jax.Array, interpret: bool,
                variant: str = "cur") -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA) * TK
    N = q5s.shape[0]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q5K))
    in_specs, out_spec = _q5k_specs(B, TN)
    return plain_pallas_call(
        functools.partial(_q5k_matmul_kernel, interpret=interpret,
                          variant=variant),
        (N // TN, K // TK), in_specs, out_spec,
        jax.ShapeDtypeStruct((B, N), jnp.float32), interpret,
    )(xpa, q5s, q5h, sm)


@functools.lru_cache(maxsize=4)
def _q5k_2d_partitioned(interpret: bool, variant: str = "cur"):
    """GSPMD rule mirroring the Q4_K kernel's: partition over N (and rows),
    never over K; tp-sharded weights compute locally."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xpa, q5s, q5h, sm):
        return _q5k_2d_raw(xpa, q5s, q5h, sm, interpret, variant)

    def partition(mesh, arg_shapes, result_shape):
        xp_s, qs_s, qh_s, sm_s = (a.sharding for a in arg_shapes)
        rows = _spec_axis(xp_s, 0)
        n_ax = _spec_axis(qs_s, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )
        result_sharding = NamedSharding(mesh, P(rows, n_ax))

        def lower(xpa, q5s, q5h, sm):
            return _q5k_2d_raw(xpa, q5s, q5h, sm, interpret, variant)

        return mesh, lower, result_sharding, arg_shardings

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule="b k, n j, n p, t n l -> b n",
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=0))


def _q5k_2d_stacked_raw(idx: jax.Array, xpa: jax.Array, q5s: jax.Array,
                        q5h: jax.Array, sm: jax.Array,
                        interpret: bool, variant: str = "cur") -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA) * TK
    N = q5s.shape[1]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q5K))
    in_specs, out_spec = _q5k_specs(B, TN)
    call = stacked_pallas_call(
        functools.partial(_q5k_matmul_kernel, interpret=interpret,
                          variant=variant),
        grid=(N // TN, K // TK),
        in_specs=in_specs,
        out_spec=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )
    return call(idx, xpa, q5s, q5h, sm)


@functools.lru_cache(maxsize=4)
def _q5k_2d_stacked_partitioned(interpret: bool, variant: str = "cur"):
    return stacked_partitioned(
        functools.partial(_q5k_2d_stacked_raw, variant=variant),
        "i, b k, l n j, l n p, l t n m -> b n", interpret)


def q5k_matmul_stacked(x: jax.Array, w: dict, idx,
                       interpret: bool | None = None) -> jax.Array:
    """x (..., K) → (..., N) against layer ``idx`` of stacked Q5_K weights
    (``q5s`` (L, N, K/2), ``q5h`` (L, N, K/8), ``sm5`` (L, K/2048, N, 128);
    or ``q5p`` (L, N, K) + ``sm5`` for the `pre` layout).  Dispatched on
    the LAYOUT (plane presence), not the env knob, so weights prepped
    under one variant can never meet the other family's kernel."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xpa = augment_x(permute_x(x).reshape(-1, K).astype(jnp.bfloat16))
    i1 = jnp.asarray(idx, jnp.int32).reshape(1)
    if "q5p" in w:
        fn = _q5k_pre_2d_stacked_partitioned(_interpret(interpret))
        y = batched_rows(lambda xp, *ws: fn(i1, xp, *ws),
                         xpa, w["q5p"], w["sm5"])
    else:
        var = _env_variant("LFKT_Q5K_KERNEL", Q5K_VARIANTS)
        fn = _q5k_2d_stacked_partitioned(
            _interpret(interpret), "cur" if var == "pre" else var)
        y = batched_rows(lambda xp, *ws: fn(i1, xp, *ws),
                         xpa, w["q5s"], w["q5h"], w["sm5"])
    return y.reshape(*lead, -1).astype(x.dtype)


def q5k_matmul(x: jax.Array, w: dict, interpret: bool | None = None) -> jax.Array:
    """x (..., K) bf16/f32 → (..., N) in x.dtype, weights in Q5_K kernel
    layout.  The fused path of ``ops.linear.linear`` for Q5_K tensors.
    Layout-dispatched like :func:`q5k_matmul_stacked`."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xpa = augment_x(permute_x(x).reshape(-1, K).astype(jnp.bfloat16))
    if "q5p" in w:
        fn = _q5k_pre_2d_partitioned(_interpret(interpret))
        y = batched_rows(fn, xpa, w["q5p"], w["sm5"])
    else:
        # `pre` is a layout variant: split-layout weights (e.g. prepped
        # before the env flip) run the split default, never a silent
        # mislabel
        var = _env_variant("LFKT_Q5K_KERNEL", Q5K_VARIANTS)
        fn = _q5k_2d_partitioned(
            _interpret(interpret), "cur" if var == "pre" else var)
        y = batched_rows(fn, xpa, w["q5s"], w["q5h"], w["sm5"])
    return y.reshape(*lead, -1).astype(x.dtype)


# devtime inventory (lfkt-lint PERF001): trace-inner fused-matmul builders
# (see ops/pallas/qmatmul.py for the attribution contract)
register_program("_q5k_2d_partitioned", site="ops.pallas.q5matmul")
register_program("_q5k_pre_2d_partitioned", site="ops.pallas.q5matmul")

"""Device-side K-quant dequantization (Pallas).

The reference dequantizes lazily inside llama.cpp's CUDA kernels (reference
docker/Dockerfile.base:30-32).  Here dequantization happens once at load
(weights-resident design, SURVEY.md §7 stage 7): the host uploads the *raw
quantized bytes* and the TPU expands them, so for an 8B Q4_K_M model the
host→device transfer is ~4.9 GB instead of the 16-32 GB a host-side
dequant would ship.

Split of labor per format:

- the *bandwidth-heavy* part of every block (the packed 4/5-bit nibbles,
  ≥72% of the bytes) is unpacked on device by a Pallas kernel;
- the *tiny* per-block headers (f16 super-scales, 6-bit sub-scales — ≤11%
  of the bytes) are pre-folded on the host with numpy into effective
  per-sub-block f32 scale/min vectors, which keeps the kernels free of
  f16 bit-twiddling and awkward 12-byte layouts.

Bit layouts follow ``gguf/quants.py`` (the numpy oracle these kernels are
tested bit-exact against).  Packed bytes are shipped as int8 (bit-identical
to uint8; int8 is the dtype Mosaic tiles natively) and unpacked with
``(q >> k) & mask`` arithmetic, which is sign-safe.

All kernels view data as (rows, 128) tiles — 128 is the TPU lane width.
Row counts that don't divide the tile height are handled by running the
numpy reference on the short tail and concatenating.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ...gguf.constants import GGML_BLOCK_SIZES, GGMLType, QK_K
from ...obs.devtime import register_program
from ...gguf.quants import dequantize as np_dequantize, unpack_scale_min_k4

# rows per grid step (row = one 128-lane vector of packed bytes)
_TILE = 256


def _interpret(override: bool | None) -> bool:
    if override is not None:
        return override
    from . import use_interpret

    return use_interpret()


def _f16_f32(b: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(b).view(np.float16).astype(np.float32).reshape(-1)


def _split_tail(nb: int) -> tuple[int, int]:
    """(kernel rows, tail rows) with kernel rows a multiple of _TILE."""
    main = (nb // _TILE) * _TILE
    return main, nb - main


def _expand(s: jax.Array, repeats: int, width: int = 128) -> jax.Array:
    """(T, n) → (T, n*repeats) blockwise ([s0×r, s1×r, …]) via a select
    chain — broadcast/select only, so it lowers on any backend."""
    T, n = s.shape
    assert n * repeats == width
    g = jax.lax.broadcasted_iota(jnp.int32, (T, width), 1) // repeats
    out = jnp.broadcast_to(s[:, 0:1], (T, width))
    for j in range(1, n):
        out = jnp.where(g == j, s[:, j:j + 1], out)
    return out


# ---------------------------------------------------------------------------
# Q8_0 — rows of 4 blocks of 32 int8 + f32 scale each
# ---------------------------------------------------------------------------

def _q8_0_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = (
        q_ref[...].astype(jnp.float32) * _expand(s_ref[...], 32)
    ).astype(o_ref.dtype)


def dequant_q8_0_device(buf: np.ndarray, n: int, dtype=jnp.float32,
                        interpret: bool | None = None) -> jax.Array:
    """Flat Q8_0 bytes → (n,) device array."""
    nb = n // 32
    blocks = buf[: nb * 34].reshape(nb, 34)
    d = _f16_f32(blocks[:, :2])                       # (nb,)
    rows = nb // 4
    main, _ = _split_tail(rows)
    parts = []
    if main:
        q = blocks[:main * 4, 2:].view(np.int8).reshape(main, 128)
        out = pl.pallas_call(
            _q8_0_kernel,
            grid=(main // _TILE,),
            in_specs=[
                pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
                pl.BlockSpec((_TILE, 4), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((main, 128), dtype),
            interpret=_interpret(interpret),
        )(jnp.asarray(q), jnp.asarray(d[: main * 4].reshape(main, 4)))
        parts.append(out.reshape(-1))
    n_main = main * 128
    if n - n_main:
        parts.append(jnp.asarray(
            np_dequantize(buf[(main * 4) * 34:], GGMLType.Q8_0, n - n_main),
            dtype,
        ))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


# ---------------------------------------------------------------------------
# Q4_K — 256-elem super-blocks; device unpacks the 128 nibble bytes
# ---------------------------------------------------------------------------

def _q4_k_kernel(qs_ref, slo_ref, shi_ref, mlo_ref, mhi_ref, lo_ref, hi_ref):
    qs = qs_ref[...].astype(jnp.int32)
    lo = (qs & 0x0F).astype(jnp.float32)
    hi = ((qs >> 4) & 0x0F).astype(jnp.float32)
    lo_ref[...] = (lo * _expand(slo_ref[...], 32)
                   - _expand(mlo_ref[...], 32)).astype(lo_ref.dtype)
    hi_ref[...] = (hi * _expand(shi_ref[...], 32)
                   - _expand(mhi_ref[...], 32)).astype(hi_ref.dtype)


def _k4_headers(blocks: np.ndarray):
    """Common Q4_K/Q5_K header folding → eff. scale/min (nb, 8) f32."""
    d = _f16_f32(blocks[:, 0:2])
    dmin = _f16_f32(blocks[:, 2:4])
    sc, mn = unpack_scale_min_k4(blocks[:, 4:16])     # (nb, 8) uint8
    scale = d[:, None] * sc.astype(np.float32)
    minv = dmin[:, None] * mn.astype(np.float32)
    return scale, minv


def _interleave_lo_hi(lo: jax.Array, hi: jax.Array, nb: int) -> jax.Array:
    """lo/hi (nb, 128) — lane g*32+i is sub-block 2g (resp. 2g+1) element i
    → flat element order (sub-block-major)."""
    y = jnp.stack([lo.reshape(nb, 4, 32), hi.reshape(nb, 4, 32)], axis=2)
    return y.reshape(nb * QK_K)


_K4_SPECS = dict(
    in_specs=[
        pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
        pl.BlockSpec((_TILE, 4), lambda i: (i, 0)),
        pl.BlockSpec((_TILE, 4), lambda i: (i, 0)),
        pl.BlockSpec((_TILE, 4), lambda i: (i, 0)),
        pl.BlockSpec((_TILE, 4), lambda i: (i, 0)),
    ],
    out_specs=(
        pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
        pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
    ),
)


def dequant_q4_k_device(buf: np.ndarray, n: int, dtype=jnp.float32,
                        interpret: bool | None = None) -> jax.Array:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q4_K][1]           # 144
    blocks = buf[: nb * bs].reshape(nb, bs)
    main, tail = _split_tail(nb)
    parts = []
    if main:
        scale, minv = _k4_headers(blocks[:main])
        qs = blocks[:main, 16:].view(np.int8)         # (main, 128)
        lo, hi = pl.pallas_call(
            _q4_k_kernel,
            grid=(main // _TILE,),
            out_shape=(jax.ShapeDtypeStruct((main, 128), dtype),
                       jax.ShapeDtypeStruct((main, 128), dtype)),
            interpret=_interpret(interpret),
            **_K4_SPECS,
        )(
            jnp.asarray(qs),
            jnp.asarray(scale[:, 0::2]), jnp.asarray(scale[:, 1::2]),
            jnp.asarray(minv[:, 0::2]), jnp.asarray(minv[:, 1::2]),
        )
        parts.append(_interleave_lo_hi(lo, hi, main))
    if tail:
        parts.append(jnp.asarray(
            np_dequantize(blocks[main:].reshape(-1), GGMLType.Q4_K, tail * QK_K),
            dtype,
        ))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


# ---------------------------------------------------------------------------
# Q5_K — Q4_K + one high bit per element from the 32-byte qh array
# ---------------------------------------------------------------------------

def _q5_k_kernel(qs_ref, qh_ref, slo_ref, shi_ref, mlo_ref, mhi_ref,
                 lo_ref, hi_ref):
    qs = qs_ref[...].astype(jnp.int32)
    lo = qs & 0x0F
    hi = (qs >> 4) & 0x0F
    T = qs.shape[0]
    # qh byte for lane g*32+i is qh[i]; tile the 32 bytes across the 4 groups
    qh = qh_ref[...].astype(jnp.int32)                # (T, 32)
    qh4 = jnp.concatenate([qh, qh, qh, qh], axis=1)   # (T, 128)
    # sub-block index: lo lanes → 2g, hi lanes → 2g+1 where g = lane // 32
    g2 = 2 * (jax.lax.broadcasted_iota(jnp.int32, (T, 128), 1) // 32)
    hb_lo = (qh4 >> g2) & 1
    hb_hi = (qh4 >> (g2 + 1)) & 1
    lo_ref[...] = ((lo + 16 * hb_lo).astype(jnp.float32)
                   * _expand(slo_ref[...], 32)
                   - _expand(mlo_ref[...], 32)).astype(lo_ref.dtype)
    hi_ref[...] = ((hi + 16 * hb_hi).astype(jnp.float32)
                   * _expand(shi_ref[...], 32)
                   - _expand(mhi_ref[...], 32)).astype(hi_ref.dtype)


def dequant_q5_k_device(buf: np.ndarray, n: int, dtype=jnp.float32,
                        interpret: bool | None = None) -> jax.Array:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q5_K][1]           # 176
    blocks = buf[: nb * bs].reshape(nb, bs)
    main, tail = _split_tail(nb)
    parts = []
    if main:
        scale, minv = _k4_headers(blocks[:main])
        qh = blocks[:main, 16:48].view(np.int8)       # (main, 32)
        qs = blocks[:main, 48:].view(np.int8)         # (main, 128)
        specs = dict(_K4_SPECS)
        specs["in_specs"] = (
            [_K4_SPECS["in_specs"][0],
             pl.BlockSpec((_TILE, 32), lambda i: (i, 0))]
            + _K4_SPECS["in_specs"][1:]
        )
        lo, hi = pl.pallas_call(
            _q5_k_kernel,
            grid=(main // _TILE,),
            out_shape=(jax.ShapeDtypeStruct((main, 128), dtype),
                       jax.ShapeDtypeStruct((main, 128), dtype)),
            interpret=_interpret(interpret),
            **specs,
        )(
            jnp.asarray(qs), jnp.asarray(qh),
            jnp.asarray(scale[:, 0::2]), jnp.asarray(scale[:, 1::2]),
            jnp.asarray(minv[:, 0::2]), jnp.asarray(minv[:, 1::2]),
        )
        parts.append(_interleave_lo_hi(lo, hi, main))
    if tail:
        parts.append(jnp.asarray(
            np_dequantize(blocks[main:].reshape(-1), GGMLType.Q5_K, tail * QK_K),
            dtype,
        ))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


# ---------------------------------------------------------------------------
# Q6_K — host unpacks the 6-bit values to int8 (minority format: only the
# output head / a few tensors in Q4_K_M files), device applies scales.
# ---------------------------------------------------------------------------

def _q6_k_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = (
        q_ref[...].astype(jnp.float32) * _expand(s_ref[...], 16)
    ).astype(o_ref.dtype)


def dequant_q6_k_device(buf: np.ndarray, n: int, dtype=jnp.float32,
                        interpret: bool | None = None) -> jax.Array:
    nb = n // QK_K
    bs = GGML_BLOCK_SIZES[GGMLType.Q6_K][1]           # 210
    blocks = buf[: nb * bs].reshape(nb, bs)
    ql = blocks[:, 0:128].reshape(nb, 2, 64)
    qh = blocks[:, 128:192].reshape(nb, 2, 32)
    sc = np.ascontiguousarray(blocks[:, 192:208]).view(np.int8).astype(np.float32)
    d = _f16_f32(blocks[:, 208:210])
    low = np.empty((nb, 2, 128), dtype=np.uint8)
    low[:, :, 0:64] = ql & 0x0F
    low[:, :, 64:128] = ql >> 4
    hi = np.empty((nb, 2, 128), dtype=np.uint8)
    hi[:, :, 0:32] = qh & 3
    hi[:, :, 32:64] = (qh >> 2) & 3
    hi[:, :, 64:96] = (qh >> 4) & 3
    hi[:, :, 96:128] = qh >> 6
    q8 = ((low | (hi << 4)).astype(np.int16) - 32).astype(np.int8)
    q8 = q8.reshape(nb * 2, 128)                               # element order
    eff = (d[:, None] * sc).astype(np.float32).reshape(nb * 2, 8)
    rows = nb * 2
    main, tail = _split_tail(rows)
    parts = []
    if main:
        out = pl.pallas_call(
            _q6_k_kernel,
            grid=(main // _TILE,),
            in_specs=[
                pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
                pl.BlockSpec((_TILE, 8), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((main, 128), dtype),
            interpret=_interpret(interpret),
        )(jnp.asarray(q8[:main]), jnp.asarray(eff[:main]))
        parts.append(out.reshape(-1))
    if tail:
        y = q8[main:].astype(np.float32) * np.repeat(eff[main:], 16, axis=1)
        parts.append(jnp.asarray(y.reshape(-1), dtype))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

_DEVICE_DEQUANT = {
    GGMLType.Q8_0: dequant_q8_0_device,
    GGMLType.Q4_K: dequant_q4_k_device,
    GGMLType.Q5_K: dequant_q5_k_device,
    GGMLType.Q6_K: dequant_q6_k_device,
}

#: latched True after the first Mosaic failure so a broken lowering pays
#: ONE failed compile, not one per tensor.  Same probe-and-degrade
#: contract as ops/pallas/probe.py (lfkt-lint KER002), applied lazily
#: because these kernels only ever run during the weight load — a startup
#: probe would just duplicate the first tensor's compile.
_FORCE_HOST = False


def _host_fallback(buf: np.ndarray, ggml_type: GGMLType, n: int,
                   dtype) -> jax.Array:
    """Numpy codec + plain upload: the degrade path when a device kernel
    is unavailable (format without a kernel) or failed to lower."""
    return jnp.asarray(np_dequantize(buf, ggml_type, n), dtype)


def device_dequant(buf: np.ndarray, ggml_type: GGMLType, n: int,
                   dtype=jnp.float32,
                   interpret: bool | None = None) -> jax.Array:  # lfkt: degrades[_FORCE_HOST]
    """Flat raw bytes → (n,) device array; falls back to the numpy codec
    (+ upload) for formats without a device kernel (F16/F32/BF16/Q4_0) and
    for ALL tensors once a device kernel fails to lower (new libtpu /
    unexpected geometry): the load completes slower instead of crash-
    looping the pod."""
    global _FORCE_HOST
    fn = _DEVICE_DEQUANT.get(GGMLType(ggml_type))
    if fn is None or _FORCE_HOST:
        return _host_fallback(buf, ggml_type, n, dtype)
    try:
        return fn(np.asarray(buf, dtype=np.uint8).reshape(-1), n, dtype,
                  interpret)
    except Exception as e:  # noqa: BLE001 — any failure means "degrade"
        _FORCE_HOST = True
        import logging

        logging.getLogger(__name__).error(
            "device dequant kernel failed for %s; loading via the numpy "
            "codec from here on: %s", GGMLType(ggml_type).name, e)
        return _host_fallback(buf, ggml_type, n, dtype)


# devtime inventory (lfkt-lint PERF001): the weight-load dequant kernels
# are host-called once per layer during load; their walls ride the load
# phases already reported by coldstart artifacts, so they are registered
# as inventory rather than wrapped (obs/devtime.py)
register_program("dequant_q8_0_device", site="ops.pallas.dequant")
register_program("dequant_q4_k_device", site="ops.pallas.dequant")
register_program("dequant_q5_k_device", site="ops.pallas.dequant")
register_program("dequant_q6_k_device", site="ops.pallas.dequant")

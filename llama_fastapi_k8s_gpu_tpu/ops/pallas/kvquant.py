"""Int8 KV-cache write quantization (Pallas) + the XLA reference path.

The int8 KV cache (``ModelConfig.kv_dtype == "int8"``, docs/KV_CACHE.md)
stores each layer's ring as int8 values plus per-head, per-token symmetric
f32 scales: ``x ≈ q * s`` with ``s = max|x| / 127`` taken over the head_dim
axis of one token's head vector.  Per-token granularity (a token-block of
one) is deliberate: decode writes land one token at a time at arbitrary ring
positions, so any multi-token scale block would need a read-requantize-write
of its previously written tokens on every decode step.

Writers quantize only the S NEW token slots per layer step (S ≤ bucket
size, not n_ctx), so the quantize cost is O(new tokens) while every ring
READ — the decode-bandwidth bottleneck — moves int8 instead of bf16.

Two implementations with identical semantics:

- :func:`quantize_kv_xla` — plain jnp, the reference used on CPU
  (``JAX_PLATFORMS=cpu`` parity tests) and as the Mosaic-failure fallback;
- :func:`quantize_kv_pallas` — a small Pallas kernel (one grid step per kv
  head) used on TPU so the quantize fuses into one VMEM pass over the new
  tokens' slab.

:func:`quantize_kv` dispatches between them; :func:`force_xla_quant` pins
the XLA path (the engine's startup probe flips it when the kernel fails to
lower — ops/pallas/probe.py pattern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ...obs.devtime import register_program

_FORCE_XLA: bool = False


def force_xla_quant(value: bool) -> None:
    """Pin the XLA quantize path (set by the engine when the Pallas kernel
    fails its startup compile probe on TPU)."""
    global _FORCE_XLA
    _FORCE_XLA = value


def _scale_and_q(x32: jax.Array):
    """x32 (..., hd) f32 → (q int8 (..., hd), s f32 (...,)): symmetric
    per-vector max-abs fit onto [-127, 127]; all-zero vectors store s=0
    (and q=0), so dequant q*s is exact there too."""
    amax = jnp.max(jnp.abs(x32), axis=-1)
    s = amax / 127.0
    inv = jnp.where(s > 0, 1.0 / s, 0.0)
    q = jnp.clip(jnp.round(x32 * inv[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def quantize_kv_xla(x: jax.Array):
    """x (n_kv, S, hd) → (q int8 (n_kv, S, hd), s f32 (n_kv, S))."""
    return _scale_and_q(x.astype(jnp.float32))


def _kvq_kernel(x_ref, q_ref, s_ref):
    x = x_ref[0].astype(jnp.float32)                  # (S, hd)
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    s = amax / 127.0
    inv = jnp.where(s > 0, 1.0 / s, 0.0)
    q_ref[0] = jnp.clip(jnp.round(x * inv), -127, 127).astype(jnp.int8)
    s_ref[...] = s.reshape(s_ref.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quantize_kv_pallas(x: jax.Array, interpret: bool = False):
    """Pallas twin of :func:`quantize_kv_xla`: one grid step per kv head
    quantizes that head's (S, hd) slab of new tokens in a single VMEM pass."""
    n_kv, S, hd = x.shape
    q, s = pl.pallas_call(
        _kvq_kernel,
        grid=(n_kv,),
        in_specs=[pl.BlockSpec((1, S, hd), lambda h: (h, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, S, hd), lambda h: (h, 0, 0)),
            pl.BlockSpec((1, S), lambda h: (h, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_kv, S, hd), jnp.int8),
            jax.ShapeDtypeStruct((n_kv, S), jnp.float32),
        ],
        interpret=interpret,
    )(x)
    return q, s


def quantize_kv(x: jax.Array):
    """Quantize the S new token slots of one layer's K or V write slab.

    x (n_kv, S, hd) head-major (the layout ``models/llama.py`` writes) →
    (q int8 (n_kv, S, hd), s f32 (n_kv, S)).  TPU runs the Pallas kernel;
    everything else (CPU tests, probe-degraded pods) runs the identical
    XLA formulation."""
    if _FORCE_XLA or jax.default_backend() != "tpu":
        return quantize_kv_xla(x)
    return quantize_kv_pallas(x)


def dequantize_kv(q: jax.Array, s: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """Reference dequant: q (..., C, hd) int8 × s (..., C) f32 → dtype.
    Used by the ring-attention path (which needs materialized bf16 K/V for
    its collectives) and by tests; the XLA/Pallas attention consumers fold
    the scales into their score/value matmuls instead and never call this."""
    return (q.astype(jnp.float32) * s[..., None].astype(jnp.float32)).astype(dtype)


# devtime inventory (lfkt-lint PERF001): the KV write-quantize kernel is
# trace-inner — it compiles as part of the prefill/decode programs that
# call it from the cache-write path (obs/devtime.py)
register_program("quantize_kv_pallas", site="ops.pallas.kvquant")

"""Fused Q4_K dequant-matmul (Pallas): decode directly from ~5-bit weights.

The decode hot loop is HBM-bandwidth-bound: every generated token reads every
weight byte once (SURVEY.md §6; the reference's llama.cpp engine solves this
on GPU with fused dequant-matmul CUDA kernels inside llama-cpp-python,
reference docker/Dockerfile.base:30-32).  The int8 path (ops/linear.py)
already halves traffic vs bf16; this kernel goes further by keeping the
weights in (almost) their GGUF Q4_K form in HBM:

- packed 4-bit nibbles, exactly as laid out in the file   → 4.00 bit/weight
- folded per-sub-block scale/min in bf16 (d·sc, dmin·mn)  → 1.00 bit/weight
                                                      total ≈ 5 bit/weight

i.e. ~0.62× the int8 bytes/token, which on a bandwidth-bound decode is a
~1.6× throughput ceiling raise.  Tiles are dequantized into VMEM only, fed
straight to the MXU, and never written back to HBM.

Layout contract (produced by :func:`prep_q4k` from raw GGUF block bytes; bit
layouts follow gguf/quants.py, the numpy oracle).  The K axis is processed
in fixed tiles of ``TK = 2048`` elements = 8 Q4_K super-blocks:

- ``qs`` (N, K/2) int8 — packed nibbles in file byte order; super-block ``b``
  of a row occupies columns [128b, 128(b+1)); byte ``g*32+i`` holds
  sub-block ``2g`` element ``i`` in its low nibble and sub-block ``2g+1``
  element ``i`` in its high nibble.
- ``sm`` (K/2048, N, 128) bf16 — per k-tile: 64 effective scales (d·sc)
  then 64 effective mins (dmin·mn), one per 32-element sub-block, ordered
  block-major with each block's 8 sub-blocks in **even/odd order**
  [s0,s2,s4,s6, s1,s3,s5,s7] — so after the kernel unpacks nibbles as
  [all-lo | all-hi] per block, output column ``j``'s sub-block is ``j//32``.
  Merging scales+mins into one 128-lane array keeps every Pallas block
  shape on Mosaic's (8, 128) tiling grid.

Activations are pre-permuted to the same order by :func:`permute_x`
(even sub-blocks of each 256-block first, then odd) — a cheap XLA reshape
fused into the surrounding graph.

Shape requirements: ``K % 2048 == 0`` and ``N % 128 == 0`` (all Llama-3 /
Mistral linear shapes qualify; loaders fall back to the int8 format
otherwise — see models/params.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ...gguf.constants import GGML_BLOCK_SIZES, GGMLType, QK_K
from ...gguf.quants import unpack_scale_min_k4

TK = 2048            # K elements per kernel step = 8 super-blocks
_SUBS = TK // 32     # 64 sub-blocks per k-tile


def _interpret(override: bool | None) -> bool:
    if override is not None:
        return override
    from . import use_interpret

    return use_interpret()


def q4k_compatible(n_out: int, k_in: int, for_tpu: bool | None = None) -> bool:
    """Whether (n_out, k_in) can use the fused kernel.  On TPU, N must tile
    to 128 sublanes; interpret mode (CPU tests) accepts any multiple of 8."""
    if for_tpu is None:
        for_tpu = not _interpret(None)
    return k_in % TK == 0 and n_out % (128 if for_tpu else 8) == 0


# ---------------------------------------------------------------------------
# host-side weight prep
# ---------------------------------------------------------------------------

def prep_q4k(raw: np.ndarray, n_out: int, k_in: int) -> dict:
    """Raw Q4_K block bytes (row-major, ``n_out`` rows of ``k_in`` elements)
    → the kernel layout dict {"qs", "sm"}."""
    if not q4k_compatible(n_out, k_in):
        raise ValueError(f"({n_out}, {k_in}) not fused-Q4_K compatible "
                         f"(need K%{TK}==0, N%128==0)")
    bs = GGML_BLOCK_SIZES[GGMLType.Q4_K][1]           # 144
    nb = k_in // QK_K
    blocks = np.ascontiguousarray(raw, dtype=np.uint8)[: n_out * nb * bs]
    blocks = blocks.reshape(n_out, nb, bs)
    d = blocks[..., 0:2].copy().view(np.float16).astype(np.float32)[..., 0]
    dmin = blocks[..., 2:4].copy().view(np.float16).astype(np.float32)[..., 0]
    sc, mn = unpack_scale_min_k4(blocks[..., 4:16])   # (N, nb, 8) uint8
    eff_s = d[..., None] * sc.astype(np.float32)      # (N, nb, 8)
    eff_m = dmin[..., None] * mn.astype(np.float32)
    # even/odd sub-block order to match the kernel's [lo | hi] unpack
    eo = np.concatenate([eff_s[..., 0::2], eff_s[..., 1::2]], axis=-1)
    mo = np.concatenate([eff_m[..., 0::2], eff_m[..., 1::2]], axis=-1)
    ktiles = k_in // TK
    eo = eo.reshape(n_out, ktiles, _SUBS)             # 8 blocks × 8 subs
    mo = mo.reshape(n_out, ktiles, _SUBS)
    sm = np.concatenate([eo, mo], axis=-1)            # (N, ktiles, 128)
    sm = np.ascontiguousarray(sm.transpose(1, 0, 2))  # (ktiles, N, 128)
    qs = blocks[..., 16:].reshape(n_out, nb * 128).view(np.int8)
    return {
        "qs": jnp.asarray(qs),
        "sm": jnp.asarray(sm, dtype=jnp.bfloat16),
    }


def permute_x(x: jax.Array) -> jax.Array:
    """(..., K) → (..., K) with each 256-block reordered to even/odd
    sub-block order (the layout :func:`prep_q4k` stores scales in)."""
    K = x.shape[-1]
    xb = x.reshape(*x.shape[:-1], K // QK_K, 8, 32)
    xe = jnp.concatenate([xb[..., 0::2, :], xb[..., 1::2, :]], axis=-2)
    return xe.reshape(*x.shape[:-1], K)


def dequant_ref(w: dict) -> jax.Array:
    """(N, K) f32 dequantized weights in **permuted** column order — the
    small-shape oracle the kernel is tested against."""
    N, half = w["qs"].shape
    nb = half // 128
    qs = w["qs"].astype(jnp.int32)
    lo = (qs & 0x0F).reshape(N, nb, 128)
    hi = ((qs >> 4) & 0x0F).reshape(N, nb, 128)
    q = jnp.concatenate([lo, hi], axis=2).reshape(N, nb * 256).astype(jnp.float32)
    sm = jnp.transpose(w["sm"], (1, 0, 2)).astype(jnp.float32)  # (N, kt, 128)
    sc = sm[..., :_SUBS].reshape(N, -1)               # (N, K/32)
    mn = sm[..., _SUBS:].reshape(N, -1)
    sub = jax.lax.broadcasted_iota(jnp.int32, q.shape, 1) // 32
    sc = jnp.take_along_axis(sc, sub, axis=1)
    mn = jnp.take_along_axis(mn, sub, axis=1)
    return q * sc - mn


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _q4k_matmul_kernel(xp_ref, qs_ref, sm_ref, o_ref):
    # xp (B, TK) bf16 permuted; qs (TN, TK/2) int8; sm (1, TN, 128) bf16
    qs = qs_ref[...].astype(jnp.int32)
    TN = qs.shape[0]
    nb = TK // QK_K                                   # 8 super-blocks
    lo = (qs & 0x0F).reshape(TN, nb, 128)
    hi = ((qs >> 4) & 0x0F).reshape(TN, nb, 128)
    q = jnp.concatenate([lo, hi], axis=2).reshape(TN, TK).astype(jnp.float32)

    sm = sm_ref[...].reshape(TN, 128)
    sc = sm[:, :_SUBS]                                # (TN, 64) bf16
    mn = sm[:, _SUBS:]

    # expand per-sub-block scale/min over their 32 lanes with a 0/1 matmul
    # (MXU-friendly; avoids unsupported small-minor-dim reshapes)
    s_idx = jax.lax.broadcasted_iota(jnp.int32, (_SUBS, TK), 0)
    col_sub = jax.lax.broadcasted_iota(jnp.int32, (_SUBS, TK), 1) // 32
    expand = (s_idx == col_sub).astype(jnp.bfloat16)  # (64, TK)
    sc_exp = jax.lax.dot_general(
        sc, expand, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (TN, TK)
    mn_exp = jax.lax.dot_general(
        mn, expand, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    a = (q * sc_exp - mn_exp).astype(jnp.bfloat16)    # dequantized tile (VMEM)
    partial = jax.lax.dot_general(
        xp_ref[...], a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (B, TN)

    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += partial


def _pick_tn(n: int, interpret: bool) -> int:
    for c in (256, 128) + ((64, 32, 16, 8) if interpret else ()):
        if n % c == 0:
            return c
    raise ValueError(f"N={n} not divisible by 128")


def _q4k_2d_raw(xp: jax.Array, qs: jax.Array, sm: jax.Array,
                interpret: bool) -> jax.Array:
    B, K = xp.shape
    N = qs.shape[0]
    TN = _pick_tn(N, interpret)
    grid = (N // TN, K // TK)
    return pl.pallas_call(
        _q4k_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((B, TK), lambda n, k: (0, k)),
            pl.BlockSpec((TN, TK // 2), lambda n, k: (n, k)),
            pl.BlockSpec((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        out_specs=pl.BlockSpec((B, TN), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )(xp, qs, sm)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q4k_matmul_2d(xp: jax.Array, qs: jax.Array, sm: jax.Array,
                   interpret: bool = False) -> jax.Array:
    return _q4k_2d_raw(xp, qs, sm, interpret)


def _spec_axis(sharding, dim: int):
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return spec[dim] if dim < len(spec) else None


@functools.lru_cache(maxsize=4)
def _q4k_2d_partitioned(interpret: bool):
    """The 2D fused matmul with a GSPMD partitioning rule: tp-sharded
    ``qs``/``sm`` (N dim) compute locally and the output comes back N-sharded
    — no all-gather of the quantized weights (VERDICT r1 #5; previously a
    sharded ``qs`` was gathered at the pallas_call, defeating tp's per-chip
    HBM purpose for exactly the format built to save bandwidth).

    Contract: partitioning is over the output dim N (and the row/batch dim
    of ``xp``); the contraction dim K is never split (mesh.py shards fused
    weights on N for row-parallel layers too — gathering the small
    activations beats gathering weights)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xp, qs, sm):
        return _q4k_2d_raw(xp, qs, sm, interpret)

    def partition(mesh, arg_shapes, result_shape):
        xp_s, qs_s, sm_s = (a.sharding for a in arg_shapes)
        rows = _spec_axis(xp_s, 0)
        n_ax = _spec_axis(qs_s, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),        # never split K
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )
        result_sharding = NamedSharding(mesh, P(rows, n_ax))

        def lower(xp, qs, sm):
            return _q4k_2d_raw(xp, qs, sm, interpret)

        return mesh, lower, result_sharding, arg_shardings

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    fn.def_partition(
        partition=partition,
        infer_sharding_from_operands=infer,
        # shardy factor rule: rows (b) and output (n) propagate; K factors
        # (k, j, t) stay unsplit by construction of the mesh.py shardings
        sharding_rule="b k, n j, t n l -> b n",
    )
    return jax.jit(fn)


_MAX_B = 128  # rows per kernel call: bounds the xp/out VMEM blocks (the
              # weight tiles dominate; a (128, 2048) bf16 xp block is 512 KiB)


def q4k_matmul(x: jax.Array, w: dict, interpret: bool | None = None) -> jax.Array:
    """x (..., K) bf16/f32 → (..., N) in x.dtype, weights in Q4_K kernel
    layout (see module docstring).  The fused path of ``ops.linear.linear``.

    Large batch/sequence dims (prefill buckets) are processed in row chunks
    of ``_MAX_B`` so VMEM blocks stay bounded."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xp = permute_x(x).reshape(-1, K).astype(jnp.bfloat16)
    itp = _interpret(interpret)
    fn = _q4k_2d_partitioned(itp)
    B = xp.shape[0]
    if B <= _MAX_B:
        y = fn(xp, w["qs"], w["sm"])
    else:
        pad = (-B) % _MAX_B
        if pad:
            xp = jnp.concatenate(
                [xp, jnp.zeros((pad, K), xp.dtype)], axis=0)
        chunks = [
            fn(xp[i:i + _MAX_B], w["qs"], w["sm"])
            for i in range(0, B + pad, _MAX_B)
        ]
        y = jnp.concatenate(chunks, axis=0)[:B]
    return y.reshape(*lead, -1).astype(x.dtype)

"""Fused Q4_K dequant-matmul (Pallas): decode directly from ~5-bit weights.

The decode hot loop is HBM-bandwidth-bound: every generated token reads every
weight byte once (SURVEY.md §6; the reference's llama.cpp engine solves this
on GPU with fused dequant-matmul CUDA kernels inside llama-cpp-python,
reference docker/Dockerfile.base:30-32).  The int8 path (ops/linear.py)
already halves traffic vs bf16; this kernel goes further by keeping the
weights in (almost) their GGUF Q4_K form in HBM:

- packed 4-bit nibbles (re-biased, see below)            → 4.00 bit/weight
- folded per-sub-block scale/min in bf16 (d·sc, dmin·mn) → 1.00 bit/weight
                                                      total ≈ 5 bit/weight

i.e. ~0.62× the int8 bytes/token, which on a bandwidth-bound decode is a
~1.6× throughput ceiling raise.  Tiles are dequantized into VMEM only, fed
straight to the MXU, and never written back to HBM.

Dequant cost design (v2 — the round-2 kernel lost 2× to int8 because it
expanded per-sub-block scales over lanes with 0/1 matmuls, ~128 MXU MACs per
weight; measured on v5e this kernel is ~1.2× *faster* than the int8 matvec
at ~0.5× the bytes):

1. **Float nibble split.**  Mosaic has no cheap int8 bit ops (int8
   elementwise lowering fails; int32 widening costs 4× the registers), so
   the packed byte is stored *re-biased*, ``v = (hi−8)·16 + lo`` ∈ [−128,127],
   and split in float arithmetic: ``h = floor(v/16) = hi−8``,
   ``l = v − 16h = lo``.  Both come out of 4 VPU ops on f32 vregs.
2. **Lane-tiled scales.**  Columns are laid out *element-major* inside each
   2048-wide K tile (column ``c`` belongs to sub-block ``c % 64``), so the
   per-sub-block scale vector expands over lanes by vreg tiling
   (``pltpu.repeat`` of the 128-lane [sc|sc] pair) — a register copy, not
   arithmetic.
3. **Affine corrections ride the matmul.**  The per-sub-block min and the
   +8 nibble bias never touch the per-weight path: since
   ``w = q·sc − mn`` and ``Σ_c x_c·const_s = const_s·(Σ x over sub-block)``,
   both fold into 128 extra "correction" K-columns — the activation side
   carries per-sub-block sums (``xsum``, ``xsum_hi``), the weight side
   carries ``[−mn | 8·sc]`` — handled by the same MXU dot that does the real
   work.  Per weight the kernel computes exactly one multiply (``l·sc`` /
   ``h·sc``) plus the bf16 cast.

Layout contract (produced by :func:`prep_q4k` from raw GGUF block bytes; bit
layouts follow gguf/quants.py, the numpy oracle).  The K axis is processed
in fixed tiles of ``TK = 2048`` elements = 8 Q4_K super-blocks:

- ``qs`` (N, K/2) int8 — re-biased packed bytes.  Tile-local byte ``b`` ∈
  [0,1024) holds the weights of columns ``b`` (lo) and ``b+1024`` (hi),
  where column ``c = e·64 + s``: sub-block ``s = c % 64`` (block-major:
  super-block ``s//8``, sub ``s%8``), element ``e = c // 64`` ∈ [0,32).
- ``sm`` (K/2048, N, 128) bf16 — per k-tile: 64 effective scales (d·sc)
  then 64 effective mins (dmin·mn), one per 32-element sub-block, in natural
  block-major order.  Merging them into one 128-lane array keeps every
  Pallas block shape on Mosaic's (8, 128) tiling grid.

Activations are pre-permuted to the same column order by :func:`permute_x`
(a reshape+transpose fused into the surrounding XLA graph) and augmented
with the per-sub-block sums by :func:`augment_x`.

Shape requirements: ``K % 2048 == 0`` and ``N % 128 == 0`` (all Llama-3 /
Mistral linear shapes qualify; loaders fall back to the int8 format
otherwise — see models/params.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ...gguf.constants import GGML_BLOCK_SIZES, GGMLType, QK_K
from ...obs.devtime import register_program
from ...gguf.quants import _garbage_tolerant, unpack_scale_min_k4

TK = 2048            # K elements per kernel step = 8 super-blocks
_SUBS = TK // 32     # 64 sub-blocks per k-tile
TKA = TK + 128       # augmented tile: + [xsum_all(64) | xsum_hi(64)] columns


def _interpret(override: bool | None) -> bool:
    if override is not None:
        return override
    from . import use_interpret

    return use_interpret()


def q4k_compatible(n_out: int, k_in: int, for_tpu: bool | None = None) -> bool:
    """Whether (n_out, k_in) can use the fused kernel.  On TPU, N must tile
    to 128 sublanes; interpret mode (CPU tests) accepts any multiple of 8."""
    if for_tpu is None:
        for_tpu = not _interpret(None)
    return k_in % TK == 0 and n_out % (128 if for_tpu else 8) == 0


# ---------------------------------------------------------------------------
# host-side weight prep
# ---------------------------------------------------------------------------

@_garbage_tolerant
def prep_q4k(raw: np.ndarray, n_out: int, k_in: int) -> dict:
    """Raw Q4_K block bytes (row-major, ``n_out`` rows of ``k_in`` elements)
    → the kernel layout dict {"qs", "sm"}.

    Dispatches to the threaded C++ packer (native/src/gguf_dequant.cpp,
    bit-identical planes — tests/test_native.py) when available; the numpy
    chain below is the reference implementation and the fallback."""
    if not q4k_compatible(n_out, k_in):
        raise ValueError(f"({n_out}, {k_in}) not fused-Q4_K compatible "
                         f"(need K%{TK}==0, N%128==0)")
    from ...native import native_prep_q4k

    nat = native_prep_q4k(raw, n_out, k_in)
    if nat is not None:
        return {"qs": jnp.asarray(nat["qs"]), "sm": jnp.asarray(nat["sm"])}
    bs = GGML_BLOCK_SIZES[GGMLType.Q4_K][1]           # 144
    nb = k_in // QK_K
    ktiles = k_in // TK
    blocks = np.ascontiguousarray(raw, dtype=np.uint8)[: n_out * nb * bs]
    blocks = blocks.reshape(n_out, nb, bs)
    d = blocks[..., 0:2].copy().view(np.float16).astype(np.float32)[..., 0]
    dmin = blocks[..., 2:4].copy().view(np.float16).astype(np.float32)[..., 0]
    sc, mn = unpack_scale_min_k4(blocks[..., 4:16])   # (N, nb, 8) uint8
    eff_s = d[..., None] * sc.astype(np.float32)      # (N, nb, 8)
    eff_m = dmin[..., None] * mn.astype(np.float32)
    sm = np.concatenate([
        eff_s.reshape(n_out, ktiles, _SUBS),          # natural block-major
        eff_m.reshape(n_out, ktiles, _SUBS),
    ], axis=-1)                                       # (N, ktiles, 128)
    sm = np.ascontiguousarray(sm.transpose(1, 0, 2))  # (ktiles, N, 128)

    # unpack file nibbles: byte g*32+i of a super-block holds sub 2g elem i
    # (lo) and sub 2g+1 elem i (hi)
    fqs = blocks[..., 16:].reshape(n_out, nb, 4, 32)
    q = np.empty((n_out, nb, 8, 32), dtype=np.uint8)  # [sub, elem]
    q[:, :, 0::2, :] = fqs & 0x0F
    q[:, :, 1::2, :] = (fqs >> 4) & 0x0F
    # tile-local element-major columns: Q[..., e, s], s = sb*8 + sub
    Q = q.reshape(n_out, ktiles, 8, 8, 32).transpose(0, 1, 4, 2, 3)
    Q = np.ascontiguousarray(Q).reshape(n_out, ktiles, 32, 64)
    lo = Q[:, :, :16, :].reshape(n_out, ktiles, TK // 2)
    hi = Q[:, :, 16:, :].reshape(n_out, ktiles, TK // 2)
    v = ((hi.astype(np.int16) - 8) << 4) + lo         # re-biased byte
    qs = v.astype(np.int8).reshape(n_out, k_in // 2)
    return {
        "qs": jnp.asarray(qs),
        "sm": jnp.asarray(sm, dtype=jnp.bfloat16),
    }


def permute_x(x: jax.Array) -> jax.Array:
    """(..., K) → (..., K) with each 2048-element k-tile reordered to the
    kernel's element-major column order (column ``e·64 + s`` ← original
    element ``(s//8)·256 + (s%8)·32 + e``)."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xb = x.reshape(*lead, K // TK, 8, 8, 32)          # [sb, sub, e]
    xe = jnp.transpose(xb, (*range(len(lead)), len(lead), len(lead) + 3,
                            len(lead) + 1, len(lead) + 2))
    return xe.reshape(*lead, K)


def augment_x(xp: jax.Array) -> jax.Array:
    """Permuted activations (B, K) → (B, K/TK·TKA): each 2048 tile gains
    128 correction columns [per-sub-block sum | per-sub-block hi-half sum]
    that the kernel dots against [−mn | 8·sc]."""
    B, K = xp.shape
    kt = K // TK
    xt = xp.reshape(B, kt, 32, _SUBS)
    xsum = jnp.sum(xt, axis=2)                        # (B, kt, 64)
    xsum_hi = jnp.sum(xt[:, :, 16:, :], axis=2)
    xpa = jnp.concatenate(
        [xt.reshape(B, kt, TK), xsum, xsum_hi], axis=-1)
    return xpa.reshape(B, kt * TKA)


def dequant_ref(w: dict) -> jax.Array:
    """(N, K) f32 dequantized weights in **permuted** column order — the
    small-shape oracle the kernel is tested against."""
    N, half = w["qs"].shape
    kt = half // (TK // 2)
    v = w["qs"].astype(jnp.float32).reshape(N, kt, TK // 2)
    h = jnp.floor(v / 16.0)
    lo = v - 16.0 * h                                 # low nibble
    hi = h + 8.0                                      # high nibble
    q = jnp.concatenate([lo, hi], axis=2)             # (N, kt, TK) elem-major
    sm = jnp.transpose(w["sm"], (1, 0, 2)).astype(jnp.float32)  # (N, kt, 128)
    sc = jnp.tile(sm[..., :_SUBS], (1, 1, TK // _SUBS))
    mn = jnp.tile(sm[..., _SUBS:], (1, 1, TK // _SUBS))
    return (q * sc - mn).reshape(N, kt * TK)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _env_variant(name: str, allowed: tuple) -> str:
    """Read a kernel-variant env knob, failing loud on typos (an A/B run
    must never silently compare the default against itself).  The value is
    threaded into every jit/lru cache key, so changing the env between
    calls re-traces instead of silently reusing the old program.  Shared
    by every fused kernel's LFKT_Q*_KERNEL knob; the read routes through
    the utils/config.py registry (lfkt-lint CFG001) with each variant
    table's first entry as the default."""
    from ...utils.config import knob

    v = knob(name, default=allowed[0]).strip().lower()
    if v not in allowed:
        raise ValueError(f"{name} must be {'|'.join(allowed)}, got {v!r}")
    return v


# Default (first) = resplit: bit-identical planes to `cur` via the exact
# lsc = v*sc - 16*(h*sc) cancellation.  On-chip B=1 geomean 125.9 vs
# cur's 126.8 us (ahead at (4096,4096) and (14336,4096), behind 0.3% at
# (4096,14336) — kernel_microbench_2026-08-01) and +1.8% end-to-end
# (72.32 vs 71.02 tok/s, bench_q4km_variant_ab vs bench_q4km_headline
# 2026-08-01).  vbf32 is ~8% faster still but FAILS the on-chip numerics
# gate (Mosaic truncates its f32 dot to single-pass bf16: rel_dev ~3e-2
# — the microbench dev_fail rows); never default it.
Q4K_VARIANTS = ("resplit", "cur", "vbf32", "onedot")


def _lane_repeat(v, times: int, interpret: bool):
    """Expand a 128-lane per-sub-block vector over a k-tile by vreg tiling
    (f32): ``jnp.tile`` in interpret mode, ``pltpu.repeat`` on TPU.  Shared
    by every fused kernel's scale-plane expansion."""
    if interpret:
        return jnp.tile(v, (1, times)).astype(jnp.float32)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.repeat(v, times, axis=1).astype(jnp.float32)


def _q4k_matmul_kernel(xpa_ref, qs_ref, sm_ref, o_ref, *, interpret,
                       variant="cur"):
    # xpa (B, TKA) bf16 permuted+augmented; qs (TN, TK/2) int8;
    # sm (1, TN, 128) bf16
    TN = qs_ref.shape[0]
    v = qs_ref[...].astype(jnp.float32)
    sm = sm_ref[...].reshape(TN, 128)
    sc, mn = sm[:, :_SUBS], sm[:, _SUBS:]
    sc2 = jnp.concatenate([sc, sc], axis=1)           # (TN, 128)
    sc_exp = _lane_repeat(sc2, TK // 256, interpret)
    h = jnp.floor(v * 0.0625)                         # hi − 8
    corr = jnp.concatenate([-mn, sc * 8.0], axis=1).astype(jnp.bfloat16)
    xpa = xpa_ref[...]

    if variant == "vbf32":
        # Activation-side nibble recombination, f32 planes:
        #   y = x_lo·(v·sc) + (x_hi − 16·x_lo)·(h·sc)
        # Per weight only 2 multiplies + the floor — no reconstruction, no
        # bf16 casts.  The two terms carry 16× the result's magnitude and
        # cancel, so the planes stay f32 (v·sc and h·sc are EXACT in f32:
        # ≤8-bit int × bf16 scale needs ≤16 mantissa bits) and the dots take
        # f32 operands.  Mosaic rejects an explicit precision attr
        # ("Unsupported dot precision: HIGH"), so accuracy rests on how its
        # f32 dot lowers (multi-pass ⇒ fine; single-pass bf16 ⇒ the
        # rejected `vb` ablation's 3.3% rms returns) — the chip microbench
        # (tools/kernel_microbench.py `rel_dev` / `dev_fail` rows) is the
        # gate; the interpret-mode tests pin the algebra either way.
        a_v = v * sc_exp
        a_h = h * sc_exp
        x_lo = xpa[:, : TK // 2].astype(jnp.float32)
        x_hi = xpa[:, TK // 2: TK].astype(jnp.float32)
        part = jax.lax.dot_general(
            x_lo, a_v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        part += jax.lax.dot_general(
            x_hi - 16.0 * x_lo, a_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        part += jax.lax.dot_general(
            xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        _q4k_accum(o_ref, part)
        return

    if variant == "resplit":
        # lsc = v·sc − 16·(h·sc): all three f32 quantities are exact
        # (v, h ≤ 8-bit ints × bf16 scale fits f32), so the cancellation
        # reproduces l·sc EXACTLY — bit-identical planes to the `cur`
        # branch with a different VPU dependency graph (the l = v − 16h
        # reconstruction never materializes)
        a_hi_f = h * sc_exp
        a_lo = (v * sc_exp - 16.0 * a_hi_f).astype(jnp.bfloat16)
        a_hi = a_hi_f.astype(jnp.bfloat16)
    else:                                             # cur | onedot
        l = v - h * 16.0                              # lo
        a_lo = (l * sc_exp).astype(jnp.bfloat16)      # (TN, TK/2)
        a_hi = (h * sc_exp).astype(jnp.bfloat16)

    if variant == "onedot":
        # One concatenated (TN, TK) plane, one MXU dot over the full tile
        # (plus the corr dot) — same planes as `cur` bit-for-bit, trading
        # a VMEM concat copy for fewer, larger matmuls.
        a = jnp.concatenate([a_lo, a_hi], axis=1)     # (TN, TK)
        part = jax.lax.dot_general(
            xpa[:, :TK], a, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        part += jax.lax.dot_general(
            xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        _q4k_accum(o_ref, part)
        return

    part = jax.lax.dot_general(
        xpa[:, : TK // 2], a_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK // 2: TK], a_hi, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    part += jax.lax.dot_general(
        xpa[:, TK:], corr, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    _q4k_accum(o_ref, part)


def _q4k_accum(o_ref, part):
    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += part


def _pick_tn(n: int, interpret: bool, prefs: tuple = (512, 256, 128)) -> int:
    """Largest N tile that divides ``n``.  512 measured fastest for the
    Q4_K kernel (docs/bench/qmatmul_v2_microbench_2026-07-29.json); the
    Q6_K kernel passes smaller ``prefs`` because its wider f32
    intermediates would crowd the ~16 MB VMEM at TN=512."""
    for c in prefs + ((64, 32, 16, 8) if interpret else ()):
        if n % c == 0:
            return c
    raise ValueError(f"N={n} not divisible by 128")


_TN_PREFS_Q4K = (512, 256, 128)  # 512 measured fastest for decode (docs/bench)


def _tn_prefs_for(B: int, prefs: tuple) -> tuple:
    """Cap TN at 256 for large row blocks, bounding the (B, TKA) activation
    block plus dequant-intermediate VMEM footprint.  Artifact-free chip
    measurement (docs/PERF.md "Measurement hygiene") shows prefill-size
    row counts perform the same at 128-row/TN=512 and 256-row/TN=256 for
    every fused format (~16.5 ms for the 8B (4096, 14336) shape at 512
    rows); the cap keeps the larger 256-row chunks (half the kernel
    calls) safely inside VMEM.  Decode (B ≤ 128) keeps the
    measured-fastest TN=512."""
    if B > 128:
        return tuple(t for t in prefs if t <= 256) or prefs[-1:]
    return prefs


def _q4k_specs(B: int, TN: int):
    """(in_specs, out_spec) as (block_shape, index_map) pairs — the single
    tiling definition consumed by BOTH the unstacked pallas_call (output
    head) and the stacked scalar-prefetch call (per-layer serving path),
    so the two can't drift."""
    return (
        [
            ((B, TKA), lambda n, k: (0, k)),
            ((TN, TK // 2), lambda n, k: (n, k)),
            ((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        ((B, TN), lambda n, k: (0, n)),
    )


def plain_pallas_call(kernel, grid, in_specs, out_spec, out_shape,
                      interpret: bool):
    """pl.pallas_call from the same (block_shape, index_map) pairs
    :func:`stacked_pallas_call` consumes."""
    o_block, o_map = out_spec
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(b, m) for b, m in in_specs],
        out_specs=pl.BlockSpec(o_block, o_map),
        out_shape=out_shape,
        interpret=interpret,
    )


def _q4k_2d_raw(xpa: jax.Array, qs: jax.Array, sm: jax.Array,
                interpret: bool, variant: str = "cur") -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA) * TK
    N = qs.shape[0]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q4K))
    in_specs, out_spec = _q4k_specs(B, TN)
    return plain_pallas_call(
        functools.partial(_q4k_matmul_kernel, interpret=interpret,
                          variant=variant),
        (N // TN, K // TK), in_specs, out_spec,
        jax.ShapeDtypeStruct((B, N), jnp.float32), interpret,
    )(xpa, qs, sm)


def _spec_axis(sharding, dim: int):
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return spec[dim] if dim < len(spec) else None


@functools.lru_cache(maxsize=4)
def _q4k_2d_partitioned(interpret: bool, variant: str = "cur"):
    """The 2D fused matmul with a GSPMD partitioning rule: tp-sharded
    ``qs``/``sm`` (N dim) compute locally and the output comes back N-sharded
    — no all-gather of the quantized weights (VERDICT r1 #5; previously a
    sharded ``qs`` was gathered at the pallas_call, defeating tp's per-chip
    HBM purpose for exactly the format built to save bandwidth).

    Contract: partitioning is over the output dim N (and the row/batch dim
    of ``xpa``); the contraction dim K is never split (mesh.py shards fused
    weights on N for row-parallel layers too — gathering the small
    activations beats gathering weights)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xpa, qs, sm):
        return _q4k_2d_raw(xpa, qs, sm, interpret, variant)

    def partition(mesh, arg_shapes, result_shape):
        xp_s, qs_s, sm_s = (a.sharding for a in arg_shapes)
        rows = _spec_axis(xp_s, 0)
        n_ax = _spec_axis(qs_s, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),        # never split K
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )
        result_sharding = NamedSharding(mesh, P(rows, n_ax))

        def lower(xpa, qs, sm):
            return _q4k_2d_raw(xpa, qs, sm, interpret, variant)

        return mesh, lower, result_sharding, arg_shardings

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        # shardy factor rule: rows (b) and output (n) propagate; K factors
        # (k, j, t) stay unsplit by construction of the mesh.py shardings
        sharding_rule="b k, n j, t n l -> b n",
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=0))


# ---------------------------------------------------------------------------
# stacked (per-layer) variants: scalar-prefetch layer indexing
# ---------------------------------------------------------------------------
#
# The model iterates its layers with ``lax.scan`` over weights stacked as
# (L, ...) arrays (models/llama.py).  A pallas_call operand must be a
# materialized buffer, so scanning the weights as xs makes XLA *copy* each
# layer's quantized planes (read+write of the full layer, ~137 MB for 8B
# Q4_K) before every kernel call — measured +6.3 ms/token on v5e, turning
# the fused win into a loss (tools/decode_breakdown.py).  The int8 path
# doesn't pay this because XLA fuses the dynamic-slice into the dot_general
# read.  The fix is TPU-idiomatic scalar prefetch: the layer index rides a
# prefetched scalar and the BlockSpec index_maps address layer ``idx[0]``
# of the stacked array directly, so block DMAs stream from the weights'
# home HBM with no intermediate copy — and the model keeps one compiled
# layer body (compile time ∝ 1, not n_layers).


class _NoLead:
    """Ref adapter hiding the leading length-1 layer axis of a stacked
    weight block, so the unstacked kernel bodies run unchanged (they only
    use ``ref.shape`` and ``ref[...]``)."""

    __slots__ = ("_ref",)

    def __init__(self, ref):
        self._ref = ref

    @property
    def shape(self):
        return self._ref.shape[1:]

    def __getitem__(self, idx):
        return self._ref[idx].reshape(self._ref.shape[1:])


def stacked_pallas_call(kernel, grid, in_specs, out_spec, out_shape,
                        interpret: bool):
    """Build ``fn(idx, xpa, *stacked_planes)`` running ``kernel`` (an
    unstacked fused kernel ``(xpa_ref, *plane_refs, o_ref)``) against layer
    ``idx[0]`` of weight planes stacked as (L, ...) arrays.

    ``in_specs`` are the UNSTACKED (block_shape, index_map) pairs — first
    the activations, then the weight planes; weight specs get the layer dim
    prepended and their index_maps extended with the prefetched scalar.
    Interpret mode (CPU tests) runs the same code path — pallas emulates
    scalar prefetch."""
    from jax.experimental.pallas import tpu as pltpu

    (x_block, x_map), *w_specs = in_specs

    def lift(block, imap):
        return pl.BlockSpec(
            (1, *block), lambda *a, _m=imap: (a[-1][0], *_m(*a[:-1])))

    specs = [pl.BlockSpec(x_block, lambda *a, _m=x_map: _m(*a[:-1]))]
    specs += [lift(b, m) for b, m in w_specs]
    o_block, o_map = out_spec
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=specs,
        out_specs=pl.BlockSpec(o_block, lambda *a, _m=o_map: _m(*a[:-1])),
    )

    def wrapped(idx_ref, xpa_ref, *rest):
        del idx_ref  # consumed by the index_maps
        kernel(xpa_ref, *(_NoLead(r) for r in rest[:-1]), rest[-1])

    return pl.pallas_call(
        wrapped, grid_spec=gs, out_shape=out_shape, interpret=interpret)


def _q4k_2d_stacked_raw(idx: jax.Array, xpa: jax.Array, qs: jax.Array,
                        sm: jax.Array, interpret: bool,
                        variant: str = "cur") -> jax.Array:
    B, KA = xpa.shape
    K = (KA // TKA) * TK
    N = qs.shape[1]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q4K))
    in_specs, out_spec = _q4k_specs(B, TN)
    call = stacked_pallas_call(
        functools.partial(_q4k_matmul_kernel, interpret=interpret,
                          variant=variant),
        grid=(N // TN, K // TK),
        in_specs=in_specs,
        out_spec=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )
    return call(idx, xpa, qs, sm)


def def_partition_compat(fn, **kwargs) -> None:
    """``fn.def_partition`` with the newer ``sharding_rule`` (Shardy) kwarg
    when this jax supports it, dropping it otherwise.  Every caller also
    passes the GSPMD callbacks (``partition`` /
    ``infer_sharding_from_operands``), so older-jax behavior is identical —
    without this the whole fused-kernel family raises TypeError at first
    trace on jax builds that predate the kwarg."""
    try:
        fn.def_partition(**kwargs)
    except TypeError:
        kwargs.pop("sharding_rule", None)
        fn.def_partition(**kwargs)


def rows_vmappable(fn, xpa_pos: int):
    """Give a fused matmul a vmap rule: batching over the activation
    operand is just more rows for the kernel (weights are shared across
    the batch).  ``custom_partitioning`` has no batching rule in JAX, so
    without this the vmapped engines (parallel/batched.py — the
    mesh-batched and continuous serving paths) raise
    ``NotImplementedError: Batching rule for 'custom_partitioning'`` the
    first time they meet fused weights."""
    from jax.custom_batching import custom_vmap

    @custom_vmap
    def wrapped(*args):
        return fn(*args)

    @wrapped.def_vmap
    def _rule(axis_size, in_batched, *args):  # noqa: ANN001
        if not in_batched[xpa_pos] or any(
                b for i, b in enumerate(in_batched) if i != xpa_pos):
            raise NotImplementedError(
                "fused matmul vmap: only the activation operand may carry "
                "the batch axis (weights are shared)")
        xpa = args[xpa_pos]
        nb, B, KA = xpa.shape
        # re-chunk the flattened rows: the caller's batched_rows bound was
        # applied to the PER-LANE shape, so nb*B can exceed _MAX_B and blow
        # the kernel's activation/output VMEM blocks at large lane counts
        out = batched_rows(
            lambda xp: fn(*args[:xpa_pos], xp, *args[xpa_pos + 1:]),
            xpa.reshape(nb * B, KA))
        return out.reshape(nb, B, -1), True

    return wrapped


def stacked_partitioned(raw_fn, sharding_rule: str, interpret: bool):
    """GSPMD rule shared by every stacked fused matmul — same contract as
    the unstacked kernels (partition over N and rows, never K) plus: the
    layer dim and the index scalar are never split.

    ``raw_fn(idx, xpa, *planes, interpret=...)`` is the stacked pallas
    call; plane shardings are derived from rank (value planes (L, N, K/x),
    scale planes (L, kt, N, 128) — N is always at ``rank - 2``)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(idx, xpa, *planes):
        return raw_fn(idx, xpa, *planes, interpret=interpret)

    def lower(idx, xpa, *planes):
        return raw_fn(idx, xpa, *planes, interpret=interpret)

    def partition(mesh, arg_shapes, result_shape):
        rows = _spec_axis(arg_shapes[1].sharding, 0)
        n_ax = _spec_axis(arg_shapes[2].sharding, 1)
        arg_shardings = [
            NamedSharding(mesh, P(None)),
            NamedSharding(mesh, P(rows, None)),
        ] + [
            NamedSharding(
                mesh, P(*([None] * (len(a.shape) - 2)), n_ax, None))
            for a in arg_shapes[2:]
        ]
        return (mesh, lower, NamedSharding(mesh, P(rows, n_ax)),
                tuple(arg_shardings))

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[1].sharding, 0),
                    _spec_axis(arg_shapes[2].sharding, 1)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule=sharding_rule,
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=1))


@functools.lru_cache(maxsize=8)
def _q4k_2d_stacked_partitioned(interpret: bool, variant: str = "cur"):
    return stacked_partitioned(
        functools.partial(_q4k_2d_stacked_raw, variant=variant),
        "i, b k, l n j, l t n m -> b n", interpret)


def q4k_matmul_stacked(x: jax.Array, w: dict, idx,
                       interpret: bool | None = None) -> jax.Array:
    """x (..., K) → (..., N) against layer ``idx`` of stacked weights
    (``qs`` (L, N, K/2), ``sm`` (L, K/2048, N, 128)).  The fused path of
    ``ops.linear.linear_at`` — no per-layer weight copy under scan."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xpa = augment_x(permute_x(x).reshape(-1, K).astype(jnp.bfloat16))
    fn = _q4k_2d_stacked_partitioned(
        _interpret(interpret), _env_variant("LFKT_Q4K_KERNEL", Q4K_VARIANTS))
    i1 = jnp.asarray(idx, jnp.int32).reshape(1)
    y = batched_rows(lambda xp, *ws: fn(i1, xp, *ws), xpa, w["qs"], w["sm"])
    return y.reshape(*lead, -1).astype(x.dtype)


_MAX_B = 256  # rows per kernel call: bounds the xpa/out VMEM blocks.
              # Rows > 128 force TN <= 256 (_tn_prefs_for), keeping the
              # budget at ~4.3 MB activations + ~6 MB dequant
              # intermediates.  Chip-measured equal to 128-row/TN=512
              # chunks for all four fused formats at prefill sizes
              # (~16.5 ms for (4096, 14336) at 512 rows) with half the
              # kernel calls.  Shared by every fused kernel via
              # batched_rows().


def batched_rows(fn, xpa: jax.Array, *weights) -> jax.Array:
    """Run a fused 2D matmul over ``xpa`` (B, K') in row chunks of
    ``_MAX_B`` so the activation/output VMEM blocks stay bounded for large
    batch/sequence dims (prefill buckets).  Shared by all fused kernels
    (Q4_K / Q5_K / Q6_K / Q8_0) — one place to tune the row bound."""
    B = xpa.shape[0]
    if B <= _MAX_B:
        return fn(xpa, *weights)
    pad = (-B) % _MAX_B
    if pad:
        xpa = jnp.concatenate(
            [xpa, jnp.zeros((pad, xpa.shape[1]), xpa.dtype)], axis=0)
    chunks = [
        fn(xpa[i:i + _MAX_B], *weights)
        for i in range(0, B + pad, _MAX_B)
    ]
    return jnp.concatenate(chunks, axis=0)[:B]


def q4k_matmul(x: jax.Array, w: dict, interpret: bool | None = None) -> jax.Array:
    """x (..., K) bf16/f32 → (..., N) in x.dtype, weights in Q4_K kernel
    layout (see module docstring).  The fused path of ``ops.linear.linear``."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xpa = augment_x(
        permute_x(x).reshape(-1, K).astype(jnp.bfloat16))
    fn = _q4k_2d_partitioned(
        _interpret(interpret), _env_variant("LFKT_Q4K_KERNEL", Q4K_VARIANTS))
    y = batched_rows(fn, xpa, w["qs"], w["sm"])
    return y.reshape(*lead, -1).astype(x.dtype)


# devtime inventory (lfkt-lint PERF001): the fused-matmul builders mint
# trace-inner programs — every jit/pallas_call they create runs inside the
# engines' prefill/decode entry programs, so compile walls are attributed
# to those entries (obs/devtime.py; /debug/compiles kind="inner")
register_program("plain_pallas_call", site="ops.pallas.qmatmul")
register_program("stacked_pallas_call", site="ops.pallas.qmatmul")
register_program("stacked_partitioned", site="ops.pallas.qmatmul")
register_program("_q4k_2d_partitioned", site="ops.pallas.qmatmul")

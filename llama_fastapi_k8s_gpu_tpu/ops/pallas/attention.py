"""Blockwise flash attention for TPU (Pallas).

The reference's attention runs inside llama.cpp's CUDA kernels (reference
docker/Dockerfile.base:30-32); the XLA fallback in ``models/llama.py``
materializes the full (S, n_ctx) score matrix.  This kernel streams K/V
HBM→VMEM in blocks with an online softmax, so VMEM usage is O(block) and
``n_ctx`` can grow past 1024 (SURVEY.md §5 "Long-context") without the
scores ever hitting HBM.

Layout: GQA folds the ``group = n_heads // n_kv_heads`` query heads that
share one KV head into the row dimension, so each grid step is a dense
(BQ, hd) × (hd, BK) MXU matmul.  The kv-block index is the *last* grid
dimension — TPU grids execute sequentially, so the running max / sum /
accumulator live in VMEM scratch across kv steps and the output is written
once on the final step.

Multi-KV-block inner loop (``kv_unroll``): each grid step fetches a FUSED
K/V block of ``kv_unroll * block_k`` tokens and iterates the online-softmax
update over the ``block_k``-sized sub-blocks in-kernel (a trace-time Python
loop, so the math per sub-block — and therefore the result — is identical
to the unrolled grid).  Fewer grid launches amortize the per-step block-DMA
setup that dominates long-context prefill on this platform (docs/PERF.md
"Roofline, revised": the 8k+ TTFT floor was per-grid-step overhead, not
FLOPs), at the cost of ``kv_unroll``× the K/V VMEM residency per step.
``LFKT_FLASH_KV_UNROLL`` sets the default; the causal classifier still
skips/interior-specializes per sub-block, so a fused block pays VPU mask
work only for the sub-blocks that need it.

Paged-KV contract (``LFKT_KV_PAGED``, parallel/kvpool.py): the pool is
**page-contiguous**, not gathered — a radix-cache hit copies its pages
into the FRONT of an ordinary dense ring before prefill, so this kernel
always sees the same head-major ``(n_kv, n_ctx, hd)`` ring it was probed
and tuned for, with no page-table indirection in the block index maps
(the KER001-003 contract is unchanged, and paged greedy decode stays
bit-identical to dense).  A gathered variant — per-block page-id
prefetch feeding the K/V index maps — only pays once pages stop being
materialized locally, i.e. the disaggregated-prefill step (ROADMAP item
6) where the page pytree becomes the wire format; grow it from the
``kv_unroll`` block loop here when that lands.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...obs.devtime import register_program

# Large-but-finite mask value: keeps exp() well-defined when an entire block
# (or an entire padded row) is masked, unlike -inf.
DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _attn_kernel(
    # scalar prefetch
    pos_ref,            # (1,) int32 — cache position of query token 0
    # inputs
    q_ref,              # (1, BQ, hd)
    k_ref,              # (1, U*BK, hd) — bf16, or int8 when quantized
    v_ref,              # (1, U*BK, hd)
    # quantized only (absent otherwise): per-token f32 scale blocks
    #   ks_ref          # (1, U*BK)
    #   vs_ref          # (1, U*BK)
    # outputs
    *rest,              # o_ref (1, BQ, hd), then scratch:
    # m_ref,            # (BQ, 128) f32  running max (lane-replicated)
    # l_ref,            # (BQ, 128) f32  running sum (lane-replicated)
    # acc_ref,          # (BQ, hd)  f32  running weighted sum
    seq_len: int,       # S — real (bucketed) query length
    block_q: int,
    block_k: int,
    kv_unroll: int,     # U — block_k-sized sub-blocks fused per grid step
    sm_scale: float,
    sliding_window: int,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Block-level causal classification (the VPU fix: the kernel was
    # mask/softmax-bound, spending identical VPU work on fully-masked
    # future blocks and on interior blocks that need no masking at all).
    # Query tokens of this tile: rows are (group, S)-flattened, so token =
    # row % S.  The tight span bound needs the tile to cover one contiguous
    # token range, which holds iff S % BQ == 0; any other shape (tile
    # wrapping mid-span, or spanning whole copies) falls back to the
    # conservative full range [0, S-1] — always correct, just fewer
    # skip/interior blocks.
    if block_q < seq_len and seq_len % block_q == 0:
        t_min = jax.lax.rem(qb * block_q, seq_len)
        t_max = t_min + block_q - 1
    else:
        t_min = 0
        t_max = seq_len - 1
    q_min = pos_ref[0] + t_min
    q_max = pos_ref[0] + t_max

    # The inner loop over the fused block's sub-blocks is a trace-time
    # Python loop (``u`` is static), so every sub-block runs the SAME
    # online-softmax update, in the same order, as the kv_unroll=1 grid —
    # the result is bit-identical; only the launch count changes.
    def _sub_block(u: int):
        kmin = (kb * kv_unroll + u) * block_k
        kmax = kmin + block_k - 1

        skip = kmin > q_max                        # fully in the masked future
        if sliding_window:
            skip |= kmax <= q_min - sliding_window  # fully behind the window
            interior = jnp.bool_(False)            # window edge → always mask
        else:
            interior = kmax <= q_min               # fully unmasked block

        lo = u * block_k

        def _body(masked: bool):
            q = q_ref[0]                           # (BQ, hd)
            k = k_ref[0, lo:lo + block_k, :]       # (BK, hd)
            if quantized:
                # fused dequant, scale-last: scores are linear in K, so the
                # per-token scale factors out of the contraction — dot the
                # RAW int8 block (cast in-register; [-127,127] is exact in
                # any float), then scale each key column once.  HBM moved
                # int8.
                k = k.astype(q.dtype)
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale                           # (BQ, BK)
            if quantized:
                scores = scores * ks_ref[:, lo:lo + block_k]  # (1, BK) bcast

            if masked:
                row = qb * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                q_pos = pos_ref[0] + jax.lax.rem(row, seq_len)
                key_pos = kmin + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                mask = key_pos <= q_pos
                if sliding_window:
                    mask &= key_pos > q_pos - sliding_window
                scores = jnp.where(mask, scores, DEFAULT_MASK_VALUE)

            m_prev = m_ref[:, :1]                  # (BQ, 1)
            l_prev = l_ref[:, :1]
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)        # rescale of old state
            p = jnp.exp(scores - m_new)            # (BQ, BK)
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

            v = v_ref[0, lo:lo + block_k, :]       # (BK, hd)
            if quantized:
                # same trick on V: p·(q·s) == (p·s)·q — fold the value
                # scales into the (BQ, BK) probability tile, contract the
                # raw int8
                p = p * vs_ref[:, lo:lo + block_k]
                v = v.astype(q_ref.dtype)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[...] = acc_ref[...] * alpha + pv
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(jnp.logical_and(jnp.logical_not(skip), interior))
        def _interior():
            _body(masked=False)

        @pl.when(jnp.logical_and(jnp.logical_not(skip),
                                 jnp.logical_not(interior)))
        def _edge():
            _body(masked=True)

    for u in range(kv_unroll):
        _sub_block(u)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finish():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)            # fully-masked (padded) rows
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pick_block(n: int, preferred: int) -> int:
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= preferred and n % b == 0:
            return b
    return n


def _env_kv_unroll() -> int:
    """The ``LFKT_FLASH_KV_UNROLL`` default, read through the knob registry
    (lfkt-lint CFG001) at trace time — the qmatmul ``_env_variant``
    convention: env knobs for kernel geometry are process-lifetime choices
    baked into the compiled programs at first trace."""
    from ...utils.config import knob

    u = int(knob("LFKT_FLASH_KV_UNROLL"))
    if u < 1:
        raise ValueError(f"LFKT_FLASH_KV_UNROLL must be >= 1, got {u}")
    return u


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "sliding_window", "block_q", "block_k",
                     "kv_unroll", "interpret"),
)
def flash_attention(
    q: jax.Array,          # (S, n_heads, hd)
    k: jax.Array,          # (n_kv_heads, n_ctx, hd) — full ring cache,
    v: jax.Array,          #   HEAD-MAJOR (models/llama.py init_cache)
    pos_offset: jax.Array, # scalar int32: cache position of q[0]
    sm_scale: float,
    sliding_window: int = 0,
    block_q: int = 512,
    block_k: int = 1024,
    kv_unroll: int | None = None,  # block_k sub-blocks fused per grid step
    #                                (None: LFKT_FLASH_KV_UNROLL)
    k_scale: jax.Array | None = None,  # (n_kv, n_ctx) f32 — int8 cache only
    v_scale: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Causal (+ sliding-window) attention of S queries over the KV ring.

    Returns (S, n_heads, hd) in q.dtype.  The causal mask ``key_pos <=
    q_pos`` makes unwritten cache slots invisible, exactly like the XLA
    path in ``models/llama.py``.  K/V arrive head-major, which is the
    kernel's own block layout — no ring-sized transpose on the way in.

    With ``k_scale``/``v_scale`` (the int8 cache's per-head per-token
    scales, docs/KV_CACHE.md), K/V are int8 and the kernel dequantizes
    in-register — the ring's HBM traffic roughly halves, which is the
    whole point of ``kv_dtype=int8`` on a bandwidth-bound decode chip.

    ``kv_unroll`` fuses that many ``block_k`` sub-blocks into one grid
    step's K/V fetch and runs the online softmax over them in-kernel —
    numerically identical to the unrolled grid (same sub-block math, same
    order), but with ``kv_unroll``× fewer grid launches to pay per-step
    block-DMA setup for.  Clamped so the fused block still divides
    ``n_ctx`` (tiny rings degrade gracefully to the plain grid).
    """
    S, n_heads, hd = q.shape
    n_kv, n_ctx, _ = k.shape
    group = n_heads // n_kv
    gs = group * S
    quantized = k_scale is not None

    bq = _pick_block(gs, block_q)
    bk = _pick_block(n_ctx, block_k)
    if kv_unroll is None:
        kv_unroll = _env_kv_unroll()
    # largest unroll <= requested whose fused block divides the ring
    u = max(1, min(int(kv_unroll), n_ctx // bk))
    while u > 1 and n_ctx % (bk * u):
        u -= 1
    bkf = bk * u                                   # fused K/V block

    # (S, n_kv, group, hd) → (n_kv, group*S, hd): row = g*S + s
    qg = q.reshape(S, n_kv, group, hd).transpose(1, 2, 0, 3).reshape(n_kv, gs, hd)
    kk = k                                         # (n_kv, n_ctx, hd)
    vv = v

    grid = (n_kv, gs // bq, n_ctx // bkf)
    kernel = functools.partial(
        _attn_kernel,
        seq_len=S,
        block_q=bq,
        block_k=bk,
        kv_unroll=u,
        sm_scale=sm_scale,
        sliding_window=sliding_window,
        quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((1, bq, hd), lambda h, qb, kb, *_: (h, qb, 0)),
        pl.BlockSpec((1, bkf, hd), lambda h, qb, kb, *_: (h, kb, 0)),
        pl.BlockSpec((1, bkf, hd), lambda h, qb, kb, *_: (h, kb, 0)),
    ]
    operands = [qg, kk, vv]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bkf), lambda h, qb, kb, *_: (h, kb)),
            pl.BlockSpec((1, bkf), lambda h, qb, kb, *_: (h, kb)),
        ]
        operands += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bq, hd), lambda h, qb, kb, *_: (h, qb, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n_kv, gs, hd), q.dtype),
        interpret=interpret,
    )(jnp.atleast_1d(pos_offset.astype(jnp.int32)), *operands)

    # (n_kv, group, S, hd) → (S, n_heads, hd)
    return out.reshape(n_kv, group, S, hd).transpose(2, 0, 1, 3).reshape(S, n_heads, hd)


# devtime inventory (lfkt-lint PERF001): flash attention is a TRACE-INNER
# dispatch site — it runs inside the prefill/decode entry programs, so its
# compile wall is attributed to whichever host program traced it
# (obs/devtime.py; /debug/compiles shows it under kind="inner")
register_program("flash_attention", site="ops.pallas.attention")

"""Fused Q8_0 dequant-matmul (Pallas): serve Q8_0 files at file fidelity.

BASELINE config #3 names Q8_0 GGUF variants; round 2 served them through a
per-ROW int8 requant of the dequantized weights, compounding a second
quantization on top of the file's.  This kernel keeps the file's own
per-32-block scales (folded to bf16, ~0.4% scale rounding — the same fold
every fused kernel here applies) at ~1.13 B/weight vs the requant path's
1.0: a ~12% bandwidth premium for serving the file's actual quantization
grid, which is what llama.cpp does with these files.

Simplest member of the fused family (ops/pallas/qmatmul.py is the design
reference): values are already int8, so the kernel is load → widen →
multiply by the lane-tiled block scale → bf16 → MXU dot.  No packed
nibbles, no correction columns.

Layout contract (:func:`prep_q8_0`), K-tile = 2048 = 64 blocks of 32:

- ``q8`` (N, K) int8 — element-major tile columns: column ``c`` holds
  block ``c % 64``, element ``c // 64`` — the SAME column order as the
  Q4_K kernel (a 32-element "sub-block" there is a 32-element block
  here), so :func:`qmatmul.permute_x` is reused for activations.
- ``sm8`` (K/2048, N, 128) bf16 — the tile's 64 block scales (f16 in
  the file, folded to bf16) duplicated
  ``[d|d]``, so one ``pltpu.repeat`` expands them over lanes with
  period 128 (column ``c`` → lane ``c % 128`` → scale ``c % 64``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ...gguf.constants import GGML_BLOCK_SIZES, GGMLType
from ...obs.devtime import register_program
from ...gguf.quants import _garbage_tolerant
from .qmatmul import (
    batched_rows,
    def_partition_compat,
    _interpret,
    _lane_repeat,
    permute_x,
    _pick_tn,
    plain_pallas_call,
    q4k_compatible,
    rows_vmappable,
    _spec_axis,
    stacked_pallas_call,
    stacked_partitioned,
    TK,
    _tn_prefs_for,
)

q8_compatible = q4k_compatible  # same divisibility classes


@_garbage_tolerant
def prep_q8_0(raw: np.ndarray, n_out: int, k_in: int) -> dict:
    """Raw Q8_0 block bytes (row-major) → {"q8", "sm8"}."""
    if not q8_compatible(n_out, k_in):
        raise ValueError(f"({n_out}, {k_in}) not fused-Q8_0 compatible "
                         f"(need K%{TK}==0, N%128==0)")
    from ...native import native_prep_q8_0

    nat = native_prep_q8_0(raw, n_out, k_in)
    if nat is not None:
        return {"q8": jnp.asarray(nat["q8"]), "sm8": jnp.asarray(nat["sm8"])}
    bs = GGML_BLOCK_SIZES[GGMLType.Q8_0][1]           # 34
    nb = k_in // 32
    kt = k_in // TK
    blocks = np.ascontiguousarray(raw, dtype=np.uint8)[: n_out * nb * bs]
    blocks = blocks.reshape(n_out, nb, bs)
    d = blocks[..., 0:2].copy().view(np.float16).astype(np.float32)[..., 0]
    q = blocks[..., 2:34].view(np.int8)               # (N, nb, 32)

    Q = q.reshape(n_out, kt, 64, 32).transpose(0, 1, 3, 2)   # [e, b]
    q8 = np.ascontiguousarray(Q).reshape(n_out, k_in)
    dsc = d.reshape(n_out, kt, 64)
    sm8 = np.concatenate([dsc, dsc], axis=-1).transpose(1, 0, 2)
    return {
        "q8": jnp.asarray(q8),
        "sm8": jnp.asarray(np.ascontiguousarray(sm8), dtype=jnp.bfloat16),
    }


def dequant_ref8(w: dict) -> jax.Array:
    """(N, K) f32 dequantized weights in **permuted** column order."""
    N, K = w["q8"].shape
    kt = K // TK
    v = w["q8"].astype(jnp.float32).reshape(N, kt, TK)
    sm = jnp.transpose(w["sm8"], (1, 0, 2)).astype(jnp.float32)
    sc = jnp.tile(sm, (1, 1, TK // 128))
    return (v * sc).reshape(N, K)


def _q8_matmul_kernel(xp_ref, q8_ref, sm_ref, o_ref, *, interpret):
    TN = q8_ref.shape[0]
    v = q8_ref[...].astype(jnp.float32)               # (TN, TK)
    sm = sm_ref[...].reshape(TN, 128)
    sc_exp = _lane_repeat(sm, TK // 128, interpret)
    a = (v * sc_exp).astype(jnp.bfloat16)
    part = jax.lax.dot_general(
        xp_ref[...], a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(1) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += part


_TN_PREFS_Q8 = (256, 128)


def _q8_specs(B: int, TN: int):
    """Single tiling definition for both the unstacked and stacked calls
    (see qmatmul._q4k_specs)."""
    return (
        [
            ((B, TK), lambda n, k: (0, k)),
            ((TN, TK), lambda n, k: (n, k)),
            ((1, TN, 128), lambda n, k: (k, n, 0)),
        ],
        ((B, TN), lambda n, k: (0, n)),
    )


def _q8_2d_raw(xp: jax.Array, q8: jax.Array, sm: jax.Array,
               interpret: bool) -> jax.Array:
    B, K = xp.shape
    N = q8.shape[0]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q8))
    in_specs, out_spec = _q8_specs(B, TN)
    return plain_pallas_call(
        functools.partial(_q8_matmul_kernel, interpret=interpret),
        (N // TN, K // TK), in_specs, out_spec,
        jax.ShapeDtypeStruct((B, N), jnp.float32), interpret,
    )(xp, q8, sm)


@functools.lru_cache(maxsize=4)
def _q8_2d_partitioned(interpret: bool):
    """GSPMD rule mirroring the Q4_K kernel's: partition over N (and rows),
    never over K."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    @custom_partitioning
    def fn(xp, q8, sm):
        return _q8_2d_raw(xp, q8, sm, interpret)

    def partition(mesh, arg_shapes, result_shape):
        xp_s, q8_s, sm_s = (a.sharding for a in arg_shapes)
        rows = _spec_axis(xp_s, 0)
        n_ax = _spec_axis(q8_s, 0)
        arg_shardings = (
            NamedSharding(mesh, P(rows, None)),
            NamedSharding(mesh, P(n_ax, None)),
            NamedSharding(mesh, P(None, n_ax, None)),
        )
        result_sharding = NamedSharding(mesh, P(rows, n_ax))

        def lower(xp, q8, sm):
            return _q8_2d_raw(xp, q8, sm, interpret)

        return mesh, lower, result_sharding, arg_shardings

    def infer(mesh, arg_shapes, result_shape):
        return NamedSharding(
            mesh, P(_spec_axis(arg_shapes[0].sharding, 0),
                    _spec_axis(arg_shapes[1].sharding, 0)))

    def_partition_compat(
        fn,
        partition=partition,
        infer_sharding_from_operands=infer,
        sharding_rule="b k, n j, t n l -> b n",
    )
    return jax.jit(rows_vmappable(fn, xpa_pos=0))


def _q8_2d_stacked_raw(idx: jax.Array, xp: jax.Array, q8: jax.Array,
                       sm: jax.Array, interpret: bool) -> jax.Array:
    B, K = xp.shape
    N = q8.shape[1]
    TN = _pick_tn(N, interpret, prefs=_tn_prefs_for(B, _TN_PREFS_Q8))
    in_specs, out_spec = _q8_specs(B, TN)
    call = stacked_pallas_call(
        functools.partial(_q8_matmul_kernel, interpret=interpret),
        grid=(N // TN, K // TK),
        in_specs=in_specs,
        out_spec=out_spec,
        out_shape=jax.ShapeDtypeStruct((B, N), jnp.float32),
        interpret=interpret,
    )
    return call(idx, xp, q8, sm)


@functools.lru_cache(maxsize=4)
def _q8_2d_stacked_partitioned(interpret: bool):
    return stacked_partitioned(
        _q8_2d_stacked_raw, "i, b k, l n j, l t n m -> b n", interpret)


def q8_matmul_stacked(x: jax.Array, w: dict, idx,
                      interpret: bool | None = None) -> jax.Array:
    """x (..., K) → (..., N) against layer ``idx`` of stacked Q8_0 weights
    (``q8`` (L, N, K), ``sm8`` (L, K/2048, N, 128))."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xp = permute_x(x).reshape(-1, K).astype(jnp.bfloat16)
    fn = _q8_2d_stacked_partitioned(_interpret(interpret))
    i1 = jnp.asarray(idx, jnp.int32).reshape(1)
    y = batched_rows(lambda xq, *ws: fn(i1, xq, *ws), xp, w["q8"], w["sm8"])
    return y.reshape(*lead, -1).astype(x.dtype)


def q8_matmul(x: jax.Array, w: dict, interpret: bool | None = None) -> jax.Array:
    """x (..., K) bf16/f32 → (..., N) in x.dtype, weights in Q8_0 kernel
    layout.  The fused path of ``ops.linear.linear`` for Q8_0 tensors."""
    K = x.shape[-1]
    lead = x.shape[:-1]
    xp = permute_x(x).reshape(-1, K).astype(jnp.bfloat16)
    fn = _q8_2d_partitioned(_interpret(interpret))
    y = batched_rows(fn, xp, w["q8"], w["sm8"])
    return y.reshape(*lead, -1).astype(x.dtype)


# devtime inventory (lfkt-lint PERF001): trace-inner fused-matmul builder
# (see ops/pallas/qmatmul.py for the attribution contract)
register_program("_q8_2d_partitioned", site="ops.pallas.q8matmul")

from .linear import linear, make_linear_bf16, make_linear_int8  # noqa: F401

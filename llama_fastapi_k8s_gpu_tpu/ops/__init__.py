from .linear import (  # noqa: F401
    linear,
    make_linear_bf16,
    make_linear_int8,
    make_linear_int8_device,
    make_linear_q4k,
    make_linear_q5k,
    make_linear_q6k,
    make_linear_q8,
)

// Native GGUF dequantization: the in-tree C++ analogue of the reference's
// native engine dependency (llama-cpp-python==0.2.77 C/CUDA kernels, reference
// docker/Dockerfile.base:30-32).  The TPU framework keeps the *compute* path
// in JAX/XLA/Pallas; this library accelerates the host-side load path — the
// multi-GB GGUF -> float32 conversion that happens once at model load —
// with multithreaded scalar kernels that g++ auto-vectorizes.
//
// Contract: bit-exact with the numpy reference codecs in gguf/quants.py
// (enforced by tests/test_native.py).  All arithmetic is float32 with the
// same operation order as the numpy expressions.
//
// C ABI (ctypes-friendly):
//   int lfkt_dequant(int ggml_type, const uint8_t* src, int64_t n_elements,
//                    float* dst, int n_threads);
//     returns 0 on success, -1 for unsupported type, -2 for bad args.
//   int lfkt_supported(int ggml_type);  // 1 if the type is handled

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---- ggml type codes (gguf/constants.py GGMLType) --------------------------
enum GgmlType : int {
  T_F32 = 0,
  T_F16 = 1,
  T_Q4_0 = 2,
  T_Q8_0 = 8,
  T_Q4_K = 12,
  T_Q5_K = 13,
  T_Q6_K = 14,
  T_BF16 = 30,
};

constexpr int QK_K = 256;

// ---- IEEE f16 -> f32 (exact, matches numpy's astype) -----------------------
float f16_to_f32_slow(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t man = h & 0x3FFu;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;  // +-0
    } else {        // subnormal: renormalize
      int e = -1;
      uint32_t m = man;
      do {
        e++;
        m <<= 1;
      } while (!(m & 0x400u));
      m &= 0x3FFu;
      bits = sign | (static_cast<uint32_t>(127 - 15 - e) << 23) | (m << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (man << 13);  // inf / nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

// one 256 KiB table beats per-element bit twiddling on the load path
struct F16Table {
  float v[65536];
  F16Table() {
    for (uint32_t i = 0; i < 65536; i++) v[i] = f16_to_f32_slow(static_cast<uint16_t>(i));
  }
};
const F16Table kF16;

inline float f16(const uint8_t* p) {
  uint16_t h;
  std::memcpy(&h, p, 2);
  return kF16.v[h];
}

// ---- per-block kernels (layouts: gguf/quants.py:15-24) ---------------------

// Q8_0  block=32: f16 d | 32 x i8
void deq_q8_0(const uint8_t* b, float* y) {
  const float d = f16(b);
  const int8_t* q = reinterpret_cast<const int8_t*>(b + 2);
  for (int i = 0; i < 32; i++) y[i] = d * static_cast<float>(q[i]);
}

// Q4_0  block=32: f16 d | 16 B nibbles; elements 0..15 = lo, 16..31 = hi
void deq_q4_0(const uint8_t* b, float* y) {
  const float d = f16(b);
  const uint8_t* qs = b + 2;
  for (int i = 0; i < 16; i++) {
    y[i] = d * (static_cast<float>(qs[i] & 0x0F) - 8.0f);
    y[i + 16] = d * (static_cast<float>(qs[i] >> 4) - 8.0f);
  }
}

// shared K-quant 6-bit scale/min unpack (gguf/quants.py unpack_scale_min_k4)
inline void scale_min_k4(const uint8_t* s, uint8_t* sc, uint8_t* mn) {
  for (int j = 0; j < 4; j++) {
    sc[j] = s[j] & 63;
    mn[j] = s[j + 4] & 63;
  }
  for (int j = 4; j < 8; j++) {
    sc[j] = static_cast<uint8_t>((s[j + 4] & 0x0F) | ((s[j - 4] >> 6) << 4));
    mn[j] = static_cast<uint8_t>((s[j + 4] >> 4) | ((s[j] >> 6) << 4));
  }
}

// Q4_K  block=256 (144 B): f16 d | f16 dmin | 12 B scales | 128 B nibbles
// sub-block 2g from low nibble of qs[32g..32g+32), 2g+1 from high nibble
void deq_q4_k(const uint8_t* b, float* y) {
  const float d = f16(b);
  const float dmin = f16(b + 2);
  uint8_t sc[8], mn[8];
  scale_min_k4(b + 4, sc, mn);
  const uint8_t* qs = b + 16;
  for (int g = 0; g < 4; g++) {
    const float s_lo = d * static_cast<float>(sc[2 * g]);
    const float m_lo = dmin * static_cast<float>(mn[2 * g]);
    const float s_hi = d * static_cast<float>(sc[2 * g + 1]);
    const float m_hi = dmin * static_cast<float>(mn[2 * g + 1]);
    const uint8_t* q = qs + 32 * g;
    float* lo = y + 64 * g;
    float* hi = lo + 32;
    for (int i = 0; i < 32; i++) {
      lo[i] = s_lo * static_cast<float>(q[i] & 0x0F) - m_lo;
      hi[i] = s_hi * static_cast<float>(q[i] >> 4) - m_hi;
    }
  }
}

// Q5_K  block=256 (176 B): f16 d | f16 dmin | 12 B scales | 32 B qh | 128 B qs
// sub-block j: low/high nibble as Q4_K, plus 16 * ((qh >> j) & 1)
void deq_q5_k(const uint8_t* b, float* y) {
  const float d = f16(b);
  const float dmin = f16(b + 2);
  uint8_t sc[8], mn[8];
  scale_min_k4(b + 4, sc, mn);
  const uint8_t* qh = b + 16;
  const uint8_t* qs = b + 48;
  for (int g = 0; g < 4; g++) {
    const int j_lo = 2 * g, j_hi = 2 * g + 1;
    const float s_lo = d * static_cast<float>(sc[j_lo]);
    const float m_lo = dmin * static_cast<float>(mn[j_lo]);
    const float s_hi = d * static_cast<float>(sc[j_hi]);
    const float m_hi = dmin * static_cast<float>(mn[j_hi]);
    const uint8_t* q = qs + 32 * g;
    float* lo = y + 64 * g;
    float* hi = lo + 32;
    for (int i = 0; i < 32; i++) {
      const int h_lo = (qh[i] >> j_lo) & 1;
      const int h_hi = (qh[i] >> j_hi) & 1;
      lo[i] = s_lo * static_cast<float>((q[i] & 0x0F) + 16 * h_lo) - m_lo;
      hi[i] = s_hi * static_cast<float>((q[i] >> 4) + 16 * h_hi) - m_hi;
    }
  }
}

// Q6_K  block=256 (210 B): 128 B ql | 64 B qh | 16 x i8 scales | f16 d
// two 128-element halves; within a half, element l (0..127):
//   low  = (l < 64 ? ql[l] & 0xF : ql[l-64] >> 4)
//   high = (qh[l % 32] >> (2 * (l / 32))) & 3
//   q    = (low | high << 4) - 32, sub-block scale sc[l / 16]
void deq_q6_k(const uint8_t* b, float* y) {
  const int8_t* scales = reinterpret_cast<const int8_t*>(b + 192);
  const float d = f16(b + 208);
  for (int half = 0; half < 2; half++) {
    const uint8_t* ql = b + 64 * half;
    const uint8_t* qh = b + 128 + 32 * half;
    float* yo = y + 128 * half;
    for (int l = 0; l < 128; l++) {
      const int low = (l < 64) ? (ql[l] & 0x0F) : (ql[l - 64] >> 4);
      const int high = (qh[l & 31] >> (2 * (l >> 5))) & 3;
      const int q = (low | (high << 4)) - 32;
      const float dsc =
          d * static_cast<float>(scales[8 * half + (l >> 4)]);
      yo[l] = dsc * static_cast<float>(q);
    }
  }
}

// ---- format table ----------------------------------------------------------
struct Fmt {
  int type;
  int64_t block_elems;
  int64_t block_bytes;
  void (*fn)(const uint8_t*, float*);
};

const Fmt kFmts[] = {
    {T_Q8_0, 32, 34, deq_q8_0},
    {T_Q4_0, 32, 18, deq_q4_0},
    {T_Q4_K, QK_K, 144, deq_q4_k},
    {T_Q5_K, QK_K, 176, deq_q5_k},
    {T_Q6_K, QK_K, 210, deq_q6_k},
};

const Fmt* find_fmt(int type) {
  for (const Fmt& f : kFmts)
    if (f.type == type) return &f;
  return nullptr;
}

// ---- float formats (threaded memcpy/convert) -------------------------------
void conv_range_f32(const uint8_t* src, float* dst, int64_t lo, int64_t hi) {
  std::memcpy(dst + lo, src + 4 * lo, 4 * static_cast<size_t>(hi - lo));
}

void conv_range_f16(const uint8_t* src, float* dst, int64_t lo, int64_t hi) {
  for (int64_t i = lo; i < hi; i++) dst[i] = f16(src + 2 * i);
}

void conv_range_bf16(const uint8_t* src, float* dst, int64_t lo, int64_t hi) {
  for (int64_t i = lo; i < hi; i++) {
    uint16_t h;
    std::memcpy(&h, src + 2 * i, 2);
    uint32_t bits = static_cast<uint32_t>(h) << 16;
    std::memcpy(dst + i, &bits, 4);
  }
}

template <typename F>
void run_threads(int64_t n_units, int n_threads, F&& body) {
  if (n_threads <= 1 || n_units < 2 * n_threads) {
    body(0, n_units);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(static_cast<size_t>(n_threads));
  const int64_t per = (n_units + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    const int64_t lo = t * per;
    const int64_t hi = std::min<int64_t>(lo + per, n_units);
    if (lo >= hi) break;
    ts.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

// ---- fused-kernel layout packers (ops/pallas/qmatmul.py prep_q4k,
// ---- ops/pallas/q6matmul.py prep_q6k) --------------------------------------
//
// The Pallas serving path keeps K-quant weights packed in HBM; the host-side
// packers reorder raw GGUF block bytes into the kernels' tile-local
// element-major layout.  The numpy reference implementations are a chain of
// full-tensor reshape/transpose passes — single-threaded and allocation
// heavy, measured as the dominant cost of an 8B cold start.  These kernels
// produce bit-identical planes (qs/q4/q2 int8 exact; sm/sm6 bf16 via
// round-to-nearest-even, matching XLA's f32->bf16 cast) in one pass per row,
// threaded over rows.

inline uint16_t bf16_rne(float f) {
  uint32_t b;
  std::memcpy(&b, &f, 4);
  if ((b & 0x7FFFFFFFu) > 0x7F800000u)  // NaN -> XLA's quiet NaN, sign kept
    return static_cast<uint16_t>(((b >> 16) & 0x8000u) | 0x7FC0u);
  b += 0x7FFFu + ((b >> 16) & 1u);
  return static_cast<uint16_t>(b >> 16);
}

constexpr int64_t TKQ = 2048;  // K elements per kernel tile (= 8 super-blocks)

// Q4_K: src blocks (row-major, 144 B each) -> qs (n, k/2) int8 + sm
// (k/2048, n, 128) bf16.  Byte b = e*64 + s of a tile packs sub-block s's
// elements e (lo) and e+16 (hi) as (hi-8)*16 + lo.
void prep_q4k_row(const uint8_t* src, int64_t n_out, int64_t k_in, int64_t row,
                  int8_t* qs, uint16_t* sm) {
  const int64_t nb = k_in / QK_K;
  const int64_t kt = k_in / TKQ;
  const uint8_t* rb = src + row * nb * 144;
  int8_t* qrow = qs + row * (k_in / 2);
  for (int64_t t = 0; t < kt; t++) {
    uint16_t* smt = sm + (t * n_out + row) * 128;
    int8_t* qt = qrow + t * (TKQ / 2);
    for (int sb = 0; sb < 8; sb++) {
      const uint8_t* blk = rb + (t * 8 + sb) * 144;
      const float d = f16(blk);
      const float dmin = f16(blk + 2);
      uint8_t sc[8], mn[8];
      scale_min_k4(blk + 4, sc, mn);
      for (int j = 0; j < 8; j++) {
        smt[sb * 8 + j] = bf16_rne(d * static_cast<float>(sc[j]));
        smt[64 + sb * 8 + j] = bf16_rne(dmin * static_cast<float>(mn[j]));
      }
      const uint8_t* fq = blk + 16;  // 128 nibble bytes: g*32+i
      for (int subp = 0; subp < 4; subp++) {     // sub-block pairs 2g/2g+1
        const uint8_t* q = fq + 32 * subp;
        const int s_even = sb * 8 + 2 * subp;
        const int s_odd = s_even + 1;
        for (int e = 0; e < 16; e++) {
          const int lo_e = q[e] & 0x0F, lo_h = q[e + 16] & 0x0F;
          const int hi_e = q[e] >> 4, hi_h = q[e + 16] >> 4;
          // byte index e*64 + s pairs nib(s,e) with nib(s,e+16)
          qt[e * 64 + s_even] =
              static_cast<int8_t>(((lo_h - 8) << 4) + lo_e);
          qt[e * 64 + s_odd] =
              static_cast<int8_t>(((hi_h - 8) << 4) + hi_e);
        }
      }
    }
  }
}

// Q6_K: src blocks (210 B) -> q4 (n, k/2) int8 + q2 (n, k/4) int8 + sm6
// (k/2048, n, 128) bf16.  Tile columns c = e*128 + s (s = sub-block of 16);
// q4 byte b = e*128+s (e<8) packs nib(s,e),nib(s,e+8); q2 byte b = e'*128+s
// (e'<4) packs crumbs of elements e', e'+4, e'+8, e'+12.
void prep_q6k_row(const uint8_t* src, int64_t n_out, int64_t k_in, int64_t row,
                  int8_t* q4, int8_t* q2, uint16_t* sm6) {
  const int64_t nb = k_in / QK_K;
  const int64_t kt = k_in / TKQ;
  const uint8_t* rb = src + row * nb * 210;
  int8_t* q4row = q4 + row * (k_in / 2);
  int8_t* q2row = q2 + row * (k_in / 4);
  uint8_t q6[256];
  for (int64_t t = 0; t < kt; t++) {
    uint16_t* smt = sm6 + (t * n_out + row) * 128;
    int8_t* q4t = q4row + t * (TKQ / 2);
    int8_t* q2t = q2row + t * (TKQ / 4);
    for (int sb = 0; sb < 8; sb++) {
      const uint8_t* blk = rb + (t * 8 + sb) * 210;
      const int8_t* scales = reinterpret_cast<const int8_t*>(blk + 192);
      const float d = f16(blk + 208);
      for (int half = 0; half < 2; half++) {
        const uint8_t* ql = blk + 64 * half;
        const uint8_t* qh = blk + 128 + 32 * half;
        uint8_t* q6h = q6 + 128 * half;
        for (int l = 0; l < 128; l++) {
          const int low = (l < 64) ? (ql[l] & 0x0F) : (ql[l - 64] >> 4);
          const int high = (qh[l & 31] >> (2 * (l >> 5))) & 3;
          q6h[l] = static_cast<uint8_t>(low | (high << 4));
        }
      }
      for (int sub = 0; sub < 16; sub++) {
        const int s = sb * 16 + sub;  // tile-local sub-block column
        smt[s] = bf16_rne(d * static_cast<float>(scales[sub]));
        const uint8_t* qe = q6 + sub * 16;  // elements of this sub-block
        for (int e = 0; e < 8; e++) {
          const int nib_lo = qe[e] & 0x0F;
          const int nib_hi = qe[e + 8] & 0x0F;
          q4t[e * 128 + s] =
              static_cast<int8_t>(((nib_hi - 8) << 4) + nib_lo);
        }
        for (int ep = 0; ep < 4; ep++) {
          const int c0 = qe[ep] >> 4;
          const int c1 = qe[ep + 4] >> 4;
          const int c2 = qe[ep + 8] >> 4;
          const int c3 = qe[ep + 12] >> 4;
          q2t[ep * 128 + s] = static_cast<int8_t>(
              (((c3 * 4 + c2) * 4 + c1) * 4 + c0) - 128);
        }
      }
    }
  }
}

// Q5_K: src blocks (176 B) -> q5s (n, k/2) int8 (Q4_K qs layout of the low
// nibbles) + q5h (n, k/8) int8 (hi-bit bytes: tile byte b packs bit j of
// columns j*256+b, biased -128) + sm5 (k/2048, n, 128) bf16.
void prep_q5k_row(const uint8_t* src, int64_t n_out, int64_t k_in, int64_t row,
                  int8_t* q5s, int8_t* q5h, uint16_t* sm5) {
  const int64_t nb = k_in / QK_K;
  const int64_t kt = k_in / TKQ;
  const uint8_t* rb = src + row * nb * 176;
  int8_t* qsrow = q5s + row * (k_in / 2);
  int8_t* qhrow = q5h + row * (k_in / 8);
  uint8_t nib[2048], hb[2048];
  for (int64_t t = 0; t < kt; t++) {
    uint16_t* smt = sm5 + (t * n_out + row) * 128;
    for (int sb = 0; sb < 8; sb++) {
      const uint8_t* blk = rb + (t * 8 + sb) * 176;
      const float d = f16(blk);
      const float dmin = f16(blk + 2);
      uint8_t sc[8], mn[8];
      scale_min_k4(blk + 4, sc, mn);
      for (int j = 0; j < 8; j++) {
        smt[sb * 8 + j] = bf16_rne(d * static_cast<float>(sc[j]));
        smt[64 + sb * 8 + j] = bf16_rne(dmin * static_cast<float>(mn[j]));
      }
      const uint8_t* qh = blk + 16;
      const uint8_t* fq = blk + 48;
      for (int sub = 0; sub < 8; sub++) {
        const int s = sb * 8 + sub;
        const uint8_t* q = fq + (sub / 2) * 32;
        for (int e = 0; e < 32; e++) {
          const int c = e * 64 + s;
          nib[c] = (sub & 1) ? (q[e] >> 4) : (q[e] & 0x0F);
          hb[c] = (qh[e] >> sub) & 1;
        }
      }
    }
    int8_t* qst = qsrow + t * (TKQ / 2);
    for (int e = 0; e < 16; e++)
      for (int s = 0; s < 64; s++)
        qst[e * 64 + s] = static_cast<int8_t>(
            ((static_cast<int>(nib[(e + 16) * 64 + s]) - 8) << 4) +
            nib[e * 64 + s]);
    int8_t* qht = qhrow + t * (TKQ / 8);
    for (int b = 0; b < 256; b++) {
      int v = 0;
      for (int j = 0; j < 8; j++) v |= static_cast<int>(hb[j * 256 + b]) << j;
      qht[b] = static_cast<int8_t>(v - 128);
    }
  }
}

// Q8_0: src blocks (34 B = f16 d | 32 x i8) -> q8 (n, k) int8 element-major
// tile columns (column c = e*64 + b) + sm8 (k/2048, n, 128) bf16 [d|d].
void prep_q8_0_row(const uint8_t* src, int64_t n_out, int64_t k_in,
                   int64_t row, int8_t* q8, uint16_t* sm8) {
  const int64_t nb = k_in / 32;
  const int64_t kt = k_in / TKQ;
  const uint8_t* rb = src + row * nb * 34;
  int8_t* qrow = q8 + row * k_in;
  for (int64_t t = 0; t < kt; t++) {
    uint16_t* smt = sm8 + (t * n_out + row) * 128;
    int8_t* qt = qrow + t * TKQ;
    for (int b = 0; b < 64; b++) {
      const uint8_t* blk = rb + (t * 64 + b) * 34;
      const uint16_t ds = bf16_rne(f16(blk));
      smt[b] = ds;
      smt[64 + b] = ds;
      const int8_t* q = reinterpret_cast<const int8_t*>(blk + 2);
      for (int e = 0; e < 32; e++) qt[e * 64 + b] = q[e];
    }
  }
}

}  // namespace

extern "C" {

int lfkt_supported(int ggml_type) {
  return (ggml_type == T_F32 || ggml_type == T_F16 || ggml_type == T_BF16 ||
          find_fmt(ggml_type) != nullptr)
             ? 1
             : 0;
}

// Fused-layout packers.  rc: 0 ok, -2 bad args.
int lfkt_prep_q4k(const uint8_t* src, int64_t n_out, int64_t k_in,
                  int8_t* qs, uint16_t* sm, int n_threads) {
  if (!src || !qs || !sm || n_out <= 0 || k_in <= 0 || k_in % TKQ != 0)
    return -2;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = 1;
  run_threads(n_out, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) prep_q4k_row(src, n_out, k_in, r, qs, sm);
  });
  return 0;
}

int lfkt_prep_q6k(const uint8_t* src, int64_t n_out, int64_t k_in,
                  int8_t* q4, int8_t* q2, uint16_t* sm6, int n_threads) {
  if (!src || !q4 || !q2 || !sm6 || n_out <= 0 || k_in <= 0 || k_in % TKQ != 0)
    return -2;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = 1;
  run_threads(n_out, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++)
      prep_q6k_row(src, n_out, k_in, r, q4, q2, sm6);
  });
  return 0;
}

int lfkt_prep_q5k(const uint8_t* src, int64_t n_out, int64_t k_in,
                  int8_t* q5s, int8_t* q5h, uint16_t* sm5, int n_threads) {
  if (!src || !q5s || !q5h || !sm5 || n_out <= 0 || k_in <= 0 ||
      k_in % TKQ != 0)
    return -2;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = 1;
  run_threads(n_out, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++)
      prep_q5k_row(src, n_out, k_in, r, q5s, q5h, sm5);
  });
  return 0;
}

int lfkt_prep_q8_0(const uint8_t* src, int64_t n_out, int64_t k_in,
                   int8_t* q8, uint16_t* sm8, int n_threads) {
  if (!src || !q8 || !sm8 || n_out <= 0 || k_in <= 0 || k_in % TKQ != 0)
    return -2;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = 1;
  run_threads(n_out, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++)
      prep_q8_0_row(src, n_out, k_in, r, q8, sm8);
  });
  return 0;
}

int lfkt_dequant(int ggml_type, const uint8_t* src, int64_t n_elements,
                 float* dst, int n_threads) {
  if (!src || !dst || n_elements < 0) return -2;
  if (n_threads <= 0)
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = 1;

  switch (ggml_type) {
    case T_F32:
      run_threads(n_elements, n_threads, [&](int64_t lo, int64_t hi) {
        conv_range_f32(src, dst, lo, hi);
      });
      return 0;
    case T_F16:
      run_threads(n_elements, n_threads, [&](int64_t lo, int64_t hi) {
        conv_range_f16(src, dst, lo, hi);
      });
      return 0;
    case T_BF16:
      run_threads(n_elements, n_threads, [&](int64_t lo, int64_t hi) {
        conv_range_bf16(src, dst, lo, hi);
      });
      return 0;
    default:
      break;
  }

  const Fmt* fmt = find_fmt(ggml_type);
  if (!fmt) return -1;
  if (n_elements % fmt->block_elems != 0) return -2;
  const int64_t n_blocks = n_elements / fmt->block_elems;
  run_threads(n_blocks, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t blk = lo; blk < hi; blk++) {
      fmt->fn(src + blk * fmt->block_bytes, dst + blk * fmt->block_elems);
    }
  });
  return 0;
}

}  // extern "C"

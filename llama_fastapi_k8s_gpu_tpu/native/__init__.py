"""Native (C++) GGUF load path: build, load, and ctypes bindings.

The reference ships its native engine as a pre-built wheel
(``llama-cpp-python==0.2.77`` compiled with cuBLAS, reference
docker/Dockerfile.base:30-32).  Here the native component is in-tree C++
(``src/gguf_dequant.cpp``) compiled on first use with the host toolchain into
a cached shared library — multithreaded dequantization of the multi-GB GGUF
tensor data at model load, bit-exact with the numpy codecs in
:mod:`..gguf.quants` (the oracle; see tests/test_native.py).

Fallback story: if no C++ compiler is available or the build fails, every
entry point degrades to the numpy reference implementation.  Set
``LFKT_NATIVE=0`` to force the numpy path.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import shutil
import subprocess
import tempfile
import threading

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src", "gguf_dequant.cpp")

# -ffp-contract=off: the kernels must round exactly like numpy's separate
# multiply/subtract ops; FMA contraction would change the last bit.
_CXXFLAGS = ["-O3", "-march=native", "-ffp-contract=off", "-fPIC", "-shared",
             "-std=c++17", "-pthread"]

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False


def _enabled() -> bool:
    from ..utils.config import env_bool

    return env_bool("LFKT_NATIVE", default=True)


def _cache_dirs() -> list[str]:
    here = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
    xdg = os.environ.get("XDG_CACHE_HOME") or os.path.join(os.path.expanduser("~"), ".cache")
    return [here, os.path.join(xdg, "lfkt_native"), os.path.join(tempfile.gettempdir(), "lfkt_native")]


def _build(so_path: str) -> bool:
    cxx = os.environ.get("CXX", "g++")
    tmp = so_path + f".tmp.{os.getpid()}"
    cmd = [cxx, *_CXXFLAGS, "-o", tmp, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native build unavailable (%s); using numpy dequant", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed; using numpy dequant:\n%s", proc.stderr[-2000:])
        return False
    try:
        os.replace(tmp, so_path)
    except OSError:
        return False
    return True


def _host_tag() -> str:
    """Compiler + microarch fingerprint: -march=native binaries must never be
    reused on a different host/compiler (SIGILL on older CPUs)."""
    import platform

    cxx = os.environ.get("CXX", "g++")
    try:
        ver = subprocess.run([cxx, "-dumpfullversion", "-dumpversion"],
                             capture_output=True, text=True, timeout=10).stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        ver = "unknown"
    march = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    march = hashlib.sha256(line.encode()).hexdigest()[:8]
                    break
    except OSError:
        march = platform.machine()
    return f"{cxx}-{ver}-{march}"


def _bind(so_path: str) -> ctypes.CDLL | None:
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.lfkt_dequant.argtypes = [
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.lfkt_dequant.restype = ctypes.c_int
    lib.lfkt_supported.argtypes = [ctypes.c_int]
    lib.lfkt_supported.restype = ctypes.c_int
    try:
        lib.lfkt_prep_q4k.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.lfkt_prep_q4k.restype = ctypes.c_int
        lib.lfkt_prep_q6k.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.lfkt_prep_q6k.restype = ctypes.c_int
        lib.lfkt_prep_q5k.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.lfkt_prep_q5k.restype = ctypes.c_int
        lib.lfkt_prep_q8_0.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.lfkt_prep_q8_0.restype = ctypes.c_int
    except AttributeError:
        # stale cached .so predating the packers: dequant still works, the
        # prep entry points just fall back to numpy
        pass
    return lib


def _load() -> ctypes.CDLL | None:
    with open(_SRC, "rb") as f:
        payload = f.read() + " ".join(_CXXFLAGS).encode() + _host_tag().encode()
    tag = hashlib.sha256(payload).hexdigest()[:16]
    name = f"gguf_dequant-{tag}.so"

    for d in _cache_dirs():
        so_path = os.path.join(d, name)
        if os.path.exists(so_path):
            lib = _bind(so_path)
            if lib is not None:
                return lib

    # Compile exactly once, into a tmpdir we know is writable.  A compile
    # failure is a property of the toolchain, not the cache dir — don't
    # retry it per directory.
    build_dir = tempfile.mkdtemp(prefix="lfkt_build_")
    built = os.path.join(build_dir, name)
    if not _build(built):
        return None

    for d in _cache_dirs():  # promote into a persistent cache for next start
        so_path = os.path.join(d, name)
        try:
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, f"{name}.tmp.{os.getpid()}")
            shutil.copyfile(built, tmp)
            os.replace(tmp, so_path)
        except OSError:
            continue
        lib = _bind(so_path)
        if lib is not None:
            return lib
    return _bind(built)  # all caches unwritable: serve from the tmp build


def get_lib() -> ctypes.CDLL | None:  # lfkt: blocks-under[_lock] -- one-time lazy native build/dlopen: concurrent callers must block until the handle exists, then every call is a cached read
    """The loaded native library, building it on first call; None if unavailable."""
    global _lib, _load_attempted
    if not _enabled():
        return None
    if _load_attempted:
        return _lib
    with _lock:
        if not _load_attempted:
            _lib = _load()
            _load_attempted = True
            if _lib is not None:
                logger.info("native GGUF dequant library loaded")
    return _lib


def _required_bytes(ggml_type: int, n_elements: int) -> int:
    from ..gguf.constants import GGML_BLOCK_SIZES, GGMLType

    block_elems, block_bytes = GGML_BLOCK_SIZES[GGMLType(ggml_type)]
    if n_elements % block_elems != 0:
        return n_elements * block_bytes  # force fallback; numpy raises cleanly
    return (n_elements // block_elems) * block_bytes


def native_supported(ggml_type: int) -> bool:
    lib = get_lib()
    return bool(lib is not None and lib.lfkt_supported(int(ggml_type)))


def native_dequantize(buf: np.ndarray, ggml_type: int, n_elements: int,
                      n_threads: int = 0) -> np.ndarray | None:
    """Flat uint8 buffer -> float32 array, or None if the native path can't
    serve this type (caller falls back to numpy)."""
    if not native_supported(ggml_type):
        return None
    lib = get_lib()
    src = np.ascontiguousarray(buf, dtype=np.uint8).reshape(-1)
    if src.size < _required_bytes(int(ggml_type), n_elements):
        # short/corrupt buffer: let the numpy path raise its shape error
        return None
    out = np.empty(n_elements, dtype=np.float32)
    rc = lib.lfkt_dequant(
        int(ggml_type),
        src.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_elements),
        out.ctypes.data_as(ctypes.c_void_p),
        int(n_threads),
    )
    if rc != 0:
        logger.warning("native dequant rc=%d for type %d; numpy fallback", rc, ggml_type)
        return None
    return out


def _bf16_view(u16: np.ndarray) -> np.ndarray:
    import ml_dtypes

    return u16.view(ml_dtypes.bfloat16)


def native_prep_q4k(raw: np.ndarray, n_out: int, k_in: int,
                    n_threads: int = 0) -> dict | None:
    """Raw Q4_K block bytes -> {"qs" int8 (n,k/2), "sm" bf16 (k/2048,n,128)}
    numpy arrays in the fused-kernel layout (ops/pallas/qmatmul.py), packed
    by the threaded C++ path; None -> caller uses the numpy packer."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lfkt_prep_q4k"):
        return None
    src = np.ascontiguousarray(raw, dtype=np.uint8).reshape(-1)
    if src.size < (n_out * k_in // 256) * 144:
        return None
    qs = np.empty((n_out, k_in // 2), dtype=np.int8)
    sm = np.empty((k_in // 2048, n_out, 128), dtype=np.uint16)
    rc = lib.lfkt_prep_q4k(
        src.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_out), ctypes.c_int64(k_in),
        qs.ctypes.data_as(ctypes.c_void_p), sm.ctypes.data_as(ctypes.c_void_p),
        int(n_threads))
    if rc != 0:
        logger.warning("native prep_q4k rc=%d; numpy fallback", rc)
        return None
    return {"qs": qs, "sm": _bf16_view(sm)}


def native_prep_q6k(raw: np.ndarray, n_out: int, k_in: int,
                    n_threads: int = 0) -> dict | None:
    """Raw Q6_K block bytes -> {"q4", "q2", "sm6"} numpy arrays in the fused
    layout (ops/pallas/q6matmul.py); None -> numpy packer."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lfkt_prep_q6k"):
        return None
    src = np.ascontiguousarray(raw, dtype=np.uint8).reshape(-1)
    if src.size < (n_out * k_in // 256) * 210:
        return None
    q4 = np.empty((n_out, k_in // 2), dtype=np.int8)
    q2 = np.empty((n_out, k_in // 4), dtype=np.int8)
    sm6 = np.empty((k_in // 2048, n_out, 128), dtype=np.uint16)
    rc = lib.lfkt_prep_q6k(
        src.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_out), ctypes.c_int64(k_in),
        q4.ctypes.data_as(ctypes.c_void_p), q2.ctypes.data_as(ctypes.c_void_p),
        sm6.ctypes.data_as(ctypes.c_void_p), int(n_threads))
    if rc != 0:
        logger.warning("native prep_q6k rc=%d; numpy fallback", rc)
        return None
    return {"q4": q4, "q2": q2, "sm6": _bf16_view(sm6)}


def native_prep_q5k(raw: np.ndarray, n_out: int, k_in: int,
                    n_threads: int = 0) -> dict | None:
    """Raw Q5_K block bytes -> {"q5s", "q5h", "sm5"} numpy arrays in the
    fused layout (ops/pallas/q5matmul.py); None -> numpy packer."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lfkt_prep_q5k"):
        return None
    src = np.ascontiguousarray(raw, dtype=np.uint8).reshape(-1)
    if src.size < (n_out * k_in // 256) * 176:
        return None
    q5s = np.empty((n_out, k_in // 2), dtype=np.int8)
    q5h = np.empty((n_out, k_in // 8), dtype=np.int8)
    sm5 = np.empty((k_in // 2048, n_out, 128), dtype=np.uint16)
    rc = lib.lfkt_prep_q5k(
        src.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_out), ctypes.c_int64(k_in),
        q5s.ctypes.data_as(ctypes.c_void_p),
        q5h.ctypes.data_as(ctypes.c_void_p),
        sm5.ctypes.data_as(ctypes.c_void_p), int(n_threads))
    if rc != 0:
        logger.warning("native prep_q5k rc=%d; numpy fallback", rc)
        return None
    return {"q5s": q5s, "q5h": q5h, "sm5": _bf16_view(sm5)}


def native_prep_q8_0(raw: np.ndarray, n_out: int, k_in: int,
                     n_threads: int = 0) -> dict | None:
    """Raw Q8_0 block bytes -> {"q8", "sm8"} numpy arrays in the fused
    layout (ops/pallas/q8matmul.py); None -> numpy packer."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "lfkt_prep_q8_0"):
        return None
    src = np.ascontiguousarray(raw, dtype=np.uint8).reshape(-1)
    if src.size < (n_out * k_in // 32) * 34:
        return None
    q8 = np.empty((n_out, k_in), dtype=np.int8)
    sm8 = np.empty((k_in // 2048, n_out, 128), dtype=np.uint16)
    rc = lib.lfkt_prep_q8_0(
        src.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n_out), ctypes.c_int64(k_in),
        q8.ctypes.data_as(ctypes.c_void_p),
        sm8.ctypes.data_as(ctypes.c_void_p), int(n_threads))
    if rc != 0:
        logger.warning("native prep_q8_0 rc=%d; numpy fallback", rc)
        return None
    return {"q8": q8, "sm8": _bf16_view(sm8)}

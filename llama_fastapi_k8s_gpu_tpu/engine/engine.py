"""The in-tree replacement for ``llama_cpp.Llama``.

The reference constructs ``Llama(model_path, n_gpu_layers=-1, n_ctx=1024)`` at
import time and calls ``create_chat_completion(...)`` from a worker thread
(reference api.py:24-28, 55-63).  This class preserves that contract —
eager load, blocking thread-safe generation, OpenAI-shaped responses and
streaming chunks (SURVEY.md §2B) — on a JAX/TPU runtime:

- load: GGUF mmap → dequant → HBM-resident params (bf16 or int8 by size);
- prefill: jit'd, prompt length padded to the nearest bucket so the set of
  compiled shapes is fixed (TTFT never pays a cold compile after warmup);
- decode: on-device scanned chunks of N tokens per host round-trip, KV cache
  and state donated so steady-state decode is allocation-free;
- sampling: llama.cpp-parity chain; defaults match llama-cpp-python 0.2.77
  (the reference relies on those defaults for top_k/min_p/repeat_penalty).
"""

from __future__ import annotations

import codecs
import dataclasses
import logging
import threading
import time
import uuid
from collections import deque
from typing import Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..gguf import GGUFFile
from ..models.config import ModelConfig
from ..models.generate import (
    generate_chunk_jit,
    init_state,
    prefill_chunk_jit,
    prefill_jit,
    sample_jit,
)
from ..models.llama import init_cache
from ..models.params import load_params, synth_params
from ..sampling.sample import SamplingParams, sampling_tensors, seed_window
from ..tokenizer import apply_chat_template, detect_chat_template, tokenizer_from_gguf
from ..obs.memledger import register_component, tree_nbytes
from ..utils.faults import FAULTS
from ..utils.health import DeadlineExceeded, Heartbeat
from ..utils.jaxcache import setup_compile_cache
from ..utils.tracing import maybe_profile

logger = logging.getLogger(__name__)

DEFAULT_BUCKETS = (128, 256, 512, 1024)


# -- memory-ledger providers (obs/memledger.py): called at snapshot time
# from scrape/incident threads; plain metadata reads of live attributes,
# so they need no lock (the kv_cache_bytes precedent) -----------------------

def _ledger_weight_bytes(eng: "Engine") -> int:
    # tree_nbytes, not weight_bytes: the ledger reconciles against what
    # the devices physically hold, so tp-replicated leaves count one
    # copy per chip (weight_bytes stays the LOGICAL figure the registry's
    # budget is defined over)
    return tree_nbytes(getattr(eng, "params", None))


def _ledger_ring_bytes(eng: "Engine") -> int:
    return tree_nbytes(getattr(eng, "_cache", None))


class _TextEmitter:
    """Incremental text emission shared by the pipelined (:meth:`Engine._run`)
    and speculative (:meth:`Engine._run_spec`) decode loops: append-only
    token list → (ready_text, stop_hit) increments via an incremental UTF-8
    decoder with stop-string prefix holdback, plus the final flush.
    Extracted so the two loops cannot drift."""

    def __init__(self, engine: "Engine", stops):
        self._eng = engine
        self._stops = stops
        self._dec = codecs.getincrementaldecoder("utf-8")(errors="replace")
        self._sent_bytes = 0
        self._held = ""        # withheld text (possible stop-string prefix)
        self._n_emitted = 0    # characters already yielded

    def process(self, gen: list, live: bool) -> tuple[str, bool]:
        """One decode of the token stream → (ready_text, stop_hit).

        On a stop hit nothing is emitted (``final`` produces the clipped
        tail).  When ``live`` is False only the stop check runs — returned
        text would be dropped by the caller, so it must not be counted as
        emitted.  The caller MUST yield a non-empty ``ready_text``."""
        eng = self._eng
        bts = eng.tokenizer.decode_bytes(gen)
        text = bts.decode("utf-8", errors="replace")
        if eng._find_stop_str(text, self._stops) != -1:
            return "", True
        if not live:
            return "", False
        self._held += self._dec.decode(bts[self._sent_bytes:])
        self._sent_bytes = len(bts)
        hold = eng._stop_prefix_holdback(self._held, self._stops)
        ready = self._held[:len(self._held) - hold]
        self._held = self._held[len(self._held) - hold:]
        self._n_emitted += len(ready)
        return ready, False

    def step(self, gen: list, done: bool, finish: str) -> tuple[str, str, bool]:
        """One emission step with the callers' shared hit convention applied:
        returns (ready_text, finish, done) — a stop hit forces
        ``("", "stop", True)``.  Extracted so the four call sites (the
        loop tails and the first-token early emits in :meth:`Engine._run`
        and :meth:`Engine._run_spec`) cannot drift."""
        ready, hit = self.process(gen, live=not done)
        if hit:
            return "", "stop", True
        return ready, finish, done

    def final(self, gen: list, finish: str) -> tuple[str, str]:
        """(text_tail, finish) once generation has ended: decode the whole
        stream, clip at a stop string, return what was never emitted."""
        text = self._eng._decode_text(gen)
        cut = self._eng._find_stop_str(text, self._stops)
        if cut != -1:
            text = text[:cut]
            finish = "stop"
        tail = text[self._n_emitted:] if len(text) > self._n_emitted else ""
        return tail, finish


class Engine:
    """Loads a GGUF model and serves chat completions on the local device(s)."""

    # -- lock discipline (machine-checked: lfkt-lint LOCK001-004, see
    # docs/RUNBOOK.md "Lock discipline annotations") ----------------------
    # _lock is the single-generator mutex: the KV ring and its prefix
    # claim may only change under it.  _id_lock is the tiny counter lock
    # shared with scheduler threads (seed sequence, last-timings swap).
    _GUARDED_BY = {
        "_cache": "_lock",
        "_prefix_ids": "_lock",
        "_paged_lease": "_lock",
        "_requests": "_id_lock",
        "last_timings": "_id_lock",
    }

    #: sliced bucket prefill (prefill_chunk/prefill_overlap) runs the ring
    #: through prefill_chunk_jit, which assumes an UNSHARDED n_ctx dim —
    #: the sequence-parallel engine (engine/sp.py) overrides this to False
    #: and keeps its rerouted monolithic ring prefill.
    _SLICE_PREFILL = True

    #: whether this engine can serve the block-paged KV pool
    #: (LFKT_KV_PAGED): page restore/store slice the ring's n_ctx dim,
    #: which must be unsharded — engine/sp.py overrides to False.
    _KV_PAGED = True

    #: whether this engine can arm layer-looped decode
    #: (LFKT_DECODE_LAYER_UNROLL, ops/pallas/decode_loop.py): the
    #: sp-sharded ring's attention crosses chips per layer, which one
    #: fused kernel cannot — engine/sp.py overrides to False and the
    #: knob degrades with attribution.
    _DECODE_LOOP = True

    def __init__(
        self,
        model_path: str | None,
        n_ctx: int = 1024,
        weight_format: str = "auto",
        decode_chunk: int = 8,  # see utils/config.py: the chunk is also
        #                         the continuous scheduler's cadence
        #                         (larger measured -33% aggregate there)
        prefill_buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_gen_tokens: int = 512,
        seed: int = 0,
        attn_impl: str = "auto",  # auto | xla | pallas (prefill flash kernel)
        kv_dtype: str | None = None,  # bf16 | int8 KV cache; None keeps the
        #                               cfg's value (docs/KV_CACHE.md)
        spec_decode: str = "off",  # off | lookup (prompt-lookup speculation)
        spec_draft: int = 8,
        prefix_cache: bool = True,  # reuse the previous request's KV prefix
        prefix_min: int = 32,       # shortest common prefix worth reusing
        prefill_chunk: int = 256,   # prefill slice size: the continuous
        #                             scheduler's admission slices AND the
        #                             serial overlapped bucket slices
        prefill_overlap: int = 2,   # un-synced prefill slices in flight
        #                             (0 = monolithic bucket prefill)
        kv_paged: bool = False,     # block-paged KV pool + radix prefix
        #                             cache (parallel/kvpool.py); the dense
        #                             ring stays the default A/B control
        kv_page_tokens: int = 128,  # token slots per pool page
        kv_pool_pages: int = 0,     # pool size in pages (0 = auto)
        kv_spill_pages: int = 0,    # host-RAM spill tier capacity (0 = off)
        decode_layer_unroll: int | None = None,  # layers fused per decode
        #                             launch (ops/pallas/decode_loop.py):
        #                             0 = per-layer chain, -1 = all layers
        #                             in ONE launch, K = K per launch;
        #                             None reads LFKT_DECODE_LAYER_UNROLL
        *,
        kv_pool=None,               # adopt a shared KVPool (multi-model
        #                             registry, docs/MULTIMODEL.md) instead
        #                             of building a private one
        kv_namespace: str | None = None,  # this engine's prefix-cache
        #                             namespace in the (shared) pool —
        #                             prefixes NEVER match across
        #                             namespaces (tenant isolation)
        _parts: tuple | None = None,  # (params, cfg, tokenizer, template_kind)
    ):
        FAULTS.fire("load")   # injection point: weight-load / re-init failure
        self.n_ctx = n_ctx
        self.decode_chunk = decode_chunk
        self.max_gen_tokens = max_gen_tokens
        #: prefill slice size shared by the serial overlapped path and the
        #: continuous scheduler's chunked admission (engine/continuous.py)
        self._prefill_chunk = max(1, int(prefill_chunk))
        self._prefill_overlap = max(0, int(prefill_overlap))
        #: optional utils.metrics.Metrics the server injects after
        #: construction (server/app.py) — engines observe prefill-slice
        #: timings into it; None (tests, benches, library use) is free
        self.metrics_sink = None
        #: progress pulse for the engine watchdog (engine/watchdog.py):
        #: one beat per device step, busy brackets around generations,
        #: an error ring for burst detection.  Engines never import the
        #: watchdog — this object is the entire interface.
        self.heartbeat = Heartbeat()
        if spec_decode not in ("off", "lookup", "auto"):
            raise ValueError(
                f"spec_decode must be off|lookup|auto, got {spec_decode!r}")
        # validated BEFORE the weight load: a typo'd LFKT_KV_DTYPE must
        # fail in milliseconds, not after a multi-GB load per crash loop
        if kv_dtype is not None and kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"kv_dtype must be bf16|int8, got {kv_dtype!r}")
        if spec_decode != "off" and not 1 <= spec_draft < n_ctx - 1:
            raise ValueError(
                f"spec_draft must be in [1, n_ctx-2], got {spec_draft}")
        # "auto" resolves AFTER params load (the decision needs the model's
        # per-token HBM bytes + a measured dispatch RTT) — engine/spec_auto.py
        self._spec_request = spec_decode
        self._spec_draft_request = spec_draft
        self.spec_auto_decision: dict | None = None
        self._spec_draft = spec_draft if spec_decode == "lookup" else 0
        if self._spec_draft and type(self) is not Engine \
                and not getattr(self, "_SPEC_LANES", False):
            logger.warning(
                "spec_decode='lookup' is served by the serial Engine and the "
                "continuous scheduler; %s serves vanilla decode "
                "(see _spec_enabled)", type(self).__name__)
        self._lock = threading.Lock()
        self._base_seed = seed
        # request counter: shared by the serial path (caller thread) and the
        # continuous scheduler thread; _next_seed() is the only writer and
        # takes _id_lock so concurrent submitters never reuse a seed
        self._id_lock = threading.Lock()
        self._requests = 0
        #: per-phase wall timings of the most recent completed request
        #: (ttft_s, decode_s, completion_tokens, tokens_per_sec) — the
        #: per-phase timers SURVEY.md §5 calls for; scraped into /metrics.
        #: Written via _record_timings (atomic dict swap under _id_lock);
        #: per-request timings also ride in each response dict under
        #: "lfkt_timings" so callers never need this shared field.
        self.last_timings: dict | None = None
        setup_compile_cache()

        #: coarse wall-clock attribution of model load (tokenizer build,
        #: fused-kernel compile probes, weight prep+transfer) — surfaced
        #: by the coldstart bench to direct startup-latency work; empty for
        #: in-memory (_parts) engines
        self.load_phases: dict = {}
        if _parts is not None:
            self.params, self.cfg, self.tokenizer, self.template_kind = _parts
            self.model_name = "in-memory"
        else:
            t0 = time.time()
            gf = GGUFFile(model_path)
            self.model_name = gf.metadata.get("general.name", model_path)
            self.cfg = ModelConfig.from_gguf(gf, n_ctx=n_ctx)
            _pt = time.time()
            self.tokenizer = tokenizer_from_gguf(gf)
            self.load_phases["tokenizer_s"] = round(time.time() - _pt, 1)
            if weight_format == "auto":
                # bf16 params ≈ 2 bytes/weight; small models keep exact
                # bf16.  Large models on TPU serve "q4k": Q4_K/Q6_K tensors
                # stay fused (~5 / ~7 bit/weight; the v2 kernels beat the
                # int8 path at every 8B shape at ~0.55x the HBM bytes —
                # docs/bench/qmatmul_v2_microbench_2026-07-29.json), and
                # anything else falls back to int8 per tensor.  On CPU
                # (tests) the interpret-mode kernels are slow, so big
                # models requantize to int8 instead.
                n_lin = self.cfg.n_layers * (
                    4 * self.cfg.dim * self.cfg.dim
                    + 3 * self.cfg.dim * self.cfg.ffn_dim
                )
                if n_lin * 2 <= 4e9:
                    weight_format = "bf16"
                elif jax.default_backend() == "tpu":
                    weight_format = "q4k"
                else:
                    weight_format = "int8"
            fused_types = None
            if weight_format == "q4k":
                present = {t.ggml_type for t in gf.tensors.values()}
                _pt = time.time()
                weight_format, fused_types = self._probe_fused_format(present)
                self.load_phases["probes_s"] = round(time.time() - _pt, 1)
            _pt = time.time()
            sub: dict = {}
            self.params = load_params(gf, self.cfg, weight_format,
                                      fused_types=fused_types, phases_out=sub)
            self.load_phases["params_s"] = round(time.time() - _pt, 1)
            self.load_phases.update(
                {f"params_{k}_s": round(v, 1) for k, v in sub.items()})
            self.template_kind = detect_chat_template(
                gf.metadata.get("tokenizer.chat_template"), self.tokenizer
            )
            logger.info(
                "loaded %s (%s, %d layers, fmt=%s) in %.1fs",
                model_path, gf.architecture, self.cfg.n_layers, weight_format,
                time.time() - t0,
            )
        if kv_dtype is not None and kv_dtype != self.cfg.kv_dtype:
            self.cfg = dataclasses.replace(self.cfg, kv_dtype=kv_dtype)
        if self.cfg.kv_dtype == "int8":
            # compile-probe the KV write-quantize kernel NOW: a Mosaic
            # failure degrades writes to the identical XLA formulation
            # instead of crash-looping the pod at its first prefill
            from ..ops.pallas.kvquant import force_xla_quant
            from ..ops.pallas.probe import probe_kv_quant

            err = probe_kv_quant()
            if err is not None:
                force_xla_quant(True)
                logger.error("pallas kv-quantize kernel failed its compile "
                             "probe; cache writes quantize via XLA: %s", err)
        if attn_impl == "auto":
            # the flash kernel wants lane-aligned heads; anything else (tiny
            # test models, CPU runs) stays on the XLA score-matrix path
            attn_impl = (
                "pallas"
                if jax.default_backend() == "tpu" and self.cfg.head_dim % 128 == 0
                else "xla"
            )
        if attn_impl not in ("xla", "pallas"):
            raise ValueError(f"attn_impl must be auto|xla|pallas, got {attn_impl!r}")
        if attn_impl == "pallas":
            # compile-probe the flash kernel NOW (ops/pallas/probe.py): a
            # Mosaic lowering failure degrades to the XLA path with correct
            # attribution instead of crash-looping the pod at warmup.  An
            # int8 cache serves prefill through the fused-dequant variant,
            # a different Mosaic program — probe the one we'll run.
            from ..ops.pallas.probe import probe_flash_attention

            err = probe_flash_attention(
                quantized=self.cfg.kv_dtype == "int8")
            if err is not None:
                logger.error("pallas flash attention failed its compile "
                             "probe; serving with attn_impl=xla: %s", err)
                attn_impl = "xla"
        if attn_impl != self.cfg.attn_impl:
            self.cfg = dataclasses.replace(self.cfg, attn_impl=attn_impl)
        # -- layer-looped decode (ROADMAP item 2; ops/pallas/decode_loop.py)
        # Resolve the knob, validate the weight plan, and compile-probe the
        # looped kernel at THIS engine's ring geometry NOW: every refusal
        # degrades to the per-layer path with attribution (the degrade
        # ledger at /debug/compiles) instead of crash-looping warmup, and
        # warmup then compiles whichever decode program was chosen.
        if decode_layer_unroll is None:
            from ..utils.config import knob
            decode_layer_unroll = int(knob("LFKT_DECODE_LAYER_UNROLL"))
        decode_layer_unroll = int(decode_layer_unroll)
        if decode_layer_unroll < -1:
            raise ValueError(
                f"decode_layer_unroll must be >= -1 (0 = off, -1 = all "
                f"layers per launch), got {decode_layer_unroll}")
        if decode_layer_unroll:
            from ..obs.devtime import DEVTIME
            if not self._DECODE_LOOP:
                msg = (f"{type(self).__name__} serves ring attention "
                       "(sp-sharded KV): layer-looped decode gates off — "
                       "serving per-layer decode")
                logger.warning(msg)
                DEVTIME.record_degrade("decode_loop", msg)
                decode_layer_unroll = 0
        if decode_layer_unroll:
            from ..models.params import decode_loop_plan
            from ..ops.pallas.probe import probe_decode_loop

            fmts, reason = decode_loop_plan(self.params, self.cfg)
            if reason is not None:
                logger.warning("layer-looped decode unavailable (%s); "
                               "serving per-layer decode", reason)
                DEVTIME.record_degrade("decode_loop", reason)
                decode_layer_unroll = 0
            else:
                err = probe_decode_loop(
                    quantized=self.cfg.kv_dtype == "int8",
                    int8_weights=fmts["wq"] == "int8",
                    n_kv=self.cfg.n_kv_heads, head_dim=self.cfg.head_dim,
                    n_ctx=self.cfg.n_ctx,
                    sliding_window=self.cfg.sliding_window,
                    n_heads=self.cfg.n_heads, ffn_dim=self.cfg.ffn_dim)
                if err is not None:
                    # pin the per-layer path for THIS kernel geometry,
                    # process-wide: direct forward() callers must not
                    # re-arm a lowering that already failed here, while
                    # a co-resident registry model with a different
                    # geometry (its own probe verdict) keeps looping
                    from ..ops.pallas.decode_loop import (
                        disable_decode_loop,
                        loop_geometry,
                    )

                    disable_decode_loop(err, loop_geometry(self.cfg, fmts))
                    logger.error(
                        "layer-looped decode kernel failed its compile "
                        "probe; serving per-layer decode: %s", err)
                    DEVTIME.record_degrade("decode_loop", err)
                    decode_layer_unroll = 0
        if decode_layer_unroll != self.cfg.decode_layer_unroll:
            self.cfg = dataclasses.replace(
                self.cfg, decode_layer_unroll=decode_layer_unroll)
        if self._spec_request == "auto":
            from .spec_auto import resolve_auto

            mode, self.spec_auto_decision = resolve_auto(self.params)
            self._spec_draft = (self._spec_draft_request
                                if mode == "lookup" else 0)
            logger.info("spec_decode=auto resolved to %r: %s", mode,
                        self.spec_auto_decision)
            if self._spec_draft and type(self) is not Engine \
                    and not getattr(self, "_SPEC_LANES", False):
                logger.warning(
                    "spec_decode=auto resolved to lookup, but %s serves "
                    "vanilla decode (see _spec_enabled)", type(self).__name__)
        self.prefill_buckets = sorted(b for b in prefill_buckets if b <= self.cfg.n_ctx)
        if not self.prefill_buckets or self.prefill_buckets[-1] < self.cfg.n_ctx:
            self.prefill_buckets.append(self.cfg.n_ctx)
        self._cache = init_cache(self.cfg)
        # -- prompt-prefix KV reuse (serial engine only) -------------------
        # The reference's engine re-evaluates the whole prompt every call;
        # llama.cpp exposes prompt caching for exactly this workload (the
        # persona + full chat history are re-sent verbatim each turn,
        # reference api.py:44-63).  Here the serial engine remembers which
        # token ids' KV entries are resident in its ring after each request
        # and, when the next prompt shares that prefix, prefills only the
        # suffix via prefill_chunk_jit — multi-turn TTFT then scales with
        # the NEW turn's length, not the whole history.  The mesh/SP/lane
        # engines manage caches differently and keep full prefill, and the
        # speculative engine keeps it too: verify steps leave rejected
        # drafts in re-claimable slots, and reuse would break spec's
        # same-seed determinism contract (a cached and an uncached eval of
        # the same prompt differ by bf16 KV rounding, so sampled tokens can
        # diverge — see tests/test_spec_decode.py).
        self._prefix_cache = (bool(prefix_cache) and type(self) is Engine
                              and not self._spec_draft)
        self._prefix_min = max(1, int(prefix_min))
        #: token ids whose KV occupy ring slots [0, len) — only ever read
        #: and written under self._lock (the single-generator invariant)
        self._prefix_ids: list[int] = []
        # -- block-paged KV pool + shared radix prefix index (ROADMAP item
        # 2; gated behind LFKT_KV_PAGED, dense ring is the A/B control) ----
        # One prefix-reuse implementation per mode: paging replaces the
        # serial single-claim above (and the continuous engine's lane
        # claims) with the process-wide radix index — shared system
        # prompts prefill once per process, multi-turn requests resume
        # from their last committed page.  Spec decode keeps the same
        # exclusion as every reuse path (verify rounds leave rejected
        # drafts in cache slots, and reuse would break spec's same-seed
        # determinism contract).
        paged = bool(kv_paged) and not self._spec_draft
        if paged and not self._KV_PAGED:
            logger.warning(
                "LFKT_KV_PAGED=1 requested but %s shards the ring's n_ctx "
                "dim; the paged pool needs it unsharded — serving with the "
                "dense ring", type(self).__name__)
            paged = False
        self._kv_paged = paged
        #: the in-flight request's pinned pool pages (exactly one live
        #: lease: the serial engines generate one request at a time).
        #: Lease lifecycle — acquire in _paged_reuse, store here
        #: (the handoff), release in _drop_lease on every exit incl.
        #: exceptions — is machine-checked by lfkt-lint RES001
        #: (docs/LINT.md), the PR-6 leak class made static.
        self._paged_lease = None
        #: prefix-cache namespace: every pool index operation is keyed by
        #: it, so co-resident models sharing one arena can never match
        #: each other's token prefixes (the registry passes the manifest
        #: model name; single-model engines use the default namespace)
        self._kv_ns = kv_namespace or ""
        if paged:
            self._prefix_cache = False
            if kv_pool is not None \
                    and not kv_pool.compatible(self.cfg, kv_page_tokens):
                # shared multi-model pool: N models partition one HBM page
                # budget dynamically instead of each provisioning
                # worst-case — but only an identical per-page cache
                # geometry can share the arena.  Gate off with attribution
                # (the SPEngine-paging idiom): this model serves from a
                # private pool instead of failing the whole fleet.
                logger.warning(
                    "model %r (n_layers=%d, n_kv_heads=%d, head_dim=%d, "
                    "kv_dtype=%s) cannot share the KV page arena: cache "
                    "page geometry differs from the pool's — serving it "
                    "from a private pool (docs/MULTIMODEL.md)",
                    self.model_name, self.cfg.n_layers,
                    self.cfg.n_kv_heads, self.cfg.head_dim,
                    self.cfg.kv_dtype)
                kv_pool = None
            if kv_pool is not None:
                self._kvpool = kv_pool
            else:
                from ..parallel.kvpool import KVPool

                self._kvpool = KVPool(
                    self.cfg, page_tokens=kv_page_tokens,
                    n_pages=kv_pool_pages, spill_pages=kv_spill_pages,
                    sink_host=self)
        else:
            self._kvpool = None
        #: disaggregated prefill/decode (serving/disagg/): the decode
        #: replica's remote-prefill client, installed by install_disagg()
        #: when LFKT_DISAGG_ROLE is decode|both.  None (the default) is
        #: THE off state — the serving paths gate on a single attribute
        #: read, so a role=off pod pays nothing (poisoned-client pin,
        #: tests/test_disagg.py).
        self._disagg = None
        # -- lfkt-mem: report this engine's allocation surfaces into the
        # process memory ledger (obs/memledger.py).  Weakly held — a
        # discarded engine's rows vanish with it; providers read live
        # shape metadata at snapshot time, never on the decode path.
        # (The pool registers itself; subclasses add their own surfaces.)
        register_component("weights", self, _ledger_weight_bytes)
        register_component("kv_ring", self, _ledger_ring_bytes)

    # ------------------------------------------------------------------
    @property
    def kv_cache_bytes(self) -> int:
        """Logical HBM bytes of EVERY resident KV ring this engine holds:
        the serial ring, the batched lane state (mesh/continuous), and the
        continuous scheduler's persistent prefill scratch — summed from the
        live pytrees so the /health and /metrics figure matches what
        actually sits in HBM (docs/KV_CACHE.md lane-headroom math).
        ``.nbytes`` is shape metadata, safe even on donated buffers."""
        total = 0
        for cache in (getattr(self, "_cache", None),
                      getattr(self, "_scratch_cache", None),
                      (getattr(self, "_bstate", None) or {}).get("cache")):
            if cache is not None:
                total += sum(leaf.nbytes for leaf in jax.tree.leaves(cache))
        pool = getattr(self, "_kvpool", None)
        if pool is not None:
            total += pool.arena_nbytes
        return total

    @property
    def weight_bytes(self) -> int:
        """Resident HBM bytes of this model's weights (shape metadata,
        summed over the params pytree) — the multi-model registry's HBM
        weight-budget unit and the ``model_weight_bytes`` gauge."""
        params = getattr(self, "params", None)
        if params is None:
            return 0
        return sum(leaf.nbytes for leaf in jax.tree.leaves(params))

    def kv_pool_occupancy(self) -> dict | None:
        """Paged-pool occupancy + event counters — the /health ``kv_pool``
        block and the ``kv_pool_pages_{used,free}`` gauges; None when
        ``LFKT_KV_PAGED`` is off."""
        pool = getattr(self, "_kvpool", None)
        if pool is None:
            return None
        return {**pool.occupancy(), **pool.stats()}

    # ------------------------------------------------------------------
    @classmethod
    def from_parts(cls, params, cfg: ModelConfig, tokenizer,
                   template_kind: str = "llama3", **kw) -> "Engine":
        """Build from in-memory parts (tests, benches, synthetic models)."""
        eng = cls(None, n_ctx=cfg.n_ctx,
                  _parts=(params, cfg, tokenizer, template_kind), **kw)
        return eng

    @classmethod
    def synthetic(cls, cfg: ModelConfig, tokenizer, fmt: str = "bf16",
                  seed: int = 0, **kw) -> "Engine":
        return cls.from_parts(synth_params(cfg, fmt=fmt, seed=seed), cfg,
                              tokenizer, **kw)

    # ------------------------------------------------------------------
    @staticmethod
    def _probe_fused_format(present_types: set | None = None) -> tuple:
        """Compile-probe the fused Q4_K/Q5_K/Q6_K kernels — only those whose
        GGML type actually appears in ``present_types`` (the loaded file's
        tensors), so a Q4_K_M pod never pays a Q5_K probe compile.  Returns
        ("q4k", {types whose probe passed}): a Mosaic failure in ONE kernel
        degrades only that format's tensors to int8, and all failing
        degrades the whole load — instead of crash-looping the pod
        (SURVEY.md §5 "Failure detection"; the reference has no analogue
        because llama.cpp ships precompiled kernels)."""
        from ..gguf.constants import GGMLType
        from ..ops.pallas.probe import (
            probe_fused_q4k,
            probe_fused_q5k,
            probe_fused_q6k,
            probe_fused_q8,
        )

        passed = set()
        probed = set()
        for name, gtype, probe in (
                ("Q4_K", GGMLType.Q4_K, probe_fused_q4k),
                ("Q5_K", GGMLType.Q5_K, probe_fused_q5k),
                ("Q6_K", GGMLType.Q6_K, probe_fused_q6k),
                ("Q8_0", GGMLType.Q8_0, probe_fused_q8)):
            if present_types is not None and gtype not in present_types:
                continue
            probed.add(gtype)
            err = probe()
            if err is None:
                passed.add(gtype)
            else:
                logger.error("fused %s kernel failed its compile probe; "
                             "its tensors load as int8 instead: %s", name, err)
        if not probed:
            # No fused-eligible quantized tensors in the file at all — the
            # F16 (or BF16) GGUF variant of BASELINE config #3.  Decision:
            # serve int8.  8B bf16 weights are ~16 GB and cannot share
            # v5e's 16 GB HBM with the KV cache; per-channel int8 requant
            # (on device, load_params) halves bytes/token and runs the MXU
            # int8 path at ~85% of its bandwidth roofline (docs/PERF.md).
            logger.info(
                "no fused-eligible quantized tensors in the file; serving "
                "weight_format=int8 (on-device per-channel requant — the "
                "documented decision for F16/BF16 GGUFs, docs/PERF.md)")
            return "int8", None
        if not passed:
            return "int8", None
        return "q4k", frozenset(passed)

    def warmup(self):  # lfkt: blocks-under[_lock] -- warmup compiles and syncs under the engine lock by design: a request must never race a half-warmed cache
        """Compile every (bucket, chunk) shape so no request pays a cold
        compile — the TPU analogue of the reference's eager model load.
        With speculation enabled this drives BOTH decode paths: a
        repeated-word prompt whose n-gram lookup hits (compiles
        ``spec_verify_jit``) and a unique-word prompt whose lookup misses
        (compiles the plain chunk fallback)."""
        t0 = time.time()
        msgs = [{"role": "user", "content": "hi hi hi hi hi hi hi hi"}]
        # TWO full decode chunks, not one: on the sharded engines the
        # donated state returns from chunk 1 with jit-chosen shardings, so
        # the steady-state chunk-2 signature is a distinct compile — found
        # by the devtime compile pins (tests/test_perf_pins.py), which now
        # hold warmup to "compiles everything steady-state decode runs"
        self.create_chat_completion(msgs,
                                    max_tokens=2 * self.decode_chunk + 1,
                                    temperature=0.0)
        if self._spec_enabled():
            self.create_chat_completion(
                [{"role": "user", "content": "alpha bravo charlie delta"}],
                max_tokens=2 * self.decode_chunk + 1, temperature=0.0)
        with self._lock:   # uncontended at warmup; the ring-write invariant
            #                (writes to _cache only under _lock) stays intact
            for b in self.prefill_buckets[1:]:
                # compile the program(s) this bucket actually serves with:
                # monolithic prefill for small buckets, the slice walk for
                # buckets the overlapped path slices (_slices_prefill)
                logits, cache = self._prefill_padded(
                    [0] * (b - 1), b - 1, b, self._cache)
                jax.block_until_ready(logits)
                self._cache = cache
            if self._prefix_cache or self._kv_paged:
                # compile the suffix pass for every bucket a reuse suffix can
                # land in (all but the largest — _prefix_reuse_len only grants
                # reuse when the suffix bucket is strictly smaller than the
                # prompt's; the paged radix path shares the same suffix-bucket
                # contract), preserving the no-cold-compile-after-warmup
                # invariant on the reuse path too.  Also drops the claim over
                # the garbage the raw bucket loop above wrote into the ring.
                # (Pool page-copy programs are NOT part of this warmed set:
                # they compile on first use — parallel/kvpool.py.)
                for b in self.prefill_buckets[:-1]:
                    logits, self._cache = prefill_chunk_jit(
                        self.params, self.cfg, jnp.zeros((b,), jnp.int32),
                        jnp.int32(0), jnp.int32(b - 1), self._cache)
                    jax.block_until_ready(logits)
                self._prefix_ids = []
        logger.info("warmup done in %.1fs (%d prefill buckets)",
                    time.time() - t0, len(self.prefill_buckets))

    # -- jit call points (subclasses reroute these onto a mesh: engine/sp.py
    # runs them sequence-parallel; the vmap/batched engines bypass them) ----
    def _prefill_call(self, tokens, length, cache):
        return prefill_jit(self.params, self.cfg, tokens, length, cache)

    def _slices_prefill(self, bucket: int) -> bool:
        """Whether a ``bucket``-sized prompt prefills as overlapped slices
        (vs one monolithic program).  Buckets at or under the slice size
        gain nothing from slicing and keep the single-program path."""
        return (self._SLICE_PREFILL and self._prefill_overlap > 0
                and bucket > self._prefill_chunk)

    def _observe_slice(self, dt: float) -> None:
        """Feed one prefill-slice host wall time into the server's metrics
        (``prefill_slice_seconds``); free when no sink is installed."""
        m = self.metrics_sink
        if m is not None:
            try:
                m.observe("prefill_slice_seconds", dt)
            except Exception:  # noqa: BLE001 — telemetry must never fail serving
                pass

    def _prefill_padded(self, ids: list, n_prompt: int, bucket: int,
                        cache, pspan=None):  # lfkt: holds[_lock]
        """Bucket prefill, monolithic or sliced: returns (logits, cache).

        The sliced path is the round-6 double-buffered pipeline: the padded
        prompt is prepared ONCE as a host int32 array, then each slice is a
        zero-copy view dispatched through ``prefill_chunk_jit`` — slice
        ``i+1``'s host prep (view + device enqueue) overlaps slice ``i``'s
        device compute because dispatch is async.  ``prefill_overlap``
        bounds the un-synced slices in flight (the oldest slice's logits
        are blocked on past the bound) so a 32k prompt cannot queue
        hundreds of slices on a tunneled device.  Slicing stops at the
        slice containing the last real token, exactly like the continuous
        scheduler's admission machine: pure-padding slices would only
        write cache garbage that is never attended.

        Greedy-bit-identity with the monolithic program is pinned by
        tests/test_prefill_pipeline.py on every engine flavor.
        """
        if not self._slices_prefill(bucket):
            padded = ids + [0] * (bucket - n_prompt)
            return self._prefill_call(
                jnp.asarray(padded, jnp.int32), jnp.int32(n_prompt), cache)
        C = self._prefill_chunk
        padded_np = np.zeros((bucket,), np.int32)
        padded_np[:n_prompt] = ids
        logits = None
        inflight: deque = deque()
        off = 0
        last = n_prompt - 1
        while off <= last:
            t_s = time.time()
            n = min(C, bucket - off)
            sl = jnp.asarray(padded_np[off:off + n])
            li = min(max(last - off, 0), n - 1)
            lg, cache = prefill_chunk_jit(
                self.params, self.cfg, sl, jnp.int32(off), jnp.int32(li),
                cache)
            if off <= last < off + n:
                logits = lg
            inflight.append(lg)
            if len(inflight) > self._prefill_overlap:
                # double-buffer bound: wait for the OLDEST slice so at most
                # `overlap` slices are queued un-synced on the device
                jax.block_until_ready(inflight.popleft())
            dt = time.time() - t_s
            self._observe_slice(dt)
            if pspan is not None:
                pspan.event("prefill_slice", offset=off, tokens=n,
                            host_s=round(dt, 6))
            off += n
        return logits, cache

    def _decode_chunk_call(self, state, st, n_steps: int, top_k: int):
        return generate_chunk_jit(self.params, self.cfg, state, st,
                                  n_steps=n_steps, top_k=top_k)

    def _next_seed(self) -> int:
        with self._id_lock:
            s = self._base_seed + self._requests
            self._requests += 1
            return s

    def _record_timings(self, timings: dict) -> None:
        with self._id_lock:
            self.last_timings = timings

    def _note_error(self, exc: BaseException) -> None:
        """Record an engine-side failure on the heartbeat for the watchdog's
        burst detector.  ValueError is a *client* input error (oversized
        prompt, bad params) — a burst of bad requests must never count as
        engine failure, or abusive traffic could trip the watchdog."""
        if isinstance(exc, ValueError):
            return
        self.heartbeat.record_error(exc)

    # -- watchdog recovery ---------------------------------------------
    def recover(self) -> bool:
        """Re-initialize serving state after a watchdog trip (bounded
        recovery, engine/watchdog.py).  The serial engine's mutable state
        is the KV ring and its prefix claim; params are immutable so a
        fresh ring is a full re-init.  Refuses (returns False) while a
        generation holds the lock — the cache cannot be swapped under a
        live decode, and a permanently held lock means a wedged device
        call, which only a pod restart (DEAD) clears."""
        FAULTS.fire("recover")   # injection point: recovery that fails
        if not self._lock.acquire(blocking=False):
            return False
        try:
            self._recover_locked()
            self.heartbeat.reset()
            return True
        finally:
            self._lock.release()

    def _recover_locked(self) -> None:  # lfkt: holds[_lock]
        """Engine-specific state re-init, called with the lock held."""
        self._cache = init_cache(self.cfg)
        self._prefix_ids = []
        if self._kvpool is not None:
            # lane/ring contents are of unknown validity after a trip —
            # nothing resident (or pinned) is trustworthy
            self._drop_lease()
            self._kvpool.reset()

    @staticmethod
    def _deadline_hit(ctx) -> bool:
        """Per-request deadline/abort propagation: True when the caller's
        deadline passed or its abort callback fired — the decode loops
        check this once per chunk so a timed-out or disconnected request
        abandons the device within one decode step instead of generating
        to budget (the reference's engine always ran to completion,
        api.py:97-100, which only its strictly serial engine could
        afford)."""
        abort = ctx.get("abort")
        if abort is not None and abort():
            return True
        deadline = ctx.get("deadline")
        return deadline is not None and time.time() > deadline

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.cfg.n_ctx

    def tokenize_messages(self, messages: Sequence[dict]) -> list[int]:
        return apply_chat_template(self.tokenizer, messages, kind=self.template_kind)

    # ------------------------------------------------------------------
    def create_chat_completion(
        self,
        messages: Sequence[dict],
        stream: bool = False,
        temperature: float = 0.2,
        top_p: float = 0.95,
        top_k: int = 40,
        min_p: float = 0.05,
        frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0,
        repeat_penalty: float = 1.1,
        max_tokens: int | None = None,
        stop: Sequence[str] | str | None = None,
        seed: int | None = None,
        deadline: float | None = None,
        abort=None,
        trace=None,
    ):
        """OpenAI-chat-shaped completion (dict), or an iterator of chunks when
        ``stream=True`` (reference call site: api.py:55-63; chunk schema per
        SURVEY.md §2B "Streaming").  Safe to call from a worker thread.

        ``deadline`` (absolute ``time.time()`` seconds) and ``abort`` (a
        callable returning True when the caller gave up) propagate the
        server's admission timeout/disconnect into the decode loop: the
        generation stops within one decode chunk of either firing, with
        ``finish_reason="deadline"``.  ``trace`` (an obs.trace.Trace, or
        None when the request is sampled out) receives the engine's span
        tree — prefill and per-decode-chunk timings; every producer site
        guards on None so an untraced request allocates nothing."""
        if stop is None:
            stop = []
        elif isinstance(stop, str):
            stop = [stop]
        sp = SamplingParams(
            temperature=temperature, top_p=top_p, top_k=top_k, min_p=min_p,
            frequency_penalty=frequency_penalty, presence_penalty=presence_penalty,
            repeat_penalty=repeat_penalty,
        )
        if stream:
            return self._generate_stream(messages, sp, max_tokens, stop, seed,
                                         deadline=deadline, abort=abort,
                                         trace=trace)
        return self._generate(messages, sp, max_tokens, stop, seed,
                              deadline=deadline, abort=abort, trace=trace)

    def _trace_attrs(self) -> dict:
        """Engine-identity attributes stamped on a traced request's
        ``engine`` span (subclasses extend — engine/sp.py adds the mesh
        geometry)."""
        return {"engine": type(self).__name__, "model": self.model_name}

    # ------------------------------------------------------------------
    def _start(self, messages, sp: SamplingParams, seed,
               espan=None, pre_ids=None):  # lfkt: holds[_lock]
        """Shared prefill + first-token path. Returns a mutable gen context.
        ``espan`` (the traced request's ``engine`` span, or None) grows a
        ``prefill`` child covering tokenize → first sampled token.
        ``pre_ids`` is the prompt already tokenized by the pre-lock
        disagg hop (_remote_prefill) so the request never pays the chat
        template + tokenizer twice."""
        t0 = time.time()
        self.heartbeat.beat()
        FAULTS.fire("prefill")
        ids = pre_ids if pre_ids is not None \
            else self.tokenize_messages(messages)
        n_prompt = len(ids)
        if n_prompt >= self.cfg.n_ctx:
            raise ValueError(
                f"Requested tokens ({n_prompt}) exceed context window of {self.cfg.n_ctx}"
            )
        bucket = self._bucket_for(n_prompt)
        st = sampling_tensors(sp)

        explicit_seed = seed is not None
        if seed is None:
            seed = self._next_seed()
        else:
            self._next_seed()  # keep the auto-seed sequence advancing

        # an explicit seed is a reproducibility request: the reuse pass
        # scores bf16-rounded cached KV where full prefill scores fresh
        # f32 K/V, so a near-tied logit can flip — same-seed calls must
        # instead be bit-identical, so they always take the full prefill
        reuse = 0 if explicit_seed else \
            self._prefix_reuse_len(ids, n_prompt, bucket)
        pspan = None
        if espan is not None:
            pspan = espan.child("prefill", t0=t0)
        if self._kv_paged and not explicit_seed:
            # paged mode: the shared radix index replaces the single-claim
            # reuse above (restores matched pages into the ring and pins
            # them for this request — parallel/kvpool.py)
            reuse = self._paged_reuse(ids, n_prompt, bucket, pspan)
        if pspan is not None:
            pspan.set(n_prompt=n_prompt, bucket=bucket, reused=reuse)
        # claim nothing while this request is in flight: an exception past
        # this point must not leave a stale prefix claim over a cache whose
        # contents are indeterminate
        self._prefix_ids = []
        if reuse:
            suffix = ids[reuse:]
            s = len(suffix)
            sbucket = self._bucket_for(s)
            logits, cache = prefill_chunk_jit(
                self.params, self.cfg,
                jnp.asarray(suffix + [0] * (sbucket - s), jnp.int32),
                jnp.int32(reuse), jnp.int32(s - 1), self._cache)
        else:
            logits, cache = self._prefill_padded(
                ids, n_prompt, bucket, self._cache, pspan=pspan)
        window, wpos = seed_window(ids)
        key = jax.random.PRNGKey(seed)
        token, window, wpos, key = sample_jit(
            logits, window, wpos, key, st, self.cfg, top_k=sp.top_k)
        state = {
            "cache": cache,
            "pos": jnp.int32(n_prompt),
            "token": token,
            "window": window,
            "wpos": wpos,
            "key": key,
        }
        first = int(token)  # device sync: first token is now materialized
        ttft_s = time.time() - t0
        if pspan is not None:
            pspan.set(ttft_s=round(ttft_s, 6))
            pspan.end()
        return {
            "state": state, "st": st, "sp": sp, "n_prompt": n_prompt,
            "ids": [], "prompt_ids": ids, "first": first, "t0": t0,
            "reused": reuse, "ttft_s": ttft_s, "span": espan,
            "bucket": bucket,
        }

    def _prefix_reuse_len(self, ids: list, n_prompt: int, bucket: int) -> int:
        """Longest usable common prefix of ``ids`` vs the KV resident in the
        ring, or 0 when reuse is off / too short / wouldn't shrink the
        prefill bucket.  Always leaves ≥1 token to prefill (the suffix pass
        must emit the last prompt token's logits)."""
        if not self._prefix_cache:
            return 0
        prev = self._prefix_ids
        lim = min(len(prev), n_prompt - 1)
        i = 0
        while i < lim and prev[i] == ids[i]:
            i += 1
        if i < self._prefix_min:
            return 0
        # The padded suffix slice [reuse, reuse + sbucket) must stay inside
        # the KV ring: dynamic_update_slice CLAMPS an out-of-range write
        # start, which would silently overwrite valid prefix slots with KV
        # whose RoPE positions disagree (code-review r4 finding).  Near the
        # context limit the reuse is therefore shortened to n_ctx - sbucket
        # (re-prefilling a little more) rather than dropped.  Smallest
        # bucket first: it admits the longest reuse.
        for b in self.prefill_buckets:
            if b >= bucket:
                break  # suffix pads into the same program: no cycles saved
            r = min(i, self.cfg.n_ctx - b)
            if r >= self._prefix_min and n_prompt - r <= b:
                return r
        return 0

    def _drop_lease(self) -> None:  # lfkt: holds[_lock]
        """Unpin the current request's pool pages (idempotent)."""
        if self._paged_lease is not None:
            self._kvpool.release(self._paged_lease)
            self._paged_lease = None

    def _paged_reuse(self, ids: list, n_prompt: int, bucket: int,
                     pspan=None) -> int:  # lfkt: holds[_lock]
        """Radix-tree prefix reuse (LFKT_KV_PAGED): the longest cached
        whole-page prefix that fits the suffix-bucket contract (exactly
        :meth:`_prefix_reuse_len`'s constraints, page-aligned), restored
        contiguously into the ring and pinned for the request's lifetime.
        Returns the reused token count (0 = full prefill)."""
        self._drop_lease()   # a prior request's exception may have leaked
        pool = self._kvpool
        T = pool.page_tokens
        i = min(pool.match_len(ids, namespace=self._kv_ns), n_prompt - 1)
        r_fit = 0
        # same clamp as _prefix_reuse_len: the padded suffix slice
        # [reuse, reuse + sbucket) must stay inside the ring, and the
        # suffix must land in a strictly smaller bucket — plus page
        # alignment, since pages are the restore grain
        for b in self.prefill_buckets:
            if b >= bucket:
                break
            r = (min(i, self.cfg.n_ctx - b) // T) * T
            if r >= max(self._prefix_min, T) and n_prompt - r <= b:
                r_fit = r
                break
        if r_fit == 0:
            pool.note_miss()
            return 0
        lease = pool.acquire(ids, r_fit, span=pspan, namespace=self._kv_ns)
        if lease is None:    # raced an eviction / spill-restore failed
            return 0
        self._paged_lease = lease
        # the ring is donated into the copy: drop our ref across the call
        # so a mid-copy failure cannot leave a dead donated buffer as
        # self._cache (the next request would trip over it) — rebuild
        # cold instead, exactly like _reinit, and propagate
        cache, self._cache = self._cache, None
        try:
            self._cache = pool.restore(lease, cache, span=pspan)
        except Exception:
            self._drop_lease()
            self._cache = init_cache(self.cfg)
            raise
        if pspan is not None:
            pspan.set(reused_pages=len(lease.page_ids), matched_tokens=i)
        return lease.tokens

    # -- disaggregated prefill/decode (serving/disagg/) -----------------
    def install_disagg(self, client) -> None:
        """Arm remote prefill (LFKT_DISAGG_ROLE=decode|both): admitted
        prompts hop to the prefill tier, whose pages import into the
        local pool's radix, so :meth:`_paged_reuse` (and the continuous
        scheduler's admission reuse) restores them like any local
        commit.  Requires the paged pool — pages ARE the wire format."""
        if self._kvpool is None:
            raise ValueError(
                "install_disagg requires LFKT_KV_PAGED=1: the disagg "
                "wire ships KV pool pages (docs/RUNBOOK.md 'Operating a "
                "split prefill/decode fleet')")
        self._disagg = client

    def _remote_prefill(self, messages, deadline, trace) -> list | None:
        """One bounded remote-prefill hop for the serial path, BEFORE the
        generation lock (in `both` mode the loopback page service takes
        that lock to prefill — holding it here would deadlock).  Never
        raises: tokenize errors re-raise properly inside _start, and the
        client degrades every wire failure to local prefill itself.
        Returns the tokenized prompt so _start never re-tokenizes (None
        when tokenization failed — _start then raises the real error)."""
        try:
            ids = self.tokenize_messages(messages)
        except Exception:  # noqa: BLE001 — _start re-raises the real error
            return None
        try:
            if len(ids) >= self.cfg.n_ctx:
                return ids              # _start's oversized-prompt 400
            span = trace.span("disagg") if trace is not None else None
            try:
                self._disagg.prefetch(ids, namespace=self._kv_ns,
                                      deadline=deadline, span=span)
            finally:
                if span is not None:
                    span.end()
        except Exception:  # noqa: BLE001 — remote prefill is an
            # optimization: any failure here must degrade to the local
            # prefill _start runs anyway, never fail the request
            logger.exception("disagg prefetch failed; serving local "
                             "prefill")
        return ids

    def _remote_prefill_ids(self, ids, deadline, span=None) -> None:
        """Tokenized variant (the continuous scheduler's admission path,
        engine/continuous.py _begin_admission).  Same never-raise
        contract as :meth:`_remote_prefill`."""
        try:
            self._disagg.prefetch(ids, namespace=self._kv_ns,
                                  deadline=deadline, span=span)
        except Exception:  # noqa: BLE001 — degrade to local prefill
            logger.exception("disagg prefetch failed; serving local "
                             "prefill")

    def prefill_to_pages(self, ids, *, namespace: str = "",  # lfkt: blocks-under[_lock] -- the serial engine's lock IS the request serialization: prefill syncs and pool spills run under it by design
                         deadline=None):
        """The prefill TIER's page service (serving/disagg/prefiller.py):
        ensure the whole-page prefix of ``ids`` is committed in the
        local pool — consulting the tier's own radix first, so a system
        prompt hot across many decode replicas prefills once per tier,
        then prefilling into the serial ring (which serves nothing else
        on a prefill-role pod) — pin it, export host page stacks,
        release.  Returns ``(leaves, tokens, first_token)`` or None when
        no whole page is exportable; ``first_token`` is the prompt's
        greedy continuation when this call ran the prefill (advisory —
        the decode side samples its own first token from the restored
        prefix, bit-identical by the suffix-prefill contract), else
        None."""
        pool = self._kvpool
        if pool is None:
            raise ValueError(
                "prefill_to_pages requires LFKT_KV_PAGED=1 (pages are "
                "the disagg wire format)")
        T = pool.page_tokens
        ids = list(ids)
        n_prompt = len(ids)
        if n_prompt >= self.cfg.n_ctx:
            raise ValueError(
                f"Requested tokens ({n_prompt}) exceed context window "
                f"of {self.cfg.n_ctx}")
        keep = (n_prompt // T) * T
        if keep < T:
            return None                  # prompt shorter than one page
        first_token = None
        with self._lock:
            self.heartbeat.enter()
            try:
                have = pool.match_len(ids[:keep], namespace=namespace)
                if have < keep:
                    if deadline is not None and time.time() > deadline:
                        # PR-2 deadline propagation spans the hop: the
                        # decode side already abandoned this request
                        raise DeadlineExceeded(
                            "deadline expired before remote prefill")
                    self.heartbeat.beat()
                    FAULTS.fire("prefill")
                    bucket = self._bucket_for(n_prompt)
                    logits, cache = self._prefill_padded(
                        ids, n_prompt, bucket, self._cache)
                    self._cache = cache
                    self._prefix_ids = []
                    first_token = int(jnp.argmax(logits))
                    pool.commit(ids[:keep], self._cache,
                                namespace=namespace)
                # commit may have degraded to the leading portion that
                # fit (squeezed pool): export what the index truly holds
                have = min(pool.match_len(ids[:keep], namespace=namespace),
                           keep)
                if have < T:
                    return None
                lease = pool.acquire(ids[:keep], have, namespace=namespace)
                if lease is None:        # raced an eviction: a miss, not
                    return None          # an error — the peer falls back
                try:
                    leaves = pool.export_pages(lease)
                    tokens = lease.tokens
                finally:
                    pool.release(lease)
                return leaves, tokens, first_token
            finally:
                self.heartbeat.leave()

    def _finish(self, ctx) -> dict:  # lfkt: holds[_lock]
        """Return the cache buffer for reuse; finalize per-phase timings.
        Returns the timings dict (also published to :attr:`last_timings`)."""
        self._cache = ctx["state"]["cache"]
        decode_s = time.time() - ctx["t0"] - ctx["ttft_s"]
        n = len(ctx["ids"])
        if self._kv_paged:
            # commit the conversation's whole-page prefix to the shared
            # pool (pages already cached are deduplicated, so a multi-turn
            # follow-up stores only its delta) and unpin this request's
            # lease.  Ring residency is the same claim as below: slots
            # [0, n_prompt + n - 1) hold prompt + generated tokens except
            # the last sampled one.
            keep = ctx["n_prompt"] + max(n - 1, 0)
            self._kvpool.commit((ctx["prompt_ids"] + ctx["ids"])[:keep],
                                self._cache, span=ctx.get("span"),
                                namespace=self._kv_ns)
            self._drop_lease()
        elif self._prefix_cache:
            # ring slots [0, n_prompt + n - 1) now hold prompt + all
            # generated tokens except the last sampled one (its KV write
            # happens only when it is fed — which a finished request never
            # does); pipelined overshoot writes land past this.  (The spec
            # path never claims: _prefix_cache is off when _spec_draft > 0,
            # because verify steps leave rejected drafts in re-claimable
            # slots.)
            keep = ctx["n_prompt"] + max(n - 1, 0)
            self._prefix_ids = (ctx["prompt_ids"] + ctx["ids"])[:keep]
        timings = {
            "ttft_s": ctx["ttft_s"],
            "decode_s": decode_s,
            "prompt_tokens": ctx["n_prompt"],
            "completion_tokens": n,
            "prefix_reused_tokens": ctx.get("reused", 0),
            # prompt bucket for the per-bucket TTFT series (obs/slo.py)
            "bucket": ctx.get("bucket", 0),
            # model label for the per-model metric series (multi-model
            # serving, docs/MULTIMODEL.md)
            "model": self.model_name,
            # first token came out of prefill; the decode phase produced n-1
            "tokens_per_sec": (n - 1) / decode_s if n > 1 and decode_s > 0 else 0.0,
        }
        if "spec" in ctx:      # speculative decode: acceptance telemetry
            timings["spec"] = ctx["spec"]
        self._record_timings(timings)
        espan = ctx.get("span")
        if espan is not None:
            espan.set(**{k: round(v, 6) if isinstance(v, float) else v
                         for k, v in timings.items() if not isinstance(v, dict)})
            espan.end()
        return timings

    def _token_budget(self, max_tokens, n_prompt):
        budget = self.max_gen_tokens if max_tokens is None else max_tokens
        return max(0, min(budget, self.cfg.n_ctx - n_prompt - 1))

    def _decode_text(self, all_ids):
        return self.tokenizer.decode(all_ids, skip_special=True)

    @staticmethod
    def _find_stop_str(text: str, stops) -> int:
        cut = -1
        for s in stops:
            i = text.find(s)
            if i != -1 and (cut == -1 or i < cut):
                cut = i
        return cut

    @staticmethod
    def _stop_prefix_holdback(text: str, stops) -> int:
        """Length of the longest suffix of ``text`` that is a proper prefix
        of a stop string.  Stream emission withholds it until the next chunk
        resolves whether the stop completes — otherwise a stop spanning a
        chunk boundary would leak its first characters to the client, making
        streamed text diverge from the batch decode."""
        best = 0
        for s in stops:
            for k in range(min(len(s) - 1, len(text)), best, -1):
                if text.endswith(s[:k]):
                    best = k
                    break
        return best

    def _next_steps(self, produced: int, pos: int, budget: int) -> int:
        """Size of the next decode chunk given host-tracked progress (no
        device sync: ``pos`` is n_prompt + decoded count, tracked on host)."""
        n = min(self.decode_chunk, budget - produced)
        n = min(n, self.cfg.n_ctx - pos - 1)  # cache slots n_prompt..n_ctx-1
        return max(0, n)

    # -- speculative decoding (prompt-lookup drafts) --------------------

    def _spec_enabled(self) -> bool:
        """Lookup speculation calls ``spec_verify_jit`` on ``self.params``
        directly, which is only valid for the plain serial engine — mesh/
        continuous/sequence-parallel engines hold sharded params and route
        their device calls differently, so they serve vanilla decode even
        if constructed with ``spec_decode="lookup"``."""
        return self._spec_draft > 0 and type(self) is Engine

    @staticmethod
    def _lookup_draft(history: list, D: int, max_ngram: int = 3):
        """Prompt-lookup draft: find the most recent earlier occurrence of
        the last n-gram (n = max_ngram..1) in ``history`` and propose its
        continuation, zero-padded to exactly ``D`` tokens (static verify
        shape).  Returns None when no n-gram recurs — the caller falls back
        to plain decode.  The same heuristic as llama.cpp's lookup-decoding
        example: free drafts from the prompt's own repetitions (chat
        history re-sent every turn, code identifiers, quoted spans)."""
        n_hist = len(history)
        for n in range(max_ngram, 0, -1):
            if n_hist < n + 1:
                continue
            pat = history[-n:]
            for j in range(n_hist - n - 1, -1, -1):
                if history[j:j + n] == pat:
                    cont = history[j + n:j + n + D]
                    if cont:
                        return cont + [0] * (D - len(cont))
        return None

    def _run_spec(self, ctx, max_tokens, stops):
        """Speculative variant of :meth:`_run` (LFKT_SPEC_DECODE=lookup).

        Each iteration drafts up to ``spec_draft`` next tokens from n-gram
        repetition in prompt+generation, verifies them in ONE forward
        (models/generate.spec_verify_jit) and emits the agreeing prefix +
        one true sample — so a hit advances several tokens for one weight
        read, and a miss costs one (wider) decode step.  Greedy output is
        identical to the vanilla path; sampled output is equal in
        distribution (same PRNG folds/window/conditioning, logits modulo
        batched-forward float reordering — see spec_verify_jit).

        NOT pipelined, unlike :meth:`_run`: the draft for step k+1 needs
        step k's accepted tokens on the host, so dispatch is sequential —
        speculation trades the overlapped round-trip for multi-token steps.
        """
        from ..models.generate import spec_verify_jit

        stop_ids = self.tokenizer.stop_ids
        budget = self._token_budget(max_tokens, ctx["n_prompt"])
        gen: list[int] = []
        em = _TextEmitter(self, stops)
        finish = "length"
        first = ctx["first"]
        if budget <= 0:
            yield "", True, "length"
            return
        if first in stop_ids:
            yield "", True, "stop"
            return
        gen.append(first)
        history = list(ctx["prompt_ids"]) + gen
        pos = ctx["n_prompt"]
        D = self._spec_draft
        done = len(gen) >= budget
        # acceptance telemetry → lfkt_timings["spec"] (scraped to /metrics):
        # accepted/drafted is THE number that says whether speculation pays
        # on this workload
        stats = ctx.setdefault(
            "spec", {"verify_steps": 0, "drafted": 0, "accepted": 0,
                     "fallback_steps": 0})
        # First-token early emit, as in _run: don't make the first text
        # increment wait for the first verify/decode round trip.
        ready, finish, done = em.step(gen, done, finish)
        if ready:
            yield ready, False, finish
        espan = ctx.get("span")   # None when untraced: the loop below then
        #                           allocates no span objects and takes no
        #                           trace locks (tests/test_obs.py pins it)
        while not done:
            if self._deadline_hit(ctx):
                finish = "deadline"
                break
            self.heartbeat.beat()
            FAULTS.fire("decode_step")
            cspan = espan.child("decode_chunk") if espan is not None else None
            remaining = budget - len(gen)
            capacity = self.cfg.n_ctx - pos - 1   # cache slots left to write
            draft = (self._lookup_draft(history, D)
                     if remaining > 1 and capacity > D else None)
            if draft is not None:
                ctx["state"], toks, cnt = spec_verify_jit(
                    self.params, self.cfg, ctx["state"], ctx["st"],
                    jnp.asarray(draft, jnp.int32), top_k=ctx["sp"].top_k)
                cnt = int(cnt)                    # host sync
                toks = np.asarray(toks)[:min(cnt, remaining)].tolist()
                pos += cnt
                stats["verify_steps"] += 1
                stats["drafted"] += D
                stats["accepted"] += cnt - 1      # beyond the always-free one
            else:
                n = self._next_steps(len(gen), pos, budget)
                if n <= 0:
                    break
                ctx["state"], t = self._decode_chunk_call(
                    ctx["state"], ctx["st"], n, ctx["sp"].top_k)
                toks = np.asarray(t).tolist()
                pos += n
                stats["fallback_steps"] += 1
            for t in toks:
                if t in stop_ids:
                    finish = "stop"
                    done = True
                    break
                gen.append(t)
                history.append(t)
            if not done and len(gen) >= budget:
                done = True
            if cspan is not None:
                cspan.set(tokens=len(gen),
                          kind="verify" if draft is not None else "chunk")
                cspan.end()
                ctx["trace"].note(tokens=len(gen))

            ready, finish, done = em.step(gen, done, finish)
            if ready:
                yield ready, False, finish

        ctx["ids"] = gen
        tail, finish = em.final(gen, finish)
        yield tail, True, finish

    def _run(self, ctx, max_tokens, stops):
        """Generate tokens; yields (new_text, done, finish_reason) increments.

        Decode is **pipelined**: chunk k+1 is dispatched to the device before
        chunk k's tokens are fetched to the host, so the host↔device
        round-trip (tens of ms over a tunneled device) overlaps with compute.
        If a stop lands mid-chunk the speculative chunk's cache writes are
        harmless — attention masks by position and every request re-prefills
        and reseeds the sampler window, so stale slots are never read.

        Text increments are produced by an incremental UTF-8 decoder over
        the (append-only) token byte stream, so the streamed concatenation
        is byte-identical to the one-shot decode even when a multi-byte
        character spans a chunk boundary.
        """
        if self._spec_enabled():
            yield from self._run_spec(ctx, max_tokens, stops)
            return
        stop_ids = self.tokenizer.stop_ids
        budget = self._token_budget(max_tokens, ctx["n_prompt"])
        gen: list[int] = []
        em = _TextEmitter(self, stops)
        finish = "length"
        first = ctx["first"]
        if budget <= 0:
            yield "", True, "length"
            return
        if first in stop_ids:
            yield "", True, "stop"
            return
        gen.append(first)

        # host-tracked cache position = the device's next-slot-to-write after
        # prefill (state["pos"] == n_prompt); starting one higher made the
        # capacity clamp in _next_steps a token stricter than pre-pipelining
        pos = ctx["n_prompt"]
        n_cur = self._next_steps(len(gen), pos, budget)
        pending = None
        if n_cur > 0:
            ctx["state"], pending = self._decode_chunk_call(
                ctx["state"], ctx["st"], n_cur, ctx["sp"].top_k)

        done = pending is None
        # Emit the first sampled token's text NOW — chunk 1 is already
        # dispatched and overlaps with this yield.  Before this, the first
        # content increment waited a full decode-chunk device round trip
        # (~chunk×t_tok + RTT), which dominated server-level TTFT: the
        # first token was materialized in _start but sat unemitted.
        ready, finish, done = em.step(gen, done, finish)
        if ready:
            yield ready, False, finish
        espan = ctx.get("span")   # None when untraced: no span allocation,
        #                           no trace lock, anywhere in this loop
        while not done:
            if self._deadline_hit(ctx):
                finish = "deadline"   # caller timed out/disconnected: free
                break                 # the device within one decode chunk
            self.heartbeat.beat()
            FAULTS.fire("decode_step")
            cspan = espan.child("decode_chunk") if espan is not None else None
            # dispatch the NEXT chunk before touching the host copy of the
            # current one (speculating that no stop token appears)
            pos += n_cur
            n_nxt = self._next_steps(len(gen) + n_cur, pos, budget)
            nxt = None
            if n_nxt > 0:
                ctx["state"], nxt = self._decode_chunk_call(
                    ctx["state"], ctx["st"], n_nxt, ctx["sp"].top_k)

            for t in np.asarray(pending).tolist():   # host sync, overlapped
                if t in stop_ids:
                    finish = "stop"
                    done = True
                    break
                gen.append(t)
            pending, n_cur = nxt, n_nxt
            if pending is None:
                done = True
            if cspan is not None:
                cspan.set(tokens=len(gen))
                cspan.end()
                ctx["trace"].note(tokens=len(gen))

            ready, finish, done = em.step(gen, done, finish)
            if ready:
                yield ready, False, finish

        ctx["ids"] = gen
        tail, finish = em.final(gen, finish)
        yield tail, True, finish

    # ------------------------------------------------------------------
    def _engine_span(self, trace, deadline):
        """Open the traced request's ``engine`` span (None passthrough)."""
        if trace is None:
            return None
        trace.note(deadline=deadline, tokens=0, **self._trace_attrs())
        return trace.span("engine").set(**self._trace_attrs())

    def _generate(self, messages, sp, max_tokens, stops, seed,  # lfkt: blocks-under[_lock] -- the serial engine's lock IS the request serialization: the whole generation (device syncs, drill sleeps, incident capture) runs under it by design
                  deadline=None, abort=None, trace=None) -> dict:
        # disagg decode role: one bounded remote-prefill hop BEFORE the
        # generation lock (loopback mode's page service needs it); role
        # off (`_disagg is None`, the default) costs this one attribute
        # read.  Explicit seeds bypass like every reuse path.
        pre_ids = None
        if self._disagg is not None and seed is None:
            pre_ids = self._remote_prefill(messages, deadline, trace)
        with self._lock, maybe_profile("generate"):
            self.heartbeat.enter()
            try:
                return self._generate_locked(messages, sp, max_tokens, stops,
                                             seed, deadline, abort, trace,
                                             pre_ids=pre_ids)
            except Exception as e:  # noqa: BLE001 — burst detection, re-raised
                self._note_error(e)
                raise
            finally:
                self.heartbeat.leave()

    def _generate_locked(self, messages, sp, max_tokens, stops, seed,
                         deadline, abort, trace=None, pre_ids=None
                         ) -> dict:  # lfkt: holds[_lock]
        t0 = time.time()
        ctx = self._start(messages, sp, seed,
                          espan=self._engine_span(trace, deadline),
                          pre_ids=pre_ids)
        ctx["trace"] = trace
        ctx["deadline"] = deadline
        ctx["abort"] = abort
        parts = []
        finish = "stop"
        for text, done, fr in self._run(ctx, max_tokens, stops):
            parts.append(text)
            finish = fr
        timings = self._finish(ctx)
        content = "".join(parts)
        completion_tokens = len(ctx["ids"])
        logger.info("generation: %.2fs, finish=%s", time.time() - t0, finish)
        return {
            "lfkt_timings": timings,
            "id": f"chatcmpl-{uuid.uuid4().hex}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": content},
                "finish_reason": finish,
            }],
            "usage": {
                "prompt_tokens": ctx["n_prompt"],
                "completion_tokens": completion_tokens,
                "total_tokens": ctx["n_prompt"] + completion_tokens,
            },
        }

    def _generate_stream(self, messages, sp, max_tokens, stops, seed,  # lfkt: blocks-under[_lock] -- the serial engine's lock IS the request serialization: the whole generation (device syncs, drill sleeps, incident capture) runs under it by design
                         deadline=None, abort=None,
                         trace=None) -> Iterator[dict]:
        # same pre-lock remote-prefill hop as _generate (one attribute
        # read when LFKT_DISAGG_ROLE is off)
        pre_ids = None
        if self._disagg is not None and seed is None:
            pre_ids = self._remote_prefill(messages, deadline, trace)
        with self._lock:
            self.heartbeat.enter()
            try:
                ctx = self._start(messages, sp, seed,
                                  espan=self._engine_span(trace, deadline),
                                  pre_ids=pre_ids)
            except Exception as e:  # noqa: BLE001 — burst detection, re-raised
                self.heartbeat.leave()
                self._note_error(e)
                raise
            ctx["trace"] = trace
            ctx["deadline"] = deadline
            ctx["abort"] = abort
            cid = f"chatcmpl-{uuid.uuid4().hex}"
            created = int(time.time())

            def chunk(delta: dict, finish=None):
                return {
                    "id": cid,
                    "object": "chat.completion.chunk",
                    "created": created,
                    "model": self.model_name,
                    "choices": [{
                        "index": 0, "delta": delta, "finish_reason": finish,
                    }],
                }

            finished = False
            try:
                yield chunk({"role": "assistant"})
                finish = "stop"
                for text, done, fr in self._run(ctx, max_tokens, stops):
                    finish = fr
                    if text:
                        yield chunk({"content": text})
                timings = self._finish(ctx)
                finished = True
                final = chunk({}, finish=finish)
                final["lfkt_timings"] = timings
                yield final
            except Exception as e:  # noqa: BLE001 — burst detection, re-raised
                self._note_error(e)
                raise
            finally:
                self.heartbeat.leave()
                if not finished:
                    # generator closed early (client gone): _finish must
                    # still run or self._cache would keep pointing at the
                    # buffer prefill donated, poisoning the next request
                    self._finish(ctx)

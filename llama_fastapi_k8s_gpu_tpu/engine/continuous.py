"""Continuous batching: slot-based scheduling over the mesh-batched engine.

:class:`MeshEngine` coalesces requests into *cycles* — everyone admitted
together, nobody new until the whole cycle drains.  This module removes the
barrier: the batch's B lanes become **slots**; at every decode-chunk boundary
finished lanes are freed and waiting requests are admitted into them
(single-sequence prefill into a scratch cache, then a jit'd lane write into
the batched state).  Decode keeps running for whatever lanes are live, so
short requests exit early and long ones never block admission — the
vLLM-style serving loop, TPU-native: static shapes throughout, one compiled
program per (bucket | chunk | lane-write) shape, batch dim sharded over
``dp`` and the model over ``tp``.

The reference's concurrency model (one generation at a time behind
Queue(5)+Semaphore(1), reference api.py:110-116) is the degenerate B=1 case;
back-pressure (503) and per-request timeouts stay at the server layer.
"""

from __future__ import annotations

import codecs
import functools
import logging
import queue as queue_mod
import threading
import time
import uuid
from concurrent.futures import CancelledError, Future
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import prefill_chunk_jit, sample_jit
from ..models.llama import init_cache
from ..obs import memledger as _memledger
from ..obs.devtime import timed_jit
from ..obs.memledger import register_component, tree_nbytes
from ..obs.trace import annotate_all_inflight
from ..parallel.batched import (
    batched_generate_chunk_perlane_jit,
    batched_spec_verify_perlane_jit,
)
from ..sampling.sample import SamplingParams, sampling_tensors, seed_window
from ..utils.faults import FAULTS
from ..utils.health import DeadlineExceeded, EngineUnavailable
from .batched import MeshEngine
from .engine import Engine

logger = logging.getLogger(__name__)


def _ledger_scratch_bytes(eng: "ContinuousEngine") -> int:
    """Memory-ledger provider: the admission scratch ring's resident
    bytes (snapshot-time metadata read — obs/memledger.py)."""
    return tree_nbytes(getattr(eng, "_scratch_cache", None))


@functools.partial(jax.jit, donate_argnames=("state", "lane_st"))
def _write_lane(state: dict, lane_st: dict, lane: jax.Array, cache1: dict,
                pos, token, window, wpos, key, st: dict):
    """Install a freshly prefilled sequence into batch lane ``lane``.
    ``cache1`` is NOT donated — the scheduler reuses it as the next
    admission's prefill scratch (no per-request cache allocation).  Leaf-
    generic over the cache pytree ({k, v} bf16 or the int8 four-leaf
    layout — models/llama.py init_cache)."""
    new_cache = jax.tree.map(
        lambda a, c: a.at[lane].set(c), state["cache"], cache1)
    new_state = {
        "cache": new_cache,
        "pos": state["pos"].at[lane].set(pos),
        "token": state["token"].at[lane].set(token),
        "window": state["window"].at[lane].set(window),
        "wpos": state["wpos"].at[lane].set(wpos),
        "key": state["key"].at[lane].set(key),
    }
    new_lane_st = jax.tree.map(
        lambda a, v: a.at[lane].set(v), lane_st, st)
    return new_state, new_lane_st


_write_lane = timed_jit("lane_write", _write_lane, site="engine.continuous")


@jax.jit
def _lane_cache_copy_jit(cache: dict, lane) -> dict:
    """Snapshot one lane's KV ring into a scratch-shaped cache (lane-prefix
    reuse: the copy becomes the next admission's prefill scratch, so the
    suffix slices start from the reused history instead of position 0).
    Leaf-generic over the cache pytree (bf16 or int8 layout)."""
    return jax.tree.map(lambda a: a[lane], cache)


_lane_cache_copy_jit = timed_jit("lane_cache_copy", _lane_cache_copy_jit,
                                 site="engine.continuous")


_STREAM_END = object()   # scheduler→stream-consumer sentinel


class AdmissionController:
    """Derives the scheduler's per-wave admission prefill-token budget from
    *measured* decode slack instead of the static ``LFKT_ADM_BUDGET``.

    The two signals, both free to measure on the scheduler thread:

    - **lane-idle fraction** — free lanes are lost throughput, so admission
      (refilling them) is the bottleneck: the budget should rise.
    - **decode pressure** — the fraction of the wave the scheduler spent
      *blocked* fetching the previous decode chunk.  A long fetch wait
      means the device was still busy when the host came back (decode is
      the bottleneck; prefill slices queued between chunks directly delay
      live lanes), so the budget should shrink.  A near-zero wait means
      the device sat idle waiting for the host — those admission slices
      were free, and more would be too.

    Both are EMA-smoothed (``alpha`` = LFKT_ADM_EMA_ALPHA; the EMAs SEED
    from the first observation, so the controller acts on measured state
    from wave one instead of riding an optimistic prior) and drive an
    AIMD update with the cut taking priority: sustained pressure halves
    the budget even while lanes sit idle (idle lanes under decode
    saturation mean decode can't keep up — feeding it more prefill is
    exactly the round-5 interference); otherwise idle lanes or plentiful
    slack grow it by one slice.  The floor is ONE slice per wave — an
    admission (deadline-bearing or not) always makes progress, so the
    controller can throttle but never starve (pinned by
    tests/test_admission.py).  Single-threaded by design: owned and
    driven by the scheduler loop.
    """

    #: ema_pressure below this means the device had idle headroom → grow
    SLACK_PRESSURE = 0.25
    #: ema_pressure above this means decode waits on the host's wave → cut
    HIGH_PRESSURE = 0.5

    def __init__(self, chunk: int, lanes: int, base: int,
                 alpha: float = 0.25, max_factor: int = 8):
        self.chunk = max(1, int(chunk))
        self.lanes = max(1, int(lanes))
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self.min_budget = self.chunk              # ≥ one slice: no starvation
        self.max_budget = max(int(base), self.chunk) * max(1, int(max_factor))
        self.budget = min(max(int(base), self.min_budget), self.max_budget)
        self.ema_idle = 0.0       # seeded from the first observation
        self.ema_pressure = 0.0
        self.waves = 0

    def observe_wave(self, lanes_live: int, fetch_wait_s: float,
                     wave_s: float, mem_pressure: bool = False) -> int:
        """Fold one scheduler wave's measurements in; returns the budget
        for the NEXT wave.  ``mem_pressure`` is the memory ledger's HBM
        headroom verdict (obs/memledger.py): low headroom forces the cut
        branch regardless of idle lanes — admitting prefill into a chip
        about to OOM converts a latency problem into a dead pod."""
        a = self.alpha
        idle = 1.0 - min(lanes_live, self.lanes) / self.lanes
        pressure = min(1.0, fetch_wait_s / wave_s) if wave_s > 0 else 0.0
        if self.waves == 0:
            # seed, don't smooth: a controller born into saturation must
            # not spend ~1/alpha waves growing on an optimistic prior
            # (that ride IS the interference it exists to close, and the
            # watchdog-recovery path deliberately re-creates controllers
            # under live load)
            self.ema_idle, self.ema_pressure = idle, pressure
        else:
            self.ema_idle += a * (idle - self.ema_idle)
            self.ema_pressure += a * (pressure - self.ema_pressure)
        self.waves += 1
        if mem_pressure or self.ema_pressure > self.HIGH_PRESSURE:
            # decode saturates the device: halve, floor at one slice.
            # Takes PRIORITY over idle — free lanes under saturation mean
            # decode can't keep up, and more prefill only starves it.
            self.budget = max(self.budget // 2, self.min_budget)
        elif self.ema_idle > 0.01 or self.ema_pressure < self.SLACK_PRESSURE:
            # lanes idle (admission-bound) or decode slack to burn: grow
            self.budget = min(self.budget + self.chunk, self.max_budget)
        return self.budget

    def stats(self) -> dict:
        """Point-in-time introspection for scheduler_stats()/metrics."""
        return {
            "adm_budget_tokens": self.budget,
            "adm_ema_idle": round(self.ema_idle, 4),
            "adm_ema_pressure": round(self.ema_pressure, 4),
        }


class _Item:
    """One queued request: a future (non-stream) OR a chunk sink (stream)."""
    __slots__ = ("future", "messages", "sp", "max_tokens", "stops", "seed",
                 "sink", "abandoned", "deadline", "abort", "rid", "trace",
                 "t_enq")

    def __init__(self, future, messages, sp, max_tokens, stops, seed,
                 sink=None, deadline=None, abort=None, trace=None):
        self.future = future
        self.messages = messages
        self.sp = sp
        self.max_tokens = max_tokens
        self.stops = stops
        self.seed = seed
        self.sink = sink                    # queue.Queue for stream chunks
        self.abandoned = threading.Event()  # caller gave up: free the lane
        self.deadline = deadline            # absolute time.time() budget
        self.abort = abort                  # callable: caller gave up?
        self.rid = 0                        # registry key (abandon/fail_inflight)
        self.trace = trace                  # obs.trace.Trace | None (sampled out)
        self.t_enq = time.time()            # pending-span start (tracing only)


class _Slot:
    __slots__ = ("future", "gens", "budget", "n_prompt", "ids",
                 "first_token", "stops", "st", "sp", "t_admit", "ttft_s",
                 "sink", "abandoned", "dec", "n_emitted", "sent_bytes",
                 "held", "cid", "created", "finished", "pending_first",
                 "reused", "deadline", "abort", "trace", "pspan", "dspan",
                 "t_chunk")

    def __init__(self, item: _Item, budget, n_prompt, ids):
        self.future = item.future
        self.sink = item.sink
        self.abandoned = item.abandoned
        self.deadline = item.deadline
        self.abort = item.abort
        self.trace = item.trace   # span sinks (None when sampled out)
        self.pspan = None         # the admission's "prefill" span
        self.dspan = None         # this slot's lane-occupancy "decode" span
        self.t_chunk = 0.0        # previous harvest time (chunk-span starts)
        self.finished = False   # set when resolved; the pipelined loop may
        #                         still hold this slot in an in-flight
        #                         chunk's lane snapshot — harvest skips it
        self.pending_first = False  # first token still on device (deferred
        #                             admission fetch); materialized at the
        #                             slot's first harvest
        self.gens: list[int] = []
        self.budget = budget
        self.n_prompt = n_prompt
        self.ids = ids
        self.reused = 0          # prompt tokens served from a lane claim
        # stream emission state: incremental UTF-8 decoder over the
        # append-only token byte stream (streamed text == batch decode)
        self.dec = codecs.getincrementaldecoder("utf-8")(errors="replace")
        self.n_emitted = 0
        self.sent_bytes = 0
        self.held = ""    # withheld text (possible stop-string prefix)
        self.cid = f"chatcmpl-{uuid.uuid4().hex}"
        self.created = int(time.time())


class ContinuousEngine(MeshEngine):
    """MeshEngine + a background scheduler thread with per-lane admission.

    Use :meth:`submit` (returns a ``concurrent.futures.Future`` resolving to
    the OpenAI-shaped dict) or the blocking ``create_chat_completion`` /
    ``create_chat_completions`` facades, which route through the scheduler.
    """

    _SPEC_LANES = True   # serves spec_decode="lookup" via batched verify

    # -- thread discipline (machine-checked: lfkt-lint LOCK001-004, see
    # docs/RUNBOOK.md "Lock discipline annotations") ----------------------
    # The scheduler thread OWNS the device state: unlike MeshEngine (whose
    # callers mutate _bstate under _lock), every serving-path write to the
    # state below happens on the lfkt-scheduler thread, so the parent's
    # lock mapping is replaced by thread confinement.  The only
    # cross-thread writes are in recover(), which runs strictly after the
    # thread is proven dead (join + alive/_loop_error guards).
    _GUARDED_BY = {
        "_bstate": None,            # scheduler-confined here (see above)
        "_cache": None,             # serial ring unused on the submit path
        "_prefix_ids": None,
        "_req_counter": "_id_lock",
    }
    _THREAD_ENTRIES = ("_loop",)
    _THREAD_CONFINED = (
        "_bstate", "_lane_st", "_scratch_cache", "_adm", "_lane_claims",
        "_prefix_stats", "_spec_stats", "_stats", "_loop_error",
        "_adm_budget", "_lane_idle_s", "_mem_hot_prev",
    )
    # cross-thread by design; individual operations are GIL-atomic
    # (dict/Queue/Event ops) or single reference stores
    _SHARED_ATOMIC = ("_items", "_pending", "_wake", "_stop", "_shutdown",
                      "_thread")

    def __init__(self, model_path: str | None, *, max_top_k: int = 64,
                 prefill_chunk: int = 256, adm_budget: int = 512,
                 adm_controller: bool = True, adm_ema_alpha: float = 0.25,
                 lane_prefix_cache: bool = True, **kw):
        # the admission prompt-slice size doubles as the serial overlapped-
        # prefill slice size, so it lives on Engine (self._prefill_chunk)
        super().__init__(model_path, prefill_chunk=prefill_chunk, **kw)
        #: prefill-token budget per scheduler wave.  Static when the
        #: admission controller is off (LFKT_ADM_CONTROLLER=0): with short
        #: prompts several COMPLETE admissions fit one wave, and a long
        #: prompt consumes the budget in slices.  With the controller on
        #: (the default) this value is rewritten every wave from the EMA of
        #: measured lane-idle/decode-slack — see AdmissionController.
        self._adm_budget = max(self._prefill_chunk, adm_budget)
        self._adm_base = self._adm_budget      # controller re-init (recover)
        self._adm_alpha = adm_ema_alpha
        self._adm_ctl = AdmissionController(
            self._prefill_chunk, self.batch_size, self._adm_budget,
            alpha=adm_ema_alpha) if adm_controller else None
        #: cumulative idle lane-seconds (free lanes × wave wall), exported
        #: as scheduler_lane_idle_seconds / the lane_idle_seconds gauge
        self._lane_idle_s = 0.0
        self._adm: dict | None = None   # in-flight chunked admission
        # -- lane-prefix reuse (default ON since round 6; the admission
        # -- controller closed the interference gap that kept it off) ------
        # A freed lane's KV ring still holds its finished conversation;
        # when the next admission's prompt shares that history (multi-turn
        # chat re-sends it verbatim, reference api.py:44-63), the claim is
        # snapshot into the scratch cache and only the suffix slices
        # prefill — directly attacking the scheduler's admission-prefill
        # bottleneck.  Reuse is chunk-aligned so the compiled slice-shape
        # set stays closed, skipped for explicit-seed requests (the serial
        # engine's reproducibility contract), and disabled under spec
        # decode (verify rounds leave rejected drafts in lanes).  Claims
        # are capped at n_ctx-1: a freed lane keeps garbage-decoding in
        # the shared batched program, but those writes land at positions
        # past the claim (clamping to slot n_ctx-1 once pos overruns).
        self._lane_prefix = bool(lane_prefix_cache) and not self._spec_draft
        # paged mode (LFKT_KV_PAGED) folds the lane claims behind the
        # shared radix tree: one prefix-reuse implementation per mode (the
        # per-lane claim path remains the dense-ring default).  An
        # admission's reuse must stay aligned to BOTH the prefill slice
        # (every suffix slice shape inside the warmed compiled set) and
        # the page size (pages are the restore grain) — the lcm below.
        if self._kv_paged:
            self._lane_prefix = False
            import math

            self._paged_align = math.lcm(self._prefill_chunk,
                                         self._kvpool.page_tokens)
        self._lane_claims: list[list | None] = [None] * self.batch_size
        #: realized admission reuse, named for the implementation that
        #: served it — "lane_prefix" (dense claims) or "radix_prefix"
        #: (paged pool) — so a paged-vs-dense A/B never shows phantom
        #: activity under the other mode's stat
        self._reuse_stat = "radix_prefix" if self._kv_paged \
            else "lane_prefix"
        self._prefix_stats = {f"{self._reuse_stat}_hits": 0,
                              f"{self._reuse_stat}_reused_tokens": 0}
        self._scratch_cache = init_cache(self.cfg)
        # lfkt-mem: attribute the persistent prefill scratch (the lane
        # state rode MeshEngine's registration; the serial ring the base's)
        register_component("kv_scratch", self, _ledger_scratch_bytes)
        #: previous wave's memory-pressure verdict: the rising edge emits
        #: ONE mem_pressure trace event + counter, not one per wave
        self._mem_hot_prev = False
        base_st = sampling_tensors(SamplingParams())
        self._lane_st = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.batch_size,)), base_st)
        # static top_k ceiling of the shared compiled decode program;
        # per-request k rides as a traced mask (sampling/sample.py) and is
        # effectively min(requested, ceiling)
        self._max_top_k = max(max_top_k, SamplingParams().top_k)
        self._req_counter = 0                # monotonic request id (abandon key)
        # per-lane speculative decoding (VERDICT r3 #7): prompt-lookup
        # drafts per lane, ONE batched verify for all lanes.  Inherits
        # Engine's spec_decode/spec_draft kwargs; _SPEC_LANES suppresses
        # the serial-only warning.
        self._spec_stats = {"verify_steps": 0, "drafted": 0, "accepted": 0,
                            "chunk_steps": 0}
        self._stats = {"lanes_live": 0, "pending": 0, "admission_inflight": 0}
        self._items: dict[int, _Item] = {}   # live request id → item (abandon)
        self._pending: queue_mod.Queue = queue_mod.Queue()
        self._wake = threading.Event()
        self._stop = False
        self._shutdown = False   # deliberate stop: recovery must refuse
        self._loop_error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._loop, name="lfkt-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def submit(self, messages: Sequence[dict], *, temperature: float = 0.2,
               top_p: float = 0.95, top_k: int = 40, min_p: float = 0.05,
               frequency_penalty: float = 0.0, presence_penalty: float = 0.0,
               repeat_penalty: float = 1.1, max_tokens: int | None = None,
               stop: Sequence[str] | str | None = None,
               seed: int | None = None,
               deadline: float | None = None, abort=None,
               trace=None) -> Future:
        """Queue one request; the scheduler admits it to a free lane.

        ``top_k`` is served per-request up to the engine's ``max_top_k``
        ceiling (the static k of the shared compiled program); larger values
        are effectively clamped to the ceiling.  ``deadline`` (absolute
        ``time.time()``) frees the request's lane within one decode chunk
        of expiry, resolving the future with :class:`DeadlineExceeded`.
        ``trace`` (obs.trace.Trace | None) collects the request's span
        tree: pending wait, chunked prefill, per-slot occupancy + decode
        chunks — produced on the scheduler thread."""
        item = self._enqueue(
            messages, temperature=temperature, top_p=top_p, top_k=top_k,
            min_p=min_p, frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty, repeat_penalty=repeat_penalty,
            max_tokens=max_tokens, stop=stop, seed=seed, deadline=deadline,
            abort=abort, trace=trace)
        fut = item.future
        fut._lfkt_req_id = item.rid
        fut.add_done_callback(
            lambda f, rid=item.rid: self._items.pop(rid, None))
        return fut

    def _enqueue(self, messages, *, temperature, top_p, top_k, min_p,
                 frequency_penalty, presence_penalty, repeat_penalty,
                 max_tokens, stop, seed, sink=None, deadline=None,
                 abort=None, trace=None) -> _Item:
        """Shared submit/submit_stream path: guards, param normalization,
        item construction, registry entry, enqueue + scheduler wake."""
        if self._loop_error is not None:
            raise EngineUnavailable("scheduler died") from self._loop_error
        if self._stop:
            raise EngineUnavailable("engine has been shut down")
        sp = SamplingParams(
            temperature=temperature, top_p=top_p, top_k=top_k, min_p=min_p,
            frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty, repeat_penalty=repeat_penalty,
        )
        if isinstance(stop, str):
            stop = [stop]
        item = _Item(None if sink is not None else Future(), list(messages),
                     sp, max_tokens, list(stop or []), seed, sink=sink,
                     deadline=deadline, abort=abort, trace=trace)
        if trace is not None:
            trace.note(deadline=deadline, tokens=0, **self._trace_attrs())
        with self._id_lock:
            self._req_counter += 1
            item.rid = self._req_counter
        # live-request registry: abandon() routes through it, and a watchdog
        # trip fails everything in it (fail_inflight) so no caller hangs on
        # a wedged scheduler.  Futures deregister via their done callback
        # (submit); streams deregister in the consumer generator's finally
        # (submit_stream).
        self._items[item.rid] = item
        self._pending.put(item)
        self._wake.set()
        return item

    def abandon(self, fut: Future) -> None:
        """Tell the scheduler the caller no longer wants ``fut``'s result:
        the request's lane is freed at the next chunk boundary instead of
        decoding to budget (the reference discards abandoned results but its
        serial engine idles anyway, reference api.py:97-100; here an occupied
        lane would delay other requests — VERDICT r1 #6)."""
        rid = getattr(fut, "_lfkt_req_id", None)
        item = self._items.get(rid) if rid is not None else None
        if item is not None:
            item.abandoned.set()

    def submit_stream(self, messages: Sequence[dict], *,
                      temperature: float = 0.2, top_p: float = 0.95,
                      top_k: int = 40, min_p: float = 0.05,
                      frequency_penalty: float = 0.0,
                      presence_penalty: float = 0.0,
                      repeat_penalty: float = 1.1,
                      max_tokens: int | None = None,
                      stop: Sequence[str] | str | None = None,
                      seed: int | None = None,
                      deadline: float | None = None, abort=None,
                      trace=None):
        """Queue one streaming request; returns an iterator of OpenAI chunk
        dicts produced as the request's lane decodes.  Closing the iterator
        abandons the request (its lane frees at the next chunk boundary).
        Defaults match :meth:`submit` (llama-cpp-python 0.2.77's)."""
        sink: queue_mod.Queue = queue_mod.Queue()
        item = self._enqueue(
            messages, temperature=temperature, top_p=top_p, top_k=top_k,
            min_p=min_p, frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty, repeat_penalty=repeat_penalty,
            max_tokens=max_tokens, stop=stop, seed=seed, sink=sink,
            deadline=deadline, abort=abort, trace=trace)

        def gen():
            try:
                while True:
                    chunk = sink.get()
                    if chunk is _STREAM_END:
                        return
                    if isinstance(chunk, BaseException):
                        raise chunk
                    yield chunk
            finally:
                item.abandoned.set()   # no-op if the stream finished cleanly
                self._items.pop(item.rid, None)
        return gen()

    def create_chat_completion(self, messages, stream: bool = False, **kw):
        if stream:  # streams ride scheduler lanes too (concurrent with
            return self.submit_stream(messages, **kw)  # batched requests)
        return self.submit(messages, **kw).result()

    def failure(self) -> BaseException | None:
        """Watchdog hook: the exception that killed the scheduler loop, or
        None while it is (believed) healthy."""
        return self._loop_error

    def fail_inflight(self, exc: BaseException) -> None:
        """Resolve every registered live request with ``exc`` (watchdog
        trip): callers get their 503 NOW instead of hanging on a wedged or
        dead scheduler until their own timeouts fire.  Items are marked
        abandoned so a still-running loop discards their lanes at the next
        harvest instead of double-resolving."""
        for item in list(self._items.values()):
            item.abandoned.set()
            if item.future is not None:
                if not item.future.done():
                    try:
                        item.future.set_exception(exc)
                    except Exception:  # noqa: BLE001 — lost race with the loop
                        pass
            elif item.sink is not None:
                item.sink.put(exc)

    def recover(self) -> bool:  # lfkt: noqa[LOCK002] -- writes scheduler-confined state only after the owning thread is proven dead (join + alive/_loop_error refusal guards above each write)
        """Bounded recovery (engine/watchdog.py): restart a *dead* scheduler
        on rebuilt device state.  Refuses while the loop thread is alive and
        unfailed — a wedged thread may still own the donated buffers, and
        restarting state under it would race; the watchdog then escalates
        to DEAD and the pod restart frees the device.  Also refuses after a
        deliberate :meth:`shutdown` (that is not a fault)."""
        FAULTS.fire("recover")   # injection point: recovery that fails
        if self._shutdown:
            return False
        if self._thread.is_alive() and self._loop_error is None:
            return False
        self._thread.join(timeout=2)
        if self._thread.is_alive():
            return False
        # fallible device re-init FIRST: if it raises (e.g. OOM — a likely
        # condition for recovery to run under), _loop_error must remain set
        # so the watchdog keeps seeing a dead engine and _enqueue keeps
        # refusing — clearing it early would leave a zombie with READY
        # probes and no scheduler thread, queueing every request into a
        # 408 (code-review r2 finding)
        with self._lock:
            self._recover_locked()          # fresh serial ring + batched state
        self._scratch_cache = init_cache(self.cfg)
        base_st = sampling_tensors(SamplingParams())
        self._lane_st = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.batch_size,)), base_st)
        # re-init succeeded: clear the fault signature and restart
        self._loop_error = None
        self._stop = False
        self._adm = None
        self._items.clear()
        self._lane_claims = [None] * self.batch_size
        self._lane_idle_s = 0.0
        if self._adm_ctl is not None:
            # fresh controller: post-recovery traffic should not inherit
            # the pre-crash EMAs (a wedged device reads as max pressure)
            self._adm_ctl = AdmissionController(
                self._prefill_chunk, self.batch_size, self._adm_base,
                alpha=self._adm_alpha)
            self._adm_budget = self._adm_ctl.budget
        else:
            self._adm_budget = self._adm_base
        self._stats = {"lanes_live": 0, "pending": 0, "admission_inflight": 0}
        self.heartbeat.reset()
        self._thread = threading.Thread(
            target=self._loop, name="lfkt-scheduler", daemon=True)
        self._thread.start()
        return True

    def create_chat_completions(self, batch_messages, **kw) -> list[dict]:
        futs = [self.submit(m, **kw) for m in batch_messages]
        out = []
        for f in futs:
            try:
                out.append(f.result())
            except ValueError as e:  # per-request input error, isolated
                out.append({"error": {"message": str(e),
                                      "type": "invalid_request_error"}})
        return out

    def shutdown(self):
        self._shutdown = True
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    def warmup(self):
        """Compile the scheduler's shapes: every admission prefill SLICE
        shape (the scheduler prefills via prefill_chunk_jit, not the serial
        engine's bucket-sized prefill_jit), first-token sampling, the lane
        write, and the batched decode chunk.  Streams ride the same lane
        programs, so one streamed request exercises (but doesn't extend)
        the compiled set."""
        t0 = time.time()
        msgs = [{"role": "user", "content": "hi"}]
        futs = [self.submit(msgs, max_tokens=self.decode_chunk + 1,
                            temperature=0.0)
                for _ in range(self.batch_size)]
        for f in futs:
            f.result()
        list(self.submit_stream(msgs, max_tokens=self.decode_chunk + 1,
                                temperature=0.0))
        if self._spec_draft:
            # compile the batched verify: a repeated-word prompt whose
            # n-gram lookup is guaranteed to hit
            self.submit([{"role": "user", "content": "hi hi hi hi hi hi"}],
                        max_tokens=self._spec_draft + self.decode_chunk + 2,
                        temperature=0.0).result()
        # every slice shape a bucket walk can produce, compiled against a
        # throwaway cache (jit program caches are global, so the scheduler
        # thread hits them warm; its own scratch cache is never touched)
        cache = init_cache(self.cfg)
        for b in self.prefill_buckets:
            off = 0
            while off < b:
                C = min(self._prefill_chunk, b - off)
                _, cache = prefill_chunk_jit(
                    self.params, self.cfg, jnp.zeros((C,), jnp.int32),
                    jnp.int32(off), jnp.int32(C - 1), cache)
                off += C
        if self._lane_prefix:
            # compile the lane→scratch snapshot gather (one program; the
            # suffix slice shapes are already in the warmed set above)
            jax.block_until_ready(_lane_cache_copy_jit(
                self._bstate["cache"], jnp.int32(0)))
        jax.block_until_ready(cache)
        logger.info("continuous warmup done in %.1fs (%d lanes)",
                    time.time() - t0, self.batch_size)

    # ------------------------------------------------------------------
    # scheduler internals (all device work on the scheduler thread)
    # ------------------------------------------------------------------

    # -- admission: a chunked-prefill state machine ---------------------
    # At most one admission is in flight; its prompt prefills in
    # ``prefill_chunk``-token slices, one slice per scheduler iteration, so
    # a 1024-token admission stalls live lanes' decode by ~one slice per
    # chunk boundary instead of a whole bucket (VERDICT r2 weak #4: vLLM's
    # chunked-prefill, TPU-static-shape edition — slice shapes come from
    # the fixed bucket set, so the compiled-program set stays closed).

    def _free_lane(self, lane: int, slot: _Slot, slots: list,
                   claim: bool = True) -> None:
        """Release ``slot``'s lane (no-op if it never occupied one) and
        record which token ids' KV remain valid there for prefix reuse —
        a lane claim in dense mode, a pool commit in paged mode.  The ONE
        place the free-lane invariant lives — every path that finishes a
        slot must come through here.  ``claim=False`` for error finishes
        (a device fault surfaced at fetch means the KV that prefill left
        in the lane is of unknown validity — it must not seed a later
        admission's reuse).

        Claim residency matches the serial engine's prefix cache
        (engine.py::_finish): ring slots [0, n_prompt + len(gens) - 1)
        hold prompt + generated tokens except the last sampled one; the
        pipelined loop's discarded decode of the freed lane writes only
        past that (capped at n_ctx-1 where overrun writes clamp)."""
        if slots[lane] is slot:
            slots[lane] = None
        keep = min(slot.n_prompt + max(len(slot.gens) - 1, 0),
                   self.cfg.n_ctx - 1)
        if self._kv_paged:
            if claim:
                # commit the finished conversation's whole-page prefix to
                # the shared pool straight from the batched lane (the
                # gather+slice+scatter fuse in one program — no lane-ring
                # copy is materialized); already-cached pages dedupe, so a
                # multi-turn follow-up stores only its delta
                self._kvpool.commit_lane(
                    (list(slot.ids) + slot.gens)[:keep],
                    self._bstate["cache"], lane, namespace=self._kv_ns)
            return
        if not self._lane_prefix:
            return
        if not claim:
            self._lane_claims[lane] = None
            return
        self._lane_claims[lane] = (list(slot.ids) + slot.gens)[:keep]

    def _find_lane_reuse(self, ids: list, n_prompt: int):
        """(reuse_len, source_lane) — the longest chunk-aligned usable
        claim prefix across freed lanes, or (0, None).  Chunk alignment
        keeps every suffix slice shape inside the warmed compiled set."""
        best, src = 0, None
        cap = n_prompt - 1   # ≥1 real token must prefill (last-token logits)
        for lane, claim in enumerate(self._lane_claims):
            if claim is None:
                continue
            lim = min(len(claim), cap)
            i = 0
            while i < lim and claim[i] == ids[i]:
                i += 1
            i = (i // self._prefill_chunk) * self._prefill_chunk
            if i > best:
                best, src = i, lane
        if best < self._prefill_chunk:
            return 0, None
        return best, src

    def _resolve_skipped(self, item: _Item, exc: BaseException | None = None
                         ) -> None:
        """Resolve an item the scheduler will never serve (abandoned,
        cancelled, or deadline-expired while queued) so no awaiter hangs."""
        if item.future is not None and not item.future.done():
            if exc is not None:
                try:
                    item.future.set_exception(exc)
                except Exception:  # noqa: BLE001 — lost race: already resolved
                    pass
            elif not item.future.cancel():
                item.future.set_exception(CancelledError())
        elif item.sink is not None:
            item.sink.put(exc if exc is not None else _STREAM_END)

    def _begin_admission(self, item: _Item) -> dict | None:
        """Guards + tokenize + machine setup (no device work yet)."""
        if item.abandoned.is_set():
            self._resolve_skipped(item)
            return None
        if item.abort is not None and item.abort():
            self._resolve_skipped(item)
            return None
        if item.deadline is not None and time.time() > item.deadline:
            # expired while queued: never occupy a lane for a caller that
            # already gave up (deadline propagation, reference-parity 408)
            self._resolve_skipped(item, DeadlineExceeded(
                "request deadline expired before admission"))
            return None
        if item.future is not None and not item.future.set_running_or_notify_cancel():
            return None                                # cancelled while queued
        t0 = time.time()
        pspan = None
        if item.trace is not None:
            # pending: submit -> the scheduler picking this item up
            item.trace.span("pending", t0=item.t_enq).end(t0)
            pspan = item.trace.span("prefill", t0=t0)
        lease = None
        try:
            ids = self.tokenize_messages(item.messages)
            if len(ids) >= self.cfg.n_ctx:
                raise ValueError(
                    f"Requested tokens ({len(ids)}) exceed context window "
                    f"of {self.cfg.n_ctx}")
            bucket = self._bucket_for(len(ids))
            # disagg decode role (serving/disagg/): one bounded remote-
            # prefill hop per admission — the peer's pages import into
            # the shared pool, so _paged_admission_reuse below restores
            # them and the suffix slices are all this wave prefills
            # locally (the "decode-only waves" shape).  Role off is one
            # attribute read; the client bounds the hop by the item's
            # deadline and degrades every failure to local prefill.
            if self._disagg is not None and item.seed is None:
                self._remote_prefill_ids(ids, item.deadline, pspan)
            reuse, src = 0, None
            if item.seed is None:
                # explicit seeds take the full prefill: the suffix pass
                # scores bf16-rounded reused KV, so a near-tied logit could
                # flip — same reproducibility contract as the serial engine
                if self._kv_paged:
                    reuse, lease = self._paged_admission_reuse(ids, pspan)
                elif self._lane_prefix:
                    reuse, src = self._find_lane_reuse(ids, len(ids))
            if lease is not None:
                # restore the matched pages straight into the scratch ring
                # (donated in place — no transient second ring, unlike the
                # lane snapshot below); the suffix slices then prefill
                # from offset ``reuse`` exactly like a lane-claim hit.
                # The scratch ref is dropped across the donating call: a
                # mid-copy failure must not leave a dead donated buffer
                # as self._scratch_cache (_dispatch_prefill_chunk
                # re-creates on None, same as the lane-snapshot path)
                scratch, self._scratch_cache = self._scratch_cache, None
                if scratch is None:
                    scratch = init_cache(self.cfg)
                self._scratch_cache = self._kvpool.restore(
                    lease, scratch, span=pspan)
            elif reuse:
                # snapshot the source lane's ring as this admission's
                # scratch; the functional gather captures the lane BEFORE
                # any later decode writes, so the claim region is stable.
                # Drop the old scratch FIRST: holding it across the copy
                # peaks HBM one full lane-ring higher, which is what tipped
                # the 8-lane 8B prefill arm into ResourceExhausted on 16 GB
                # (suite3 2026-08-01).  If the copy itself fails, scratch
                # stays None and _dispatch_prefill_chunk lazily re-creates
                # it — allocating a replacement HERE, inside the failure,
                # would be a second allocation on the same exhausted HBM.
                self._scratch_cache = None
                self._scratch_cache = _lane_cache_copy_jit(
                    self._bstate["cache"], jnp.int32(src))
                # stats are counted in _finish_admission: an item abandoned
                # mid-prefill (or failing later) must not inflate /metrics
            if pspan is not None:
                pspan.set(n_prompt=len(ids), bucket=bucket, reused=reuse)
            # host-side slice prep happens ONCE, here, while lanes decode:
            # one int32 array for the padded prompt; every slice dispatch
            # then takes a zero-copy view instead of re-converting a list
            # (the round-6 overlap of slice prep with device compute)
            padded = np.zeros((bucket,), np.int32)
            padded[:len(ids)] = ids
            return {
                "item": item, "ids": ids, "n_prompt": len(ids),
                "bucket": bucket,
                "padded": padded,
                "st": sampling_tensors(item.sp),
                "seed": item.seed if item.seed is not None else self._next_seed(),
                "t0": t0, "offset": reuse, "reused": reuse, "logits": None,
                "span": pspan, "lease": lease,
            }
        except Exception as e:  # noqa: BLE001 — per-request isolation
            self._note_error(e)
            if lease is not None:
                self._kvpool.release(lease)
            if item.future is not None:
                item.future.set_exception(e)
            elif item.sink is not None:
                item.sink.put(e)
            return None

    def _paged_admission_reuse(self, ids: list, pspan=None):
        """(reuse_tokens, lease | None): the longest cached whole-page
        prefix aligned to ``_paged_align``, pinned.  No bucket constraint
        (admissions prefill in slices from the reuse offset) — the same
        cap and alignment contract as :meth:`_find_lane_reuse`, against
        the process-wide radix index instead of per-lane claims."""
        pool = self._kvpool
        i = min(pool.match_len(ids, namespace=self._kv_ns), len(ids) - 1)
        r = (i // self._paged_align) * self._paged_align
        if r < self._paged_align:
            pool.note_miss()
            return 0, None
        lease = pool.acquire(ids, r, span=pspan, namespace=self._kv_ns)
        if lease is None:      # raced an eviction / spill-restore failed
            return 0, None
        if pspan is not None:
            # guarded: between acquire and the handoff below, a raising
            # span setter is the ONE thing that could leak the pinned
            # pages — _begin_admission's cleanup releases its own `lease`
            # local, which is still None while this call is on the stack
            # (found by lfkt-lint RES001; regression-pinned in
            # tests/test_kv_paged_engines.py)
            try:
                pspan.set(reused_pages=len(lease.page_ids),
                          matched_tokens=i)
            except Exception:  # noqa: BLE001 — telemetry must never pin pages
                pass
        return r, lease

    def _release_adm_lease(self, adm) -> None:
        """Unpin an admission's pool pages (idempotent: the lease is
        consumed from the machine dict) — called from every admission
        exit: finish, abandon, dispatch failure."""
        lease = adm.pop("lease", None) if adm else None
        if lease is not None:
            self._kvpool.release(lease)

    def _dispatch_prefill_chunk(self, adm: dict) -> None:
        """Run ONE prompt slice through the model into the scratch cache.
        Keeps the logits of the slice containing the last real token.

        The dispatch is async — its host wall (observed into the
        ``prefill_slice_seconds`` histogram and the span's per-slice
        event) is slice prep + device enqueue, overlapping the previous
        slice's / decode chunk's compute; a long wall here means the
        device queue pushed back (the interference signal the admission
        controller is closing)."""
        t_s = time.time()
        self.heartbeat.beat()
        FAULTS.fire("prefill")
        if self._scratch_cache is None:
            # a failed lane snapshot (_begin_admission reuse path) dropped
            # the scratch; re-create it now that the failing allocation is
            # gone.  Prefill needs no zeroing: positions past the prompt
            # are never attended.
            self._scratch_cache = init_cache(self.cfg)
        off = adm["offset"]
        C = min(self._prefill_chunk, adm["bucket"] - off)
        sl = jnp.asarray(adm["padded"][off:off + C])
        li = min(max(adm["n_prompt"] - 1 - off, 0), C - 1)
        logits, cache = prefill_chunk_jit(
            self.params, self.cfg, sl, jnp.int32(off), jnp.int32(li),
            self._scratch_cache)
        self._scratch_cache = cache
        if off <= adm["n_prompt"] - 1 < off + C:
            adm["logits"] = logits
        adm["offset"] = off + C
        dt = time.time() - t_s
        self._observe_slice(dt)
        if adm.get("span") is not None:
            adm["span"].event("prefill_slice", offset=off, tokens=C,
                              host_s=round(dt, 6))

    def _finish_admission(self, adm: dict, lane: int, slots: list) -> None:
        """Prefill complete: sample the first token, write the lane, install.

        When other lanes are decoding, the first-token fetch is DEFERRED
        (async copy now, materialized at the slot's first harvest): a
        blocking ``int(token)`` here drains the whole queued device
        pipeline through the dispatch round-trip on every admission, which
        under churn serializes the loop and starves live lanes (measured:
        batch-4 aggregate throughput below a single lane's).  With no live
        lanes nothing is starved, so the synchronous path keeps the
        tightest TTFT for unloaded traffic."""
        item = adm["item"]
        try:
            ids, n_prompt, st = adm["ids"], adm["n_prompt"], adm["st"]
            self._lane_claims[lane] = None   # lane overwritten below
            window, wpos = seed_window(ids)
            token, window, wpos, key = sample_jit(
                adm["logits"], window, wpos, jax.random.PRNGKey(adm["seed"]),
                st, self.cfg, top_k=self._max_top_k)
            self._bstate, self._lane_st = _write_lane(
                self._bstate, self._lane_st, jnp.int32(lane),
                self._scratch_cache, jnp.int32(n_prompt), token, window,
                wpos, key, st)

            budget = min(self._token_budget(item.max_tokens, n_prompt),
                         max(0, self.cfg.n_ctx - 1 - n_prompt))
            slot = _Slot(item, budget, n_prompt, ids)
            slot.stops = item.stops
            slot.st = st
            slot.sp = item.sp
            slot.t_admit = adm["t0"]
            slot.pspan = adm.get("span")
            slot.reused = adm.get("reused", 0)
            if slot.reused:     # count only realized reuse (lane written)
                self._prefix_stats[f"{self._reuse_stat}_hits"] += 1
                self._prefix_stats[
                    f"{self._reuse_stat}_reused_tokens"] += slot.reused
            if any(s is not None for s in slots):
                try:
                    token.copy_to_host_async()
                except Exception:  # noqa: BLE001 — optional fast path
                    pass
                slot.first_token = token        # device array
                slot.ttft_s = None              # set at materialize
                slot.pending_first = True
                self._open_decode_span(lane, slot)
                slots[lane] = slot
                return
            slot.first_token = int(token)   # host sync: prefill done = TTFT
            slot.ttft_s = time.time() - adm["t0"]
            self._end_prefill_span(slot)
            if slot.sink is not None:       # stream: open the chunk stream
                slot.sink.put(self._chunk(slot, {"role": "assistant"}))
            self._install(lane, slots, slot)
        except Exception as e:  # noqa: BLE001 — per-request isolation
            self._note_error(e)
            if adm.get("span") is not None:
                adm["span"].set(error=str(e)).end()
            if item.future is not None and not item.future.done():
                item.future.set_exception(e)
            elif item.sink is not None:
                item.sink.put(e)
        finally:
            # the lease's job ends once the restored scratch has been
            # written into the lane (or the admission failed): unpin so
            # the pages become evictable again
            self._release_adm_lease(adm)

    def _end_prefill_span(self, slot: _Slot) -> None:
        """Close the admission's ``prefill`` span at TTFT.  Idempotent —
        the deadline/abandon path in _harvest re-runs it after a normal
        close, and the tokens=1 note must not clobber the per-chunk token
        counts recorded since — so the span reference is consumed here."""
        if slot.pspan is not None:
            if slot.ttft_s is not None:
                slot.pspan.set(ttft_s=round(slot.ttft_s, 6))
            slot.pspan.end()
            slot.pspan = None
            slot.trace.note(tokens=1)

    def _materialize_first(self, lane: int, slot: _Slot, slots: list) -> None:
        """Deferred-admission bookkeeping, run at the slot's first harvest
        (its sample landed before the chunk just fetched, so this fetch
        does not wait on new device work): first-token value, TTFT, stream
        open, first stop/budget checks."""
        slot.pending_first = False
        try:
            slot.first_token = int(slot.first_token)
        except Exception as e:  # noqa: BLE001 — per-request isolation
            self._note_error(e)
            slot.finished = True
            self._end_prefill_span(slot)
            self._free_lane(lane, slot, slots, claim=False)
            if slot.sink is not None:
                slot.sink.put(e)
            elif not slot.future.done():
                slot.future.set_exception(e)
            return
        slot.ttft_s = time.time() - slot.t_admit
        self._end_prefill_span(slot)
        if slot.sink is not None:
            slot.sink.put(self._chunk(slot, {"role": "assistant"}))
        self._install(lane, slots, slot)

    def _open_decode_span(self, lane: int, slot: _Slot) -> None:
        """Start the slot's lane-occupancy ``decode`` span when it takes a
        lane; per-chunk children hang off it at every harvest.  Idempotent:
        a deferred admission passes here twice (lane assignment in
        _finish_admission, then _install at first harvest) and must not
        leak a second, never-ended span."""
        if slot.trace is not None and slot.dspan is None:
            slot.trace.note(lane=lane)
            slot.dspan = slot.trace.span("decode").set(lane=lane)
            slot.t_chunk = time.time()

    def _close_decode_span(self, slot: _Slot, finish: str) -> None:
        if slot.dspan is not None:
            slot.dspan.set(finish=finish, tokens=len(slot.gens))
            slot.dspan.end()
            slot.dspan = None

    def _chunk(self, slot: _Slot, delta: dict, finish=None) -> dict:
        return {
            "id": slot.cid,
            "object": "chat.completion.chunk",
            "created": slot.created,
            "model": self.model_name,
            "choices": [{
                "index": 0, "delta": delta, "finish_reason": finish,
            }],
        }

    def _emit_stream(self, slot: _Slot, done: bool) -> str | None:
        """Push the newly decoded text increment to the stream sink.  Returns
        "stop" if a stop string was hit (caller finishes the slot)."""
        bts = self.tokenizer.decode_bytes(slot.gens)
        text = bts.decode("utf-8", errors="replace")
        cut = self._find_stop_str(text, slot.stops)
        hit = cut != -1
        if hit:
            text = text[:cut]
        if done or hit:             # flush: emit exactly up to the final text
            if len(text) > slot.n_emitted:
                slot.sink.put(
                    self._chunk(slot, {"content": text[slot.n_emitted:]}))
                slot.n_emitted = len(text)
        else:
            slot.held += slot.dec.decode(bts[slot.sent_bytes:])
            slot.sent_bytes = len(bts)
            hold = self._stop_prefix_holdback(slot.held, slot.stops)
            ready = slot.held[:len(slot.held) - hold]
            slot.held = slot.held[len(slot.held) - hold:]
            if ready:
                slot.sink.put(self._chunk(slot, {"content": ready}))
                slot.n_emitted += len(ready)
        return "stop" if hit else None

    def _slot_timings(self, slot: _Slot) -> dict:
        decode_s = time.time() - slot.t_admit - slot.ttft_s
        n = len(slot.gens)
        return {
            "ttft_s": slot.ttft_s, "decode_s": decode_s,
            "prompt_tokens": slot.n_prompt, "completion_tokens": n,
            "prefix_reused_tokens": slot.reused,
            # prompt bucket for the per-bucket TTFT series (obs/slo.py)
            "bucket": self._bucket_for(slot.n_prompt),
            # model label for the per-model metric series (multi-model)
            "model": self.model_name,
            "tokens_per_sec": (n - 1) / decode_s
            if n > 1 and decode_s > 0 else 0.0,
        }

    def _finish_slot(self, slot: _Slot, finish: str):
        slot.finished = True
        timings = self._slot_timings(slot)
        self._record_timings(timings)
        self._close_decode_span(slot, finish)
        if slot.sink is not None:
            hit = self._emit_stream(slot, done=True)
            final = self._chunk(slot, {}, finish=hit or finish)
            final["lfkt_timings"] = timings
            slot.sink.put(final)
            slot.sink.put(_STREAM_END)
            return
        text = self._decode_text(slot.gens)
        cut = self._find_stop_str(text, slot.stops)
        if cut != -1:
            text = text[:cut]
            finish = "stop"
        if slot.future.done():
            # resolved externally (watchdog fail_inflight / deadline) while
            # this chunk was in flight: the result has nowhere to go
            return
        slot.future.set_result({
            "lfkt_timings": timings,
            "id": slot.cid,
            "object": "chat.completion",
            "created": slot.created,
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish,
            }],
            "usage": {
                "prompt_tokens": slot.n_prompt,
                "completion_tokens": len(slot.gens),
                "total_tokens": slot.n_prompt + len(slot.gens),
            },
        })

    def _install(self, lane: int, slots: list, slot: _Slot) -> None:
        """Post-prefill bookkeeping for a freshly admitted slot: first-token
        stop/budget checks, stream open, and lane assignment."""
        stop_ids = self.tokenizer.stop_ids
        first = slot.first_token
        if slot.budget <= 0:
            self._finish_slot(slot, "length")
        elif first in stop_ids:
            self._finish_slot(slot, "stop")
        else:
            slot.gens.append(first)
            if len(slot.gens) >= slot.budget:
                self._finish_slot(slot, "length")
            elif (slot.sink is not None
                  and self._emit_stream(slot, done=False) == "stop"):
                self._finish_slot(slot, "stop")
            else:
                self._open_decode_span(lane, slot)
                slots[lane] = slot
        if slot.finished:
            # finished at install (or never occupied the lane): its prompt
            # KV is a valid reuse claim (the first sampled token's KV was
            # never fed/written)
            self._free_lane(lane, slot, slots)

    def _admit_step(self, slots: list) -> int | None:
        """One unit of admission progress: begin the next queued item (and
        dispatch its first prefill slice), or dispatch the in-flight
        admission's next slice — finishing it (sample + lane write) when the
        last slice lands.  Returns the number of prefill tokens dispatched
        (0 for bookkeeping-only progress), or None when there is nothing
        to do."""
        if self._adm is None:
            if not any(s is None for s in slots):
                return None                     # no free lane to admit into
            try:
                item = self._pending.get_nowait()
            except queue_mod.Empty:
                return None
            self._adm = self._begin_admission(item)
            if self._adm is None:
                return 0                        # item resolved/skipped: progress
        adm = self._adm
        if adm["item"].abandoned.is_set():       # caller gave up mid-prefill
            if adm.get("span") is not None:
                adm["span"].set(abandoned=True).end()
            self._release_adm_lease(adm)
            self._resolve_skipped(adm["item"])
            self._adm = None
            return 0
        off_before = adm["offset"]
        try:
            self._dispatch_prefill_chunk(adm)
        except Exception as e:  # noqa: BLE001 — per-request isolation: a
            item = adm["item"]  # failed admission must not kill the scheduler
            self._adm = None
            self._release_adm_lease(adm)
            self._note_error(e)
            if adm.get("span") is not None:
                adm["span"].set(error=str(e)).end()
            if item.future is not None:
                item.future.set_exception(e)
            elif item.sink is not None:
                item.sink.put(e)
            return 0
        # stop at the slice containing the last REAL token: pure-padding
        # slices would only write cache garbage decode overwrites anyway,
        # while costing one scheduler iteration of TTFT each under load
        if adm["offset"] >= adm["n_prompt"]:
            self._adm = None
            lane = next(i for i, s in enumerate(slots) if s is None)
            self._finish_admission(adm, lane, slots)
        return adm["offset"] - off_before

    def _admit_round(self, slots: list) -> bool:
        """Admissions for ONE scheduler wave: admission progress — complete
        short admissions AND successive slices of one long prompt — is
        taken until the per-wave prefill-token budget runs out or the
        lanes/queue are exhausted.  At most one admission is ever
        mid-prompt, so prefill slices of different requests never
        interleave on the device queue and the single scratch cache stays
        safe: a completed admission's lane write is dispatched BEFORE the
        next admission's first slice.  With the admission controller ON a
        long prompt advances by up to ``budget`` tokens per wave (round 5
        advanced exactly one slice per wave regardless of budget, which
        put a 32k admission ~128 decode waves away from its first token);
        the controller shrinks the budget back toward one slice when that
        interleaving pressures live lanes' decode.  With the controller
        OFF (LFKT_ADM_CONTROLLER=0) a mid-prompt admission still yields
        after ONE slice — the static mode IS the pre-round-6 behavior,
        so it stays a valid A/B control arm (nothing then adapts the
        budget down if a big static number turned out to stall decode).
        Returns True if any progress was made."""
        budget = self._adm_budget
        progressed = False
        while budget > 0:
            spent = self._admit_step(slots)
            if spent is None:
                break
            progressed = True
            budget -= spent
            if self._adm is not None and self._adm_ctl is None:
                break   # static mode: long admission yields after one slice
        return progressed

    def _note_mem_pressure(self) -> None:
        """Rising edge of the HBM-pressure signal: count it and stamp
        every in-flight trace with the headroom numbers — the budget cuts
        this wave starts are then self-explaining in the waterfall
        (tools/trace_report.py renders mem_pressure with byte counts)."""
        hr = _memledger.MEMLEDGER.last_headroom
        attrs = {}
        if hr is not None:
            attrs = {"headroom_bytes": hr[0], "limit_bytes": hr[1]}
        logger.warning(
            "HBM memory pressure: admission budget cut (headroom %s of "
            "%s bytes — docs/RUNBOOK.md 'Diagnosing HBM OOM')",
            attrs.get("headroom_bytes", "?"), attrs.get("limit_bytes", "?"))
        annotate_all_inflight("mem_pressure", **attrs)
        m = self.metrics_sink
        if m is not None:
            try:
                m.inc("mem_pressure_events_total")
            except Exception:  # noqa: BLE001 — telemetry must never fail serving
                pass

    def scheduler_stats(self) -> dict:
        """Point-in-time scheduler occupancy for ``/metrics`` (lanes_live,
        pending queue depth, whether an admission prefill is in flight,
        the live admission budget and its controller EMAs, cumulative
        lane-idle seconds) — the observability the lane model adds over
        the reference's single queue-depth number.  Written once per loop
        iteration; reads are a dict swap, no lock needed."""
        out = {"batch_size": self.batch_size, **self._stats}
        if self._lane_prefix or self._kv_paged:
            out.update(self._prefix_stats)
        if self._spec_draft:
            out["spec"] = dict(self._spec_stats)
        return out

    def _harvest(self, pre: list, chunk: "np.ndarray", slots: list,
                 counts: "np.ndarray | None" = None) -> None:
        """Fold one fetched decode chunk into its lanes' slots.

        ``pre`` is the lane snapshot taken when the chunk was DISPATCHED —
        with the pipelined loop that is one iteration ago, so a lane's slot
        may have finished (budget/stop found in the previous chunk) while
        this chunk was already in flight on the device; those rows are
        discarded (``slot.finished``).  Abandoned requests (client timeout /
        disconnect) free their lane here instead of decoding to budget:
        unlike the reference's serial engine (api.py:97-100, where a
        discarded generation delays nobody), an occupied lane would hold up
        waiting requests.

        ``counts`` (spec-verify rounds): lane ``l`` emitted only
        ``chunk[:counts[l], l]`` — rows beyond that are samples conditioned
        on rejected draft tokens and must be discarded."""
        stop_ids = self.tokenizer.stop_ids
        now = time.time()
        for lane in range(len(pre)):
            slot = pre[lane]
            if slot is None or slot.finished:
                continue
            expired = slot.deadline is not None and now > slot.deadline
            if expired or slot.abandoned.is_set() or (
                    slot.abort is not None and slot.abort()) or (
                    slot.future is not None and slot.future.cancelled()):
                # checked BEFORE materializing a deferred first token: an
                # abandoned slot's stream would otherwise be opened (role
                # chunk nobody reads) at the cost of a blocking int() fetch.
                # Deadline expiry rides the same path: the lane frees at
                # this chunk boundary instead of decoding to budget.
                slot.finished = True
                exc = DeadlineExceeded(
                    "request deadline expired mid-generation") if expired \
                    else None
                self._end_prefill_span(slot)
                self._close_decode_span(
                    slot, "deadline" if expired else "abandoned")
                if slot.sink is not None:
                    slot.sink.put(exc if exc is not None else _STREAM_END)
                elif not slot.future.done():
                    # resolve so a caller still awaiting (e.g. via
                    # asyncio.wrap_future) unblocks as cancelled/timed out
                    if exc is not None:
                        slot.future.set_exception(exc)
                    else:
                        slot.future.set_exception(CancelledError())
                self._free_lane(lane, slot, slots)
                continue
            if slot.pending_first:
                # deferred admission: its sample was queued before the chunk
                # just fetched — materialize the first token now, then fold
                # in this chunk's rows (its tokens 2..n for this lane)
                self._materialize_first(lane, slot, slots)
                if slot.finished:
                    continue
            finish = None
            col = chunk[:, lane]
            if counts is not None:
                col = col[: int(counts[lane])]
            for t in col.tolist():
                if t in stop_ids:
                    finish = "stop"
                    break
                slot.gens.append(t)
                if len(slot.gens) >= slot.budget:
                    finish = "length"
                    break
            if slot.dspan is not None:
                slot.dspan.child("decode_chunk", t0=slot.t_chunk).set(
                    tokens=len(slot.gens),
                    kind="verify" if counts is not None else "chunk").end(now)
                slot.t_chunk = now
                slot.trace.note(tokens=len(slot.gens))
            if finish is not None:
                self._finish_slot(slot, finish)
                self._free_lane(lane, slot, slots)
            elif slot.sink is not None:
                if self._emit_stream(slot, done=False) == "stop":
                    self._finish_slot(slot, "stop")
                    self._free_lane(lane, slot, slots)

    def _spec_drafts(self, slots: list) -> "tuple | None":
        """(drafts (B, D) int32, hit_lanes) — zero rows for lanes with no
        n-gram hit, no capacity, or no slot (they advance by one true
        sample).  None when NO lane has a hit: the plain pipelined chunk
        path is strictly better then (a zero-draft verify emits 1 token
        per weight pass AND forfeits the one-chunk-deep pipeline)."""
        D = self._spec_draft
        drafts = np.zeros((self.batch_size, D), np.int32)
        hits = []
        for lane, slot in enumerate(slots):
            if slot is None or slot.finished:
                continue
            # cache capacity: the batched verify writes D+1 K/V slots at
            # EVERY live lane's pos (zero-draft lanes included).  A lane
            # past this bound would have its dynamic_update_slice start
            # clamped, overwriting real earlier cache slots with K/V
            # RoPE'd for later positions — so one such lane vetoes spec
            # rounds entirely (the chunk path serves it safely).  +2
            # margin covers a pending_first lane's un-materialized token.
            pos = slot.n_prompt + len(slot.gens)
            if pos + D + 2 >= self.cfg.n_ctx:
                return None
            if slot.pending_first:
                continue
            if slot.budget - len(slot.gens) <= 1:
                continue
            d = Engine._lookup_draft(list(slot.ids) + slot.gens, D)
            if d is not None:
                drafts[lane] = d
                hits.append(lane)
        return (drafts, hits) if hits else None

    def _spec_round(self, slots: list, got: tuple) -> None:
        """One batched verify step for every live lane (pipeline already
        flushed by the caller; ``got`` = the precomputed drafts): dispatch,
        overlap admissions, then fetch per-lane emitted prefixes.
        Telemetry mirrors the serial engine's acceptance counters
        (accepted/drafted is THE pays-or-not number)."""
        drafts, hits = got
        pre = list(slots)
        self._bstate, toks, cnts = batched_spec_verify_perlane_jit(
            self.params, self.cfg, self._bstate, self._lane_st,
            jnp.asarray(drafts), top_k=self._max_top_k)
        self._admit_round(slots)         # overlap admissions with the verify
        cnts = np.asarray(cnts)
        self._harvest(pre, np.asarray(toks).T, slots, counts=cnts)
        self._spec_stats["verify_steps"] += 1
        self._spec_stats["drafted"] += self._spec_draft * len(hits)
        self._spec_stats["accepted"] += int(
            sum(max(0, int(cnts[l]) - 1) for l in hits))

    def _loop(self):
        B = self.batch_size
        slots: list[_Slot | None] = [None] * B
        pending = None   # (lane snapshot, un-fetched device tokens)
        t_prev_wave = time.time()   # decode-wave clock (controller signals)
        try:
            while not self._stop:
                if not any(s is not None for s in slots) and pending is None:
                    # nothing decoding: admission prefills stall nobody;
                    # drive the machine at full speed until a lane fills
                    progressed = False
                    while not any(s is not None for s in slots):
                        if self._admit_step(slots) is None:
                            break
                        progressed = True
                    if not any(s is not None for s in slots):
                        if not progressed:
                            self._wake.wait(timeout=0.05)
                            self._wake.clear()
                        continue
                    t_prev_wave = time.time()   # lanes just filled: new wave

                # ---- one decode chunk for every live lane (per-lane sampling
                # knobs incl. traced top_k ride in self._lane_st; the static
                # k is the engine-wide ceiling).  Dispatch is async AND
                # pipelined one chunk deep: this chunk queues on the device
                # BEFORE the previous chunk's tokens are fetched, so the
                # host round-trip (dispatch latency; ~72 ms on the tunneled
                # bench device) overlaps device compute instead of
                # serializing with it.  Cost of the pipeline: a lane whose
                # request finished in the previous chunk decodes one extra
                # chunk before being freed (its rows are discarded), and an
                # admission lands one chunk later.
                # ---- speculative rounds (spec_decode="lookup"): when any
                # live lane's history has an n-gram hit, flush the pipeline
                # (drafts need current host-side history), then run batched
                # verify steps — NOT pipelined: the next drafts depend on
                # this round's accepted tokens, so each verify pays the
                # dispatch round-trip in exchange for multi-token steps.
                if self._spec_draft and any(s is not None for s in slots):
                    got = self._spec_drafts(slots)
                    if got is not None and pending is not None:
                        self._harvest(pending[0], np.asarray(pending[1]),
                                      slots)
                        pending = None
                        got = self._spec_drafts(slots)  # histories advanced
                    while not self._stop and got is not None:
                        self._spec_round(slots, got)
                        got = self._spec_drafts(slots)

                if any(s is not None for s in slots):
                    pre = list(slots)   # lanes live in THIS chunk
                    FAULTS.fire("decode_step")
                    self._bstate, toks = batched_generate_chunk_perlane_jit(
                        self.params, self.cfg, self._bstate, self._lane_st,
                        n_steps=self.decode_chunk, top_k=self._max_top_k)
                    self._spec_stats["chunk_steps"] += 1
                    dispatched = (pre, toks)
                else:
                    dispatched = None

                # ---- overlap: admission prefills run while the chunk
                # executes, up to the per-iteration token budget (several
                # complete short admissions, or one slice of a long one);
                # each lane write queues after the dispatched chunks, and an
                # admitted request's tokens start with the chunk dispatched
                # NEXT iteration (pre[] snapshots who gets each chunk's
                # rows).  Chunked prefill bounds the per-iteration stall to
                # the budget even for full-bucket prompts.
                self._admit_round(slots)

                # ---- harvest the PREVIOUS chunk (fetch blocks only until
                # that chunk is done; the one dispatched above keeps the
                # device busy meanwhile).  The fetch's blocking time IS the
                # decode-pressure signal: a long wait means the device was
                # still decoding when the host came back (admission slices
                # queued this wave delay the NEXT chunk, surfacing here one
                # wave later); a near-zero wait means the device sat idle —
                # the admission controller converts that slack into budget.
                fetch_wait = 0.0
                if pending is not None:
                    t_f = time.time()
                    chunk_np = np.asarray(pending[1])
                    fetch_wait = time.time() - t_f
                    self._harvest(pending[0], chunk_np, slots)
                now = time.time()
                wave_s = max(now - t_prev_wave, 0.0)
                t_prev_wave = now
                mem_hot = False
                if dispatched is not None:
                    live_wave = sum(s is not None for s in dispatched[0])
                    # idle lane-seconds: free lanes while others decode are
                    # lost throughput (the admission controller's raw signal)
                    self._lane_idle_s += (B - live_wave) * wave_s
                    if self._adm_ctl is not None:
                        # HBM headroom joins the wave signals (lfkt-mem):
                        # disarmed/stat-less, pressure() is one attribute
                        # read returning False — nothing on this path
                        # allocates (poisoned-ledger pin)
                        mem_hot = _memledger.MEMLEDGER.pressure()
                        self._adm_budget = self._adm_ctl.observe_wave(
                            live_wave, fetch_wait, wave_s,
                            mem_pressure=mem_hot)
                        if mem_hot and not self._mem_hot_prev:
                            self._note_mem_pressure()
                        self._mem_hot_prev = mem_hot
                pending = dispatched
                stats = {
                    "lanes_live": sum(s is not None for s in slots),
                    "pending": self._pending.qsize(),
                    "admission_inflight": int(self._adm is not None),
                    "adm_budget_tokens": self._adm_budget,
                    "lane_idle_seconds": round(self._lane_idle_s, 3),
                    "mem_pressure": int(mem_hot),
                }
                if self._adm_ctl is not None:
                    stats.update(self._adm_ctl.stats())
                self._stats = stats
                # watchdog pulse: a beat per loop iteration, busy = queued +
                # occupied work.  A loop wedged inside a device call stops
                # beating with busy > 0 — the stall signature.
                self.heartbeat.beat()
                self.heartbeat.set_busy(
                    self._stats["lanes_live"] + self._stats["pending"]
                    + self._stats["admission_inflight"])
        except BaseException as e:  # noqa: BLE001 — fail all, loudly
            self._loop_error = e
            self.heartbeat.record_error(e)
            logger.exception("scheduler loop died")
        finally:
            # graceful stop AND crash both resolve every outstanding request:
            # a caller blocked in Future.result() or sink.get() must not hang
            err = self._loop_error or RuntimeError("engine has been shut down")
            if self._adm is not None:       # admission mid-prefill: resolve it
                item = self._adm["item"]
                self._release_adm_lease(self._adm)
                self._adm = None
                if item.sink is not None:
                    item.sink.put(err if self._loop_error else _STREAM_END)
                elif not item.future.done():
                    item.future.set_exception(err)
            for s in slots:
                if s is None:
                    continue
                if s.sink is not None:
                    s.sink.put(err if self._loop_error else _STREAM_END)
                elif not s.future.done():
                    s.future.set_exception(err)
            while True:
                try:
                    item = self._pending.get_nowait()
                except queue_mod.Empty:
                    break
                if item.sink is not None:
                    item.sink.put(err if self._loop_error else _STREAM_END)
                elif not item.future.done() and not item.future.cancel():
                    item.future.set_exception(err)
            # zero the occupancy gauges LAST (after the drain): a dead loop
            # must not keep reporting pre-crash lanes_live/pending/
            # admission_inflight to /metrics, masking the outage from
            # dashboards built on them
            self._stats = {"lanes_live": 0, "pending": 0,
                           "admission_inflight": 0}
            self.heartbeat.set_busy(0)

"""Continuous batching: slot-based scheduling over the mesh-batched engine.

:class:`MeshEngine` coalesces requests into *cycles* — everyone admitted
together, nobody new until the whole cycle drains.  This module removes the
barrier: the batch's B lanes become **slots**; at every decode-chunk boundary
finished lanes are freed and waiting requests are admitted into them
(single-sequence prefill into a scratch cache, then a jit'd lane write into
the batched state).  Decode keeps running for whatever lanes are live, so
short requests exit early and long ones never block admission — the
vLLM-style serving loop, TPU-native: static shapes throughout, one compiled
program per (bucket | chunk | lane-write) shape, batch dim sharded over
``dp`` and the model over ``tp``.

The reference's concurrency model (one generation at a time behind
Queue(5)+Semaphore(1), reference api.py:110-116) is the degenerate B=1 case;
back-pressure (503) and per-request timeouts stay at the server layer.
"""

from __future__ import annotations

import functools
import logging
import queue as queue_mod
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import prefill_jit, sample_jit
from ..models.llama import init_cache
from ..parallel.batched import batched_generate_chunk_perlane_jit
from ..sampling.sample import SamplingParams, sampling_tensors, seed_window
from .batched import MeshEngine
from .engine import Engine

logger = logging.getLogger(__name__)


@functools.partial(jax.jit, donate_argnames=("state", "lane_st"))
def _write_lane(state: dict, lane_st: dict, lane: jax.Array, cache1: dict,
                pos, token, window, wpos, key, st: dict):
    """Install a freshly prefilled sequence into batch lane ``lane``.
    ``cache1`` is NOT donated — the scheduler reuses it as the next
    admission's prefill scratch (no per-request cache allocation)."""
    new_cache = {
        "k": state["cache"]["k"].at[lane].set(cache1["k"]),
        "v": state["cache"]["v"].at[lane].set(cache1["v"]),
    }
    new_state = {
        "cache": new_cache,
        "pos": state["pos"].at[lane].set(pos),
        "token": state["token"].at[lane].set(token),
        "window": state["window"].at[lane].set(window),
        "wpos": state["wpos"].at[lane].set(wpos),
        "key": state["key"].at[lane].set(key),
    }
    new_lane_st = jax.tree.map(
        lambda a, v: a.at[lane].set(v), lane_st, st)
    return new_state, new_lane_st


class _Slot:
    __slots__ = ("future", "gens", "budget", "n_prompt", "ids",
                 "first_token", "stops", "st", "sp", "t_admit", "ttft_s")

    def __init__(self, future, budget, n_prompt, ids):
        self.future = future
        self.gens: list[int] = []
        self.budget = budget
        self.n_prompt = n_prompt
        self.ids = ids


class ContinuousEngine(MeshEngine):
    """MeshEngine + a background scheduler thread with per-lane admission.

    Use :meth:`submit` (returns a ``concurrent.futures.Future`` resolving to
    the OpenAI-shaped dict) or the blocking ``create_chat_completion`` /
    ``create_chat_completions`` facades, which route through the scheduler.
    """

    def __init__(self, model_path: str | None, **kw):
        super().__init__(model_path, **kw)
        self._scratch_cache = init_cache(self.cfg)
        base_st = sampling_tensors(SamplingParams())
        self._lane_st = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.batch_size,)), base_st)
        self._default_top_k = SamplingParams().top_k
        self._pending: queue_mod.Queue = queue_mod.Queue()
        self._wake = threading.Event()
        self._stop = False
        self._loop_error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._loop, name="lfkt-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def submit(self, messages: Sequence[dict], *, temperature: float = 0.2,
               top_p: float = 0.95, top_k: int = 40, min_p: float = 0.05,
               frequency_penalty: float = 0.0, presence_penalty: float = 0.0,
               repeat_penalty: float = 1.1, max_tokens: int | None = None,
               stop: Sequence[str] | str | None = None,
               seed: int | None = None) -> Future:
        """Queue one request; the scheduler admits it to a free lane."""
        if self._loop_error is not None:
            raise RuntimeError("scheduler died") from self._loop_error
        if self._stop:
            raise RuntimeError("engine has been shut down")
        if top_k != self._default_top_k:
            # top_k is a static jit arg of the shared decode program; lanes
            # can't mix values (every other knob is per-lane)
            raise ValueError(
                f"continuous scheduler serves a fixed top_k="
                f"{self._default_top_k}; per-request top_k is not supported")
        sp = SamplingParams(
            temperature=temperature, top_p=top_p, top_k=top_k, min_p=min_p,
            frequency_penalty=frequency_penalty,
            presence_penalty=presence_penalty, repeat_penalty=repeat_penalty,
        )
        if isinstance(stop, str):
            stop = [stop]
        fut: Future = Future()
        self._pending.put((fut, list(messages), sp, max_tokens,
                           list(stop or []), seed))
        self._wake.set()
        return fut

    def create_chat_completion(self, messages, stream: bool = False, **kw):
        if stream:  # serial streaming path unchanged (warmed by warmup)
            return super().create_chat_completion(messages, stream=True, **kw)
        return self.submit(messages, **kw).result()

    def create_chat_completions(self, batch_messages, **kw) -> list[dict]:
        futs = [self.submit(m, **kw) for m in batch_messages]
        out = []
        for f in futs:
            try:
                out.append(f.result())
            except ValueError as e:  # per-request input error, isolated
                out.append({"error": {"message": str(e),
                                      "type": "invalid_request_error"}})
        return out

    def shutdown(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    def warmup(self):
        """Compile the scheduler's shapes: serial prefill (every bucket),
        first-token sampling, the lane write, the batched decode chunk, and
        the serial streaming path."""
        t0 = time.time()
        msgs = [{"role": "user", "content": "hi"}]
        futs = [self.submit(msgs, max_tokens=self.decode_chunk + 1,
                            temperature=0.0)
                for _ in range(self.batch_size)]
        for f in futs:
            f.result()
        # serial streaming path (its decode-chunk program is separate)
        list(Engine.create_chat_completion(
            self, msgs, stream=True, max_tokens=self.decode_chunk + 1,
            temperature=0.0))
        Engine.warmup(self)  # remaining prefill buckets
        logger.info("continuous warmup done in %.1fs (%d lanes)",
                    time.time() - t0, self.batch_size)

    # ------------------------------------------------------------------
    # scheduler internals (all device work on the scheduler thread)
    # ------------------------------------------------------------------

    def _admit_one(self, lane: int, item) -> _Slot | None:
        fut, messages, sp, max_tokens, stops, seed = item
        if not fut.set_running_or_notify_cancel():
            return None                                # cancelled while queued
        t0 = time.time()
        try:
            ids = self.tokenize_messages(messages)
            if len(ids) >= self.cfg.n_ctx:
                raise ValueError(
                    f"Requested tokens ({len(ids)}) exceed context window "
                    f"of {self.cfg.n_ctx}")
            n_prompt = len(ids)
            bucket = self._bucket_for(n_prompt)
            padded = ids + [0] * (bucket - n_prompt)
            st = sampling_tensors(sp)
            if seed is None:
                seed = self._base_seed + self._requests
            self._requests += 1

            logits, cache1 = prefill_jit(
                self.params, self.cfg, jnp.asarray(padded, jnp.int32),
                jnp.int32(n_prompt), self._scratch_cache)
            window, wpos = seed_window(ids)
            token, window, wpos, key = sample_jit(
                logits, window, wpos, jax.random.PRNGKey(seed), st, self.cfg,
                top_k=sp.top_k)
            self._bstate, self._lane_st = _write_lane(
                self._bstate, self._lane_st, jnp.int32(lane), cache1,
                jnp.int32(n_prompt), token, window, wpos, key, st)
            self._scratch_cache = cache1  # not donated: next prefill reuses it

            budget = min(self._token_budget(max_tokens, n_prompt),
                         max(0, self.cfg.n_ctx - 1 - n_prompt))
            slot = _Slot(fut, budget, n_prompt, ids)
            slot.first_token = int(token)   # host sync: prefill done = TTFT
            slot.stops = stops
            slot.st = st
            slot.sp = sp
            slot.t_admit = t0
            slot.ttft_s = time.time() - t0
            return slot
        except Exception as e:  # noqa: BLE001 — per-request isolation
            fut.set_exception(e)
            return None

    def _finish_slot(self, slot: _Slot, finish: str):
        text = self._decode_text(slot.gens)
        cut = self._find_stop_str(text, slot.stops)
        if cut != -1:
            text = text[:cut]
            finish = "stop"
        decode_s = time.time() - slot.t_admit - slot.ttft_s
        n = len(slot.gens)
        self.last_timings = {
            "ttft_s": slot.ttft_s, "decode_s": decode_s,
            "prompt_tokens": slot.n_prompt, "completion_tokens": n,
            "tokens_per_sec": (n - 1) / decode_s
            if n > 1 and decode_s > 0 else 0.0,
        }
        slot.future.set_result({
            "id": f"chatcmpl-{uuid.uuid4().hex}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish,
            }],
            "usage": {
                "prompt_tokens": slot.n_prompt,
                "completion_tokens": len(slot.gens),
                "total_tokens": slot.n_prompt + len(slot.gens),
            },
        })

    def _loop(self):
        B = self.batch_size
        slots: list[_Slot | None] = [None] * B
        stop_ids = self.tokenizer.stop_ids
        try:
            while not self._stop:
                # ---- admit into free lanes ---------------------------------
                for lane in range(B):
                    if slots[lane] is not None:
                        continue
                    try:
                        item = self._pending.get_nowait()
                    except queue_mod.Empty:
                        break
                    slot = self._admit_one(lane, item)
                    if slot is None:
                        continue
                    first = slot.first_token
                    if slot.budget <= 0:
                        self._finish_slot(slot, "length")
                    elif first in stop_ids:
                        self._finish_slot(slot, "stop")
                    else:
                        slot.gens.append(first)
                        if len(slot.gens) >= slot.budget:
                            self._finish_slot(slot, "length")
                        else:
                            slots[lane] = slot

                live = [s for s in slots if s is not None]
                if not live:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue

                # ---- one decode chunk for every lane (per-lane sampling
                # knobs ride in self._lane_st; top_k is globally static) ----
                self._bstate, toks = batched_generate_chunk_perlane_jit(
                    self.params, self.cfg, self._bstate, self._lane_st,
                    n_steps=self.decode_chunk, top_k=self._default_top_k)
                chunk = np.asarray(toks)                   # (n_steps, B)

                # ---- harvest ----------------------------------------------
                # (There is no mid-generation abort for abandoned clients —
                # reference parity, api.py:97-100: the generation runs to
                # completion and the result is simply discarded downstream.)
                for lane in range(B):
                    slot = slots[lane]
                    if slot is None:
                        continue
                    finish = None
                    for t in chunk[:, lane].tolist():
                        if t in stop_ids:
                            finish = "stop"
                            break
                        slot.gens.append(t)
                        if len(slot.gens) >= slot.budget:
                            finish = "length"
                            break
                    if finish is not None:
                        self._finish_slot(slot, finish)
                        slots[lane] = None
        except BaseException as e:  # noqa: BLE001 — fail all, loudly
            self._loop_error = e
            logger.exception("scheduler loop died")
        finally:
            # graceful stop AND crash both resolve every outstanding future:
            # a caller blocked in Future.result() must never hang
            err = self._loop_error or RuntimeError("engine has been shut down")
            for s in slots:
                if s is not None and not s.future.done():
                    s.future.set_exception(err)
            while True:
                try:
                    fut = self._pending.get_nowait()[0]
                except queue_mod.Empty:
                    break
                if not fut.done() and not fut.cancel():
                    fut.set_exception(err)

"""Engine watchdog: stall/burst/death detection + bounded in-process recovery.

The reference handles a wedged or crashing engine by letting the pod die,
dropping every in-flight request with it (SURVEY.md §2C).  This thread
watches one engine's :class:`~..utils.health.Heartbeat` and trips on:

- **stalled decode** — the engine reports in-flight work but its beat has
  not advanced for ``stall_seconds`` (covers a wedged scheduler loop AND a
  hung device call, which look identical from the host);
- **exception burst** — ≥ ``error_burst`` engine-side errors inside
  ``error_window`` seconds (a crash loop in request clothing);
- **scheduler death** — the engine's ``failure()`` hook reports its
  background loop died (ContinuousEngine).

On a trip the watchdog fails the engine's registered in-flight futures
with :class:`~..utils.health.EngineUnavailable` (the server maps it to
503), flips health to DEGRADED with the trip reason, then attempts
**bounded recovery**: exponential backoff, ``engine.recover()`` (engine
re-init — each engine defines what that means), escalating to DEAD after
``max_recoveries`` trips within one incident window (trips are forgotten
after ``trip_forget_seconds`` of healthy serving — the budget bounds a
crash loop, not the pod's lifetime incident count).  DEAD fails the
liveness probe, handing the *last* resort back to k8s — which is where
the reference started.

The watchdog holds no engine internals: the contract is three optional
attributes (``heartbeat``, ``recover()``, ``fail_inflight(exc)``) plus the
optional ``failure()`` hook, so fakes and future engines plug in freely.
"""

from __future__ import annotations

import logging
import threading
import time

from ..obs import flightrec as _flightrec
from ..obs.trace import annotate_all_inflight
from ..utils.health import DEAD, DEGRADED, READY, EngineUnavailable

logger = logging.getLogger(__name__)


class Watchdog:
    """Samples an engine heartbeat; degrades, recovers, or escalates."""

    # -- thread discipline (lfkt-lint LOCK002; docs/RUNBOOK.md) -----------
    # the trip/recovery bookkeeping is watchdog-thread-confined: check()/
    # handle_trip() are public for tests and the drill, but a live serving
    # process drives them only from _loop.  _stop is a threading.Event
    # (atomic by design) shared with stop().
    _THREAD_ENTRIES = ("_loop",)
    _THREAD_CONFINED = ("trips", "recoveries", "trips_window",
                        "_last_trip_at", "last_trip_reason")
    _SHARED_ATOMIC = ("_stop",)

    def __init__(self, engine, health, metrics=None, *,
                 stall_seconds: float = 30.0,
                 poll_seconds: float = 1.0,
                 max_recoveries: int = 3,
                 error_burst: int = 5,
                 error_window: float = 30.0,
                 backoff_seconds: float = 1.0,
                 backoff_max: float = 60.0,
                 trip_forget_seconds: float = 600.0):
        self.engine = engine
        self.health = health
        self.metrics = metrics
        self.stall_seconds = stall_seconds
        self.poll_seconds = poll_seconds
        self.max_recoveries = max_recoveries
        self.error_burst = error_burst
        self.error_window = error_window
        self.backoff_seconds = backoff_seconds
        self.backoff_max = backoff_max
        self.trip_forget_seconds = trip_forget_seconds
        #: trip/recovery counters (also pushed to metrics when provided)
        self.trips = 0
        self.recoveries = 0
        #: trips inside the current incident window — the DEAD escalation
        #: budget.  Resets after ``trip_forget_seconds`` of trip-free READY
        #: serving: the budget bounds a crash *loop*, not the pod's total
        #: lifetime incidents (weeks apart, each fully recovered, must not
        #: accumulate into an eventual needless restart).
        self.trips_window = 0
        self._last_trip_at: float | None = None
        self.last_trip_reason: str | None = None
        self._stop = threading.Event()
        # the flight recorder's bundles carry the health-transition trail
        # and scheduler stats via weakly-held refs; the watchdog owns the
        # authoritative pair for this engine, so install them here — this
        # also covers in-process drills that never build a server app
        _flightrec.FLIGHTREC.install(health=health, engine=engine)
        self._thread = threading.Thread(
            target=self._loop, name="lfkt-watchdog", daemon=True)

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    # ------------------------------------------------------------------
    def _inc(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(name)

    def check(self) -> str | None:
        """One detection pass; returns the trip reason or None.  Public so
        tests (and the drill) can drive detection without the thread."""
        failure = getattr(self.engine, "failure", None)
        err = failure() if callable(failure) else None
        if err is not None:
            return f"scheduler_died: {type(err).__name__}: {err}"
        hb = getattr(self.engine, "heartbeat", None)
        if hb is None:
            return None
        if hb.busy_count() > 0 and hb.idle_for() > self.stall_seconds:
            return (f"stalled_decode: no engine progress in "
                    f"{hb.idle_for():.1f}s with {hb.busy_count()} in flight")
        if hb.error_burst(self.error_burst, self.error_window):
            return (f"exception_burst: >={self.error_burst} engine errors "
                    f"in {self.error_window:.0f}s ({hb.last_error})")
        return None

    def _record_incident(self, kind: str, reason: str) -> None:
        """Bundle this incident (obs/flightrec.py).  The health snapshot
        and scheduler stats ride the bundle's top-level fields via the
        recorder's installed refs (see __init__) — ``extra`` carries only
        the watchdog's own counters, so nothing is captured twice."""
        _flightrec.record_incident(kind, reason, extra={"watchdog": {
            "trips": self.trips, "trips_window": self.trips_window,
            "max_recoveries": self.max_recoveries,
        }})

    def handle_trip(self, reason: str) -> None:
        """DEGRADED → fail in-flight → backoff → recover (or escalate)."""
        self.trips += 1
        self.trips_window += 1
        self._last_trip_at = time.monotonic()
        self.last_trip_reason = reason
        self._inc("watchdog_trips_total")
        logger.error("watchdog trip #%d: %s", self.trips, reason)
        # every in-flight trace carries the trip: the 503s this causes are
        # then attributable from the trace alone (lfkt-obs)
        annotate_all_inflight("watchdog_trip", trip=self.trips,
                              reason=reason)
        self.health.transition(DEGRADED, reason)
        # flight recorder (obs/flightrec.py): snapshot the incident BEFORE
        # failing in-flight futures, so the tripping request's trace is
        # still in the bundle.  Disarmed (no LFKT_INCIDENT_DIR) this is a
        # single attribute read inside record().
        self._record_incident("watchdog_trip", reason)
        hb = getattr(self.engine, "heartbeat", None)
        if hb is not None:
            # the burst evidence is consumed by this trip: re-tripping must
            # require NEW errors, or one transient burst would re-trip every
            # poll until the recovery budget is spent (stall and
            # scheduler-death evidence live elsewhere and persist)
            hb.clear_errors()

        fail_inflight = getattr(self.engine, "fail_inflight", None)
        if callable(fail_inflight):
            try:
                fail_inflight(EngineUnavailable(f"watchdog trip: {reason}"))
            except Exception:  # noqa: BLE001 — failing futures is best-effort
                logger.exception("watchdog fail_inflight raised")

        if self.trips_window > self.max_recoveries:
            self._inc("watchdog_escalations_total")
            logger.error("watchdog recovery budget exhausted "
                         "(%d trips this incident > max_recoveries=%d): "
                         "escalating to DEAD",
                         self.trips_window, self.max_recoveries)
            self.health.transition(
                DEAD, f"max_recoveries_exceeded after: {reason}")
            # the pod is about to fail its liveness probe and restart:
            # this bundle is the only evidence that survives it
            self._record_incident(
                "dead_escalation",
                f"max_recoveries_exceeded after: {reason}")
            self._stop.set()
            return

        # exponential backoff before touching the engine: a fault with a
        # cause that clears (transient device error) gets time to clear;
        # the wait is interruptible so stop() never blocks on it
        backoff = min(self.backoff_max,
                      self.backoff_seconds * (2 ** (self.trips_window - 1)))
        if self._stop.wait(backoff):
            return
        recover = getattr(self.engine, "recover", None)
        ok = False
        in_place = False
        if callable(recover):
            try:
                ok = bool(recover())
            except Exception:  # noqa: BLE001 — a recovery crash is a failure
                logger.exception("engine recover() raised")
                ok = False
        if not ok and self.check() is None:
            # recover() refused because the engine is BUSY serving (a live
            # unfailed scheduler loop / a generation holding the lock) and
            # no fault signature remains — e.g. an exception burst whose
            # evidence this trip consumed.  The engine is demonstrably
            # functioning; forcing a re-init it refuses would walk a
            # healthy pod to DEAD, the crash-loop this layer exists to
            # end.  Re-ready in place; a real wedge keeps its stall/death
            # signature, fails this check, and still escalates.
            ok = in_place = True
        if ok:
            hb = getattr(self.engine, "heartbeat", None)
            if hb is not None and not in_place:
                hb.reset()
            self.recoveries += 1
            self._inc("watchdog_recoveries_total")
            logger.warning("watchdog recovery #%d %s after: %s",
                           self.recoveries,
                           "in place (engine healthy)" if in_place
                           else "succeeded", reason)
            self.health.transition(READY, f"recovered_from: {reason}")
        else:
            # stay DEGRADED: the next poll re-detects and re-trips, walking
            # the backoff ladder until recovery works or the budget is spent
            logger.error("engine recover() failed; staying DEGRADED")

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_seconds):
            try:
                if (self.trips_window and self._last_trip_at is not None
                        and self.health.state == READY
                        and time.monotonic() - self._last_trip_at
                        > self.trip_forget_seconds):
                    logger.info("watchdog: %d trip(s) forgotten after %.0fs "
                                "of healthy serving", self.trips_window,
                                self.trip_forget_seconds)
                    self.trips_window = 0
                reason = self.check()
                if reason is not None:
                    self.handle_trip(reason)
            except Exception:  # noqa: BLE001 — the watchdog must not die
                logger.exception("watchdog pass raised")

"""Deterministic fake engine for server/integration tests.

Implements the §2B response contract (SURVEY.md) with injectable latency and
failures so the admission-control paths — queue-full 503, 25 s timeout 408,
engine-error 500 (reference api.py:155-173) — can be exercised without a
model or a device (SURVEY.md §4 "Integration").
"""

from __future__ import annotations

import threading
import time
import uuid


class FakeEngine:
    def __init__(self, reply: str = "ok", delay: float = 0.0,
                 fail: Exception | None = None, chunk_delay: float = 0.0):
        self.reply = reply
        self.delay = delay
        self.fail = fail
        self.chunk_delay = chunk_delay   # slow-drip streaming (deadline tests)
        self.calls: list[list[dict]] = []
        self._lock = threading.Lock()

    def warmup(self):
        pass

    def create_chat_completion(self, messages, stream=False, **kwargs):
        with self._lock:
            self.calls.append(list(messages))
        if self.delay:
            time.sleep(self.delay)
        if self.fail is not None:
            raise self.fail
        content = self.reply
        base = {
            "id": f"chatcmpl-{uuid.uuid4().hex}",
            "created": int(time.time()),
            "model": "fake",
        }
        if not stream:
            return {
                **base,
                "object": "chat.completion",
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": content},
                    "finish_reason": "stop",
                }],
                "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                          "total_tokens": 2},
            }

        def gen():
            yield {**base, "object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {"role": "assistant"},
                                "finish_reason": None}]}
            for ch in content:
                if self.chunk_delay:
                    time.sleep(self.chunk_delay)
                yield {**base, "object": "chat.completion.chunk",
                       "choices": [{"index": 0, "delta": {"content": ch},
                                    "finish_reason": None}]}
            yield {**base, "object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}]}
        return gen()

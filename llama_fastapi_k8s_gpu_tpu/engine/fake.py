"""Deterministic fake engine for server/integration tests.

Implements the §2B response contract (SURVEY.md) with injectable latency and
failures so the admission-control paths — queue-full 503, 25 s timeout 408,
engine-error 500 (reference api.py:155-173) — can be exercised without a
model or a device (SURVEY.md §4 "Integration").

Resilience-aware since the watchdog PR: carries a real
:class:`~..utils.health.Heartbeat`, honors the ``decode_step`` fault
injection point (utils/faults.py — inert unless armed), and implements the
watchdog recovery contract (``recover``/``fail_inflight``), so the full
trip → DEGRADED → recover → READY path is drillable against a live server
with no model (tools/fault_drill.py, tests/test_resilience.py).
"""

from __future__ import annotations

import threading
import time
import uuid

from ..utils.faults import FAULTS
from ..utils.health import Heartbeat


class FakeEngine:
    def __init__(self, reply: str = "ok", delay: float = 0.0,
                 fail: Exception | None = None, chunk_delay: float = 0.0):
        self.reply = reply
        self.delay = delay
        self.fail = fail
        self.chunk_delay = chunk_delay   # slow-drip streaming (deadline tests)
        self.calls: list[list[dict]] = []
        self._lock = threading.Lock()
        self.heartbeat = Heartbeat()
        self.recoveries = 0              # recover() invocations (assertable)
        self.failed_inflight: list = []  # exceptions from fail_inflight

    def warmup(self):
        pass

    # -- watchdog contract (engine/watchdog.py) -------------------------
    def recover(self) -> bool:
        FAULTS.fire("recover")
        self.recoveries += 1
        self.heartbeat.reset()
        return True

    def fail_inflight(self, exc: BaseException) -> None:
        self.failed_inflight.append(exc)

    def create_chat_completion(self, messages, stream=False, **kwargs):
        with self._lock:
            self.calls.append(list(messages))
        self.heartbeat.enter()
        try:
            if self.delay:
                time.sleep(self.delay)
            try:
                FAULTS.fire("decode_step")
                if self.fail is not None:
                    raise self.fail
            except Exception as e:  # noqa: BLE001 — burst detection, re-raised
                self.heartbeat.record_error(e)
                raise
            self.heartbeat.beat()
        finally:
            self.heartbeat.leave()
        content = self.reply
        base = {
            "id": f"chatcmpl-{uuid.uuid4().hex}",
            "created": int(time.time()),
            "model": "fake",
        }
        if not stream:
            return {
                **base,
                "object": "chat.completion",
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": content},
                    "finish_reason": "stop",
                }],
                "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                          "total_tokens": 2},
            }

        def gen():
            yield {**base, "object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {"role": "assistant"},
                                "finish_reason": None}]}
            for ch in content:
                if self.chunk_delay:
                    time.sleep(self.chunk_delay)
                self.heartbeat.beat()
                yield {**base, "object": "chat.completion.chunk",
                       "choices": [{"index": 0, "delta": {"content": ch},
                                    "finish_reason": None}]}
            yield {**base, "object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}]}
        return gen()

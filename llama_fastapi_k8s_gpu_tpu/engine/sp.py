"""Sequence-parallel serving engine: long context over an sp×tp mesh.

The reference *suppresses* context (n_ctx=1024, 400-char clips, oldest-
message eviction — reference api.py:27,37-46); this engine scales it
instead: the KV cache's n_ctx dimension shards over the ``sp`` mesh axis and
attention runs as ring attention for prefill / sharded-LSE for decode
(parallel/ring.py), so no chip ever holds more than 1/sp of the KV.  Max
context grows linearly with the ring size while the serving surface — the
``create_chat_completion`` contract, streaming, admission control — stays
exactly :class:`Engine`'s (only the two jit call points are rerouted onto
the mesh).

Enable from the server with ``LFKT_MESH_SP > 1`` (utils/config.py); combine
with ``LFKT_MESH_TP`` for heads-sharded attention inside the ring.
"""

from __future__ import annotations

import dataclasses
import logging

import jax

from ..models.llama import init_cache
from ..parallel.mesh import make_mesh, shard_params
from ..parallel.ring import sp_generate_chunk, sp_prefill, sp_state_shardings
from .engine import Engine

logger = logging.getLogger(__name__)


class SPEngine(Engine):
    """An :class:`Engine` whose KV cache and attention are sequence-parallel.

    Serial like the base engine (one generation at a time, the reference's
    concurrency model); the mesh is spent on *context length*, not batch.
    """

    #: the overlapped slice-prefill path (Engine._prefill_padded) drives
    #: prefill_chunk_jit against an unsharded ring; this engine's ring is
    #: sp-sharded over n_ctx and its prefill is the rerouted ring program
    #: (sp_prefill), so it keeps monolithic bucket prefill.
    _SLICE_PREFILL = False

    #: the paged KV pool (LFKT_KV_PAGED, parallel/kvpool.py) slices and
    #: updates the ring's n_ctx dim, which this engine shards over the sp
    #: axis — paging stays off (Engine.__init__ warns and serves the
    #: dense sharded ring; greedy output is identical either way).
    _KV_PAGED = False

    #: layer-looped decode (LFKT_DECODE_LAYER_UNROLL) gates off: each
    #: layer's decode attention is a cross-chip sharded-LSE collective
    #: (parallel/ring.py), which a single fused kernel cannot express —
    #: Engine.__init__ degrades with attribution and serves per-layer.
    _DECODE_LOOP = False

    def __init__(self, model_path: str | None, *, sp: int = 2, tp: int = 1,
                 n_ctx: int = 4096, **kw):
        if sp < 2:
            raise ValueError(f"SPEngine needs sp >= 2, got {sp} "
                             f"(use Engine for single-chip serving)")
        attn = kw.pop("attn_impl", "auto")
        if attn not in ("auto", "ring"):
            raise ValueError(
                f"SPEngine serves ring attention; attn_impl must be "
                f"auto|ring, got {attn!r}")
        super().__init__(model_path, n_ctx=n_ctx, attn_impl="xla", **kw)
        if self.cfg.n_ctx % sp:
            raise ValueError(f"n_ctx {self.cfg.n_ctx} must divide sp={sp}")
        self.mesh = make_mesh(dp=1, tp=tp, sp=sp)
        self.sp = sp
        self.params = shard_params(self.params, self.mesh)
        self.cfg = dataclasses.replace(self.cfg, attn_impl="ring")
        # ring prefill shards the token dim: buckets round up to sp multiples
        self.prefill_buckets = sorted(
            {min(self.cfg.n_ctx, -(-b // sp) * sp) for b in self.prefill_buckets})
        self._cache = jax.device_put(
            init_cache(self.cfg), sp_state_shardings(self.cfg, self.mesh))
        logger.info("SPEngine: n_ctx=%d over sp=%d tp=%d (%d devices)",
                    self.cfg.n_ctx, sp, tp, sp * tp)

    def _trace_attrs(self) -> dict:
        """The ``engine`` span / /debug/requests identity, extended with
        the ring geometry so a slow long-context request's waterfall says
        which mesh shape served it."""
        return {**super()._trace_attrs(), "sp": self.sp,
                "devices": self.sp * self.mesh.shape["tp"],
                "tp": self.mesh.shape["tp"]}

    def _recover_locked(self) -> None:  # lfkt: holds[_lock]
        """Watchdog recovery: the fresh ring must carry the same sp-sharded
        layout __init__ installed — the base class's unsharded init_cache
        would replicate the full n_ctx ring per device, defeating the
        reason sp exists (HBM) on the first post-recovery request."""
        super()._recover_locked()
        self._cache = jax.device_put(
            init_cache(self.cfg), sp_state_shardings(self.cfg, self.mesh))

    # -- jit call points rerouted onto the mesh -----------------------------
    def _prefill_call(self, tokens, length, cache):
        return sp_prefill(self.params, self.cfg, tokens, length, cache,
                          self.mesh)

    def _decode_chunk_call(self, state, st, n_steps: int, top_k: int):
        return sp_generate_chunk(self.params, self.cfg, state, st, self.mesh,
                                 n_steps, top_k)

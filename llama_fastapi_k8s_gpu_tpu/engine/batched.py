"""Mesh-batched engine: B concurrent chat completions in one program.

The reference scales concurrent load with 4 shared-nothing single-GPU pods
behind a k8s Service (reference helm/values.yaml:17; SURVEY.md §2A
"Parallelism strategies") — each pod still generates strictly serially
(Semaphore(1), reference api.py:114).  The TPU-native equivalent for the
"concurrent /response load on v5e-4" config (BASELINE.json) batches
requests *inside* one process instead: requests coalesce into a batch of B
sequences, vmap-lifted over the model (parallel/batched.py) and laid out on
a dp×tp ``jax.sharding.Mesh`` — the batch dim shards over ``dp`` chips, the
model over ``tp``, XLA inserts the ICI collectives.

Decode efficiency is the point: a single-sequence decode matvec cannot
saturate HBM/MXU; batching B requests multiplies decode throughput at
nearly constant step latency (weights are read once per step regardless of
B).  FIFO admission order is preserved by the server's consumer, which
drains up to B queued requests per cycle (server/app.py).
"""

from __future__ import annotations

import functools
import logging
import time
import uuid
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.batched import (
    batched_generate_chunk_jit,
    batched_prefill_jit,
    init_batched_state,
)
from ..obs.devtime import timed_jit
from ..parallel.mesh import make_mesh, shard_params, state_shardings
from ..sampling.sample import (
    PENALTY_WINDOW,
    SamplingParams,
    sample_chain,
    sampling_tensors,
    seed_window,
)
from ..obs.memledger import register_component
from ..parallel.batched import state_nbytes
from ..utils.faults import FAULTS
from .engine import Engine

logger = logging.getLogger(__name__)


def _ledger_lane_bytes(eng: "MeshEngine") -> int:
    """Memory-ledger provider: the batched lane state's resident bytes
    (snapshot-time metadata read — obs/memledger.py)."""
    return state_nbytes(getattr(eng, "_bstate", None))


@functools.partial(jax.jit, static_argnames=("top_k",))
def _batched_first_sample(logits, windows, wposes, keys, st, top_k=40):
    """Sample the first token of every sequence from prefill logits."""

    def single(lg, window, wpos, key):
        key, sub = jax.random.split(key)
        tok = sample_chain(lg, window, sub, st, top_k=top_k)
        window = window.at[wpos % PENALTY_WINDOW].set(tok)
        return tok, window, wpos + 1, key

    return jax.vmap(single)(logits, windows, wposes, keys)


_batched_first_sample = timed_jit("batched_first_sample",
                                  _batched_first_sample,
                                  site="engine.batched")


class MeshEngine(Engine):
    """An :class:`Engine` that serves batches of requests over a device mesh.

    ``create_chat_completion`` still works (batch of one).  The batch entry
    point is :meth:`create_chat_completions`, which the server's consumer
    feeds with up-to-``batch_size`` queued requests at a time.
    """

    # the batched state joins the serial ring under the generation mutex
    # (lfkt-lint LOCK001; docs/RUNBOOK.md "Lock discipline annotations")
    _GUARDED_BY = {"_bstate": "_lock"}

    def __init__(self, model_path: str | None, *, dp: int | None = None,
                 tp: int = 1, batch_size: int | None = None, **kw):
        super().__init__(model_path, **kw)
        avail = max(1, len(jax.devices()) // tp)
        if dp is None:
            if batch_size is None:
                dp = avail
            else:  # largest device count the batch shards evenly over
                dp = max(d for d in range(1, avail + 1) if batch_size % d == 0)
        self.mesh = make_mesh(dp=dp, tp=tp)
        self.batch_size = batch_size or dp
        if self.batch_size % dp:
            raise ValueError(
                f"batch_size {self.batch_size} must be divisible by dp={dp}")
        self.params = shard_params(self.params, self.mesh)
        state = init_batched_state(self.cfg, self.batch_size)
        self._bstate = jax.device_put(
            state, state_shardings(self.cfg, self.mesh, batched=True))
        # lfkt-mem: the shared lane state is this engine family's biggest
        # serving allocation — attribute it (provider reads the live
        # reference, so watchdog re-inits stay correct automatically)
        register_component("kv_lanes", self, _ledger_lane_bytes)

    def _recover_locked(self) -> None:  # lfkt: holds[_lock]
        """Watchdog recovery: a crash mid-cycle may have poisoned the donated
        batched state, so rebuild it (sharded) along with the serial ring."""
        super()._recover_locked()
        state = init_batched_state(self.cfg, self.batch_size)
        self._bstate = jax.device_put(
            state, state_shardings(self.cfg, self.mesh, batched=True))

    # ------------------------------------------------------------------
    def warmup(self):
        """Compile every shape a request can hit: the batched prefill for
        every bucket + the batched decode chunk, AND the serial path (the
        server's /response/stream uses Engine's streaming generation)."""
        t0 = time.time()
        msgs = [{"role": "user", "content": "hi"}]
        # TWO full decode chunks: chunk 2's donated state carries jit-chosen
        # shardings, a distinct compile the one-chunk warmup used to leave
        # for the first real request (devtime pin, tests/test_perf_pins.py)
        self.create_chat_completions([msgs] * self.batch_size,
                                     max_tokens=2 * self.decode_chunk + 1,
                                     temperature=0.0)
        with self._lock:   # uncontended at warmup; keeps the _bstate
            #                write invariant (writes only under _lock)
            for bucket in self.prefill_buckets[1:]:
                tokens = jnp.zeros((self.batch_size, bucket), jnp.int32)
                lengths = jnp.ones((self.batch_size,), jnp.int32)
                _, caches = batched_prefill_jit(
                    self.params, self.cfg, tokens, lengths,
                    self._bstate["cache"])
                self._bstate["cache"] = caches
        super().warmup()  # serial buckets + decode chunk (streaming path)
        logger.info("mesh warmup done in %.1fs (dp=%d tp=%d batch=%d)",
                    time.time() - t0, self.mesh.shape["dp"],
                    self.mesh.shape["tp"], self.batch_size)

    # ------------------------------------------------------------------
    def create_chat_completions(  # lfkt: blocks-under[_lock] -- the mesh engine serializes whole batches under its lock by design: drill sleeps and incident capture ride the generation path
        self,
        batch_messages: Sequence[Sequence[dict]],
        *,
        temperature: float = 0.2,
        top_p: float = 0.95,
        top_k: int = 40,
        min_p: float = 0.05,
        frequency_penalty: float = 0.0,
        presence_penalty: float = 0.0,
        repeat_penalty: float = 1.1,
        max_tokens: int | None = None,
        stop: Sequence[str] | str | None = None,
        seed: int | None = None,
        deadlines: Sequence[float | None] | None = None,
        aborts: Sequence | None = None,
        traces: Sequence | None = None,
    ) -> list[dict]:
        """Generate up to ``batch_size`` completions in one batched program.
        Returns one OpenAI-shaped dict per input, in order.

        ``deadlines``/``aborts`` are per-entry: entry ``b`` stops
        accumulating tokens (``finish_reason="deadline"``) within one
        decode chunk of its deadline passing or its abort callback firing
        — its lane keeps stepping on-device (vmap advances every lane) but
        the cycle ends as soon as every live entry is done, so one
        timed-out request no longer pins the whole batch to its budget."""
        if not batch_messages:
            return []
        if len(batch_messages) > self.batch_size:
            raise ValueError(
                f"batch of {len(batch_messages)} exceeds batch_size {self.batch_size}")
        if stop is None:
            stop = []
        elif isinstance(stop, str):
            stop = [stop]
        sp = SamplingParams(
            temperature=temperature, top_p=top_p, top_k=top_k, min_p=min_p,
            frequency_penalty=frequency_penalty, presence_penalty=presence_penalty,
            repeat_penalty=repeat_penalty,
        )
        with self._lock:
            self.heartbeat.enter()
            try:
                return self._generate_batch(list(batch_messages), sp,
                                            max_tokens, stop, seed,
                                            deadlines=deadlines, aborts=aborts,
                                            traces=traces)
            except Exception as e:  # noqa: BLE001 — burst detection, re-raised
                self._note_error(e)
                raise
            finally:
                self.heartbeat.leave()

    # ------------------------------------------------------------------
    @staticmethod
    def _lane_expired(b: int, deadlines, aborts, now: float) -> bool:
        if aborts is not None and b < len(aborts) and aborts[b] is not None \
                and aborts[b]():
            return True
        return (deadlines is not None and b < len(deadlines)
                and deadlines[b] is not None and now > deadlines[b])

    def _generate_batch(self, batch_messages, sp, max_tokens, stops, seed,
                        deadlines=None, aborts=None,
                        traces=None):  # lfkt: holds[_lock]
        B = self.batch_size
        n_real = len(batch_messages)
        # per-entry engine spans: entry b's trace gets its own span tree
        # even though the cycle's device work is shared (the shared-timing
        # caveat is stamped as an attr); None everywhere when untraced
        espans: list = [None] * B
        if traces is not None:
            for b, tr in enumerate(traces[:B]):
                if tr is not None:
                    tr.note(lane=b, tokens=0, **self._trace_attrs())
                    espans[b] = tr.span("engine").set(
                        lane=b, shared_cycle=True, **self._trace_attrs())
        dummy = [self.tokenizer.bos_id or 0]
        # An oversized prompt is that request's own input error — it must not
        # fail its batch neighbors (reference semantics are per-request,
        # api.py:76-78).  Replace it with a dummy slot and report per-entry.
        ids_list, errors = [], {}
        for i, m in enumerate(batch_messages):
            ids = self.tokenize_messages(m)
            if len(ids) >= self.cfg.n_ctx:
                errors[i] = (f"Requested tokens ({len(ids)}) exceed context "
                             f"window of {self.cfg.n_ctx}")
                ids = dummy
            ids_list.append(ids)
        # pad the batch with a minimal dummy prompt (static batch shape)
        ids_list += [dummy] * (B - n_real)
        if seed is None:
            seed = self._next_seed()
        else:
            self._next_seed()
        with self._id_lock:  # advance past the whole batch
            self._requests += n_real - 1

        bucket = self._bucket_for(max(len(i) for i in ids_list))
        lengths = jnp.asarray([len(i) for i in ids_list], jnp.int32)
        tokens = jnp.asarray(
            [i + [0] * (bucket - len(i)) for i in ids_list], jnp.int32)
        st = sampling_tensors(sp)

        t0 = time.time()
        state = self._bstate
        logits, caches = batched_prefill_jit(
            self.params, self.cfg, tokens, lengths, state["cache"])
        windows, wposes = zip(*(seed_window(i) for i in ids_list))
        keys = jax.random.split(jax.random.PRNGKey(seed), B)
        toks, windows, wposes, keys = _batched_first_sample(
            logits, jnp.stack(windows), jnp.stack(wposes), keys, st,
            top_k=sp.top_k)
        state = {
            "cache": caches, "pos": lengths, "token": toks,
            "window": windows, "wpos": wposes, "key": keys,
        }
        first = np.asarray(toks).tolist()  # host sync: TTFT for the batch
        ttft = time.time() - t0
        for b, es in enumerate(espans):
            if es is not None:
                es.child("prefill", t0=t0).set(
                    n_prompt=len(ids_list[b]), bucket=bucket,
                    ttft_s=round(ttft, 6)).end()

        stop_ids = self.tokenizer.stop_ids
        # Per-lane budget AND per-lane cache capacity: lane b may store
        # n_ctx-1-len_b new tokens regardless of its neighbors' prompt
        # lengths (a global clamp would let the longest prompt truncate
        # everyone).  Lanes that exhaust their own capacity keep decoding
        # on-device (vmap advances every lane) — their writes clamp to the
        # last slot of their own cache and their tokens are discarded here;
        # the next batch re-prefills, so the garbage is never read.
        budgets = [
            min(self._token_budget(max_tokens, len(i)),
                max(0, self.cfg.n_ctx - 1 - len(i)))
            for i in ids_list
        ]
        gens: list[list[int]] = []
        done = [False] * B
        finishes = ["length"] * B                     # same default as Engine._run
        for b, tok in enumerate(first):
            if b >= n_real or b in errors or budgets[b] <= 0:
                gens.append([])
                done[b] = True
            elif tok in stop_ids:
                gens.append([])
                done[b] = True
                finishes[b] = "stop"
            else:
                gens.append([tok])

        while not all(done):
            # deadline/abort propagation: expired entries stop accumulating
            # (and can end the cycle) within one decode chunk
            now = time.time()
            for b in range(B):
                if not done[b] and self._lane_expired(b, deadlines, aborts, now):
                    done[b] = True
                    finishes[b] = "deadline"
            if all(done):
                break
            self.heartbeat.beat()
            FAULTS.fire("decode_step")
            remaining = max(budgets[b] - len(gens[b]) for b in range(B) if not done[b])
            n_steps = min(self.decode_chunk, remaining)
            if n_steps <= 0:
                break                                 # capacity: "length"
            t_chunk = time.time()
            state, toks = batched_generate_chunk_jit(
                self.params, self.cfg, state, st,
                n_steps=n_steps, top_k=sp.top_k)
            chunk = np.asarray(toks)                  # (n_steps, B) host sync
            for b in range(B):
                if done[b]:
                    continue
                for t in chunk[:, b].tolist():
                    if t in stop_ids:
                        done[b] = True
                        finishes[b] = "stop"
                        break
                    if len(gens[b]) >= budgets[b]:
                        done[b] = True
                        break
                    gens[b].append(t)
                if len(gens[b]) >= budgets[b]:
                    done[b] = True
                if espans[b] is not None:
                    espans[b].child("decode_chunk", t0=t_chunk).set(
                        tokens=len(gens[b])).end()
                    traces[b].note(tokens=len(gens[b]))

        self._bstate = state                          # reuse buffers
        for b, es in enumerate(espans):
            if es is not None:
                es.set(finish=finishes[b], completion_tokens=len(gens[b]))
                es.end()
        decode_s = time.time() - t0 - ttft
        total_new = sum(len(g) for g in gens[:n_real])
        timings = {
            "ttft_s": ttft, "decode_s": decode_s,
            "prompt_tokens": int(sum(len(i) for i in ids_list[:n_real])),
            # shared cycle: every lane prefilled in one bucket program
            "bucket": bucket,
            # model label for the per-model metric series (multi-model)
            "model": self.model_name,
            "completion_tokens": total_new,
            "tokens_per_sec": (total_new - n_real) / decode_s
            if decode_s > 0 and total_new > n_real else 0.0,
        }
        self._record_timings(timings)

        out = []
        for b in range(n_real):
            if b in errors:
                out.append({"error": {"message": errors[b],
                                      "type": "invalid_request_error"}})
                continue
            text = self._decode_text(gens[b])
            cut = self._find_stop_str(text, stops)
            finish = finishes[b]
            if cut != -1:
                text = text[:cut]
                finish = "stop"
            out.append({
                "lfkt_timings": timings,  # batch-level (one shared cycle)
                "id": f"chatcmpl-{uuid.uuid4().hex}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": self.model_name,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish,
                }],
                "usage": {
                    "prompt_tokens": len(ids_list[b]),
                    "completion_tokens": len(gens[b]),
                    "total_tokens": len(ids_list[b]) + len(gens[b]),
                },
            })
        return out

from .engine import Engine  # noqa: F401
from .fake import FakeEngine  # noqa: F401

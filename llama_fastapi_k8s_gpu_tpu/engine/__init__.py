from .batched import MeshEngine  # noqa: F401
from .continuous import ContinuousEngine  # noqa: F401
from .engine import Engine  # noqa: F401
from .fake import FakeEngine  # noqa: F401
from .sp import SPEngine  # noqa: F401
from .watchdog import Watchdog  # noqa: F401

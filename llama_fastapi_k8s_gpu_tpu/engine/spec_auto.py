"""``spec_decode="auto"``: decide speculation from MEASURED dispatch latency.

Round 4 shipped prompt-lookup speculation default-off because the *bench
device's* ~72 ms tunneled dispatch round trip puts its breakeven acceptance
at ~6 — but that calibration is specific to the tunnel, not the product
(VERDICT r4 weak #5).  A pod on a locally-attached v5e sees ~1-2 ms
dispatch, where lookup's typical 1-3 acceptance on re-sent-history chat
pays handily.  Rather than ship either deployment's constant, "auto" makes
the decision from the deployment's own numbers at engine construction.

Cost model (docs/PERF.md "Speculative decoding under the continuous
scheduler"): pipelined chunked decode hides dispatch behind device compute,
so its steady per-token cost is the weight read
``t_tok = bytes_per_token / hbm_bw``.  A verify round cannot pipeline —
drafts depend on the previous round's accepted tokens — so each round pays
the full dispatch round trip ``rtt`` and yields ``1 + a`` tokens
(``a`` = acceptance).  Per-token cost ``(t_tok + rtt) / (1 + a)`` beats
``t_tok`` iff ``a > rtt / t_tok``:

    breakeven_acceptance = rtt / t_tok

"auto" enables lookup iff breakeven < ``LFKT_SPEC_AUTO_ACCEPT`` (default
1.0 — the conservative end of prompt-lookup's 1-3 on workloads that re-send
persona + chat history verbatim, reference api.py:44-63).  The decision and
all its inputs are logged and exposed as ``engine.spec_auto_decision``.
"""

from __future__ import annotations

import time

from ..utils.config import knob

HBM_GBPS_DEFAULT = 819.0   # v5e spec; override via LFKT_HBM_GBPS
#                            (registry default mirrors this constant)


def measure_dispatch_rtt_s(n: int = 7) -> float:
    """Median wall time of a minimal jitted dispatch + host fetch.

    This is the per-verify-round overhead spec decoding pays: the host→
    device dispatch plus the device→host fetch of the sampled tokens.  Two
    warm executions are discarded first (early-process executions are
    20-40x slow on the tunneled platform — docs/PERF.md "Measurement
    hygiene")."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)  # lfkt: noqa[PERF001] -- raw-dispatch RTT probe: devtime wrapping would add the very overhead being measured
    x = jnp.zeros((), jnp.int32)
    for _ in range(2):
        int(f(x))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        int(f(x))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[n // 2]


def decode_bytes_per_token(params) -> int:
    """HBM bytes one decode token must read: every weight byte except the
    token-embedding table (a single-row gather)."""
    import jax

    emb = params.get("tok_emb") if isinstance(params, dict) else None
    emb_bytes = getattr(emb, "nbytes", 0)
    total = sum(getattr(leaf, "nbytes", 0)
                for leaf in jax.tree.leaves(params))
    return max(total - emb_bytes, 1)


def resolve_auto(params, *, hbm_gbps: float | None = None,
                 accept: float | None = None) -> tuple[str, dict]:
    """→ ("lookup" | "off", decision record).  Never raises: a measurement
    failure resolves to "off" with the error recorded (degradation
    contract, docs/PERF.md)."""
    if hbm_gbps is None:
        hbm_gbps = knob("LFKT_HBM_GBPS", default=HBM_GBPS_DEFAULT)
    if accept is None:
        accept = knob("LFKT_SPEC_AUTO_ACCEPT")
    try:
        # module-global lookup so tests can monkeypatch the measurement
        rtt_s = measure_dispatch_rtt_s()
        bpt = decode_bytes_per_token(params)
        t_tok_s = bpt / (hbm_gbps * 1e9)
        breakeven = rtt_s / t_tok_s
        mode = "lookup" if breakeven < accept else "off"
        return mode, {
            "rtt_ms": round(rtt_s * 1e3, 3),
            "bytes_per_token": int(bpt),
            "t_tok_ms": round(t_tok_s * 1e3, 3),
            "breakeven_acceptance": round(breakeven, 3),
            "assumed_acceptance": accept,
            "resolved": mode,
        }
    except Exception as e:  # noqa: BLE001 — serve without speculation
        return "off", {"resolved": "off", "error": str(e)[:200]}

"""PERF001-002: the devtime registry and the SLO catalog stay total.

The lfkt-perf contract (obs/devtime.py, obs/slo.py):

- PERF001 — every ``jax.jit``/``pjit``/``pl.pallas_call`` entry point in
  the package is registered with the devtime registry, so compile and
  dispatch attribution can never silently lose a program.  A site counts
  as registered when (a) the jit-creating call is lexically inside a
  ``timed_jit(...)``/``register_program(...)`` call (the wrap-at-build
  form: ``timed_jit("sp_prefill", jax.jit(fn))``), or (b) the decorated
  function's name — or the enclosing function's name, for call-expression
  sites — appears as an argument (string or name) of a registration call
  somewhere in the same module (the module-level forms:
  ``prefill_jit = timed_jit("prefill", prefill_jit)`` after a decorated
  def, ``register_program("flash_attention", ...)`` for trace-inner
  dispatch sites whose compile wall belongs to their caller).
- PERF002 — every :class:`~..obs.slo.SLO` entry in ``obs/slo.py``
  references a metric family declared in the obs/catalog.py catalog
  (exactly, or via a ``prefix=True`` family): an SLO over a phantom
  family would evaluate forever-green burn rates against series that can
  never exist.

``obs/devtime.py`` itself is exempt from PERF001 (it creates no programs;
its fixtures of the wrapper would self-trigger on pathological parses).
"""

from __future__ import annotations

import ast

from .core import Context, Finding, const_str, dotted
from .jit import _decorator_is_jit
from .obsreg import _catalog, _covered

RULES = {
    "PERF001": "jax.jit/pallas_call entry point not registered with the "
               "devtime registry (obs/devtime.py)",
    "PERF002": "SLO references a metric family missing from the "
               "obs/catalog.py catalog",
}

SLO_REL = "obs/slo.py"
_EXEMPT = ("obs/devtime.py",)
_REG_FNS = ("timed_jit", "register_program")
_JIT_TAILS = ("jit", "pjit")


def _registration_info(tree: ast.AST) -> tuple[set[str], set[int]]:
    """(names registered in this module, ids of nodes lexically inside a
    registration call's arguments)."""
    names: set[str] = set()
    inside: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = dotted(node.func)
        if f is None or f.split(".")[-1] not in _REG_FNS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            s = const_str(arg)
            if s:
                names.add(s)
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            for sub in ast.walk(arg):
                inside.add(id(sub))
    return names, inside


def _enclosing_fn_map(tree: ast.AST) -> dict[int, str | None]:
    """node id -> name of the innermost enclosing function def (or None
    at module level)."""
    out: dict[int, str | None] = {}

    def assign(node: ast.AST, owner: str | None):
        for child in ast.iter_child_nodes(node):
            is_fn = isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
            out[id(child)] = owner
            assign(child, child.name if is_fn else owner)

    assign(tree, None)
    return out


def _decorator_nodes(tree: ast.AST) -> set[int]:
    """ids of every node inside a decorator expression (decorator-form jit
    sites are checked through their FunctionDef, not the call walk)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            for dec in node.decorator_list:
                for sub in ast.walk(dec):
                    out.add(id(sub))
    return out


def _is_jit_call(node: ast.Call) -> bool:
    f = dotted(node.func)
    if f is None:
        return False
    tail = f.split(".")[-1]
    if tail in _JIT_TAILS:
        return True
    if tail == "partial":
        # functools.partial(jax.jit, ...) — a jit factory being built
        for a in node.args:
            ad = dotted(a)
            if ad and ad.split(".")[-1] in _JIT_TAILS:
                return True
    return False


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []

    # -- PERF001: every jit/pallas program is devtime-registered -----------
    for src in ctx.sources:
        if src.rel in _EXEMPT:
            continue
        path = ctx.display_path(src)
        registered, inside_reg = _registration_info(src.tree)
        enclosing = _enclosing_fn_map(src.tree)
        in_decorator = _decorator_nodes(src.tree)

        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not any(_decorator_is_jit(d) for d in node.decorator_list):
                    continue
                if node.name in registered:
                    continue
                out.append(Finding(
                    "PERF001", path, node.lineno,
                    f"jit-decorated {node.name} is not registered with the "
                    "devtime registry: wrap it (name = timed_jit(...)) or "
                    "declare it (register_program(...)) so compile/dispatch "
                    "attribution cannot lose it (obs/devtime.py)"))
                continue
            if not isinstance(node, ast.Call) or id(node) in in_decorator:
                continue
            f = dotted(node.func)
            tail = f.split(".")[-1] if f else None
            if tail == "pallas_call" or _is_jit_call(node):
                if id(node) in inside_reg:
                    continue
                owner = enclosing.get(id(node))
                if owner is not None and owner in registered:
                    continue
                kind = "pallas_call" if tail == "pallas_call" else "jax.jit"
                where = f"inside {owner}" if owner else "at module level"
                out.append(Finding(
                    "PERF001", path, node.lineno,
                    f"{kind} {where} is not registered with the devtime "
                    "registry: wrap the built callable in timed_jit(...) "
                    "or register_program() the enclosing function "
                    "(obs/devtime.py)"))

    # -- PERF002: SLO -> catalog coverage ----------------------------------
    metrics, have_catalog = _catalog(ctx)
    if not have_catalog:
        return out
    for src in ctx.sources:
        if src.rel != SLO_REL:
            continue
        path = ctx.display_path(src)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = dotted(node.func)
            if f is None or f.split(".")[-1] != "SLO":
                continue
            metric = None
            for kw in node.keywords:
                if kw.arg == "metric":
                    metric = const_str(kw.value)
            if metric is None and len(node.args) > 1:
                metric = const_str(node.args[1])
            if metric is None:
                continue                    # dynamic: runtime lookup guards
            if not _covered(metric, metrics):
                out.append(Finding(
                    "PERF002", path, node.lineno,
                    f"SLO references metric {metric!r}, which is not in "
                    "the obs/catalog.py catalog — its burn rate would "
                    "evaluate forever-green against series that cannot "
                    "exist"))
    return out

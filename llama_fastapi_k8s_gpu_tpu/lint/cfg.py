"""Intraprocedural control-flow graphs + a forward dataflow solver.

The AST pattern rules (LOCK/JIT/CFG/OBS/KER/PERF/DEAD) check *where things
are written*; the PR-6/PR-7 bug class — pool pages leaked on exception
paths, leases dropped before release, donated buffers served dead — is
about *which paths exist*.  This module gives the lint suite the missing
substrate: a statement-level CFG over stdlib ``ast`` with explicit
exception edges, and a generic worklist solver the rule families
(resources.py RES*, donation.py DON*, degrade.py EXC*) run may/must
analyses on.

Graph model
-----------

One :class:`Node` per statement plus pseudo nodes (``entry``, ``exit``,
``raise``, dispatch/join points).  Edges carry a kind:

- ``norm`` — ordinary fall-through / completion;
- ``exc``  — the statement raised (its effect did NOT happen: transfer
  functions apply gen/kill on normal out-edges only);
- ``true``/``false`` — the two branches of an ``if``/``while``/``for``
  header (rules use these for conditional-acquire and ``is None`` guard
  patterns).

``try/finally`` duplicates the ``finally`` body per continuation kind
(normal / exception / return / break / continue) — the CPython-compiler
model — so a may-analysis cannot launder an exceptional path through the
normal continuation.  ``except``-handler dispatch is conservative: an
exception inside a ``try`` reaches every handler, and also propagates
outward unless some handler is a catch-all (bare, ``Exception``, or
``BaseException``).  ``with contextlib.suppress(...)`` bodies get an
extra edge from their exception paths to the normal continuation (the
suppression is real control flow).

Raise model: a statement can raise iff it contains a ``Call``, ``Raise``,
``Assert``, or ``Await`` (compound headers: only their test/iter/items
count).  Attribute/subscript access without a call is assumed
non-raising — the pragmatic lint trade: modelling every attribute load as
throwing would mark the very statement that *hands off* a resource as a
leak path.

Nothing here imports jax or executes analyzed code (core.py contract).
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable

from .core import dotted

__all__ = ["Node", "CFG", "build_cfg", "can_raise", "eval_roots",
           "solve_forward", "reachable"]

#: context-manager call tails whose body exceptions may resume normally
SUPPRESS_TAILS = ("suppress",)

#: handler annotations that catch everything (conservatively: anything we
#: cannot resolve also counts as a catch-all, so no false "propagates")
_CATCH_ALL = ("Exception", "BaseException")


class Node:
    """One CFG node: a statement (``stmt`` set) or a pseudo point."""

    __slots__ = ("stmt", "label", "succ")

    def __init__(self, stmt: ast.stmt | None, label: str):
        self.stmt = stmt
        self.label = label          # entry|exit|raise|stmt|join|dispatch
        self.succ: list[tuple["Node", str]] = []

    def add(self, target: "Node", kind: str = "norm") -> None:
        edge = (target, kind)
        if edge not in self.succ:
            self.succ.append(edge)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        at = getattr(self.stmt, "lineno", "?")
        return f"<Node {self.label}@{at}>"


class CFG:
    """entry → ... → exit (normal completion / return) and raise_exit
    (uncaught exception).  ``nodes`` holds every node, duplicated
    ``finally`` copies included."""

    def __init__(self) -> None:
        self.nodes: list[Node] = []
        self.entry = self.new(None, "entry")
        self.exit = self.new(None, "exit")
        self.raise_exit = self.new(None, "raise")

    def new(self, stmt: ast.stmt | None, label: str) -> Node:
        n = Node(stmt, label)
        self.nodes.append(n)
        return n

    def stmt_nodes(self) -> list[Node]:
        return [n for n in self.nodes if n.stmt is not None]


def _header_exprs(stmt: ast.stmt) -> list[ast.AST]:
    """The expressions evaluated by a compound statement's header (its
    body executes in its own nodes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [it.context_expr for it in stmt.items]
    if isinstance(stmt, (ast.Try,)):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return list(stmt.decorator_list)
    return [stmt]


def eval_roots(stmt: ast.stmt) -> list[ast.AST]:
    """What a CFG node for ``stmt`` actually EVALUATES: the header
    expressions for compound statements (their bodies live in their own
    nodes), the whole statement otherwise.  Transfer functions must scan
    these — walking a compound statement would attribute its body's
    effects to the header node.  Nested function/lambda bodies are the
    caller's concern (they do not execute here)."""
    return _header_exprs(stmt)


def can_raise(stmt: ast.stmt) -> bool:
    """Whether executing this statement (its header, for compounds) may
    raise — see the raise model in the module docstring."""
    for root in _header_exprs(stmt):
        for sub in ast.walk(root):
            if isinstance(sub, (ast.Call, ast.Raise, ast.Assert, ast.Await)):
                return True
    return False


class _Ctx:
    """Where abnormal exits go from the current position (all targets are
    already routed through any enclosing ``finally`` copies)."""

    __slots__ = ("ret", "exc", "brk", "cont")

    def __init__(self, ret: Node, exc: Node,
                 brk: Node | None = None, cont: Node | None = None):
        self.ret = ret
        self.exc = exc
        self.brk = brk
        self.cont = cont

    def replace(self, **kw) -> "_Ctx":
        out = _Ctx(self.ret, self.exc, self.brk, self.cont)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names: list[ast.AST] = (list(handler.type.elts)
                            if isinstance(handler.type, ast.Tuple)
                            else [handler.type])
    for t in names:
        d = dotted(t)
        if d is not None and d.split(".")[-1] in _CATCH_ALL:
            return True
        if d is None:
            return True         # unresolvable: assume it catches
    return False


def _with_suppresses(stmt: ast.With | ast.AsyncWith) -> bool:
    for item in stmt.items:
        if isinstance(item.context_expr, ast.Call):
            d = dotted(item.context_expr.func)
            if d is not None and d.split(".")[-1] in SUPPRESS_TAILS:
                return True
    return False


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    # -- public ---------------------------------------------------------
    def build(self, body: list[ast.stmt]) -> None:
        ctx = _Ctx(ret=self.cfg.exit, exc=self.cfg.raise_exit)
        out = self.stmts(body, [(self.cfg.entry, "norm")], ctx)
        self.connect(out, self.cfg.exit)

    # -- plumbing -------------------------------------------------------
    def connect(self, preds: list[tuple[Node, str]], target: Node) -> None:
        for node, kind in preds:
            node.add(target, kind)

    def stmts(self, body: list[ast.stmt], preds, ctx: _Ctx):
        for stmt in body:
            preds = self.one(stmt, preds, ctx)
        return preds

    def one(self, stmt: ast.stmt, preds, ctx: _Ctx):
        n = self.cfg.new(stmt, "stmt")
        self.connect(preds, n)
        raising = can_raise(stmt)
        if raising:
            n.add(ctx.exc, "exc")

        if isinstance(stmt, ast.Return):
            n.add(ctx.ret, "norm")
            return []
        if isinstance(stmt, ast.Raise):
            # already has the exc edge (Raise always "can raise")
            return []
        if isinstance(stmt, ast.Break):
            if ctx.brk is not None:
                n.add(ctx.brk, "norm")
            return []
        if isinstance(stmt, ast.Continue):
            if ctx.cont is not None:
                n.add(ctx.cont, "norm")
            return []
        if isinstance(stmt, ast.Assert):
            # a failing assert raises; the exc edge above covers it
            return [(n, "norm")]

        if isinstance(stmt, ast.If):
            body_out = self.stmts(stmt.body, [(n, "true")], ctx)
            else_out = (self.stmts(stmt.orelse, [(n, "false")], ctx)
                        if stmt.orelse else [(n, "false")])
            return body_out + else_out

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            join = self.cfg.new(None, "join")
            inner = ctx.replace(brk=join, cont=n)
            body_out = self.stmts(stmt.body, [(n, "true")], inner)
            self.connect(body_out, n)                     # loop back edge
            else_out = (self.stmts(stmt.orelse, [(n, "false")], ctx)
                        if stmt.orelse else [(n, "false")])
            self.connect(else_out, join)
            return [(join, "norm")]

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            after = self.cfg.new(None, "join")
            inner = ctx
            if _with_suppresses(stmt):
                sup = self.cfg.new(None, "dispatch")
                sup.add(after, "norm")     # suppressed: resume after body
                sup.add(ctx.exc, "exc")    # conservatively: may not match
                inner = ctx.replace(exc=sup)
            body_out = self.stmts(stmt.body, [(n, "norm")], inner)
            self.connect(body_out, after)
            return [(after, "norm")]

        if isinstance(stmt, ast.Try):
            return self.try_stmt(stmt, n, ctx)

        return [(n, "norm")]

    # -- try/except/else/finally ---------------------------------------
    def try_stmt(self, stmt: ast.Try, n: Node, ctx: _Ctx):
        if stmt.finalbody:
            # route every continuation through its own copy of finally
            memo: dict[int, Node] = {}

            def via_final(target: Node) -> Node:
                got = memo.get(id(target))
                if got is not None:
                    return got
                head = self.cfg.new(None, "join")
                memo[id(target)] = head
                out = self.stmts(stmt.finalbody, [(head, "norm")], ctx)
                self.connect(out, target)
                return head

            inner = _Ctx(
                ret=via_final(ctx.ret),
                exc=via_final(ctx.exc),
                brk=via_final(ctx.brk) if ctx.brk is not None else None,
                cont=via_final(ctx.cont) if ctx.cont is not None else None,
            )
            body_out = self.try_core(stmt, n, inner)
            after = self.cfg.new(None, "join")
            self.connect(body_out, via_final(after))
            return [(after, "norm")]
        return self.try_core(stmt, n, ctx)

    def try_core(self, stmt: ast.Try, n: Node, ctx: _Ctx):
        """try body + handlers + orelse (``ctx`` already finally-wrapped)."""
        if not stmt.handlers:
            body_out = self.stmts(stmt.body, [(n, "norm")], ctx)
            if stmt.orelse:
                body_out = self.stmts(stmt.orelse, body_out, ctx)
            return body_out
        hdisp = self.cfg.new(None, "dispatch")
        inner = ctx.replace(exc=hdisp)
        body_out = self.stmts(stmt.body, [(n, "norm")], inner)
        if stmt.orelse:
            # orelse exceptions are NOT caught by this try's handlers
            body_out = self.stmts(stmt.orelse, body_out, ctx)
        out = list(body_out)
        for handler in stmt.handlers:
            out += self.stmts(handler.body, [(hdisp, "norm")], ctx)
        if not any(_is_catch_all(h) for h in stmt.handlers):
            hdisp.add(ctx.exc, "exc")       # unmatched: propagates
        return out


def build_cfg(body: list[ast.stmt] | ast.FunctionDef | ast.AsyncFunctionDef
              ) -> CFG:
    """CFG for a function body (pass the def node or its ``body`` list)."""
    if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)):
        body = body.body
    cfg = CFG()
    _Builder(cfg).build(list(body))
    return cfg


# ---------------------------------------------------------------------------
# the forward worklist solver
# ---------------------------------------------------------------------------

def solve_forward(cfg: CFG, init,
                  flow: Callable[[Node, object], dict],
                  join: Callable[[object, object], object]) -> dict[Node, object]:
    """Forward dataflow to fixpoint.

    ``flow(node, in_state)`` returns ``{edge_kind: out_state}`` with ``"*"``
    as the default for unlisted kinds (return ``{"*": state}`` for
    kind-insensitive transfers).  ``join`` merges states at confluence
    points (set-union for a *may* analysis, intersection for *must*).
    Returns ``IN``: the state at each node's entry; unreachable nodes are
    absent (callers treat a missing exit as "no such path").

    Transfer functions MUST be monotone over a finite state space
    (frozensets of tokens are the intended currency) — the worklist then
    terminates.
    """
    IN: dict[Node, object] = {cfg.entry: init}
    work = [cfg.entry]
    while work:
        node = work.pop()
        outs = flow(node, IN[node])
        default = outs.get("*")
        for target, kind in node.succ:
            state = outs.get(kind, default)
            if state is None:
                continue
            cur = IN.get(target)
            new = state if cur is None else join(cur, state)
            if cur is None or new != cur:
                IN[target] = new
                work.append(target)
    return IN


def reachable(start: Node, kinds: Iterable[str] | None = None) -> set[Node]:
    """Nodes reachable from ``start`` (optionally along edge kinds in
    ``kinds`` only) — the CFG-shape test helper."""
    want = set(kinds) if kinds is not None else None
    seen: set[int] = set()
    out: set[Node] = set()
    todo = [start]
    while todo:
        n = todo.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        out.add(n)
        for target, kind in n.succ:
            if want is None or kind in want:
                todo.append(target)
    return out

"""LOCK005/LOCK006 + ASY001/ASY002: interprocedural concurrency rules.

The intraprocedural families (LOCK001-004, RES, DON) check protocols one
function at a time; the PR-10 post-review rounds kept hand-finding the
same two INTERprocedural shapes — blocking work reachable while a lock
is held (the KVPool fragmentation scan), and blocking calls stalling the
asyncio serving loop (the /debug/incidents disk reads).  This module
computes per-function summaries over the whole-package call graph
(lint/callgraph.py) and runs three rule families on them:

- **LOCK005** — lock-order cycles.  Every ``with self._a:`` that
  (transitively) reaches an acquire of ``_b`` contributes a held→acquired
  edge ``a → b``; a cycle in that graph is a potential deadlock, and the
  finding carries a witness call path for EVERY edge of the cycle (see
  docs/LINT.md "Reading a lock-order cycle report").  A self-edge on a
  non-reentrant lock (re-acquire while held) is a one-lock cycle.
- **LOCK006** — a may-block call (socket/file I/O, ``time.sleep``,
  blocking queue/condition waits, subprocess, device syncs, the disagg
  ``FrameSender`` bounded put — and, the PR-10 lesson, a ``sorted()``
  scan) reachable while a tracked lock is held.  Deliberate
  hold-and-block sites are discharged by the audited grammar
  ``# lfkt: blocks-under[<lock>] -- reason`` (mirroring ``transfers[]``:
  reason-less → LINT000, unknown lock name → LINT001); on a ``def`` line
  it covers the function, elsewhere its own line.
- **ASY001/ASY002** — a may-block call reachable from an ``async def``
  body without an ``asyncio.to_thread``/executor hop (the hop is
  invisible to these rules by construction: deferred arguments are not
  call edges).  ASY001 follows sync call chains from the coroutine body;
  ASY002 flags an ``await`` of a package coroutine that itself
  transitively blocks.  ``sorted()`` is NOT in the ASY classification —
  CPU work on the loop is ordinary; it only matters under a lock.

Blocking propagates over sync call edges only: a function whose blocking
runs on its own thread (``Thread(target=...)``, ``executor.submit``,
``asyncio.to_thread``) never taints its spawner, because an argument
reference is not a call edge.  Resolution over-approximates (docstring
of lint/callgraph.py) — a false edge costs a written audit, a missing
edge costs silence.

Summaries are (de)serializable: ``python -m llama_fastapi_k8s_gpu_tpu.lint
--changed`` reuses a cached whole-package pass for files `git diff`
doesn't name (lint/__main__.py), re-deriving only changed files — the
finding set is identical to a full run by construction (pinned by
tests/test_lint.py).
"""

from __future__ import annotations

import re

from .callgraph import CallGraph, build_graph
from .core import Context, Finding, Source

RULES = {
    "LOCK005": "lock-order cycle across the package (potential deadlock)",
    "LOCK006": "may-block call reachable while a lock is held",
    "ASY001": "may-block call reachable from an async def without an "
              "asyncio.to_thread/executor hop",
    "ASY002": "await of a coroutine that transitively blocks",
}

#: ``# lfkt: blocks-under[<lock>, ...] -- reason`` — the audited
#: discharge for deliberate hold-and-block sites (LOCK006).  Angle
#: brackets here keep this very comment from parsing as an annotation.
_BLOCKS_UNDER_RE = re.compile(
    r"#\s*lfkt:\s*blocks-under\[([\w,\s]*)\]\s*(?:--\s*(\S.*))?")

#: cap on rendered witness-chain hops (the full chain exists; messages
#: stay readable)
_MAX_CHAIN = 4


# ---------------------------------------------------------------------------
# per-file summaries (the serializable unit of the --changed cache)
# ---------------------------------------------------------------------------

def summarize(graph: CallGraph) -> dict[str, dict]:
    """rel-path -> {qualname -> summary dict} over the whole package.
    Summary dicts are JSON-serializable (the --changed cache contract)."""
    out: dict[str, dict] = {}
    for key, facts in graph.facts.items():
        fn = graph.index.fns[key]
        rel = fn.src.rel
        out.setdefault(rel, {})[key[1]] = {
            "module": key[0],
            "qual": key[1],
            "is_async": facts.is_async,
            "direct_blocks": [
                [line, reason, sorted(held)]
                for line, reason, held in facts.direct_blocks],
            "acquires": [
                [lock, line, sorted(held)]
                for lock, line, held in facts.acquires],
            "calls": [
                [c.line, [list(k) for k in c.callees], sorted(c.held),
                 c.kind, c.desc, c.exact]
                for c in facts.calls],
            "asserted": sorted(facts.asserted),
        }
    return out


def resolution_digest(graph: CallGraph) -> str:
    """Fingerprint of everything call RESOLUTION depends on beyond a
    file's own text: the symbol tables, class methods, receiver types
    and the lock inventory.  A --changed pass may only reuse cached
    summaries while this digest matches — an added/renamed
    function/class anywhere can change how an UNCHANGED file's calls
    resolve."""
    import hashlib
    import json

    doc = {
        "fns": sorted(f"{m}:{q}" for m, q in graph.index.fns),
        "methods": {name: sorted(f"{m}:{q}" for m, q in keys)
                    for name, keys in sorted(graph.methods_by_name.items())},
        "locks": dict(sorted(graph.locks.items())),
        "types": {f"{c.module}.{c.name}": {
            a: (list(t) if isinstance(t, tuple) else t)
            for a, t in sorted(c.attr_types.items())}
            for c in graph.classes.values()},
        # module-level instance bindings (`FAULTS = FaultInjector()`):
        # cross-file receiver resolution reads these, so rebinding one
        # must invalidate every cached summary that resolved through it
        "module_types": {m: {k: (list(t) if isinstance(t, tuple) else t)
                             for k, t in sorted(mt.items())}
                         for m, mt in sorted(graph.module_types.items())
                         if mt},
        "imports": {m: {k: sorted(map(str, v)) for k, v in sorted(t.items())}
                    for m, t in sorted(graph.index.imports.items())},
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


class _Summary:
    """One function's summary, whichever side of the cache it came from."""

    __slots__ = ("key", "rel", "is_async", "direct_blocks", "acquires",
                 "calls", "asserted")

    def __init__(self, rel: str, doc: dict):
        self.key = (doc["module"], doc["qual"])
        self.rel = rel
        self.is_async = bool(doc["is_async"])
        self.direct_blocks = [
            (int(line), reason, frozenset(held))
            for line, reason, held in doc["direct_blocks"]]
        self.acquires = [
            (lock, int(line), frozenset(held))
            for lock, line, held in doc["acquires"]]
        self.calls = [
            (int(line), [tuple(k) for k in keys], frozenset(held),
             kind, desc, bool(exact))
            for line, keys, held, kind, desc, exact in doc["calls"]]
        self.asserted = frozenset(doc.get("asserted", ()))


# ---------------------------------------------------------------------------
# fixpoints over the summary set
# ---------------------------------------------------------------------------

def _is_cpu_scan(entry: tuple) -> bool:
    """The ``sorted()`` classification counts only under a lock: LOCK006
    consumes it, the ASY family filters it (CPU work on the event loop
    is ordinary)."""
    return entry[0].startswith("O(n log n)")


def _sync_blocks(summaries: dict[tuple, _Summary]) -> dict[tuple, tuple]:
    """key -> (reason, chain) for functions that may block through SYNC
    call edges (their own body or a sync callee's).  ``chain`` is a list
    of rendered hops ending at the blocking operation.  ``sorted()``
    scans PROPAGATE like every other reason — the PR-10 fragmentation
    scan factored into a one-level helper must still fire LOCK006 at the
    locked call site — but a genuine blocking reason always wins over a
    scan-only one, so the ASY rules (which filter scans) never lose a
    real finding behind one."""
    blocks: dict[tuple, tuple] = {}
    for key, s in sorted(summaries.items()):
        best = None
        for line, reason, _held in s.direct_blocks:
            entry = (reason, [f"{reason} at {s.rel}:{line}"])
            if not _is_cpu_scan(entry):
                best = entry
                break
            if best is None:
                best = entry
        if best is not None:
            blocks[key] = best
    changed = True
    while changed:
        changed = False
        for key, s in sorted(summaries.items()):
            cur = blocks.get(key)
            if cur is not None and not _is_cpu_scan(cur):
                continue        # already carries a genuine reason
            for line, callees, _held, kind, desc, _exact in s.calls:
                if kind != "sync":
                    continue
                hits = [c for c in callees if c in blocks]
                hit = next((c for c in hits
                            if not _is_cpu_scan(blocks[c])),
                           hits[0] if hits else None)
                if hit is None:
                    continue
                entry = blocks[hit]
                if cur is None or (_is_cpu_scan(cur)
                                   and not _is_cpu_scan(entry)):
                    reason, chain = entry
                    cur = (reason, [f"{desc}() at {s.rel}:{line}"]
                           + chain[:_MAX_CHAIN])
                    blocks[key] = cur
                    changed = True
                if not _is_cpu_scan(cur):
                    break
    return blocks


def _trans_acquires(summaries: dict[tuple, _Summary]
                    ) -> dict[tuple, dict[str, list]]:
    """key -> {lock id -> witness chain} of locks a call to this function
    may (transitively) acquire — await edges included: an awaited
    coroutine runs on the caller's task."""
    acq: dict[tuple, dict[str, list]] = {}
    for key, s in sorted(summaries.items()):
        mine: dict[str, list] = {}
        for lock, line, _held in s.acquires:
            mine.setdefault(lock, [f"acquires {lock} at {s.rel}:{line}"])
        if mine:
            acq[key] = mine
    changed = True
    while changed:
        changed = False
        for key, s in sorted(summaries.items()):
            mine = acq.setdefault(key, {})
            for line, callees, _held, _kind, desc, exact in s.calls:
                if not exact:
                    continue
                for c in callees:
                    for lock, chain in acq.get(c, {}).items():
                        if lock not in mine:
                            mine[lock] = ([f"{desc}() at {s.rel}:{line}"]
                                          + chain[:_MAX_CHAIN])
                            changed = True
            if not mine:
                acq.pop(key, None)
    return acq


def _coro_blocks(summaries: dict[tuple, _Summary],
                 blocks: dict[tuple, tuple]) -> dict[tuple, tuple]:
    """Async functions that block on their own task: sync-blocking, or
    awaiting a coroutine that does (transitively)."""
    out = {k: v for k, v in blocks.items()
           if k in summaries and summaries[k].is_async
           and not _is_cpu_scan(v)}   # scans on the loop are ordinary CPU
    changed = True
    while changed:
        changed = False
        for key, s in sorted(summaries.items()):
            if not s.is_async or key in out:
                continue
            for line, callees, _held, kind, desc, _exact in s.calls:
                if kind != "await":
                    continue
                hit = next((c for c in callees if c in out), None)
                if hit is not None:
                    reason, chain = out[hit]
                    out[key] = (reason,
                                [f"await {desc}() at {s.rel}:{line}"]
                                + chain[:_MAX_CHAIN])
                    changed = True
                    break
    return out


# ---------------------------------------------------------------------------
# the blocks-under[] discharge grammar
# ---------------------------------------------------------------------------

class _Discharges:
    """Parsed ``blocks-under[...]`` annotations for one source file:
    line -> lock-name set, plus def-spans covering whole functions."""

    def __init__(self, src: Source):
        import ast as _ast

        self.by_line: dict[int, set[str]] = {}
        self.reasonless: list[int] = []
        for i, line in enumerate(src.lines, start=1):
            m = _BLOCKS_UNDER_RE.search(line)
            if m is None:
                continue
            names = {x.strip() for x in m.group(1).split(",") if x.strip()}
            self.by_line[i] = names
            if not m.group(2):
                self.reasonless.append(i)
        self.def_spans: list[tuple[int, int, set[str]]] = []
        if self.by_line:
            for node in _ast.walk(src.tree):
                if isinstance(node, (_ast.FunctionDef,
                                     _ast.AsyncFunctionDef)):
                    # SIGNATURE lines only (exclusive of the first
                    # body line): the documented grammar is def-line =
                    # whole function, anywhere else = that line only —
                    # an annotation on the first body statement must not
                    # silently discharge the rest of the function
                    body_start = (node.body[0].lineno if node.body
                                  else node.lineno + 1)
                    for line in range(node.lineno, body_start):
                        names = self.by_line.get(line)
                        if names and node.end_lineno is not None:
                            self.def_spans.append(
                                (node.lineno, node.end_lineno, names))
                            break

    def covers(self, line: int, lock_short: str) -> bool:
        if lock_short in self.by_line.get(line, ()):
            return True
        return any(lo <= line <= hi and lock_short in names
                   for lo, hi, names in self.def_spans)

    def all_names(self):
        for names in self.by_line.values():
            yield from names


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------

def _shorten(chain: list[str]) -> str:
    if len(chain) > _MAX_CHAIN + 1:
        chain = chain[:_MAX_CHAIN] + ["..."] + chain[-1:]
    return " -> ".join(chain)


def _lock_cycles(edges: dict[tuple[str, str], tuple]) -> list[list[str]]:
    """Simple cycles in the held→acquired lock graph, canonicalized
    (rotated to the smallest lock id, deduplicated).  Self-edges are
    one-lock cycles."""
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str],
            on_path: set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                lo = path.index(min(path))
                cycles.add(tuple(path[lo:] + path[:lo]))
            elif nxt not in on_path and nxt > start and len(path) < 6:
                # only explore nodes > start: each cycle is found once,
                # from its smallest member
                on_path.add(nxt)
                dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for a, b in sorted(edges):
        if a == b:
            cycles.add((a,))
    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return [list(c) for c in sorted(cycles)]


def check_summaries(ctx: Context, graph: CallGraph,
                    summaries: dict[tuple, _Summary]) -> list[Finding]:
    blocks = _sync_blocks(summaries)
    acq = _trans_acquires(summaries)
    coro = _coro_blocks(summaries, blocks)
    by_rel = {s.rel: s for s in ctx.sources}
    discharges = {rel: _Discharges(src) for rel, src in by_rel.items()}
    rlocks = {lk for lk, kind in graph.locks.items() if kind == "rlock"}
    known_lock_names = graph.known_lock_names()
    out: list[Finding] = []

    def dpath(rel: str) -> str:
        src = by_rel.get(rel)
        return ctx.display_path(src) if src is not None else rel

    # -- the blocks-under grammar audits itself (LINT000/LINT001) --------
    for rel, d in sorted(discharges.items()):
        for line in d.reasonless:
            out.append(Finding(
                "LINT000", dpath(rel), line,
                "blocks-under annotation without a reason: write "
                "`# lfkt: blocks-under[<lock>] -- why`"))
        for line, names in sorted(d.by_line.items()):
            if not names:
                out.append(Finding(
                    "LINT001", dpath(rel), line,
                    "blocks-under annotation names no lock"))
            for name in sorted(names):
                if name not in known_lock_names:
                    out.append(Finding(
                        "LINT001", dpath(rel), line,
                        f"blocks-under names unknown lock {name!r} "
                        "(no threading.Lock/RLock/Condition attribute by "
                        "that name exists in the package)"))

    # -- LOCK005: the held→acquired graph and its cycles ------------------
    # edge (held, acquired) -> (rel, line, witness chain)
    edges: dict[tuple[str, str], tuple] = {}
    for key, s in sorted(summaries.items()):
        for lock, line, held in s.acquires:
            for h in sorted(held):
                if h == lock and lock in rlocks:
                    continue        # re-entrant by construction
                edges.setdefault(
                    (h, lock),
                    (s.rel, line,
                     [f"{key[1]} holds {h} and acquires {lock} "
                      f"at {s.rel}:{line}"]))
        for line, callees, held, _kind, desc, exact in s.calls:
            if not held or not exact:
                continue
            for c in callees:
                for lock, chain in acq.get(c, {}).items():
                    for h in sorted(held):
                        if h == lock and lock in rlocks:
                            continue
                        edges.setdefault(
                            (h, lock),
                            (s.rel, line,
                             [f"{key[1]} holds {h}, calls "
                              f"{desc}() at {s.rel}:{line}"]
                             + chain[:_MAX_CHAIN]))
    for cycle in _lock_cycles(edges):
        ring = cycle + cycle[:1] if len(cycle) > 1 else cycle * 2
        legs = []
        anchor = None
        for a, b in zip(ring, ring[1:]):
            rel, line, chain = edges[(a, b)]
            if anchor is None:
                anchor = (rel, line)
            legs.append(f"{a} -> {b} [{_shorten(chain)}]")
        out.append(Finding(
            "LOCK005", dpath(anchor[0]), anchor[1],
            ("lock re-acquired while held (one-lock cycle): "
             if len(cycle) == 1 else
             f"lock-order cycle over {len(cycle)} locks: ")
            + "; ".join(legs)))

    # -- LOCK006: may-block while a lock is held --------------------------
    seen006: set[tuple] = set()
    for key, s in sorted(summaries.items()):
        d = discharges.get(s.rel)
        for line, reason, held in s.direct_blocks:
            for h in sorted(held):
                if h in s.asserted:
                    # the lock is a caller's (`# lfkt: holds[..]`): the
                    # finding lands at the call site that actually TOOK
                    # it, where the fix (or the audit) belongs
                    continue
                short = graph.lock_short(h)
                if d is not None and d.covers(line, short):
                    continue
                mark = (s.rel, line, h)
                if mark not in seen006:
                    seen006.add(mark)
                    out.append(Finding(
                        "LOCK006", dpath(s.rel), line,
                        f"{key[1]} does {reason} while holding {h} — "
                        "move the blocking work outside the lock "
                        "(copy-then-release), or audit with "
                        f"`# lfkt: blocks-under[{short}] -- why`"))
        for line, callees, held, kind, desc, _exact in s.calls:
            if not held or kind != "sync":
                continue
            hit = next((c for c in callees if c in blocks), None)
            if hit is None:
                continue
            reason, chain = blocks[hit]
            for h in sorted(held):
                if h in s.asserted:
                    continue    # reported at the lock-taking call site
                short = graph.lock_short(h)
                if d is not None and d.covers(line, short):
                    continue
                mark = (s.rel, line, h)
                if mark not in seen006:
                    seen006.add(mark)
                    out.append(Finding(
                        "LOCK006", dpath(s.rel), line,
                        f"{key[1]} holds {h} across a call that may "
                        f"block ({reason}): {_shorten([f'{desc}()'] + chain)}"
                        " — move it outside the lock, or audit with "
                        f"`# lfkt: blocks-under[{short}] -- why`"))

    # -- ASY001/ASY002: blocking on the event loop ------------------------
    seen_asy: set[tuple] = set()
    for key, s in sorted(summaries.items()):
        if not s.is_async:
            continue
        for line, reason, _held in s.direct_blocks:
            if reason.startswith("O(n log n)"):
                continue
            mark = ("ASY001", s.rel, line)
            if mark not in seen_asy:
                seen_asy.add(mark)
                out.append(Finding(
                    "ASY001", dpath(s.rel), line,
                    f"async {key[1]} does {reason} on the event loop — "
                    "hop it off with `await asyncio.to_thread(...)` (or "
                    "an executor)"))
        for line, callees, _held, kind, desc, _exact in s.calls:
            if kind == "sync":
                hit = next((c for c in callees if c in blocks
                            and not _is_cpu_scan(blocks[c])), None)
                if hit is None:
                    continue
                reason, chain = blocks[hit]
                mark = ("ASY001", s.rel, line)
                if mark not in seen_asy:
                    seen_asy.add(mark)
                    out.append(Finding(
                        "ASY001", dpath(s.rel), line,
                        f"async {key[1]} calls {desc}() which may block "
                        f"({reason}) on the event loop: "
                        f"{_shorten(chain)} — hop it off with "
                        "`await asyncio.to_thread(...)` (or an executor)"))
            else:   # await edge
                hit = next((c for c in callees if c in coro), None)
                if hit is None:
                    continue
                reason, chain = coro[hit]
                mark = ("ASY002", s.rel, line)
                if mark not in seen_asy:
                    seen_asy.add(mark)
                    out.append(Finding(
                        "ASY002", dpath(s.rel), line,
                        f"async {key[1]} awaits {desc}() which "
                        f"transitively blocks ({reason}): "
                        f"{_shorten(chain)} — the awaited coroutine "
                        "needs the to_thread/executor hop"))
    return out


def check(ctx: Context) -> list[Finding]:
    """Full pass, or — when lint/__main__.py armed ``ctx.lint_incremental``
    (the ``--changed`` mode) — a pass that re-derives summaries only for
    files whose content hash moved since the cached whole-package run.
    The rule families always run over the COMPLETE summary set, so the
    finding set equals a full run's by construction."""
    graph = build_graph(ctx)
    inc = getattr(ctx, "lint_incremental", None)
    if inc is None:
        graph.extract_facts()
        per_file = summarize(graph)
    else:
        digest = resolution_digest(graph)
        cache = inc.get("cache") or {}
        cached_files = (cache.get("files", {})
                        if cache.get("digest") == digest else {})
        shas = inc["shas"]          # rel -> current content sha
        reuse = {rel: entry for rel, entry in cached_files.items()
                 if shas.get(rel) == entry.get("sha")}
        graph.extract_facts(skip_rels=set(reuse))
        per_file = summarize(graph)
        for rel, entry in reuse.items():
            per_file.setdefault(rel, entry["summaries"])
        inc["reused"] = sorted(reuse)
        inc["out"] = {
            "digest": digest,
            "files": {rel: {"sha": shas[rel], "summaries": fns}
                      for rel, fns in per_file.items() if rel in shas},
        }
    summaries: dict[tuple, _Summary] = {}
    for rel, fns in per_file.items():
        for doc in fns.values():
            s = _Summary(rel, doc)
            summaries[s.key] = s
    return check_summaries(ctx, graph, summaries)

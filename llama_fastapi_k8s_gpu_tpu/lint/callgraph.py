"""Whole-package interprocedural call graph — the lfkt-lint v3 substrate.

The jit checker's call-graph (lint/jit.py ``_Index``) resolves calls by
simple name, ``self.method()`` and package imports — enough for "is this
reachable from a trace root", not enough for concurrency questions like
"does the fleet router's proxy loop ever join a thread on the event
loop".  This module extends that edge builder with the three resolution
layers the concurrency rules (lint/concurrency.py LOCK005/006,
ASY001/002) need:

- **receiver types** — ``self._conn = FrameConn(sock)`` /
  ``sender = FrameSender(conn)`` / module-level ``FAULTS =
  FaultInjector()`` bind an attribute, local or module global to a
  package class; ``self._lock = threading.Lock()`` (and Queue /
  Condition / Event / Thread / Semaphore) binds it to a stdlib
  concurrency type, which both classifies blocking method calls
  (``q.get()``, ``thread.join()``) and feeds the lock inventory;
- **conservative method resolution** — ``x.m()`` with an untyped
  receiver resolves to EVERY package class defining ``m``, unless ``m``
  collides with a builtin container/str/bytes method name (``.get()``
  is almost always a dict; smearing every dict read into
  ``FlightRecorder.get`` would drown the rules).  Over-approximation is
  the family trade: a false edge costs a written audit, a missing edge
  costs silence — the builtin-name carve-out is the one deliberate
  under-approximation, documented in docs/LINT.md;
- **the lock inventory** — every ``threading.Lock/RLock/Condition``
  assigned to a ``self.<attr>`` (resolved base-first over the
  in-package MRO, so subclasses share the base's lock identity) or a
  module-level name.  Lock identities are ``module.Class.attr`` /
  ``module.NAME`` — two classes' ``_lock`` attrs are distinct locks.

Call EDGES (as opposed to the jit checker's reference reachability) are
actual invocations only: a function passed as an argument
(``Thread(target=f)``, ``asyncio.to_thread(f)``, ``executor.submit(f)``)
is NOT an edge — the first two are exactly the sanctioned "move the
blocking work off this thread" idioms, and conflating them with calls
would flag the fix as the bug.  An ``await f()`` of a package coroutine
is an ``await`` edge (it runs on the caller's task), and a bare ``f()``
of a coroutine from ASYNC code counts the same (it is almost always
handed straight to ``create_task``/``_spawn`` onto the same loop); a
bare ``f()`` of a coroutine from sync code is dropped (the coroutine
object is created, not run, and the lint cannot know which loop
eventually runs it).  A call site whose by-name fan-out mixes sync and
async candidates is split into one edge of each kind, so a blocking
sync candidate is never hidden behind an await edge.

Nothing here imports jax or executes analyzed code (core.py contract).
"""

from __future__ import annotations

import ast

from .core import Context, Source, dotted, self_attr
from .jit import _Fn, _Index
from .locks import _HOLDS_RE

__all__ = ["CallGraph", "CallSite", "FnFacts", "build_graph"]

#: threading-module constructor tails -> receiver type tag
_THREADING_TYPES = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Event": "event", "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore", "Thread": "thread",
}
#: queue-module constructor tails (any alias of the queue module; the
#: asyncio twins are awaited and never classify as blocking)
_QUEUE_TYPES = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")

#: tracked lock kinds (held-region analysis + the LOCK005 graph).
#: Semaphores/events are deliberately NOT mutual exclusion — holding a
#: permit while blocking is the admission pattern, not a lock hazard.
LOCK_KINDS = ("lock", "rlock", "condition")

def _builtin_methods() -> frozenset:
    """Method names of builtin containers, str/bytes, files, loggers,
    threads/locks/queues and asyncio streams — never resolved by name
    (an untyped ``.get()`` is almost always a dict, an untyped
    ``.write()`` a file or asyncio writer; smearing those into package
    classes would mint phantom edges everywhere).  This is the one
    deliberate under-approximation in the resolution stack — typed
    receivers (``sender = FrameSender(conn)``) still resolve these
    names precisely."""
    import io
    import logging
    import queue
    import threading

    out = set()
    for t in (dict, list, tuple, set, frozenset, str, bytes, bytearray,
              io.IOBase, io.RawIOBase, io.BufferedIOBase, io.TextIOBase,
              logging.Logger, threading.Thread, threading.Event,
              queue.Queue):
        out.update(n for n in dir(t) if not n.startswith("__"))
    # asyncio StreamWriter/StreamReader surface (not imported: asyncio
    # pulls in a lot at import time for no extra coverage)
    out.update(("drain", "wait_closed", "is_closing", "get_extra_info",
                "read", "readline", "readexactly", "readuntil", "at_eof",
                "write", "writelines", "close", "abort", "can_write_eof",
                "write_eof", "transport"))
    return frozenset(out)


#: see :func:`_builtin_methods`
_BUILTIN_METHODS = _builtin_methods()

#: call tails that defer their function-valued arguments to another
#: thread/loop — arguments are never call edges anywhere, but these are
#: listed so concurrency.py can name the sanctioned hop in its messages
DEFER_TAILS = frozenset({"to_thread", "run_in_executor", "submit",
                         "call_soon_threadsafe", "start"})


class CallSite:
    """One resolved invocation inside a function body."""

    __slots__ = ("line", "callees", "held", "kind", "desc", "exact")

    def __init__(self, line: int, callees: list[tuple], held: frozenset,
                 kind: str, desc: str, exact: bool):
        self.line = line
        self.callees = callees      # [(module, qualname), ...]
        self.held = held            # frozenset of lock ids held here
        self.kind = kind            # "sync" | "await"
        self.desc = desc            # rendered call text for messages
        #: resolution was unique/typed.  Ambiguous by-name fan-outs
        #: still propagate MAY-BLOCK (a false edge costs an audit) but
        #: are excluded from the LOCK005 lock graph (a false edge there
        #: mints an unfixable phantom deadlock) — lint/concurrency.py
        self.exact = exact


class FnFacts:
    """Per-function raw facts the summaries are computed from."""

    __slots__ = ("key", "is_async", "direct_blocks", "acquires", "calls",
                 "asserted")

    def __init__(self, key: tuple, is_async: bool):
        self.key = key
        self.is_async = is_async
        #: [(line, reason, held frozenset)]
        self.direct_blocks: list[tuple] = []
        #: [(lock_id, line, held-before frozenset)]
        self.acquires: list[tuple] = []
        self.calls: list[CallSite] = []
        #: lock ids a `# lfkt: holds[..]` marker asserts held throughout
        self.asserted: frozenset = frozenset()


class _Class:
    """One class's resolution surface: methods, attr types, lock attrs."""

    __slots__ = ("key", "name", "module", "node", "src", "bases",
                 "methods", "attr_types", "declared")

    def __init__(self, src: Source, module: str, node: ast.ClassDef):
        self.key = (module, node.name)
        self.name = node.name
        self.module = module
        self.node = node
        self.src = src
        self.bases = [b.split(".")[-1] for b in
                      (dotted(base) for base in node.bases) if b]
        self.methods: dict[str, tuple] = {}      # name -> fn key
        self.attr_types: dict[str, object] = {}  # attr -> tag | _Class key
        self.declared = any(
            isinstance(s, ast.Assign) and len(s.targets) == 1
            and isinstance(s.targets[0], ast.Name)
            and s.targets[0].id in ("_GUARDED_BY", "_THREAD_ENTRIES")
            for s in node.body)


def _ctor_tag(call: ast.Call, graph: "CallGraph", module: str):
    """Type of ``<ctor>(...)``: a stdlib tag string, a package class key,
    or None."""
    d = dotted(call.func)
    if d is not None:
        parts = d.split(".")
        head, tail = parts[0], parts[-1]
        if tail in _THREADING_TYPES and head != "asyncio":
            return _THREADING_TYPES[tail]
        if tail in _QUEUE_TYPES and head != "asyncio":
            return "queue"
        if d in ("socket.socket", "socket.create_connection"):
            return "socket"
    # package class constructor (unique simple name across the package)
    if d is not None:
        simple = d.split(".")[-1]
        hits = graph.classes_by_name.get(simple, [])
        if len(hits) == 1:
            return hits[0].key
    return None


class CallGraph:
    """The package-wide resolution surface (see module docstring)."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.index = _Index(ctx)
        self.classes: dict[tuple, _Class] = {}
        self.classes_by_name: dict[str, list[_Class]] = {}
        #: method name -> [fn keys] over ALL package classes (the
        #: conservative fallback domain)
        self.methods_by_name: dict[str, list[tuple]] = {}
        #: module -> {global var -> type (tag or class key)}
        self.module_types: dict[str, dict[str, object]] = {}
        #: lock id -> kind ("lock"|"rlock"|"condition")
        self.locks: dict[str, str] = {}
        self._collect_classes()
        self._infer_types()
        #: filled by :meth:`extract_facts` — kept separate from
        #: construction so the --changed cache can skip unchanged files'
        #: extraction (the expensive phase) while the resolution surface
        #: above is always current
        self.facts: dict[tuple, FnFacts] = {}

    def extract_facts(self, skip_rels: frozenset | set = frozenset()
                      ) -> None:
        for key, fn in self.index.fns.items():
            if fn.src.rel in skip_rels:
                continue
            self.facts[key] = self._extract(fn)

    # -- class + type collection ----------------------------------------
    def _collect_classes(self) -> None:
        for src in self.ctx.sources:
            module = self.ctx.module_name(src)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                cls = _Class(src, module, node)
                self.classes[cls.key] = cls
                self.classes_by_name.setdefault(cls.name, []).append(cls)
        # method tables come from the jit index (it already walked defs)
        for module, by_cls in self.index.methods.items():
            for cname, methods in by_cls.items():
                cls = self.classes.get((module, cname))
                for mname, key in methods.items():
                    if cls is not None:
                        cls.methods[mname] = key
                    self.methods_by_name.setdefault(mname, []).append(key)

    def _mro(self, cls: _Class) -> list[_Class]:
        """Base-first chain over in-package single inheritance."""
        seen = {cls.key}
        chain: list[_Class] = []

        def add(c: _Class) -> None:
            for base in c.bases:
                hits = self.classes_by_name.get(base, [])
                if len(hits) == 1 and hits[0].key not in seen:
                    seen.add(hits[0].key)
                    add(hits[0])
                    chain.append(hits[0])

        add(cls)
        chain.append(cls)
        return chain

    def _infer_types(self) -> None:
        for src in self.ctx.sources:
            module = self.ctx.module_name(src)
            mt = self.module_types.setdefault(module, {})
            for stmt in src.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Call):
                    tag = _ctor_tag(stmt.value, self, module)
                    if tag is not None:
                        mt[stmt.targets[0].id] = tag
        for cls in self.classes.values():
            for node in ast.walk(cls.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.value, ast.Call):
                    attr = self_attr(node.targets[0])
                    if attr is not None:
                        tag = _ctor_tag(node.value, self, cls.module)
                        if tag is not None:
                            cls.attr_types.setdefault(attr, tag)
        # lock inventory: attr locks resolve base-first over the MRO so a
        # subclass's `with self._lock:` names the DEFINING class's lock
        for cls in self.classes.values():
            for attr, tag in cls.attr_types.items():
                if tag in LOCK_KINDS:
                    self.locks[f"{cls.module}.{cls.name}.{attr}"] = tag
        for module, mt in self.module_types.items():
            for var, tag in mt.items():
                if tag in LOCK_KINDS:
                    self.locks[f"{module}.{var}"] = tag

    # -- type / lock lookup ----------------------------------------------
    def attr_type(self, cls: _Class, attr: str):
        for c in reversed(self._mro(cls)):
            if attr in c.attr_types:
                return c.attr_types[attr]
        return None

    def lock_id(self, cls: _Class | None, module: str,
                expr: ast.AST) -> str | None:
        """Lock identity of ``self.<attr>`` / module-level ``<name>``
        when it is a tracked lock, else None."""
        attr = self_attr(expr)
        if attr is not None and cls is not None:
            for c in self._mro(cls):
                if c.attr_types.get(attr) in LOCK_KINDS:
                    return f"{c.module}.{c.name}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if self.module_types.get(module, {}).get(expr.id) in LOCK_KINDS:
                return f"{module}.{expr.id}"
        return None

    def lock_short(self, lock_id: str) -> str:
        """The attr/name part annotations use (``_lock``)."""
        return lock_id.rsplit(".", 1)[-1]

    def known_lock_names(self) -> set[str]:
        return {self.lock_short(lk) for lk in self.locks}

    def fn_class(self, fn: _Fn) -> _Class | None:
        if fn.cls is None:
            return None
        return self.classes.get((fn.module, fn.cls))

    # -- call resolution --------------------------------------------------
    def _recv_type(self, fn: _Fn, cls: _Class | None,
                   local_types: dict[str, object], recv: ast.AST):
        attr = self_attr(recv)
        if attr is not None and cls is not None:
            return self.attr_type(cls, attr)
        if isinstance(recv, ast.Name):
            if recv.id in local_types:
                return local_types[recv.id]
            mt = self.module_types.get(fn.module, {})
            if recv.id in mt:
                return mt[recv.id]
            # `from ..utils.faults import FAULTS` — imported instance
            for imp in self.index.imports.get(fn.module, {}) \
                    .get(recv.id, []):
                if imp[0] == "name":
                    t = self.module_types.get(imp[1], {}).get(imp[2])
                    if t is not None:
                        return t
        return None

    def resolve_call(self, fn: _Fn, cls: _Class | None,
                     local_types: dict[str, object],
                     call: ast.Call) -> tuple[list[tuple], object, bool]:
        """(callee keys, receiver type, exact) for one Call node —
        ``exact`` is False only for the conservative all-classes by-name
        fan-out (see :class:`CallSite`)."""
        func = call.func
        got = self.index.resolve(fn.module, func, scope=fn)
        if got:
            return list(dict.fromkeys(got)), None, True
        if isinstance(func, ast.Attribute):
            rt = self._recv_type(fn, cls, local_types, func.value)
            if isinstance(rt, tuple):            # package class instance
                target = self.classes.get(rt)
                if target is not None:
                    for c in reversed(self._mro(target)):
                        if func.attr in c.methods:
                            return [c.methods[func.attr]], rt, True
                return [], rt, True
            if isinstance(rt, str):              # stdlib concurrency type
                return [], rt, True
            # conservative fallback: every package class defining the
            # method, unless the name collides with builtin containers
            if func.attr not in _BUILTIN_METHODS:
                keys = list(dict.fromkeys(
                    self.methods_by_name.get(func.attr, [])))
                return keys, None, len(keys) <= 1
        return [], None, True

    # -- per-function fact extraction -------------------------------------
    def _extract(self, fn: _Fn) -> FnFacts:
        cls = self.fn_class(fn)
        facts = FnFacts(fn.key, isinstance(fn.node, ast.AsyncFunctionDef))
        facts.asserted = self._asserted(fn, cls)

        # local receiver types: annotated params (`sender: FrameSender`),
        # `x = Ctor(...)` constructions and `x = self.attr` aliases
        local_types: dict[str, object] = {}
        args = fn.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + [x for x in (args.vararg, args.kwarg) if x]):
            if a.annotation is not None:
                d = dotted(a.annotation)
                if d is not None:
                    hits = self.classes_by_name.get(d.split(".")[-1], [])
                    if len(hits) == 1:
                        local_types[a.arg] = hits[0].key
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                if isinstance(node.value, ast.Call):
                    tag = _ctor_tag(node.value, self, fn.module)
                    if tag is not None:
                        local_types[node.targets[0].id] = tag
                else:
                    attr = self_attr(node.value)
                    if attr is not None and cls is not None:
                        t = self.attr_type(cls, attr)
                        if t is not None:
                            local_types[node.targets[0].id] = t

        # walk the fn's OWN body (nested defs are their own functions),
        # tracking the held-lock set through with-blocks
        def visit(node: ast.AST, held: frozenset, awaited: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn.node:
                return
            if isinstance(node, ast.Lambda):
                return      # a lambda body runs at CALL time, elsewhere
            if isinstance(node, (ast.With, ast.AsyncWith)):
                add = set()
                for item in node.items:
                    lk = self.lock_id(cls, fn.module, item.context_expr)
                    if lk is not None:
                        add.add(lk)
                        facts.acquires.append(
                            (lk, item.context_expr.lineno,
                             held | facts.asserted))
                    visit(item.context_expr, held, awaited)
                inner = held | frozenset(add)
                for item in node.items:
                    if item.optional_vars is not None:
                        visit(item.optional_vars, inner, awaited)
                for child in node.body:
                    visit(child, inner, awaited)
                return
            if isinstance(node, ast.Await):
                visit(node.value, held, True)
                return
            if isinstance(node, ast.Call):
                self._classify_call(fn, cls, local_types, facts, node,
                                    held | facts.asserted, awaited)
                awaited = False     # only the outermost call is awaited
            for child in ast.iter_child_nodes(node):
                visit(child, held, awaited)

        for stmt in fn.node.body:
            visit(stmt, frozenset(), False)
        return facts

    def _asserted(self, fn: _Fn, cls: _Class | None) -> frozenset:
        """Lock ids a def-line ``# lfkt: holds[..]`` marker asserts."""
        node = fn.node
        body_start = node.body[0].lineno if node.body else node.lineno
        out = set()
        for line in fn.src.lines[node.lineno - 1: body_start]:
            for name in _HOLDS_RE.findall(line):
                if cls is not None:
                    for c in self._mro(cls):
                        if c.attr_types.get(name) in LOCK_KINDS:
                            out.add(f"{c.module}.{c.name}.{name}")
                            break
        return frozenset(out)

    def _classify_call(self, fn: _Fn, cls, local_types, facts: FnFacts,
                       call: ast.Call, held: frozenset,
                       awaited: bool) -> None:
        d = dotted(call.func)
        desc = (d or ("." + call.func.attr
                      if isinstance(call.func, ast.Attribute) else "<call>"))
        callees, recv_type, exact = self.resolve_call(
            fn, cls, local_types, call)

        # bare lock.acquire() / release() regions: treat a direct
        # .acquire() on a tracked lock as an acquire event (the RES002
        # rule owns the release-on-every-path question)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "acquire":
            lk = self.lock_id(cls, fn.module, call.func.value)
            if lk is not None:
                facts.acquires.append((lk, call.lineno, held))
                return

        if not awaited:
            reason = self._block_reason(call, d, recv_type)
            if reason is not None:
                facts.direct_blocks.append((call.lineno, reason, held))

        if callees:
            # an ambiguous by-name fan-out may mix sync and async
            # candidates — they get SEPARATE call sites so a blocking
            # sync candidate is never hidden behind an await edge (the
            # rule fixpoints follow sync and await edges differently)
            sync_keys, await_keys = [], []
            for key in callees:
                target = self.index.fns.get(key)
                if target is None:
                    continue
                if isinstance(target.node, ast.AsyncFunctionDef):
                    if facts.is_async:
                        # awaited, or created-in-async-context: a bare
                        # coroutine call inside an async def is almost
                        # always handed to create_task/_spawn onto the
                        # SAME loop, so it rides the await fixpoint too
                        await_keys.append(key)
                    # sync caller of an async def: coroutine created, not
                    # run — no edge (see module docstring)
                elif not awaited:
                    sync_keys.append(key)
            if sync_keys:
                facts.calls.append(CallSite(
                    call.lineno, sync_keys, held, "sync", desc, exact))
            if await_keys:
                facts.calls.append(CallSite(
                    call.lineno, await_keys, held, "await", desc, exact))

    @staticmethod
    def _block_reason(call: ast.Call, d: str | None,
                      recv_type) -> str | None:
        """Why this (non-awaited) call may block, or None."""
        if d is not None:
            parts = d.split(".")
            head, tail = parts[0], parts[-1]
            if d == "time.sleep":
                return "time.sleep"
            if head == "subprocess" and tail in (
                    "run", "Popen", "call", "check_call", "check_output"):
                return f"subprocess ({d})"
            if d in ("socket.create_connection", "socket.getaddrinfo"):
                return f"socket I/O ({d})"
            if d == "open":
                return "file I/O (open)"
            if d in ("os.fsync", "os.listdir", "os.remove", "os.replace",
                     "os.makedirs", "os.rename", "os.stat",
                     "os.path.getsize"):
                return f"file I/O ({d})"
            if len(parts) > 1 and tail in ("block_until_ready",
                                           "device_get"):
                return f"device sync ({tail})"
            if d == "sorted":
                # the PR-10 fragmentation-scan lesson: an O(n log n) scan
                # is "blocking" exactly when something else is queued on
                # the lock it runs under — classified for LOCK006 only
                # (concurrency.py ignores it for the ASY family: sorting
                # on the event loop is ordinary CPU work)
                return "O(n log n) scan (sorted)"
        if isinstance(call.func, ast.Attribute):
            tail = call.func.attr
            if tail == "item" and not call.args:
                return "device sync (.item())"
            if tail in ("recv", "recv_into", "sendall", "accept",
                        "getresponse", "makefile", "request"):
                return f"socket I/O (.{tail}())"
            if recv_type == "queue" and tail in ("get", "put", "join"):
                return f"blocking queue .{tail}()"
            if recv_type in ("condition", "event") \
                    and tail in ("wait", "wait_for"):
                return f"{recv_type} .{tail}()"
            if recv_type == "thread" and tail == "join":
                return "thread join"
            if recv_type == "socket" and tail in ("connect", "send",
                                                  "recv", "accept"):
                return f"socket I/O (.{tail}())"
        return None


def build_graph(ctx: Context) -> CallGraph:
    """One CallGraph per lint pass: concurrency and taint both ride the
    resolution surface, and building it twice would double the dominant
    cost of a whole-package run — so the graph memoizes on the Context.
    ``extract_facts`` stays idempotent-per-caller (facts accumulate per
    key), so sharing is safe across checkers."""
    graph = getattr(ctx, "_lint_callgraph", None)
    if graph is None:
        graph = CallGraph(ctx)
        ctx._lint_callgraph = graph
    return graph

"""LOCK001-004: declarative lock discipline for concurrency-heavy classes.

A class opts in by declaring (class-body literal assignments):

``_GUARDED_BY = {"_cache": "_lock", ...}``
    attribute -> the ``self.<lock>`` that must be held to WRITE it (writes
    are assignments, augmented assignments, deletes, subscript stores, and
    calls of mutating methods — append/put/clear/...).  ``__init__`` is
    exempt (construction precedes publication).  Inherited and mergeable:
    a subclass entry overrides the base's; mapping an attribute to
    ``None`` removes it (the subclass replaces the lock protocol with a
    different discipline — declare which below).

``_THREAD_ENTRIES = ("_loop",)``
    methods that run as their own thread (scheduler/watchdog loops).
    Methods reachable from an entry (same-class call graph over
    ``self.m()``) may write only declared attributes — anything else is an
    undeclared cross-thread share (LOCK002).

``_THREAD_CONFINED = ("_bstate", ...)``
    attributes written ONLY by the owning thread (reads elsewhere are
    racy-by-design snapshots).  A write from a non-entry-reachable method
    is a confinement break (LOCK002) unless suppressed with a reason
    (e.g. ``recover()`` runs strictly after the thread died).

``_SHARED_ATOMIC = ("_items", "_stop", ...)``
    attributes shared across threads whose individual operations are
    atomic by design (GIL dict/list ops, threading.Event) — exempt from
    write checks, but the declaration keeps the inventory honest.

A method whose ``def`` line carries ``# lfkt: holds[_lock]`` asserts it is
only ever called with that lock held; LOCK001 then accepts its writes, and
LOCK003 verifies every same-class call site actually holds the lock (a
``with self._lock:`` block, an ``acquire()``/``release()`` region, another
``holds`` method, or ``__init__``).

The convention is documented for engine authors in docs/RUNBOOK.md
("Lock discipline annotations") and docs/LINT.md.
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, Source, const_str, dotted, self_attr, str_seq

RULES = {
    "LOCK001": "write to a _GUARDED_BY attribute without holding its lock",
    "LOCK002": "thread-entry method writes an undeclared shared attribute "
               "(or a thread-confined attribute is written off-thread)",
    "LOCK003": "call to a `# lfkt: holds[lock]` method without the lock",
    "LOCK004": "lock-discipline declaration names an unknown lock/method",
}

#: method calls that mutate their receiver — a call on a guarded attr is a
#: write for LOCK001 purposes
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft", "clear",
    "update", "add", "remove", "discard", "setdefault", "put", "put_nowait",
    "sort", "reverse",
})

_HOLDS_RE = re.compile(r"#\s*lfkt:\s*holds\[(\w+)\]")


class _ClassInfo:
    def __init__(self, src: Source, node: ast.ClassDef):
        self.src = src
        self.node = node
        self.name = node.name
        self.bases = [b.split(".")[-1] for b in
                      (dotted(base) for base in node.bases) if b]
        self.guarded: dict[str, str | None] = {}
        self.entries: list[str] = []
        self.confined: list[str] = []
        self.atomic: list[str] = []
        self.declared = False
        self.methods: dict[str, ast.FunctionDef] = {}
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if name == "_GUARDED_BY" and isinstance(stmt.value, ast.Dict):
                    self.declared = True
                    for k, v in zip(stmt.value.keys, stmt.value.values):
                        ks = const_str(k) if k is not None else None
                        if ks is None:
                            continue
                        if isinstance(v, ast.Constant) and v.value is None:
                            self.guarded[ks] = None
                        else:
                            self.guarded[ks] = const_str(v)
                elif name in ("_THREAD_ENTRIES", "_THREAD_CONFINED",
                              "_SHARED_ATOMIC"):
                    vals = str_seq(stmt.value)
                    if vals is not None:
                        self.declared = True
                        if name == "_THREAD_ENTRIES":
                            self.entries = vals
                        elif name == "_THREAD_CONFINED":
                            self.confined = vals
                        else:
                            self.atomic = vals

    def holds_marker(self, fn: ast.FunctionDef) -> set[str]:
        """Locks asserted held by a ``# lfkt: holds[..]`` comment on any
        line of the (possibly multi-line) def signature."""
        body_start = fn.body[0].lineno if fn.body else fn.lineno
        out: set[str] = set()
        for line in self.src.lines[fn.lineno - 1: body_start]:
            out.update(_HOLDS_RE.findall(line))
        return out


def _collect_classes(ctx: Context) -> dict[str, _ClassInfo]:
    out: dict[str, _ClassInfo] = {}
    for src in ctx.sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                # last definition wins; class names are unique in practice
                out[node.name] = _ClassInfo(src, node)
    return out


def _mro(info: _ClassInfo, classes: dict[str, _ClassInfo],
         seen: set[str] | None = None) -> list[_ClassInfo]:
    """Base-first linearization over in-package single inheritance."""
    seen = seen or set()
    chain: list[_ClassInfo] = []
    for base in info.bases:
        b = classes.get(base)
        if b is not None and b.name not in seen:
            seen.add(b.name)
            chain.extend(_mro(b, classes, seen))
    chain.append(info)
    return chain


def _effective_guarded(info: _ClassInfo,
                       classes: dict[str, _ClassInfo]) -> dict[str, str]:
    merged: dict[str, str | None] = {}
    for c in _mro(info, classes):
        merged.update(c.guarded)
    return {k: v for k, v in merged.items() if v is not None}


def _held_regions(fn: ast.FunctionDef, locks: set[str]):
    """(with_map, acquire_spans): for each lock, the set of nodes inside a
    ``with self.<lock>`` body, plus (first, last) line spans between an
    ``self.<lock>.acquire()`` call and the matching ``release()``."""
    with_nodes: dict[int, set[str]] = {}     # id(node) -> locks held there

    def visit(node: ast.AST, held: frozenset):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            add = set()
            for item in node.items:
                d = self_attr(item.context_expr)
                if d in locks:
                    add.add(d)
            held = held | frozenset(add)
        with_nodes[id(node)] = set(held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(fn, frozenset())

    spans: dict[str, tuple[int, int]] = {}
    acq: dict[str, int] = {}
    rel: dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            lock = self_attr(node.func.value)
            if lock in locks:
                if node.func.attr == "acquire":
                    acq.setdefault(lock, node.lineno)
                elif node.func.attr == "release":
                    rel[lock] = max(rel.get(lock, 0), node.lineno)
    for lock, start in acq.items():
        if lock in rel:
            spans[lock] = (start, rel[lock])
    return with_nodes, spans


def _holds_at(node: ast.AST, lock: str, with_nodes, spans,
              asserted: set[str]) -> bool:
    if lock in asserted:
        return True
    if lock in with_nodes.get(id(node), ()):
        return True
    span = spans.get(lock)
    return span is not None and span[0] <= getattr(node, "lineno", 0) <= span[1]


def _writes(fn: ast.FunctionDef):
    """Yield (node, attr) for every write to a ``self.<attr>`` in fn."""
    for node in ast.walk(fn):
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", True) is not None:
                targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            attr = self_attr(node.func.value)
            if attr is not None:
                yield node, attr
            continue
        for t in targets:
            for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                attr = self_attr(el)
                if attr is not None:
                    yield node, attr


def _entry_reachable(info: _ClassInfo) -> set[str]:
    """Methods reachable from _THREAD_ENTRIES via same-class self.m() calls."""
    edges: dict[str, set[str]] = {}
    for name, fn in info.methods.items():
        calls = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if (isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in info.methods):
                    calls.add(node.func.attr)
        edges[name] = calls
    seen = set()
    todo = [e for e in info.entries if e in info.methods]
    while todo:
        m = todo.pop()
        if m in seen:
            continue
        seen.add(m)
        todo.extend(edges.get(m, ()))
    return seen


def check(ctx: Context) -> list[Finding]:
    classes = _collect_classes(ctx)
    out: list[Finding] = []
    for info in classes.values():
        chain = _mro(info, classes)
        if not any(c.declared for c in chain):
            continue
        guarded = _effective_guarded(info, classes)
        path = ctx.display_path(info.src)
        confined = set(info.confined)
        atomic = set(info.atomic)
        declared_attrs = set(guarded) | confined | atomic
        entry_set = _entry_reachable(info)

        # holds-markers across the MRO (call sites may target base methods)
        holds_by_method: dict[str, set[str]] = {}
        for c in chain:
            for name, fn in c.methods.items():
                marks = c.holds_marker(fn)
                if marks:
                    holds_by_method[name] = marks

        # locks to track in held-region analysis: everything the guarded
        # map names PLUS locks holds-marked callees require (a subclass may
        # drop an attr from _GUARDED_BY yet still call base holds-methods)
        locks = {v for v in guarded.values()} | {
            lk for marks in holds_by_method.values() for lk in marks}

        # LOCK004: declaration sanity (only for the declaring class itself)
        if info.declared:
            init_assigns: set[str] = set()
            for c in chain:
                init = c.methods.get("__init__")
                if init is not None:
                    for _, attr in _writes(init):
                        init_assigns.add(attr)
            for attr, lock in sorted(info.guarded.items()):
                if lock is not None and lock not in init_assigns:
                    out.append(Finding(
                        "LOCK004", path, info.node.lineno,
                        f"{info.name}._GUARDED_BY maps {attr!r} to "
                        f"{lock!r}, which no __init__ in its MRO assigns"))
            for entry in info.entries:
                if not any(entry in c.methods for c in chain):
                    out.append(Finding(
                        "LOCK004", path, info.node.lineno,
                        f"{info.name}._THREAD_ENTRIES names unknown "
                        f"method {entry!r}"))

        for name, fn in info.methods.items():
            if name == "__init__":
                continue
            asserted = holds_by_method.get(name, set())
            with_nodes, spans = _held_regions(fn, locks)

            for node, attr in _writes(fn):
                if attr in guarded:
                    lock = guarded[attr]
                    if not _holds_at(node, lock, with_nodes, spans, asserted):
                        out.append(Finding(
                            "LOCK001", path, node.lineno,
                            f"{info.name}.{name} writes self.{attr} "
                            f"without holding self.{lock}"))
                elif attr in confined and info.entries \
                        and name not in entry_set:
                    out.append(Finding(
                        "LOCK002", path, node.lineno,
                        f"{info.name}.{name} writes thread-confined "
                        f"self.{attr} outside the owning thread's methods"))
                elif attr not in declared_attrs and name in entry_set:
                    out.append(Finding(
                        "LOCK002", path, node.lineno,
                        f"thread-entry path {info.name}.{name} writes "
                        f"undeclared self.{attr} (declare it in _GUARDED_BY, "
                        f"_THREAD_CONFINED or _SHARED_ATOMIC)"))

            # LOCK003: calls into holds-marked methods must hold the lock
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    continue
                callee = node.func.attr
                needs = holds_by_method.get(callee, set())
                for lock in needs:
                    if not _holds_at(node, lock, with_nodes, spans, asserted):
                        out.append(Finding(
                            "LOCK003", path, node.lineno,
                            f"{info.name}.{name} calls self.{callee}() "
                            f"(# lfkt: holds[{lock}]) without holding "
                            f"self.{lock}"))
    return out

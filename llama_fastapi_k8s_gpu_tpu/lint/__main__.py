"""``python -m llama_fastapi_k8s_gpu_tpu.lint`` — run the lint suite.

Exit status 0 when the tree has zero unsuppressed findings, 1 otherwise
(machine-consumable: CI gates on it).  stdlib-only, no jax import, runs
in a couple of seconds on CPU.

Options:
  --json           one JSON object per finding on stdout (machine-readable)
  --all            include suppressed findings in the output
  --rule R [...]   restrict to specific rule IDs
  --package DIR    analyze a different package tree (fixture self-tests)
  --root DIR       repo root for helm/docs cross-checks
  --changed        incremental mode for the pre-commit loop: re-derive
                   interprocedural summaries only for files whose
                   content hash moved since the cached whole-package
                   pass (in practice: what `git diff --name-only`
                   names — the diff is reported, the hashes decide);
                   every other file's summaries come from the cache the
                   last pass wrote (.lfkt_lint_cache.json, repo-root,
                   gitignored).  The finding set is IDENTICAL to a full
                   run — pinned by tests/test_lint.py
  --list-rules     print the rule catalog and exit
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import all_rules, run_lint

CACHE_NAME = ".lfkt_lint_cache.json"
CACHE_SCHEMA = 1


def _git_changed(root: str) -> list[str]:
    """Repo-relative paths `git diff --name-only HEAD` (plus untracked)
    reports — ADVISORY ONLY, for the operator-facing message: content
    hashes (not this list) decide what actually re-derives, so a stale
    or failed diff can only mislabel the message, never the findings."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=5)
        extra = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.TimeoutExpired):
        return []
    out = []
    for proc in (diff, extra):
        if proc.returncode == 0:
            out.extend(ln.strip() for ln in proc.stdout.splitlines()
                       if ln.strip())
    return sorted(set(out))


def _load_cache(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if doc.get("schema") == CACHE_SCHEMA else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="llama_fastapi_k8s_gpu_tpu.lint")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--all", action="store_true",
                    help="include suppressed findings")
    ap.add_argument("--rule", nargs="*", default=None)
    ap.add_argument("--package", default=None)
    ap.add_argument("--root", default=None)
    ap.add_argument("--changed", action="store_true",
                    help="incremental pre-commit mode (see module help)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(all_rules().items()):
            print(f"{rule}  {desc}")
        return 0

    incremental = None
    cache_path = None
    if args.changed:
        root = args.root
        if root is None:
            pkg = args.package or os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            cand = os.path.dirname(os.path.abspath(pkg))
            root = cand if os.path.isdir(os.path.join(cand, "tests")) \
                else None
        cache_path = os.path.join(root or ".", CACHE_NAME)
        incremental = {"cache": _load_cache(cache_path)}
        changed = _git_changed(root) if root else []
        if changed and not args.json:
            print(f"--changed: git names {len(changed)} changed file(s); "
                  "content hashes decide reuse", file=sys.stderr)

    findings = run_lint(package_dir=args.package, repo_root=args.root,
                        rules=args.rule, incremental=incremental)

    if incremental is not None and incremental.get("out") is not None \
            and cache_path is not None:
        doc = {"schema": CACHE_SCHEMA, **incremental["out"]}
        try:
            with open(cache_path, "w", encoding="utf-8") as f:
                json.dump(doc, f)
        except OSError as e:
            print(f"--changed: cache not written ({e})", file=sys.stderr)
        reused = incremental.get("reused") or []
        print(f"--changed: reused cached summaries for "
              f"{len(reused)} file(s)", file=sys.stderr)
    live = [f for f in findings if not f.suppressed]
    shown = findings if args.all else live
    if args.json:
        for f in shown:
            print(json.dumps(vars(f)))
    else:
        for f in shown:
            print(f.render())
        n_sup = sum(1 for f in findings if f.suppressed)
        print(f"lfkt-lint: {len(live)} finding(s), {n_sup} suppressed",
              file=sys.stderr)
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())

"""``python -m llama_fastapi_k8s_gpu_tpu.lint`` — run the lint suite.

Exit status 0 when the tree has zero unsuppressed findings, 1 otherwise
(machine-consumable: CI gates on it).  stdlib-only, no jax import, runs
in a couple of seconds on CPU.

Options:
  --json           one JSON object per finding on stdout (machine-readable)
  --all            include suppressed findings in the output
  --rule R [...]   restrict to specific rule IDs
  --package DIR    analyze a different package tree (fixture self-tests)
  --root DIR       repo root for helm/docs cross-checks
  --list-rules     print the rule catalog and exit
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import all_rules, run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="llama_fastapi_k8s_gpu_tpu.lint")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--all", action="store_true",
                    help="include suppressed findings")
    ap.add_argument("--rule", nargs="*", default=None)
    ap.add_argument("--package", default=None)
    ap.add_argument("--root", default=None)
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(all_rules().items()):
            print(f"{rule}  {desc}")
        return 0

    findings = run_lint(package_dir=args.package, repo_root=args.root,
                        rules=args.rule)
    live = [f for f in findings if not f.suppressed]
    shown = findings if args.all else live
    if args.json:
        for f in shown:
            print(json.dumps(vars(f)))
    else:
        for f in shown:
            print(f.render())
        n_sup = sum(1 for f in findings if f.suppressed)
        print(f"lfkt-lint: {len(live)} finding(s), {n_sup} suppressed",
              file=sys.stderr)
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())

"""EXC001: degrade-path obligations for swallowed failures.

KER002 proves a kernel module HAS a fallback; nothing proved the fallback
paths ATTRIBUTE themselves.  The contract (ops/pallas/probe.py,
dequant.py's ``_FORCE_HOST`` latch): when a function swallows a
lowering/compile error and degrades to a slower path, it must record the
degrade — otherwise a pod silently serves the slow path forever and every
dashboard says "healthy".

A function opts in with a def-line annotation naming the attribution it
owes (a latch global, a ``self.<attr>``, a config field):

```python
def device_dequant(...):  # lfkt: degrades[_FORCE_HOST]
```

**EXC001** then fires when

- any ``except`` handler in the function can complete WITHOUT raising
  (it swallows) while some path through it never writes every named
  attribution — checked over the handler-body CFG with a must-analysis,
  so a write hidden under only one branch of the handler still fires; or
- the annotation names an attribution the function never writes at all
  (a typo'd registry checks nothing — the LOCK004 principle).

Handlers that re-raise on every path owe nothing (the failure is not
swallowed).  Suppress per-handler with a line noqa when a specific
handler is exempt for a structural reason.
"""

from __future__ import annotations

import ast
import re

from .cfg import build_cfg, solve_forward
from .core import Context, Finding, Source

RULES = {
    "EXC001": "`# lfkt: degrades[attr]` function swallows an exception "
              "without setting its fallback attribution on every path",
}

_DEGRADES_RE = re.compile(r"#\s*lfkt:\s*degrades\[([\w,\s]*)\]")


def _degrades_marker(src: Source, fn) -> set[str]:
    body_start = fn.body[0].lineno if fn.body else fn.lineno
    out: set[str] = set()
    for line in src.lines[fn.lineno - 1: body_start]:
        for m in _DEGRADES_RE.finditer(line):
            out.update(x.strip() for x in m.group(1).split(",") if x.strip())
    return out


def _writes_in(stmt: ast.stmt, attrs: set[str]) -> set[str]:
    """Attributions written by this statement: an assign whose target's
    terminal name matches (``_FORCE_HOST = True``, ``self.attn_impl = x``,
    ``cfg.attn_impl = x``)."""
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: set[str] = set()
    for t in targets:
        for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
            name = None
            if isinstance(el, ast.Name):
                name = el.id
            elif isinstance(el, ast.Attribute):
                name = el.attr
            if name in attrs:
                out.add(name)
    return out


def _own_handlers(fn) -> list[ast.ExceptHandler]:
    """Except handlers lexically in ``fn``, skipping nested defs (their
    handlers belong to their own annotated function, if any)."""
    out: list[ast.ExceptHandler] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.ExceptHandler):
                out.append(child)
            walk(child)

    walk(fn)
    return out


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for src in ctx.sources:
        path = ctx.display_path(src)
        for fn in ast.walk(src.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            required = _degrades_marker(src, fn)
            if not required:
                continue
            # sanity: every named attribution is written SOMEWHERE in the
            # function (otherwise the annotation checks nothing)
            written_anywhere: set[str] = set()
            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.stmt):
                    written_anywhere |= _writes_in(stmt, required)
            ghost = required - written_anywhere
            if ghost:
                out.append(Finding(
                    "EXC001", path, fn.lineno,
                    f"degrades[{', '.join(sorted(ghost))}] names an "
                    f"attribution {fn.name} never sets — typo'd "
                    "annotations check nothing"))
            required = required & written_anywhere
            if not required:
                continue
            for handler in _own_handlers(fn):
                cfg = build_cfg(handler.body)

                def flow(node, state, _req=required):
                    stmt = node.stmt
                    if stmt is None:
                        return {"*": state}
                    done = state | frozenset(_writes_in(stmt, _req))
                    # the write happened iff the statement completed
                    return {"*": done, "exc": state}

                IN = solve_forward(cfg, frozenset(), flow,
                                   lambda a, b: a & b)
                at_exit = IN.get(cfg.exit)
                if at_exit is None:
                    continue        # every path re-raises: not swallowed
                missing = required - at_exit
                if missing:
                    out.append(Finding(
                        "EXC001", path, handler.lineno,
                        f"this handler can swallow the failure without "
                        f"setting {', '.join(sorted(missing))} on every "
                        f"path — the degrade would be unattributed "
                        f"(# lfkt: degrades[...] on {fn.name})"))
    return out

"""JIT001-003: purity of everything reachable from a jit trace.

A Python side effect inside a traced function does not run per step — it
runs once at trace time and silently bakes its value into the compiled
program (env reads, ``time.*``), retraces on closed-over-state mutation,
or forces a host⇄device synchronization (``.item()``,
``block_until_ready``) in the middle of the decode hot loop — the exact
synchronization-boundary overhead Kernel Looping (arXiv:2410.23668)
identifies as dominating decode.  None of these fail loudly; all of them
are invisible in tests that only check outputs.

The checker builds a call graph from the module ASTs:

- roots: functions decorated with ``jax.jit``/``pjit`` (directly or via
  ``functools.partial``), and functions passed to ``jax.jit(...)`` /
  ``shard_map(...)`` call expressions;
- edges: calls by simple name (nearest lexical scope, then module level),
  ``self.method()`` (same class), names imported from package modules
  (``from ..x import y`` / ``from .. import x; x.f()``); functions passed
  as call *arguments* inside reachable code (``lax.scan(body, ...)``,
  ``pl.pallas_call(kernel, ...)``) are reachable too, as is everything
  lexically nested in a reachable function.  Resolution is by name and
  deliberately over-approximates — a false edge costs a suppression with
  a written reason, a missing edge costs silence.

Within the reachable set it flags:

- JIT001 — impure calls: ``time.*``, ``os.environ`` / ``os.getenv``,
  ``np.random.*`` / ``random.*``, ``print``.  Trace-time-only reads that
  are deliberately baked into the program (and keyed into the jit cache)
  carry a def-line ``# lfkt: noqa[JIT001] -- reason``.
- JIT002 — mutation of closed-over Python state: ``global`` / ``nonlocal``
  declarations inside a traced function.
- JIT003 — host syncs: ``.item()``, ``jax.block_until_ready``,
  ``jax.device_get``, ``np.asarray``/``np.array``.  (``float()``/``int()``
  casts are NOT flagged: on static Python scalars they are legitimate and
  common, and the AST cannot see tracedness — the runtime's
  ConcretizationTypeError stays the guard there.)
"""

from __future__ import annotations

import ast

from .core import Context, Finding, Source, dotted

RULES = {
    "JIT001": "impure call (time/os.environ/np.random/print) inside "
              "jit-reachable code",
    "JIT002": "closed-over Python state mutated (global/nonlocal) inside "
              "jit-reachable code",
    "JIT003": "host synchronization (.item()/block_until_ready/device_get/"
              "np.asarray) inside jit-reachable code",
}

_JIT_NAMES = {"jit", "pjit", "shard_map"}


class _Fn:
    __slots__ = ("key", "src", "node", "module", "cls", "nested_in")

    def __init__(self, key, src, node, module, cls, nested_in):
        self.key = key              # (module, qualname)
        self.src = src
        self.node = node
        self.module = module
        self.cls = cls              # enclosing class name or None
        self.nested_in = nested_in  # enclosing function key or None


class _Index:
    """All functions + package-internal import aliases, per module."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.fns: dict[tuple, _Fn] = {}
        #: module -> simple name -> [keys]
        self.by_name: dict[str, dict[str, list[tuple]]] = {}
        #: module -> class -> method name -> key
        self.methods: dict[str, dict[str, dict[str, tuple]]] = {}
        #: module -> alias -> [("mod", module) | ("name", module, name)]
        #: (multi-valued: the same local alias may bind different targets
        #: in different function scopes — ``from .pallas import X as m``)
        self.imports: dict[str, dict[str, list[tuple]]] = {}
        #: children keyed by enclosing function
        self.nested: dict[tuple, list[tuple]] = {}
        self.modules = {ctx.module_name(s) for s in ctx.sources}
        for src in ctx.sources:
            self._scan(src)

    def _resolve_from(self, node: ast.ImportFrom, module: str,
                      is_pkg: bool) -> str | None:
        """Package-relative dotted path of an import's source module,
        '' for the package root, None for out-of-package imports."""
        if node.level == 0:
            pkg = self.ctx.package_name
            m = node.module or ""
            if m == pkg:
                return ""
            if m.startswith(pkg + "."):
                return m[len(pkg) + 1:]
            return None
        parts = [p for p in module.split(".") if p]
        pkg_parts = parts if is_pkg else parts[:-1]
        up = node.level - 1
        if up > len(pkg_parts):
            return None
        base = pkg_parts[: len(pkg_parts) - up]
        tail = [p for p in (node.module or "").split(".") if p]
        return ".".join(base + tail)

    def _scan(self, src: Source):
        module = self.ctx.module_name(src)
        is_pkg = src.rel.endswith("__init__.py")
        names = self.by_name.setdefault(module, {})
        methods = self.methods.setdefault(module, {})
        imports = self.imports.setdefault(module, {})

        def walk(node, cls, nested_in):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if nested_in:
                        qual = f"{nested_in[1]}.<locals>.{child.name}"
                    elif cls:
                        qual = f"{cls}.{child.name}"
                    else:
                        qual = child.name
                    key = (module, qual)
                    fn = _Fn(key, src, child, module, cls, nested_in)
                    self.fns[key] = fn
                    names.setdefault(child.name, []).append(key)
                    if nested_in:
                        self.nested.setdefault(nested_in, []).append(key)
                    if cls and not nested_in:
                        methods.setdefault(cls, {})[child.name] = key
                    walk(child, None, key)
                elif isinstance(child, ast.ClassDef):
                    walk(child, child.name, nested_in)
                else:
                    if isinstance(child, ast.ImportFrom):
                        target = self._resolve_from(child, module, is_pkg)
                        if target is not None:
                            for alias in child.names:
                                local = alias.asname or alias.name
                                sub = ".".join(
                                    p for p in (target, alias.name) if p)
                                if sub in self.modules:
                                    imports.setdefault(local, []).append(
                                        ("mod", sub))
                                elif target in self.modules:
                                    imports.setdefault(local, []).append(
                                        ("name", target, alias.name))
                    walk(child, cls, nested_in)

        walk(src.tree, None, None)

    def resolve(self, module: str, node: ast.AST,
                scope: "_Fn | None") -> list[tuple]:
        """Function keys a Name/Attribute expression may refer to."""
        names = self.by_name.get(module, {})
        imports = self.imports.get(module, {})
        if isinstance(node, ast.Name):
            cands = names.get(node.id, [])
            if scope is not None:
                local = [k for k in cands
                         if self.fns[k].nested_in == scope.key]
                if local:
                    return local
                if scope.cls:
                    m = self.methods.get(module, {}).get(scope.cls, {})
                    if node.id in m:
                        return [m[node.id]]
            if cands:
                return cands
            out = []
            for imp in imports.get(node.id, []):
                if imp[0] == "name":
                    out.extend(self.by_name.get(imp[1], {}).get(imp[2], []))
            return out
        elif isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            base, attr = node.value.id, node.attr
            if base == "self" and scope is not None and scope.cls is not None:
                k = self.methods.get(module, {}).get(scope.cls, {}).get(attr)
                if k is not None:
                    return [k]
            out = []
            for imp in imports.get(base, []):
                if imp[0] == "mod":
                    out.extend(self.by_name.get(imp[1], {}).get(attr, []))
            return out
        return []


def _decorator_is_jit(dec: ast.AST) -> bool:
    d = dotted(dec)
    if d and d.split(".")[-1] in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        # @functools.partial(jax.jit, ...) or @jax.jit(...)-style factories
        f = dotted(dec.func)
        if f and f.split(".")[-1] in _JIT_NAMES:
            return True
        if f and f.split(".")[-1] == "partial":
            for a in dec.args:
                ad = dotted(a)
                if ad and ad.split(".")[-1] in _JIT_NAMES:
                    return True
    return False


def _roots(index: _Index) -> set[tuple]:
    roots: set[tuple] = set()
    for key, fn in index.fns.items():
        if any(_decorator_is_jit(d) for d in fn.node.decorator_list):
            roots.add(key)
    # jax.jit(f) / shard_map(f, ...) with f a resolvable function name;
    # also functools.partial(jax.jit, ...)(f)-free assignment forms
    for src in index.ctx.sources:
        module = index.ctx.module_name(src)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = dotted(node.func)
            if not (f and f.split(".")[-1] in _JIT_NAMES):
                continue
            for a in node.args[:1]:
                roots.update(index.resolve(module, a, scope=None))
    return roots


def _reachable(index: _Index, roots: set[tuple]) -> set[tuple]:
    seen: set[tuple] = set()
    todo = list(roots)
    while todo:
        key = todo.pop()
        if key in seen or key not in index.fns:
            continue
        seen.add(key)
        fn = index.fns[key]
        # everything lexically nested in a traced function runs under trace
        todo.extend(index.nested.get(key, []))
        for node in ast.walk(fn.node):
            # follow any resolvable function REFERENCE, not just direct
            # calls: dispatch tables return/store function objects
            # (ops/linear._fused_fns) and higher-order wrappers take them
            # as arguments (lax.scan bodies, pallas_call kernels)
            if isinstance(node, (ast.Name, ast.Attribute)):
                todo.extend(index.resolve(fn.module, node, scope=fn))
    return seen


def _scan_body(fn: _Fn, ctx: Context) -> list[Finding]:
    out = []
    path = ctx.display_path(fn.src)
    qual = fn.key[1]

    for node in ast.walk(fn.node):
        # nested defs are separate reachable nodes; don't double-report.
        # (ast.walk can't skip subtrees, so filter by ownership instead)
        if _owner(fn.node, node) is not fn.node:
            continue
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            out.append(Finding(
                "JIT002", path, node.lineno,
                f"{qual} mutates closed-over state "
                f"({'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                f" {', '.join(node.names)}) in jit-reachable code"))
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d:
                parts = d.split(".")
                head, tail = parts[0], parts[-1]
                if head == "time" and len(parts) > 1:
                    out.append(Finding(
                        "JIT001", path, node.lineno,
                        f"{qual} calls {d}() in jit-reachable code "
                        "(trace-time constant, not a per-step clock)"))
                elif d in ("os.getenv", "os.environ.get"):
                    out.append(Finding(
                        "JIT001", path, node.lineno,
                        f"{qual} reads the environment in jit-reachable "
                        "code (baked in at trace time)"))
                elif (head in ("np", "numpy") and len(parts) > 2
                      and parts[1] == "random") or head == "random" \
                        and len(parts) > 1:
                    out.append(Finding(
                        "JIT001", path, node.lineno,
                        f"{qual} calls {d}() in jit-reachable code "
                        "(host RNG freezes at trace; use jax.random)"))
                elif d == "print":
                    out.append(Finding(
                        "JIT001", path, node.lineno,
                        f"{qual} calls print() in jit-reachable code "
                        "(runs once at trace; use jax.debug.print)"))
                elif tail in ("block_until_ready", "device_get") \
                        and len(parts) > 1:
                    out.append(Finding(
                        "JIT003", path, node.lineno,
                        f"{qual} calls {tail}() in jit-reachable code "
                        "(host sync in the traced graph)"))
                elif head in ("np", "numpy") and len(parts) == 2 \
                        and tail in ("asarray", "array"):
                    out.append(Finding(
                        "JIT003", path, node.lineno,
                        f"{qual} calls {d}() in jit-reachable code "
                        "(device→host materialization)"))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                out.append(Finding(
                    "JIT003", path, node.lineno,
                    f"{qual} calls .item() in jit-reachable code "
                    "(host sync in the traced graph)"))
        if isinstance(node, ast.Subscript) \
                and dotted(node.value) == "os.environ":
            out.append(Finding(
                "JIT001", path, node.lineno,
                f"{qual} reads os.environ in jit-reachable code "
                "(baked in at trace time)"))
    return out


def _owner(root: ast.AST, node: ast.AST) -> ast.AST:
    """The innermost function def (or root) lexically containing node —
    computed via a cached parent map on the root."""
    cache = getattr(root, "_lfkt_owner", None)
    if cache is None:
        cache = {}

        def assign(n, owner):
            for child in ast.iter_child_nodes(n):
                cache[id(child)] = owner
                assign(child, child if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                    else owner)

        assign(root, root)
        root._lfkt_owner = cache
    return cache.get(id(node), root)


def check(ctx: Context) -> list[Finding]:
    index = _Index(ctx)
    reachable = _reachable(index, _roots(index))
    out: list[Finding] = []
    for key in sorted(reachable):
        fn = index.fns.get(key)
        if fn is not None:
            out.extend(_scan_body(fn, ctx))
    return out

"""lfkt-lint — in-tree static analysis enforcing this repo's invariants.

The codebase grew from a 597-line reference into ~13k LoC of
concurrency-heavy serving code whose correctness rests on hand-maintained
protocols: lock disciplines in the engines (engine/engine.py,
engine/continuous.py), purity of everything reachable from a ``jax.jit``
trace (a stray host sync or env read inside the traced graph is the
synchronization-boundary tax Kernel Looping, arXiv:2410.23668, identifies
as the dominant decode overhead), a single env-knob registry
(utils/config.py) that Helm and the docs must agree with, and the
probe/fallback contract every Pallas kernel follows (ops/pallas/probe.py).
None of those invariants were machine-checked; PR 2 found lock/heartbeat
bugs only via fault drills, after the fact.

This package checks them at test time, on CPU, stdlib-``ast`` only:

- :mod:`.locks`     — LOCK001-004: ``_GUARDED_BY`` lock discipline and
                      thread-confinement declarations on engine classes.
- :mod:`.jit`       — JIT001-003: impure calls, closed-over-state mutation
                      and host syncs inside jit-reachable functions.
- :mod:`.configreg` — CFG001-005: every LFKT_* env read routes through the
                      utils/config.py registry; registry ↔ docs ↔ Helm
                      three-way cross-check; probe routes exist.
- :mod:`.obsreg`    — OBS001-003: every metric name recorded into
                      utils/metrics.py appears in the obs/catalog.py
                      metric catalog, the catalog is fully documented
                      (the docs table is generated from it), and every
                      memory-ledger ``register_component`` name appears
                      in the MEM_COMPONENTS catalog (obs/memledger.py).
- :mod:`.kernels`   — KER001-003: Pallas kernels carry an interpret gate,
                      a probe or XLA fallback, and static block shapes.
- :mod:`.perf`      — PERF001-002: every jit/pallas entry point is
                      registered with the devtime compile/dispatch
                      registry (obs/devtime.py), and every SLO references
                      a cataloged metric family (obs/slo.py).
- :mod:`.resources` — RES001-003: resource lifecycle over the CFG —
                      leases/handles/futures and bare lock acquires must
                      release or hand off on every path including
                      exception edges (``# lfkt: transfers[...]`` is the
                      handoff annotation); use-after-release.
- :mod:`.donation`  — DON001-002: donated-buffer safety at jit call
                      sites: reads of a donated value after dispatch and
                      stale aliases that outlive it.
- :mod:`.degrade`   — EXC001: ``# lfkt: degrades[attr]`` functions must
                      set their fallback attribution in every swallowing
                      ``except`` path.
- :mod:`.deadcode`  — DEAD001-002: unreferenced module-level functions and
                      bogus ``__all__`` entries.

The RES/DON/EXC families run on :mod:`.cfg` — statement-level control-
flow graphs with exception edges plus a generic forward may/must
dataflow solver (the v2 substrate; authoring guide in docs/LINT.md).

Run ``python -m llama_fastapi_k8s_gpu_tpu.lint`` (exit 1 on findings,
``--json`` for machine-readable output), ``tools/lint_report.py`` for a
per-rule table (``--baseline`` for the rule-tightening ratchet),
``tools/ci_gate.py`` for the aggregated repo gate, or the tier-1 tests
in tests/test_lint.py.  Suppress a finding with
``# lfkt: noqa[<RULE>] -- reason`` (the reason is mandatory; unknown
rule IDs are themselves findings).  Rule catalog: docs/LINT.md.
"""

from .core import Finding, all_rules, run_lint  # noqa: F401

__all__ = ["Finding", "all_rules", "run_lint"]

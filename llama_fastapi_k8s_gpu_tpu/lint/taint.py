"""TAINT — interprocedural trust-boundary taint analysis (lfkt-lint v4).

The fleet's ingress surface is adversarial: the router proxies raw
client bytes, the page wire and the migration service parse peer JSON,
``POST /admin/models/reload`` accepts a manifest over the network.  PR
17 fixed one provenance bug by hand (clients commanding KV pulls via
forged ``x-lfkt-prior-owner`` copies); this checker makes the whole
class static.  It rides the package call graph (lint/callgraph.py) the
same way the concurrency rules do: per-function summaries with SYMBOLIC
taint (params, call results), then a whole-package fixpoint that binds
call sites to callee summaries — so a header read three frames up the
stack still reaches the ``connect()`` four calls down.

**Sources** (where attacker-reachable bytes enter)::

    http-request   raw request reads: asyncio reader tails
                   (readline/readexactly/readuntil), ``.headers``
                   reads, ``.json()``/``.body()`` call tails
    wire-frame     decoded page-wire frame headers (``recv_frame()``)
    peer-http      a peer's HTTP response (``getresponse()`` and
                   everything read off it) — /health docs above all
    manifest       ``ModelSpec.path``: the network-suppliable model
                   manifest (POST /admin/models/reload)

**Sinks** (where tainted bytes become authority):

- **TAINT001** network addresses (``connect``/``create_connection``/
  ``HTTPConnection``/``getaddrinfo``) and outbound header construction
  (an f-string containing a literal CR/LF with a tainted interpolation);
- **TAINT002** filesystem paths (``open``, ``os.path.join``, the
  ``os.*`` mutators) and subprocess argv;
- **TAINT003** log-record interpolation without the CR/LF-stripping
  sanitizer (``obs.logctx.sanitize_text`` — line-framed logs make an
  embedded newline a forged record).

**Declassification** is explicit: ``sanitize_text`` is the registered
sanitizer for the ``log``/``header`` sink classes; a containment guard
(``realpath`` + ``startswith``/``commonpath`` + raise) discharges the
``path`` class; a membership guard against an allowlist (``if addr not
in peers: return``) discharges ``addr``.  Everything else needs an
audited comment::

    # lfkt: sanitizes[<source>] -- reason

On a ``def`` line the function is declared a validator for that source:
findings inside it are discharged AND the source is dropped from its
return taint (callers trust its output).  On any other line it covers
that line only — a source read there, or a sink there, is audited.
A reasonless audit is LINT000; an unknown source name is LINT001 (the
suppression-grammar audit rules, which cannot themselves be
suppressed).

Deliberate limits (documented, not accidental): the per-function walk
is a single forward pass (no loop fixpoint), so the rebinding idiom
``msg = sanitize_text(msg)`` cleans everything after it; lambda bodies
are skipped (they run elsewhere); attribute STORES are not tracked
(``self.x = tainted`` does not taint later ``self.x`` reads) — the
registered TAINTED_ATTRS table covers the attrs that matter.
"""

from __future__ import annotations

import ast
import re

from .callgraph import build_graph
from .core import Context, Finding, Source, dotted, self_attr

RULES = {
    "TAINT001": "attacker-tainted value reaches a network-address or "
                "outbound-header sink",
    "TAINT002": "attacker-tainted value reaches a filesystem-path or "
                "subprocess-argv sink",
    "TAINT003": "attacker-tainted value interpolated into a log record "
                "without the CR/LF-stripping sanitizer",
}

#: the declared source vocabulary — `sanitizes[...]` audits must name one
SOURCE_TAGS = ("http-request", "wire-frame", "peer-http", "manifest")

#: sink classes -> rule (header folds into TAINT001, argv into TAINT002)
SINK_RULES = {"addr": "TAINT001", "header": "TAINT001",
              "path": "TAINT002", "argv": "TAINT002", "log": "TAINT003"}
_ALL_CLASSES = frozenset(SINK_RULES)

#: call tails that MINT taint
SOURCE_TAILS = {
    "recv_frame": "wire-frame",
    "getresponse": "peer-http",
    "readline": "http-request",
    "readexactly": "http-request",
    "readuntil": "http-request",
    "json": "http-request",
    "body": "http-request",
}

#: (class name, attribute) -> source tag, for `self.attr` / typed-param
#: attribute reads
TAINTED_ATTRS = {("ModelSpec", "path"): "manifest"}

#: registered sanitizers: call tail -> sink classes it declassifies
SANITIZER_TAILS = {"sanitize_text": frozenset({"log", "header"})}

#: call tails whose result is clean regardless of argument taint (casts
#: that cannot carry bytes through, and digests — a hash of attacker
#: bytes is not attacker bytes)
_CLEAN_TAILS = frozenset({
    "int", "float", "bool", "len", "abs", "round", "hash", "id", "ord",
    "isinstance", "hasattr", "callable", "time", "monotonic",
    "sha256", "sha1", "md5", "digest", "hexdigest", "_sha",
})

_LOG_TAILS = frozenset({"debug", "info", "warning", "error", "exception",
                        "critical", "log"})
_ADDR_TAILS = frozenset({"create_connection", "HTTPConnection",
                         "getaddrinfo", "connect", "connect_ex"})
_OS_PATH_TAILS = frozenset({"remove", "replace", "rename", "makedirs",
                            "mkdir", "rmdir", "unlink", "listdir"})
_SUBPROCESS_TAILS = frozenset({"run", "Popen", "call", "check_call",
                               "check_output"})

_SANITIZES_RE = re.compile(
    r"#\s*lfkt:\s*sanitizes\[([A-Za-z0-9_,\s-]*)\]\s*(?:--\s*(\S.*))?")

#: interprocedural fixpoint bound — the lattice is finite (tags grow,
#: cleaned-sets shrink) so this is a backstop, not a semantics
_MAX_ITER = 40


# ---------------------------------------------------------------------------
# the sanitizes[] audit grammar (mirrors concurrency._Discharges)
# ---------------------------------------------------------------------------

class _Sanitizes:
    """Parsed ``sanitizes[...]`` audits for one source file: line ->
    source-tag set, plus def-spans declaring whole-function validators."""

    def __init__(self, src: Source):
        self.by_line: dict[int, set[str]] = {}
        self.reasonless: list[int] = []
        for i, line in enumerate(src.lines, start=1):
            m = _SANITIZES_RE.search(line)
            if m is None:
                continue
            names = {x.strip() for x in m.group(1).split(",") if x.strip()}
            self.by_line[i] = names
            if not m.group(2):
                self.reasonless.append(i)
        #: (def line, end line, tags) — SIGNATURE lines only, same
        #: grammar as blocks-under[]: def line = whole function
        self.def_spans: list[tuple[int, int, set[str]]] = []
        if self.by_line:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    body_start = (node.body[0].lineno if node.body
                                  else node.lineno + 1)
                    for line in range(node.lineno, body_start):
                        names = self.by_line.get(line)
                        if names and node.end_lineno is not None:
                            self.def_spans.append(
                                (node.lineno, node.end_lineno, names))
                            break

    def covers(self, line: int, tag: str) -> bool:
        if tag in self.by_line.get(line, ()):
            return True
        return any(lo <= line <= hi and tag in names
                   for lo, hi, names in self.def_spans)

    def fn_tags(self, node) -> set[str]:
        """Tags a def-line audit declares for this exact function."""
        body_start = (node.body[0].lineno if node.body
                      else node.lineno + 1)
        out: set[str] = set()
        for line in range(node.lineno, body_start):
            out |= self.by_line.get(line, set())
        return out


# ---------------------------------------------------------------------------
# taint values: {atom -> frozenset(cleaned sink classes)}
#   atom = ("s", tag) | ("p", param index) | ("c", call site id)
# ---------------------------------------------------------------------------

def _join(a: dict, b: dict) -> dict:
    """Merge two taint values: atoms union, cleaned-sets intersect on
    collision (a value cleaned on only one inflow is not cleaned)."""
    if not a:
        return dict(b)
    if not b:
        return dict(a)
    out = dict(a)
    for atom, cleaned in b.items():
        cur = out.get(atom)
        out[atom] = cleaned if cur is None else (cur & cleaned)
    return out


def _clean_more(val: dict, classes: frozenset) -> dict:
    return {atom: cleaned | classes for atom, cleaned in val.items()}


def _ser_val(val: dict) -> list:
    return [[list(atom), sorted(cleaned)]
            for atom, cleaned in sorted(val.items(), key=lambda kv: str(kv))]


def _de_val(doc: list) -> dict:
    return {(a[0], a[1] if a[0] == "s" else int(a[1])): frozenset(c)
            for a, c in doc}


# ---------------------------------------------------------------------------
# per-function analysis
# ---------------------------------------------------------------------------

class _FnTaint:
    """One function's taint summary (symbolic; JSON round-trippable)."""

    __slots__ = ("key", "rel", "params", "calls", "ret", "sinks", "audited")

    def __init__(self, key, rel, params):
        self.key = key
        self.rel = rel
        self.params = params            # positional param names, in order
        #: call id -> (line, [callee keys], [arg vals], {kw: val}, attr?)
        self.calls: dict[int, tuple] = {}
        self.ret: dict = {}
        #: (sink class, line, desc, val)
        self.sinks: list[tuple] = []
        self.audited: set[str] = set()  # def-line sanitizes[] tags

    def to_doc(self) -> dict:
        return {
            "params": list(self.params),
            "calls": {str(cid): [line, [list(k) for k in keys],
                                 [_ser_val(v) for v in args],
                                 {k: _ser_val(v) for k, v in kw.items()},
                                 attr]
                      for cid, (line, keys, args, kw, attr)
                      in self.calls.items()},
            "ret": _ser_val(self.ret),
            "sinks": [[cls, line, desc, _ser_val(val)]
                      for cls, line, desc, val in self.sinks],
            "audited": sorted(self.audited),
        }

    @classmethod
    def from_doc(cls, key, rel, doc) -> "_FnTaint":
        s = cls(key, rel, doc["params"])
        s.calls = {int(cid): (line, [tuple(k) for k in keys],
                              [_de_val(v) for v in args],
                              {k: _de_val(v) for k, v in kw.items()},
                              bool(attr))
                   for cid, (line, keys, args, kw, attr)
                   in doc["calls"].items()}
        s.ret = _de_val(doc["ret"])
        s.sinks = [(cls_, int(line), desc, _de_val(val))
                   for cls_, line, desc, val in doc["sinks"]]
        s.audited = set(doc.get("audited", ()))
        return s


class _Analyzer:
    """The forward walk over one function body.  ``env`` maps local
    names to taint values; branches fork it and join after; nested defs
    are walked inline with a copy (closure taint) and separately as
    their own functions (findings dedup on (path, line, rule, tag))."""

    def __init__(self, graph, fn, audits: _Sanitizes):
        self.graph = graph
        self.fn = fn
        self.cls = graph.fn_class(fn)
        self.audits = audits
        args = fn.node.args
        self.params = [a.arg for a in args.posonlyargs + args.args]
        self.out = _FnTaint(fn.key, fn.src.rel, self.params)
        self.out.audited = audits.fn_tags(fn.node)
        self._next_call = 0
        #: path-sink records pending a containment-guard discharge:
        #: [target var | None, index into out.sinks]
        self._path_sinks: list[list] = []
        #: realpath/abspath derivation edges: derived var -> origin vars
        self._derived: dict[str, set[str]] = {}
        # annotated params resolve typed-receiver calls and TAINTED_ATTRS
        self._ann: dict[str, str] = {}
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.annotation is not None:
                d = dotted(a.annotation)
                if d is not None:
                    self._ann[a.arg] = d.split(".")[-1]

    # -- entry points ----------------------------------------------------
    def run(self) -> _FnTaint:
        env: dict[str, dict] = {}
        kwonly = [a.arg for a in self.fn.node.args.kwonlyargs]
        for i, name in enumerate(self.params + kwonly):
            if name in ("self", "cls"):
                continue
            env[name] = {("p", i): frozenset()}
        self._walk(self.fn.node.body, env)
        # undischarged path sinks stay; discharged ones were cleaned
        return self.out

    # -- expression evaluation -------------------------------------------
    def _src_atom(self, tag: str, line: int) -> dict:
        """A fresh source atom — fully declassified when the read line
        carries a `sanitizes[tag]` audit."""
        if self.audits.covers(line, tag) or tag in self.out.audited:
            return {("s", tag): frozenset(_ALL_CLASSES)}
        return {("s", tag): frozenset()}

    def _attr_source(self, node: ast.Attribute, env) -> dict | None:
        """TAINTED_ATTRS reads (`self.path` inside ModelSpec, or
        `spec.path` off an annotated param) and `.headers` reads."""
        base = node.value
        cname = None
        if isinstance(base, ast.Name):
            if base.id == "self" and self.fn.cls is not None:
                cname = self.fn.cls
            else:
                cname = self._ann.get(base.id)
        tag = TAINTED_ATTRS.get((cname, node.attr)) if cname else None
        if tag is not None:
            return self._src_atom(tag, node.lineno)
        if node.attr == "headers":
            # request.headers / self.headers: the HTTP header map —
            # reads off it carry client bytes
            return self._src_atom("http-request", node.lineno)
        return None

    def _ev(self, node, env) -> dict:
        if node is None or isinstance(node, (ast.Constant, ast.Lambda)):
            return {}
        if isinstance(node, ast.Name):
            return env.get(node.id, {})
        if isinstance(node, ast.Attribute):
            src = self._attr_source(node, env)
            if src is not None:
                return src
            return self._ev(node.value, env)
        if isinstance(node, ast.Await):
            return self._ev(node.value, env)
        if isinstance(node, ast.Call):
            return self._ev_call(node, env)
        if isinstance(node, ast.JoinedStr):
            return self._ev_fstring(node, env)
        if isinstance(node, ast.Compare):
            for sub in ast.iter_child_nodes(node):
                self._ev(sub, env)
            return {}       # a boolean carries no attacker bytes
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return {}
        # default: union over child expressions (tuples, dicts, binops,
        # comprehensions, subscripts, ...)
        out: dict = {}
        for child in ast.iter_child_nodes(node):
            out = _join(out, self._ev(child, env))
        return out

    def _ev_fstring(self, node: ast.JoinedStr, env) -> dict:
        out: dict = {}
        # header joins are CR/LF-framed; a bare "\n" f-string is terminal
        # or file output, which the log sink (not this one) covers
        has_crlf = any(isinstance(v, ast.Constant)
                       and isinstance(v.value, str)
                       and "\r" in v.value
                       for v in node.values)
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                t = self._ev(v.value, env)
                if has_crlf and t:
                    self._sink("header", node.lineno,
                               "CR/LF-joined f-string", t)
                out = _join(out, t)
        return out

    def _ev_call(self, call: ast.Call, env) -> dict:
        func = call.func
        d = dotted(func)
        tail = (func.attr if isinstance(func, ast.Attribute)
                else (d or ""))
        argvals = [self._ev(a, env) for a in call.args]
        kwvals = {kw.arg: self._ev(kw.value, env)
                  for kw in call.keywords if kw.arg is not None}
        for kw in call.keywords:
            if kw.arg is None:       # **kwargs splat
                argvals.append(self._ev(kw.value, env))
        recv = (self._ev(func.value, env)
                if isinstance(func, ast.Attribute) else {})
        everything = recv
        for v in argvals:
            everything = _join(everything, v)
        for v in kwvals.values():
            everything = _join(everything, v)

        # sources first: the call result IS the tainted object
        if tail in SOURCE_TAILS:
            return _join(self._src_atom(SOURCE_TAILS[tail], call.lineno),
                         recv)

        # registered sanitizers: propagate inner taint, cleaned
        san = SANITIZER_TAILS.get(tail)
        if san is not None:
            inner: dict = {}
            for v in argvals:
                inner = _join(inner, v)
            return _clean_more(inner, san)

        if tail in _CLEAN_TAILS:
            return {}

        callees, _recv_type, _exact = self.graph.resolve_call(
            self.fn, self.cls, {}, call)
        pkg_callees = [k for k in callees if k in self.graph.index.fns]
        if pkg_callees:
            cid = self._next_call
            self._next_call += 1
            self.out.calls[cid] = (
                call.lineno, pkg_callees, argvals, kwvals,
                isinstance(func, ast.Attribute))
            # a resolved call's result is its callees' (symbolic) return
            # taint; the receiver's own taint rides along (a method on a
            # tainted object usually hands back its bytes).  Sinks are
            # NOT checked here: the analysis follows the args into the
            # callee and reports at the real sink inside it.
            return _join({("c", cid): frozenset()}, recv)
        # unresolved: check sinks here, and conservatively the result
        # carries everything that went in (str(x), json.loads(x),
        # x.decode(), dict lookups, ...)
        self._check_sinks(call, d, tail, argvals, kwvals, env)
        return everything

    # -- sinks ------------------------------------------------------------
    def _sink(self, cls: str, line: int, desc: str, val: dict) -> None:
        if not val:
            return
        self.out.sinks.append((cls, line, desc, val))

    def _check_sinks(self, call, d, tail, argvals, kwvals, env) -> None:
        head = (d or "").split(".")[0]
        everything: dict = {}
        for v in argvals:
            everything = _join(everything, v)
        for v in kwvals.values():
            everything = _join(everything, v)

        if tail in _ADDR_TAILS:
            self._sink("addr", call.lineno, f"{d or '.' + tail}()",
                       everything)
        if head == "subprocess" and tail in _SUBPROCESS_TAILS:
            self._sink("argv", call.lineno, f"{d}()", everything)
        if d == "open" or d == "os.path.join" or (
                head == "os" and tail in _OS_PATH_TAILS):
            # path sinks remember their assignment target so a later
            # containment guard (realpath + startswith + raise) can
            # discharge them retroactively
            if everything:
                self.out.sinks.append(
                    ("path", call.lineno, f"{d}()", everything))
                self._path_sinks.append([None, len(self.out.sinks) - 1])
        if tail in _LOG_TAILS and (
                "logger" in head.lower() or head == "logging"
                or (isinstance(call.func, ast.Attribute)
                    and "logger" in (dotted(call.func.value) or "").lower())):
            self._sink("log", call.lineno, f"{d or '.' + tail}()",
                       everything)

    # -- statements --------------------------------------------------------
    def _walk(self, stmts, env) -> None:
        for stmt in stmts:
            self._stmt(stmt, env)

    def _assign_target(self, target, val, env) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = dict(val)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign_target(el, val, env)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, val, env)
        # attribute / subscript stores: untracked (see module docstring)

    def _stmt(self, stmt, env) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # inline closure walk: the nested body sees the parent's
            # locals (build_head reading the enclosing handler's key);
            # its params are unknown here, hence clean
            inner = dict(env)
            for a in (stmt.args.posonlyargs + stmt.args.args
                      + stmt.args.kwonlyargs):
                inner.pop(a.arg, None)
            self._walk(stmt.body, inner)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is None:
                return
            before = len(self.out.sinks)
            val = self._ev(value, env)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if isinstance(stmt, ast.AugAssign):
                tname = (stmt.target.id
                         if isinstance(stmt.target, ast.Name) else None)
                if tname is not None:
                    env[tname] = _join(env.get(tname, {}), val)
                return
            for t in targets:
                self._assign_target(t, val, env)
            # bookkeeping for the containment-guard discharge
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                tname = targets[0].id
                for rec in self._path_sinks:
                    if rec[0] is None and rec[1] >= before:
                        rec[0] = tname
                if isinstance(value, ast.Call):
                    vd = dotted(value.func)
                    if vd in ("os.path.realpath", "os.path.abspath",
                              "os.path.normpath"):
                        names = {n.id for n in ast.walk(value)
                                 if isinstance(n, ast.Name)}
                        self._derived.setdefault(tname, set()).update(names)
            return
        if isinstance(stmt, ast.Return):
            self.out.ret = _join(self.out.ret, self._ev(stmt.value, env))
            return
        if isinstance(stmt, ast.Expr):
            self._ev(stmt.value, env)
            return
        if isinstance(stmt, ast.If):
            self._ev(stmt.test, env)
            terminates = any(isinstance(s, (ast.Return, ast.Raise,
                                            ast.Continue, ast.Break))
                             for s in stmt.body)
            self._guards(stmt, env, terminates)
            body_env = dict(env)
            self._in_guard(stmt.test, body_env)
            self._walk(stmt.body, body_env)
            else_env = dict(env)
            self._walk(stmt.orelse, else_env)
            merged = else_env if terminates else self._merge(body_env,
                                                             else_env)
            env.clear()
            env.update(merged)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self._ev(stmt.iter, env)
            body_env = dict(env)
            self._assign_target(stmt.target, it, body_env)
            self._walk(stmt.body, body_env)
            self._walk(stmt.orelse, body_env)
            merged = self._merge(env, body_env)
            env.clear()
            env.update(merged)
            return
        if isinstance(stmt, ast.While):
            self._ev(stmt.test, env)
            body_env = dict(env)
            self._walk(stmt.body, body_env)
            self._walk(stmt.orelse, body_env)
            merged = self._merge(env, body_env)
            env.clear()
            env.update(merged)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                v = self._ev(item.context_expr, env)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, v, env)
            self._walk(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, env)
            for h in stmt.handlers:
                h_env = dict(env)
                self._walk(h.body, h_env)
                merged = self._merge(env, h_env)
                env.clear()
                env.update(merged)
            self._walk(stmt.orelse, env)
            self._walk(stmt.finalbody, env)
            return
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._ev(stmt.exc, env)
            return
        if isinstance(stmt, ast.Delete):
            return
        # anything else: evaluate child expressions for sink effects
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._ev(child, env)
            elif isinstance(child, ast.stmt):
                self._stmt(child, env)

    @staticmethod
    def _merge(a: dict, b: dict) -> dict:
        out = {}
        for name in set(a) | set(b):
            out[name] = _join(a.get(name, {}), b.get(name, {}))
        return out

    # -- guard-based declassification --------------------------------------
    def _in_guard(self, test, body_env) -> None:
        """`if x in allowed:` — inside the body, x is allowlisted for
        the addr class."""
        if isinstance(test, ast.Compare) \
                and isinstance(test.left, ast.Name) \
                and len(test.ops) == 1 and isinstance(test.ops[0], ast.In):
            name = test.left.id
            if name in body_env:
                body_env[name] = _clean_more(body_env[name],
                                             frozenset({"addr"}))

    def _guards(self, stmt: ast.If, env, terminates: bool) -> None:
        """Terminating guards declassify for the code AFTER the If:

        - `if x not in allowed: return/raise`  -> x allowlisted (addr);
        - `if not real.startswith(base): raise` (or commonpath) with
          `real = os.path.realpath(joined)` -> the path sink that
          produced `joined` is discharged, and the contained value's
          path class is cleaned for everything downstream.
        """
        if not terminates:
            return
        test = stmt.test
        # membership: x (or `str(x)`) on the LEFT of NotIn only — a
        # right-operand membership like `":" not in str(addr)` is a
        # shape check, not an allowlist, and must NOT launder
        comparisons = [test]
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            comparisons = [test.operand]
        for comp in comparisons:
            if isinstance(comp, ast.Compare) \
                    and isinstance(comp.left, ast.Name) \
                    and len(comp.ops) == 1 \
                    and isinstance(comp.ops[0], ast.NotIn):
                name = comp.left.id
                if name in env:
                    env[name] = _clean_more(env[name],
                                            frozenset({"addr"}))
        # containment: any startswith/commonpath reference in the test
        has_contain = any(
            (isinstance(n, ast.Attribute)
             and n.attr in ("startswith", "commonpath"))
            for n in ast.walk(test))
        if not has_contain:
            return
        names = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
        covered = set(names)
        for derived, origins in self._derived.items():
            if derived in names:
                covered |= origins
        for rec in self._path_sinks:
            var = rec[0]
            if var is not None and var in covered:
                cls, line, desc, val = self.out.sinks[rec[1]]
                self.out.sinks[rec[1]] = (
                    cls, line, desc, _clean_more(val, frozenset({"path"})))
                if var in env:
                    env[var] = _clean_more(env[var], frozenset({"path"}))


# ---------------------------------------------------------------------------
# package-level phase: summaries -> fixpoint -> findings
# ---------------------------------------------------------------------------

def _analyze_file(graph, src: Source, audits: _Sanitizes) -> dict:
    """qual -> summary doc for every function defined in one file."""
    out: dict = {}
    for key, fn in graph.index.fns.items():
        if fn.src is not src:
            continue
        s = _Analyzer(graph, fn, audits).run()
        # a def-line audit declares the function a validator: its ret no
        # longer carries the audited sources
        if s.audited:
            s.ret = {atom: cleaned for atom, cleaned in s.ret.items()
                     if not (atom[0] == "s" and atom[1] in s.audited)}
        out[key[1]] = s.to_doc()
    return out


def _rehydrate(per_file: dict, rel_to_module) -> dict:
    summaries: dict[tuple, _FnTaint] = {}
    for rel, fns in per_file.items():
        module = rel_to_module(rel)
        if module is None:
            continue
        for qual, doc in fns.items():
            s = _FnTaint.from_doc((module, qual), rel, doc)
            summaries[s.key] = s
    return summaries


def _resolve_val(val: dict, key, summaries, rets, paramin,
                 depth: int = 0) -> dict:
    """Concrete taint (tag -> cleaned) for a symbolic value in ``key``'s
    frame, under the current fixpoint state."""
    out: dict[str, frozenset] = {}

    def add(tag, cleaned):
        cur = out.get(tag)
        out[tag] = cleaned if cur is None else (cur & cleaned)

    for atom, cleaned in val.items():
        kind = atom[0]
        if kind == "s":
            add(atom[1], cleaned)
        elif kind == "p":
            for tag, c2 in paramin.get(key, {}).get(atom[1], {}).items():
                add(tag, c2 | cleaned)
        elif kind == "c" and depth < 8:
            s = summaries.get(key)
            if s is None:
                continue
            entry = s.calls.get(atom[1])
            if entry is None:
                continue
            for callee in entry[1]:
                for tag, c2 in rets.get(callee, {}).items():
                    add(tag, c2 | cleaned)
    return out


def _concrete_join(a: dict, b: dict) -> dict:
    out = dict(a)
    for tag, cleaned in b.items():
        cur = out.get(tag)
        out[tag] = cleaned if cur is None else (cur & cleaned)
    return out


def _fixpoint(summaries: dict) -> tuple[dict, dict]:
    """(rets, paramin): concrete return taint per function and concrete
    inbound taint per (function, param index), to fixpoint."""
    rets: dict[tuple, dict] = {}
    paramin: dict[tuple, dict[int, dict]] = {}
    for _ in range(_MAX_ITER):
        changed = False
        for key, s in sorted(summaries.items()):
            for cid, (line, callees, args, kwargs, attr_call) in \
                    sorted(s.calls.items()):
                for callee in callees:
                    cs = summaries.get(callee)
                    if cs is None:
                        continue
                    offset = (1 if attr_call and cs.params
                              and cs.params[0] in ("self", "cls") else 0)
                    slots = paramin.setdefault(callee, {})
                    for j, av in enumerate(args):
                        if not av:
                            continue
                        concrete = _resolve_val(av, key, summaries,
                                                rets, paramin)
                        if not concrete:
                            continue
                        idx = j + offset
                        cur = slots.get(idx, {})
                        new = _concrete_join(cur, concrete)
                        if new != cur:
                            slots[idx] = new
                            changed = True
                    for name, av in kwargs.items():
                        if not av or name not in cs.params:
                            continue
                        concrete = _resolve_val(av, key, summaries,
                                                rets, paramin)
                        if not concrete:
                            continue
                        idx = cs.params.index(name)
                        cur = slots.get(idx, {})
                        new = _concrete_join(cur, concrete)
                        if new != cur:
                            slots[idx] = new
                            changed = True
            new_ret = _resolve_val(s.ret, key, summaries, rets, paramin)
            if s.audited:
                new_ret = {t: c for t, c in new_ret.items()
                           if t not in s.audited}
            if new_ret != rets.get(key, {}):
                rets[key] = new_ret
                changed = True
        if not changed:
            break
    return rets, paramin


_SINK_HINTS = {
    "addr": "validate it against the admitted peer table (an `if x not "
            "in peers: return` guard), or audit with "
            "`# lfkt: sanitizes[{tag}] -- why`",
    "header": "pass it through obs.logctx.sanitize_text before header "
              "construction, or audit with "
              "`# lfkt: sanitizes[{tag}] -- why`",
    "path": "contain it under the trusted root (realpath + startswith "
            "+ raise — serving/manifest.py is the model), or audit "
            "with `# lfkt: sanitizes[{tag}] -- why`",
    "argv": "never splice network bytes into argv; audit with "
            "`# lfkt: sanitizes[{tag}] -- why` if the value is provably "
            "operator-controlled",
    "log": "pass it through obs.logctx.sanitize_text first, or audit "
           "with `# lfkt: sanitizes[{tag}] -- why`",
}


def check(ctx: Context) -> list[Finding]:
    graph = build_graph(ctx)
    audits = {src.rel: _Sanitizes(src) for src in ctx.sources}
    by_rel = {src.rel: src for src in ctx.sources}

    def dpath(rel: str) -> str:
        src = by_rel.get(rel)
        return ctx.display_path(src) if src is not None else rel

    out: list[Finding] = []

    # -- the sanitizes[] grammar audits itself (LINT000/LINT001) ----------
    for rel, a in sorted(audits.items()):
        for line in a.reasonless:
            out.append(Finding(
                "LINT000", dpath(rel), line,
                "sanitizes annotation without a reason: write "
                "`# lfkt: sanitizes[<source>] -- why`"))
        for line, names in sorted(a.by_line.items()):
            if not names:
                out.append(Finding(
                    "LINT001", dpath(rel), line,
                    "sanitizes annotation names no source"))
            for name in sorted(names):
                if name not in SOURCE_TAGS:
                    out.append(Finding(
                        "LINT001", dpath(rel), line,
                        f"sanitizes names unknown source {name!r} "
                        f"(declared sources: {', '.join(SOURCE_TAGS)})"))

    # -- per-file summaries (with the --changed cache) ---------------------
    module_of = {src.rel: ctx.module_name(src) for src in ctx.sources}
    inc = getattr(ctx, "lint_incremental", None)
    per_file: dict[str, dict] = {}
    if inc is None or inc.get("out") is None:
        for src in ctx.sources:
            per_file[src.rel] = _analyze_file(graph, src, audits[src.rel])
    else:
        # piggyback on the concurrency checker's cache protocol: same
        # digest guard (call RESOLUTION is shared), per-file sha match,
        # and a "taint" side-table next to its "summaries"
        from .concurrency import resolution_digest

        digest = resolution_digest(graph)
        cache = inc.get("cache") or {}
        cached_files = (cache.get("files", {})
                        if cache.get("digest") == digest else {})
        shas = inc["shas"]
        for src in ctx.sources:
            entry = cached_files.get(src.rel)
            if entry is not None and shas.get(src.rel) == entry.get("sha") \
                    and entry.get("taint") is not None:
                per_file[src.rel] = entry["taint"]
            else:
                per_file[src.rel] = _analyze_file(graph, src,
                                                  audits[src.rel])
        for rel, fns in per_file.items():
            slot = inc["out"]["files"].get(rel)
            if slot is not None:
                slot["taint"] = fns

    summaries = _rehydrate(per_file, module_of.get)

    # -- the interprocedural fixpoint and the findings ---------------------
    rets, paramin = _fixpoint(summaries)
    seen: set[tuple] = set()
    for key, s in sorted(summaries.items()):
        a = audits.get(s.rel)
        for cls, line, desc, val in s.sinks:
            concrete = _resolve_val(val, key, summaries, rets, paramin)
            for tag in sorted(concrete):
                if cls in concrete[tag]:
                    continue            # declassified for this class
                if tag in s.audited:
                    continue            # the function is a validator
                if a is not None and a.covers(line, tag):
                    continue            # line-level audit at the sink
                rule = SINK_RULES[cls]
                mark = (s.rel, line, rule, tag)
                if mark in seen:
                    continue
                seen.add(mark)
                hint = _SINK_HINTS[cls].format(tag=tag)
                out.append(Finding(
                    rule, dpath(s.rel), line,
                    f"tainted value (source: {tag}) reaches "
                    f"{'log sink' if cls == 'log' else cls + ' sink'} "
                    f"{desc} in {key[1]} — {hint}"))
    return out

"""CFG001-005: the LFKT_* env-knob registry is the single source of truth.

The serving stack is parameterized by ~50 ``LFKT_*`` env vars.  Before
this checker they were read in nine different modules with hand-rolled
parsing, so a knob could exist in code but not in the Helm chart, in the
RUNBOOK but not in code, or be typo'd in a values file and silently
ignored.  The contract now:

- every knob is declared once, as a :class:`Knob` entry in
  ``utils/config.py`` (name, default, cast, help, serving-relevance);
- package code reads knobs ONLY through that module's accessors
  (``get_settings``/``knob``/``env_bool``) — never ``os.environ`` raw;
- every registered knob is documented (docs/CONFIG.md or any docs page);
- every LFKT_* name mentioned in the Helm chart exists in the registry,
  and every serving-relevant knob is plumbed (or documented) there;
- every k8s probe path in the Helm deployment is a real registered route
  in server/app.py.

Rules:

- CFG001 — raw ``os.environ``/``os.getenv`` read of an ``LFKT_*`` name
  outside utils/config.py.
- CFG002 — registered knob missing from the docs (README.md + docs/).
- CFG003 — helm ↔ registry mismatch: an LFKT_* name in helm/ that is not
  registered, or a ``serving=True`` knob absent from helm/.
- CFG004 — a probe path in helm/templates is not a registered route in
  server/app.py.
- CFG005 — ``knob()``/``env_bool()``/``_env_variant()`` called with an
  unregistered literal name (the static twin of the accessors' runtime
  KeyError).

Repo-level cross-checks (CFG002-004) skip themselves when the package is
analyzed outside a checkout (no helm/ or docs/ present).
"""

from __future__ import annotations

import ast
import os
import re

from .core import Context, Finding, const_str, dotted

RULES = {
    "CFG001": "raw os.environ read of an LFKT_* name outside utils/config.py",
    "CFG002": "registered knob not documented in README/docs",
    "CFG003": "helm chart references an unregistered LFKT_* name (or a "
              "serving knob is absent from helm)",
    "CFG004": "helm probe path is not a registered route in server/app.py",
    "CFG005": "registered-accessor call with an unregistered knob name",
}

CONFIG_REL = "utils/config.py"
_LFKT_RE = re.compile(r"LFKT_[A-Z0-9_]+")
_ACCESSORS = ("knob", "env_bool", "_env_variant", "_env")

#: bench/test-harness-only knob prefixes: read exclusively by the repo's
#: out-of-package entrypoints (bench.py, bench_server.py, tools/), so they
#: are deliberately NOT in the serving registry; docs and helm comments
#: may still mention them (the ISSUE's "test-only knobs" allowlist)
TEST_ONLY_PREFIXES = ("LFKT_BENCH_", "LFKT_COLDSTART_")


def _registry(ctx: Context) -> tuple[dict[str, dict], bool]:
    """(name -> {"serving": bool}, found): parsed statically from the
    ``Knob(...)`` literals in utils/config.py."""
    knobs: dict[str, dict] = {}
    for src in ctx.sources:
        if src.rel != CONFIG_REL:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                f = dotted(node.func)
                if f and f.split(".")[-1] == "Knob" and node.args:
                    name = const_str(node.args[0])
                    if name:
                        serving = any(
                            kw.arg == "serving"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True
                            for kw in node.keywords)
                        knobs[name] = {"serving": serving}
        return knobs, True
    return knobs, False


def _env_read_name(node: ast.Call) -> str | None:
    """The literal env-var name of an os.environ.get()/os.getenv() call."""
    d = dotted(node.func)
    if d in ("os.environ.get", "os.getenv") and node.args:
        return const_str(node.args[0])
    return None


def _read_text(path: str) -> str:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def _tree_text(root: str, exts: tuple) -> dict[str, str]:
    out = {}
    if not os.path.isdir(root):
        return out
    for dirpath, _, filenames in os.walk(root):
        for f in sorted(filenames):
            if f.endswith(exts):
                p = os.path.join(dirpath, f)
                out[p] = _read_text(p)
    return out


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    knobs, have_registry = _registry(ctx)

    # -- CFG001 + CFG005: in-package read discipline ------------------------
    for src in ctx.sources:
        if src.rel == CONFIG_REL:
            continue
        path = ctx.display_path(src)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                name = _env_read_name(node)
                if name and name.startswith("LFKT_"):
                    out.append(Finding(
                        "CFG001", path, node.lineno,
                        f"raw env read of {name!r}: route it through "
                        "utils/config.py (get_settings/knob/env_bool)"))
                f = dotted(node.func)
                if f and f.split(".")[-1] in _ACCESSORS and node.args:
                    arg = const_str(node.args[0])
                    if arg and arg.startswith("LFKT_") and arg not in knobs:
                        out.append(Finding(
                            "CFG005", path, node.lineno,
                            f"{f.split('.')[-1]}({arg!r}) reads a knob "
                            "missing from the utils/config.py registry"))
            elif isinstance(node, ast.Subscript) \
                    and dotted(node.value) == "os.environ":
                name = const_str(node.slice)
                if name and name.startswith("LFKT_"):
                    out.append(Finding(
                        "CFG001", path, node.lineno,
                        f"raw env read of {name!r}: route it through "
                        "utils/config.py (get_settings/knob/env_bool)"))

    # -- repo-level cross-checks -------------------------------------------
    if not (have_registry and ctx.repo_root):
        return out
    cfg_src = next(s for s in ctx.sources if s.rel == CONFIG_REL)
    cfg_path = ctx.display_path(cfg_src)

    # CFG002: knob -> docs coverage
    docs_text = _read_text(os.path.join(ctx.repo_root, "README.md"))
    for _, text in sorted(
            _tree_text(os.path.join(ctx.repo_root, "docs"), (".md",)).items()):
        docs_text += text
    if docs_text:
        documented = set(_LFKT_RE.findall(docs_text))
        for name in sorted(knobs):
            if name not in documented:
                out.append(Finding(
                    "CFG002", cfg_path, 1,
                    f"registered knob {name} is documented nowhere under "
                    "README.md/docs/ (add it to docs/CONFIG.md)"))

    # CFG003 + CFG004: helm cross-checks
    helm_files = _tree_text(os.path.join(ctx.repo_root, "helm"),
                            (".yaml", ".yml", ".tpl"))
    if helm_files:
        helm_text = "".join(helm_files.values())
        helm_names = set(_LFKT_RE.findall(helm_text))
        unknown = {n for n in helm_names - set(knobs)
                   if not n.startswith(TEST_ONLY_PREFIXES)}
        for name in sorted(unknown):
            # attribute to the first helm file mentioning it
            fpath, line = cfg_path, 1
            for p, text in sorted(helm_files.items()):
                for i, ln in enumerate(text.splitlines(), start=1):
                    if name in ln:
                        fpath = os.path.relpath(p, ctx.repo_root)
                        line = i
                        break
                if line != 1 or fpath != cfg_path:
                    break
            out.append(Finding(
                "CFG003", fpath, line,
                f"helm references {name}, which is not in the "
                "utils/config.py registry (typo'd knobs are silently "
                "ignored by the app)"))
        for name in sorted(k for k, meta in knobs.items()
                           if meta["serving"] and k not in helm_names):
            out.append(Finding(
                "CFG003", cfg_path, 1,
                f"serving-relevant knob {name} is not plumbed or "
                "documented anywhere in helm/"))

        # CFG004: probe paths must be registered app routes
        routes: set[str] = set()
        for src in ctx.sources:
            if not src.rel.endswith("server/app.py"):
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call) and dec.args:
                            f = dotted(dec.func)
                            if f and f.split(".")[-1] in (
                                    "get", "post", "put", "delete", "route"):
                                r = const_str(dec.args[0])
                                if r:
                                    routes.add(r)
        probe_re = re.compile(r"^\s*path:\s*(/[^\s{]+)\s*$", re.M)
        for p, text in sorted(helm_files.items()):
            for m in probe_re.finditer(text):
                probe = m.group(1)
                if routes and probe not in routes:
                    line = text[: m.start()].count("\n") + 1
                    out.append(Finding(
                        "CFG004", os.path.relpath(p, ctx.repo_root), line,
                        f"helm probe path {probe} is not a registered "
                        "route in server/app.py"))
    return out

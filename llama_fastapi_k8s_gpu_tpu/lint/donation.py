"""DON001-002: donated-buffer safety at the CALL sites.

Every hot-path jit in this codebase donates its state (``donate_argnames``
on the prefill/decode/page-copy programs): XLA reuses the argument's HBM
for the result, so the caller's reference is dead the moment the call is
dispatched.  The engines' contract is rebind-from-result
(``self._bstate, toks = chunk_jit(..., self._bstate, ...)``) or
drop-the-ref-across-the-call (the PR-6 restore hardening).  Nothing
checked that contract statically — a stale alias serves garbage (or
crashes with a donated-buffer error) at first request, not at review.

- **DON001** — the caller reads the donated local/attribute after the
  dispatch without rebinding it first (``f(self._cache)`` then
  ``self._cache["k"]``).
- **DON002** — an *alias* of the donated value survives the dispatch: a
  name assigned from it before the call and read after, or a
  ``self.<attr>`` stash of the value still live at function exit
  (``self._snap = cache; f(cache)`` — ``self._snap`` now names a dead
  buffer for whoever runs next).

The donor registry is built from the same surface PERF001 enumerates:
``jax.jit``/``functools.partial(jax.jit, ...)`` entry points with
``donate_argnames`` (decorator, assignment, and ``timed_jit``-wrapped
forms), jit *factories* (a function returning a donating jit over a
nested def — parallel/ring.py's ``_sp_*_fn`` pattern), plus one level of
interprocedural propagation: a function that forwards its own parameter
into a donated position donates that parameter too (``KVPool.restore``'s
``ring``, ``Engine._prefill_padded``'s ``cache``).

Scope: intraprocedural per caller, names and ``self.<attr>`` keys only;
attribute writes by callees are invisible.  Deliberately donation-only —
plain aliasing is fine, it is aliasing ACROSS a donating dispatch that
the runtime forbids.
"""

from __future__ import annotations

import ast

from .cfg import build_cfg, eval_roots, solve_forward
from .core import Context, Finding, Source, const_str, dotted, str_seq

RULES = {
    "DON001": "donated argument is read after the donating dispatch "
              "without being rebound (use-after-donate)",
    "DON002": "an alias of a donated value survives the dispatch (stale "
              "reference to a dead buffer)",
}

_JIT_TAILS = ("jit", "pjit")


class _Donor:
    __slots__ = ("params", "donated", "method")

    def __init__(self, params: list[str], donated: list[str], method: bool):
        self.params = params
        self.donated = [d for d in donated if d in params]
        self.method = method


def _donate_kw(call: ast.Call) -> list[str] | None:
    for kw in call.keywords:
        if kw.arg in ("donate_argnames", "donate_argnums"):
            if kw.arg == "donate_argnums":
                return None     # index form unused in-tree; skip safely
            seq = str_seq(kw.value)
            if seq is not None:
                return seq
            one = const_str(kw.value)
            if one is not None:
                return [one]
    return None


def _is_jit_call(call: ast.Call) -> bool:
    f = dotted(call.func)
    return bool(f) and f.split(".")[-1] in _JIT_TAILS


def _jit_donation(call: ast.Call) -> list[str] | None:
    """Donated names when ``call`` builds a donating jit: ``jax.jit(...,
    donate_argnames=...)`` or ``partial(jax.jit, donate_argnames=...)``."""
    f = dotted(call.func)
    tail = f.split(".")[-1] if f else None
    if tail in _JIT_TAILS:
        return _donate_kw(call)
    if tail == "partial" and any(
            (d := dotted(a)) and d.split(".")[-1] in _JIT_TAILS
            for a in call.args):
        return _donate_kw(call)
    return None


def _params_of(fn) -> list[str]:
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def _parent_class_map(tree: ast.AST) -> dict[int, bool]:
    """id(FunctionDef) -> is a method (direct child of a ClassDef)."""
    out: dict[int, bool] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[id(child)] = True
    return out


def _unwrap_timed(call: ast.Call) -> ast.AST:
    """``timed_jit("name", X, ...)`` -> X (the wrapped callable expr)."""
    f = dotted(call.func)
    if f and f.split(".")[-1] == "timed_jit" and len(call.args) >= 2:
        return call.args[1]
    return call


def build_registry(ctx: Context) -> tuple[dict, dict]:
    """(donors, factories): donors maps a callable name (def name, assign
    target, or propagated function/method name) -> _Donor; factories maps
    a factory function name -> the inner def's _Donor (for ``F(...)(...)``
    call-of-call sites)."""
    donors: dict[str, _Donor] = {}
    factories: dict[str, _Donor] = {}
    fns_by_name: list[tuple[Source, object, bool]] = []

    for src in ctx.sources:
        methods = _parent_class_map(src.tree)
        local_defs = {n.name: n for n in ast.walk(src.tree)
                      if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns_by_name.append((src, node, id(node) in methods))
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        don = _jit_donation(dec)
                        if don:
                            donors[node.name] = _Donor(
                                _params_of(node), don, id(node) in methods)
                # factory: returns a (possibly timed_jit-wrapped) donating
                # jit over a nested def
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Return) or sub.value is None:
                        continue
                    expr = sub.value
                    if isinstance(expr, ast.Call):
                        expr = _unwrap_timed(expr)
                    if isinstance(expr, ast.Call):
                        don = _jit_donation(expr)
                        inner = expr.args[0] if expr.args else None
                        if don and isinstance(inner, ast.Name):
                            target = local_defs.get(inner.id)
                            if target is not None:
                                factories[node.name] = _Donor(
                                    _params_of(target), don, False)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                expr = _unwrap_timed(node.value)
                don = None
                params: list[str] | None = None
                if isinstance(expr, ast.Call):
                    don = _jit_donation(expr)
                    inner = expr.args[0] if expr.args else None
                    if don is None and isinstance(expr.func, ast.Call):
                        # partial(jax.jit, donate_argnames=...)(fnref)
                        don = _jit_donation(expr.func)
                        inner = expr.args[0] if expr.args else None
                    if don and isinstance(inner, ast.Name):
                        target = local_defs.get(inner.id)
                        if target is not None:
                            params = _params_of(target)
                if don and params is not None:
                    donors[node.targets[0].id] = _Donor(params, don, False)
                elif isinstance(node.value, ast.Call):
                    # name-preserving rewrap: X = timed_jit("n", X) keeps
                    # X's existing registration — nothing to do
                    pass

    # one-level-per-round propagation to fixpoint: F donates parameter p
    # when F's body forwards p into a donated position of a known donor
    for _ in range(6):
        changed = False
        for src, fn, is_method in fns_by_name:
            params = _params_of(fn)
            pool = set(params[1:] if is_method else params)
            found: list[str] = []
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                for arg_expr, _pname in donated_args(call, donors, factories):
                    if isinstance(arg_expr, ast.Name) \
                            and arg_expr.id in pool:
                        found.append(arg_expr.id)
            if found:
                cur = donors.get(fn.name)
                new = sorted(set(found) | set(cur.donated if cur else ()))
                if cur is None or set(new) != set(cur.donated):
                    donors[fn.name] = _Donor(params, new, is_method)
                    changed = True
        if not changed:
            break
    return donors, factories


def donated_args(call: ast.Call, donors: dict, factories: dict
                 ) -> list[tuple[ast.AST, str]]:
    """(argument expression, donated param name) pairs for this call."""
    donor = None
    method_call = False
    f = dotted(call.func)
    if f is not None:
        donor = donors.get(f.split(".")[-1])
        method_call = isinstance(call.func, ast.Attribute)
    elif isinstance(call.func, ast.Call):
        inner = dotted(call.func.func)
        if inner is not None:
            donor = factories.get(inner.split(".")[-1])
    if donor is None:
        return []
    params = donor.params
    if donor.method and method_call:
        params = params[1:]         # bound call: self is implicit
    out: list[tuple[ast.AST, str]] = []
    for name in donor.donated:
        if name not in params:
            continue
        idx = params.index(name)
        if idx < len(call.args):
            out.append((call.args[idx], name))
            continue
        for kw in call.keywords:
            if kw.arg == name:
                out.append((kw.value, name))
    return out


# ---------------------------------------------------------------------------
# per-caller dataflow
# ---------------------------------------------------------------------------

def _key_of(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return "self." + expr.attr
    return None


def _loads(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    for root in eval_roots(stmt):
        for sub in ast.walk(root):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.add(sub.id)
            elif isinstance(sub, ast.Attribute) \
                    and isinstance(sub.ctx, ast.Load) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "self":
                out.add("self." + sub.attr)
    return out


def _assign_pairs(stmt: ast.stmt) -> list[tuple[ast.AST, ast.AST | None]]:
    """(target, value_expr | None) pairs; tuple unpacking against a tuple
    literal pairs element-wise (the ``a, b = b, None`` swap idiom), other
    unpacking yields fresh (None-valued) bindings."""
    if isinstance(stmt, ast.Assign):
        out: list[tuple[ast.AST, ast.AST | None]] = []
        for t in stmt.targets:
            if isinstance(t, ast.Tuple):
                if isinstance(stmt.value, ast.Tuple) \
                        and len(stmt.value.elts) == len(t.elts):
                    out += list(zip(t.elts, stmt.value.elts))
                else:
                    out += [(el, None) for el in t.elts]
            else:
                out.append((t, stmt.value))
        return out
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return [(stmt.target, None)]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        t = stmt.target
        return [(el, None) for el in
                (t.elts if isinstance(t, ast.Tuple) else [t])]
    return []


def _check_function(ctx: Context, src: Source, fn, donors, factories
                    ) -> list[Finding]:
    calls = [c for c in ast.walk(fn) if isinstance(c, ast.Call)
             and donated_args(c, donors, factories)]
    if not calls:
        return []
    path = ctx.display_path(src)
    cfg = build_cfg(fn)
    out: list[Finding] = []
    reported: set[tuple] = set()

    # state: (aliases, dead) — aliases: frozenset of normalized (a, b)
    # key pairs established by plain `a = b` assignments and killed when
    # either side is rebound; dead: frozenset[(key, donor_key, line)].
    # Keys are locals ('x') and self attributes ('self.x').  Kill-on-
    # rebind keeps the donate-and-rebind loop idiom naturally stable.
    def closure(aliases, key):
        group = {key}
        grew = True
        while grew:
            grew = False
            for a, b in aliases:
                if a in group and b not in group:
                    group.add(b)
                    grew = True
                elif b in group and a not in group:
                    group.add(a)
                    grew = True
        return group

    def flow(node, state):
        stmt = node.stmt
        if stmt is None:
            return {"*": state}
        aliases, dead = state
        # 1) reads of dead values (against the IN state: same-statement
        #    donation has not happened yet)
        for key in _loads(stmt):
            for dk, donor_key, line in dead:
                if dk != key:
                    continue
                rule = "DON001" if key == donor_key else "DON002"
                mark = (rule, stmt.lineno, key)
                if mark not in reported:
                    reported.add(mark)
                    what = "the donated argument" if rule == "DON001" \
                        else "an alias of the value donated"
                    out.append(Finding(
                        rule, path, stmt.lineno,
                        f"{key!r} is {what} at line {line}; its buffer "
                        "is dead after the dispatch — rebind it from "
                        "the result (or drop the reference) first"))
                break
        # 2) donations performed by this statement (the donated key and
        #    everything currently aliasing it die together)
        new_dead = set(dead)
        for sub in (s for root in eval_roots(stmt)
                    for s in ast.walk(root)):
            if isinstance(sub, ast.Call):
                for arg_expr, _p in donated_args(sub, donors, factories):
                    key = _key_of(arg_expr)
                    if key is None:
                        continue
                    for k2 in closure(aliases, key):
                        new_dead.add((k2, key, stmt.lineno))
        # exc edge: the donation is assumed dispatched (conservative — the
        # PR-6 restore hardening exists because a mid-copy failure leaves
        # the buffer dead) but the REBIND below did not happen.  This is
        # what catches `self._c = f(self._c)` serving a dead buffer out of
        # a swallowing except.
        exc_state = (aliases, frozenset(new_dead))
        # 3) assignments: rebinds revive their targets; plain `a = b`
        #    additionally records the alias (unless b was rebound by the
        #    same statement — the swap idiom's None side)
        pairs = _assign_pairs(stmt)
        targets = {tk for t, _v in pairs if (tk := _key_of(t)) is not None}
        if targets:
            new_dead = {(k, dk, ln) for k, dk, ln in new_dead
                        if k not in targets}
            new_alias = {(a, b) for a, b in aliases
                         if a not in targets and b not in targets}
            for t, v in pairs:
                tk = _key_of(t)
                vk = _key_of(v) if v is not None else None
                if tk is not None and vk is not None and vk not in targets \
                        and tk != vk:
                    new_alias.add(tuple(sorted((tk, vk))))
        else:
            new_alias = set(aliases)
        return {"*": (frozenset(new_alias), frozenset(new_dead)),
                "exc": exc_state}

    def join(a, b):
        return (a[0] | b[0], a[1] | b[1])

    IN = solve_forward(cfg, (frozenset(), frozenset()), flow, join)

    # 4) at exit: a dead self-attr ALIAS outlives the frame — the
    #    "stashed on self then donated" trap (the donated key itself is
    #    the caller's rebind-or-drop contract, flagged only on reads)
    state = IN.get(cfg.exit)
    if state is not None:
        _aliases, dead = state
        for k, donor_key, line in dead:
            if k != donor_key and k.startswith("self."):
                mark = ("DON002-exit", line, k)
                if mark not in reported:
                    reported.add(mark)
                    out.append(Finding(
                        "DON002", path, line,
                        f"{k!r} still references the buffer donated "
                        "here at function exit — the next reader gets "
                        "a dead buffer; rebind or clear it"))
    return out


def check(ctx: Context) -> list[Finding]:
    donors, factories = build_registry(ctx)
    out: list[Finding] = []
    for src in ctx.sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_check_function(
                    ctx, src, node, donors, factories))
    return out

"""KER001-003: the Pallas kernel contract (ops/pallas/probe.py pattern).

A Mosaic lowering failure on a new libtpu must degrade a pod to a slower
path, never crash-loop it; and CPU tests must be able to execute every
kernel in interpret mode.  The contract every kernel module in
``ops/pallas/`` follows (probe.py + kvquant.py are the reference
instances):

- KER001 — every ``pl.pallas_call(...)`` call site threads an explicit
  ``interpret=`` argument (the interpret-mode gate: ``use_interpret()``
  decides by backend, tests can force it).  A pallas_call without it
  compiles Mosaic unconditionally — including on the CPU tier-1 gate.
- KER002 — every module that invokes ``pallas_call`` is covered by a
  startup compile probe (referenced from ``ops/pallas/probe.py``) or
  defines a degrade path in-module (an ``*xla*``- or ``*fallback*``-named
  function), so the *caller* can pick the fallback with correct
  attribution.
- KER003 — block shapes stay static: a ``pl.BlockSpec`` shape element
  must be a constant / name / arithmetic thereof — a function call inside
  a block shape is how dynamic (traced) extents sneak into the grid,
  which Mosaic rejects with an unattributable error at first serving
  request rather than at probe time.

Only modules under ``ops/pallas/`` are checked (the contract is about
kernel authorship, not kernel use).
"""

from __future__ import annotations

import ast

from .core import Context, Finding, dotted

RULES = {
    "KER001": "pl.pallas_call without an explicit interpret= gate",
    "KER002": "pallas kernel module with no startup probe and no XLA "
              "fallback",
    "KER003": "pl.BlockSpec block shape contains a call (dynamic extent)",
}

_DIR = "ops/pallas/"


def _shape_has_call(node: ast.AST) -> ast.Call | None:
    # shape elements may be names/constants/arithmetic/attribute chains
    # (``TK // 2``, ``x.shape[0]`` — all static at trace time); a Call is
    # the one form that can smuggle in a dynamic extent
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            return sub
    return None


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    kernel_srcs = [s for s in ctx.sources
                   if s.rel.startswith(_DIR) and
                   not s.rel.endswith(("__init__.py", "probe.py"))]
    probe_src = next((s for s in ctx.sources
                      if s.rel == _DIR + "probe.py"), None)
    # modules probe.py actually imports from (AST, not text — a prose
    # mention in a comment must not count as probe coverage)
    probed_mods: set[str] = set()
    if probe_src is not None:
        for node in ast.walk(probe_src.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                probed_mods.add(node.module.split(".")[-1])

    for src in kernel_srcs:
        path = ctx.display_path(src)
        uses_pallas_call = False
        has_xla_fallback = False
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if "xla" in node.name.lower() \
                        or "fallback" in node.name.lower():
                    has_xla_fallback = True
            if not isinstance(node, ast.Call):
                continue
            f = dotted(node.func)
            tail = f.split(".")[-1] if f else None
            if tail == "pallas_call":
                uses_pallas_call = True
                kw = {k.arg for k in node.keywords}
                if "interpret" not in kw:
                    out.append(Finding(
                        "KER001", path, node.lineno,
                        "pl.pallas_call without interpret=: thread the "
                        "use_interpret() gate so CPU/tests never compile "
                        "Mosaic (ops/pallas/probe.py pattern)"))
            elif tail == "BlockSpec" and node.args:
                shape = node.args[0]
                call = _shape_has_call(shape)
                if call is not None:
                    out.append(Finding(
                        "KER003", path, call.lineno,
                        "pl.BlockSpec block shape contains a call — block "
                        "shapes must be static (constants, params, or "
                        "arithmetic thereof)"))
        if uses_pallas_call:
            mod = src.rel[len(_DIR):-3]            # e.g. 'qmatmul'
            if not (mod in probed_mods or has_xla_fallback):
                out.append(Finding(
                    "KER002", path, 1,
                    f"kernel module {mod}.py calls pallas_call but has no "
                    "compile probe in ops/pallas/probe.py and no in-module "
                    "XLA fallback — a Mosaic failure will crash-loop the "
                    "pod instead of degrading"))
    return out

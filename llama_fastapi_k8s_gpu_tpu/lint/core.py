"""lfkt-lint core: sources, suppressions, the checker registry and runner.

Design constraints (docs/LINT.md):

- stdlib only (``ast`` + ``re``): the lint must run in the tier-1 CPU gate
  with zero new dependencies and without importing jax or the package
  under analysis (everything is derived from parsed source, so a broken
  module still lints).
- suppressions are *audited*: ``# lfkt: noqa[<RULE>] -- reason`` requires a
  reason string (LINT000) and a known rule ID (LINT001).  A noqa on a
  ``def`` line covers the whole function body — the idiom for "this
  function is exempt for a structural reason" — otherwise it covers its
  own line only.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Iterable

#: ``# lfkt: noqa[<RULE1>,<RULE2>] -- reason`` (reason mandatory, see LINT000)
_NOQA_RE = re.compile(
    r"#\s*lfkt:\s*noqa\[([A-Za-z0-9_,\s]*)\]\s*(?:--\s*(\S.*))?")

#: core's own rules — violations of the suppression grammar itself
CORE_RULES = {
    "LINT000": "a `# lfkt: noqa[...]` comment is missing its `-- reason`",
    "LINT001": "a `# lfkt: noqa[...]` comment names an unknown rule ID",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-root-relative (or absolute when outside it)
    line: int
    message: str
    suppressed: bool = False
    reason: str | None = None   # the noqa reason when suppressed

    def render(self) -> str:
        sup = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{sup}"


class Source:
    """One parsed python file plus its suppression map."""

    def __init__(self, path: str, rel: str):
        self.path = path
        self.rel = rel                       # package-relative posix path
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        #: line -> (set of rule ids ('' set means malformed), reason | None)
        self.noqa: dict[int, tuple[set[str], str | None]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _NOQA_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.noqa[i] = (rules, m.group(2))
        #: line ranges of function defs carrying a def-line noqa:
        #: (first body line, last line) -> noqa entry.  "def line" means
        #: any line of the (possibly multi-line) signature.
        self._def_spans: list[tuple[int, int, set[str], str | None]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                body_start = node.body[0].lineno if node.body else node.lineno
                for line in range(node.lineno, body_start + 1):
                    entry = self.noqa.get(line)
                    if entry is not None and node.end_lineno is not None:
                        self._def_spans.append(
                            (node.lineno, node.end_lineno,
                             entry[0], entry[1]))
                        break

    def suppression(self, line: int, rule: str) -> str | None:
        """The noqa reason covering (line, rule), or None.  A def-line
        noqa covers the whole function body for its rules."""
        entry = self.noqa.get(line)
        if entry is not None and rule in entry[0]:
            return entry[1] or ""
        for lo, hi, rules, reason in self._def_spans:
            if lo <= line <= hi and rule in rules:
                return reason or ""
        return None


class Context:
    """Everything a checker may look at.

    ``sources`` are the package's own files (findings are reported here);
    ``ref_sources`` are reference-only roots (tests, tools, bench
    entrypoints) consulted for cross-references (dead-code, docs).
    ``repo_root`` may be None when the package is analyzed outside a repo
    checkout — repo-level cross-checks (helm, docs) then skip themselves.
    """

    def __init__(self, package_dir: str, repo_root: str | None,
                 ref_roots: Iterable[str] = ()):
        self.package_dir = os.path.abspath(package_dir)
        self.package_name = os.path.basename(self.package_dir)
        self.repo_root = os.path.abspath(repo_root) if repo_root else None
        self.sources: list[Source] = []
        self.ref_sources: list[Source] = []
        for path in _py_files(self.package_dir):
            rel = os.path.relpath(path, self.package_dir).replace(os.sep, "/")
            self.sources.append(Source(path, rel))
        for root in ref_roots:
            if os.path.isfile(root) and root.endswith(".py"):
                self.ref_sources.append(
                    Source(root, os.path.basename(root)))
            elif os.path.isdir(root):
                for path in _py_files(root):
                    rel = os.path.relpath(
                        path, os.path.dirname(root)).replace(os.sep, "/")
                    self.ref_sources.append(Source(path, rel))

    def display_path(self, src: Source) -> str:
        if self.repo_root:
            try:
                return os.path.relpath(src.path, self.repo_root)
            except ValueError:
                pass
        return src.path

    def module_name(self, src: Source) -> str:
        """Dotted module path of a package source, e.g. 'engine.engine'."""
        mod = src.rel[:-3] if src.rel.endswith(".py") else src.rel
        mod = mod.replace("/", ".")
        if mod == "__init__":
            return ""
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


def _sha256(text: str) -> str:
    import hashlib

    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _py_files(root: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git", "_build")]
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


# ---------------------------------------------------------------------------
# checker registry
# ---------------------------------------------------------------------------

def _checkers() -> list[tuple[dict, Callable[[Context], list[Finding]]]]:
    # imported lazily so a syntax error in one checker names itself cleanly
    from . import (concurrency, configreg, deadcode, degrade, donation,
                   jit, kernels, locks, obsreg, perf, resources, taint,
                   wire)

    # taint rides concurrency's --changed cache doc (it augments
    # inc["out"] with its own per-file summaries), so it must run after
    return [(mod.RULES, mod.check)
            for mod in (locks, concurrency, taint, jit, configreg, obsreg,
                        wire, kernels, perf, resources, donation, degrade,
                        deadcode)]


def all_rules() -> dict[str, str]:
    """rule id -> one-line description, across every checker."""
    rules = dict(CORE_RULES)
    for mod_rules, _ in _checkers():
        rules.update(mod_rules)
    return rules


def _core_findings(ctx: Context, known: set[str]) -> list[Finding]:
    """LINT000/LINT001: audit the suppression comments themselves."""
    out = []
    for src in ctx.sources:
        path = ctx.display_path(src)
        for line, (rules, reason) in sorted(src.noqa.items()):
            if not reason:
                out.append(Finding(
                    "LINT000", path, line,
                    "suppression without a reason: write "
                    "`# lfkt: noqa[<RULE>] -- why`"))
            if not rules:
                out.append(Finding(
                    "LINT001", path, line, "suppression names no rule ID"))
            for r in rules:
                if r not in known:
                    out.append(Finding(
                        "LINT001", path, line,
                        f"unknown rule ID {r!r} in suppression"))
    return out


def run_lint(package_dir: str | None = None, repo_root: str | None = None,
             rules: Iterable[str] | None = None,
             incremental: dict | None = None) -> list[Finding]:
    """Run every checker; returns ALL findings with ``suppressed`` applied
    (callers filter).  Defaults analyze this installed package and, when it
    lives in a repo checkout, the repo's tests/tools/bench/helm/docs.

    ``incremental`` is the ``--changed`` plumbing (lint/__main__.py): a
    mutable dict with the loaded summary ``cache`` and current content
    ``shas``; lint/concurrency.py reuses cached per-file summaries whose
    sha still matches and writes the refreshed cache doc back under
    ``incremental["out"]``."""
    if package_dir is None:
        package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root is None:
        cand = os.path.dirname(os.path.abspath(package_dir))
        # a checkout is recognized by its test tree; site-packages is not
        if os.path.isdir(os.path.join(cand, "tests")):
            repo_root = cand
    ref_roots: list[str] = []
    if repo_root:
        for name in ("tests", "tools", "bench.py", "bench_server.py",
                     "__graft_entry__.py"):
            p = os.path.join(repo_root, name)
            if os.path.exists(p):
                ref_roots.append(p)
    ctx = Context(package_dir, repo_root, ref_roots)
    if incremental is not None:
        incremental.setdefault(
            "shas", {src.rel: _sha256(src.text) for src in ctx.sources})
        ctx.lint_incremental = incremental

    wanted = set(rules) if rules is not None else None
    known = set(all_rules())
    findings = _core_findings(ctx, known)
    for mod_rules, check in _checkers():
        if wanted is not None and not (set(mod_rules) & wanted):
            continue
        findings.extend(check(ctx))
    if wanted is not None:
        findings = [f for f in findings if f.rule in wanted]

    # apply suppressions (path -> Source lookup by display path)
    by_path = {ctx.display_path(s): s for s in ctx.sources}
    for f in findings:
        src = by_path.get(f.path)
        if src is None or f.rule in ("LINT000", "LINT001"):
            continue   # the suppression audit rules cannot be suppressed
        reason = src.suppression(f.line, f.rule)
        if reason is not None:
            f.suppressed = True
            f.reason = reason
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# shared AST helpers (used by several checkers)
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> str | None:
    """'X' when node is ``self.X`` (possibly through subscripts:
    ``self.X[k]`` / ``self.X[k][j]``), else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_seq(node: ast.AST) -> list[str] | None:
    """['a', 'b'] for a literal tuple/list/set of strings, else None."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for elt in node.elts:
            s = const_str(elt)
            if s is None:
                return None
            out.append(s)
        return out
    return None

"""RES001-003: resource lifecycle over the CFG (lint/cfg.py).

The PR-6/PR-7 hardening inventory was one bug class found by review, not
tooling: pool pages, radix pins, leases and capture locks leaked — or
served dead — on exception paths.  These rules make that class static.
A *registered acquire-like call* creates an obligation; dataflow over the
CFG (exception edges included) proves every path discharges it:

- **RES001** — a value-producing acquire (``KVPool.acquire`` leases,
  ``open()`` file handles, ``executor.submit()`` futures) must reach a
  registered release or an ownership handoff on EVERY path to function
  exit, exceptional paths included.  The canonical fix is ``finally:``
  (or ``with``); the canonical handoff is storing the value somewhere
  that outlives the frame.
- **RES002** — a bare ``<lock>.acquire()`` outside ``with`` must reach
  ``<lock>.release()`` on every path.  The conditional idiom
  ``if not lock.acquire(blocking=False): return`` is understood: the
  obligation starts only on the acquired branch.
- **RES003** — a tracked resource read after it was released on every
  path reaching the read (use-after-release / double-release): the
  release revoked what the name points at.

Ownership handoffs that discharge RES001 (intraprocedural humility —
once a value escapes the frame, its lifecycle belongs to someone else):

- returned (or yielded), directly or inside a literal container;
- stored to an attribute or subscript (``self._paged_lease = lease``);
- placed in a dict/list/tuple/set literal (``{"lease": lease}``);
- captured by a lambda / nested def (callback closures);
- futures only: passed as a call argument (``asyncio.wrap_future(fut)``
  takes the handle over);
- an explicit ``# lfkt: transfers[name] -- reason`` on the statement
  line — the annotation grammar for handoffs the dataflow cannot see
  (a semaphore permit released by a spawned task, a lease a callee
  stores).  The reason is part of the audit trail, like noqa's.

Scope limits (documented, deliberate): only simple ``name = <acquire>()``
bindings are tracked (comprehension/chained forms are not), attributes
written by callees are invisible, and rebinding a tracked name drops the
obligation.  A false negative costs silence; a false positive here would
cost a written ``transfers``/noqa — the same trade every lfkt-lint family
makes.
"""

from __future__ import annotations

import ast
import re

from .cfg import build_cfg, eval_roots, solve_forward
from .core import Context, Finding, dotted

RULES = {
    "RES001": "acquired resource (lease/file/future) may leak: a path "
              "reaches function exit without release or handoff",
    "RES002": "lock.acquire() outside `with` is not released on every "
              "path (use try/finally or with)",
    "RES003": "use of a resource after it was released on every path "
              "(use-after-release / double-release)",
}

#: value-producing acquires: call tail -> resource kind
VALUE_ACQUIRE_TAILS = {"acquire": "lease", "submit": "future"}
#: method tails that release a value resource passed as an ARGUMENT
RELEASE_ARG_TAILS = ("release", "closing")
#: method tails that release a value resource as the RECEIVER
RELEASE_RECV_TAILS = ("release", "close", "cancel", "result", "shutdown",
                      "add_done_callback")
#: the subset that actually REVOKES the handle (RES003's gen set):
#: futures stay fully usable after result()/cancel()/add_done_callback(),
#: so those discharge the leak obligation but are not use-after-release
REVOKE_RECV_TAILS = ("release", "close", "shutdown")
LOCK_TAIL = "acquire"

#: names are simple identifiers (the bound local, or a lock's terminal
#: attribute) — dots excluded so prose mentions of `transfers[...]` in
#: docstrings never parse as annotations
_TRANSFERS_RE = re.compile(
    r"#\s*lfkt:\s*transfers\[([\w,\s]*)\]\s*(?:--\s*(\S.*))?")


class _Site:
    __slots__ = ("line", "kind", "key", "what")

    def __init__(self, line: int, kind: str, key: str, what: str):
        self.line = line
        self.kind = kind            # lease | file | future | lock
        self.key = key              # bound name, or lock's dotted chain
        self.what = what            # human description for the finding


def _tail(call: ast.Call) -> str | None:
    d = dotted(call.func)
    return d.split(".")[-1] if d else None


def _recv(call: ast.Call) -> str | None:
    """Dotted receiver of a method call (``a.b.acquire()`` -> 'a.b')."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return None


def _find_call(node: ast.AST, tail: str) -> ast.Call | None:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _tail(sub) == tail:
            return sub
    return None


def _names_loaded(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _transfer_names(src, stmt: ast.stmt) -> set[str]:
    """Names given in ``# lfkt: transfers[...]`` on any line of ``stmt``
    (compound statements: header lines only — their bodies have their own
    statements)."""
    out: set[str] = set()
    end = getattr(stmt, "end_lineno", None) or stmt.lineno
    body = getattr(stmt, "body", None)
    if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
        # exclusive of the first body line (an annotation there belongs to
        # that statement, not to every branch of the compound), except for
        # one-line compounds where header and body share the line
        end = min(end, max(body[0].lineno - 1, stmt.lineno))
    for line in src.lines[stmt.lineno - 1: end]:
        m = _TRANSFERS_RE.search(line)
        if m:
            out.update(x.strip() for x in m.group(1).split(",") if x.strip())
    return out


def _with_item_calls(fn: ast.AST) -> set[int]:
    """ids of calls inside ``with`` items — the context manager owns the
    release, so they are not tracked acquires."""
    out: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    out.add(id(sub))
    return out


def _collect_sites(src, fn, in_with: set[int]) -> dict[int, _Site]:
    """Acquire sites keyed by id(stmt) of the owning statement.  An
    acquire whose own line carries a ``transfers`` annotation naming the
    resource is a declared immediate handoff and is not tracked at all."""
    sites: dict[int, _Site] = {}
    for stmt in ast.walk(fn):
        if not isinstance(stmt, ast.stmt):
            continue
        # value form: name = [await] <acquire-like>(...)
        value = stmt.value if isinstance(stmt, ast.Assign) else None
        if isinstance(value, ast.Await):
            value = value.value
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(value, ast.Call) \
                and id(value) not in in_with:
            call = value
            tail = _tail(call)
            kind = None
            if tail in VALUE_ACQUIRE_TAILS:
                kind = VALUE_ACQUIRE_TAILS[tail]
            elif isinstance(call.func, ast.Name) and call.func.id == "open":
                kind = "file"
            if kind is not None:
                name = stmt.targets[0].id
                if name in _transfer_names(src, stmt):
                    continue
                sites[id(stmt)] = _Site(
                    stmt.lineno, kind, name,
                    f"{kind} {name!r} from {dotted(call.func) or 'open'}()")
            continue
        # lock form: bare/awaited/tested <recv>.acquire()
        call = None
        if isinstance(stmt, ast.Expr):
            v = stmt.value
            if isinstance(v, ast.Await):
                v = v.value
            if isinstance(v, ast.Call) and _tail(v) == LOCK_TAIL:
                call = v
        elif isinstance(stmt, ast.If):
            call = _find_call(stmt.test, LOCK_TAIL)
        if call is not None and id(call) not in in_with:
            recv = _recv(call)
            if recv is not None:
                declared = _transfer_names(src, stmt)
                if recv in declared or recv.split(".")[-1] in declared:
                    continue
                sites[id(stmt)] = _Site(
                    call.lineno, "lock", recv, f"lock {recv}.acquire()")
    return sites


def _gen_edges(stmt: ast.stmt, site: _Site) -> tuple[str, ...]:
    """Edge kinds on which the acquire SUCCEEDED (the obligation starts).
    ``if not lock.acquire(): ...`` acquires on the false edge."""
    if not isinstance(stmt, ast.If):
        return ("norm", "true", "false")
    test = stmt.test
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and _find_call(test.operand, LOCK_TAIL) is not None:
        return ("false",)
    if isinstance(test, ast.Call):
        return ("true",)
    return ("true", "false")


def _none_test(stmt: ast.stmt) -> tuple[str, str] | None:
    """('name', edge-kind-where-None) for ``if name is None`` /
    ``if name is not None`` tests — the failed-acquire guard pattern."""
    if not isinstance(stmt, ast.If):
        return None
    t = stmt.test
    if isinstance(t, ast.Compare) and len(t.ops) == 1 \
            and isinstance(t.comparators[0], ast.Constant) \
            and t.comparators[0].value is None \
            and isinstance(t.left, ast.Name):
        if isinstance(t.ops[0], ast.Is):
            return t.left.id, "true"
        if isinstance(t.ops[0], ast.IsNot):
            return t.left.id, "false"
    return None


def _escapes(src, stmt: ast.stmt, key: str, kind: str) -> bool:
    """Does ``stmt`` hand ownership of value-resource ``key`` off?"""
    if key in _transfer_names(src, stmt):
        return True
    if isinstance(stmt, ast.Return) and stmt.value is not None \
            and key in _names_loaded(stmt.value):
        return True
    # assignment of the value into an attribute / subscript slot
    targets: list[ast.AST] = []
    value = None
    if isinstance(stmt, ast.Assign):
        targets, value = list(stmt.targets), stmt.value
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets, value = [stmt.target], getattr(stmt, "value", None)
    if value is not None and key in _names_loaded(value) and any(
            isinstance(t, (ast.Attribute, ast.Subscript)) for t in targets):
        return True
    for root in eval_roots(stmt):
        for sub in ast.walk(root):
            # placed in a literal container (incl. `return a, b` tuples,
            # machine dicts, argument lists built as literals)
            if isinstance(sub, (ast.Dict, ast.List, ast.Tuple, ast.Set)) \
                    and not isinstance(getattr(sub, "ctx", ast.Load()),
                                       ast.Store) \
                    and key in _names_loaded(sub):
                return True
            if isinstance(sub, ast.Lambda) and key in _names_loaded(sub.body):
                return True
            if isinstance(sub, ast.Yield) and sub.value is not None \
                    and key in _names_loaded(sub.value):
                return True
            if kind == "future" and isinstance(sub, ast.Call):
                # futures: passing the handle to any call shares/transfers
                for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                    if isinstance(a, ast.Name) and a.id == key:
                        return True
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            and any(key in _names_loaded(s) for s in stmt.body):
        return True
    return False


def _released_here(src, stmt: ast.stmt, site: _Site, keys: set[str],
                   revoking_only: bool = False) -> bool:
    """Does ``stmt`` perform a registered release of ``site`` (known under
    any name in ``keys``)?  ``revoking_only`` restricts to calls that
    actually revoke the handle (RES003's gen set): ``fut.result()``
    discharges the leak obligation but the future stays readable."""
    if site.kind == "lock":
        if site.key.split(".")[-1] in _transfer_names(src, stmt) \
                or site.key in _transfer_names(src, stmt):
            return True
        for root in eval_roots(stmt):
            for sub in ast.walk(root):
                if isinstance(sub, ast.Call) and _tail(sub) == "release" \
                        and _recv(sub) == site.key:
                    return True
        return False
    # `with f:` over an already-bound tracked resource: the context
    # manager guarantees the close on every path — a release
    if isinstance(stmt, (ast.With, ast.AsyncWith)) and any(
            isinstance(it.context_expr, ast.Name)
            and it.context_expr.id in keys for it in stmt.items):
        return True
    recv_tails = REVOKE_RECV_TAILS if revoking_only else RELEASE_RECV_TAILS
    arg_tails = ("release",) if revoking_only else RELEASE_ARG_TAILS
    for root in eval_roots(stmt):
        for sub in ast.walk(root):
            if not isinstance(sub, ast.Call):
                continue
            tail = _tail(sub)
            if tail in arg_tails:
                for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                    if isinstance(a, ast.Name) and a.id in keys:
                        return True
            if tail in recv_tails \
                    and isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id in keys:
                return True
    return False


def _rebound_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    for t in targets:
        for el in (t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]):
            if isinstance(el, ast.Name):
                out.add(el.id)
    return out


def _alias_pair(stmt: ast.stmt) -> tuple[str, str] | None:
    """('new', 'old') for a simple ``new = old`` aliasing assignment."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name) \
            and isinstance(stmt.value, ast.Name):
        return stmt.targets[0].id, stmt.value.id
    return None


def _check_function(ctx: Context, src, fn) -> list[Finding]:
    in_with = _with_item_calls(fn)
    sites = _collect_sites(src, fn, in_with)
    if not sites:
        return []
    cfg = build_cfg(fn)
    path = ctx.display_path(src)
    by_stmt = sites                      # id(stmt) -> _Site
    site_ids = {id(s): s for s in sites.values()}

    # ---- RES001/RES002: may-analysis of outstanding obligations --------
    # state: frozenset of (site_token, bound_name)
    def flow(node, state):
        stmt = node.stmt
        if stmt is None:
            return {"*": state}
        # escape kills apply on NORMAL completion only (an exception means
        # the handoff did not happen); RELEASE kills apply on the exc edge
        # too — a release() that itself raises leaves the resource state
        # murky, and flagging it would demand try/finally around finally
        out = set(state)
        exc_out = set(state)
        keys_by_site: dict[int, set[str]] = {}
        for tok, key in state:
            keys_by_site.setdefault(tok, set()).add(key)
        for tok, keys in keys_by_site.items():
            site = site_ids[tok]
            released = _released_here(src, stmt, site, keys)
            if released:
                exc_out = {(t, k) for t, k in exc_out if t != tok}
            if released or any(
                    _escapes(src, stmt, k, site.kind) for k in keys
                    if site.kind != "lock"):
                out = {(t, k) for t, k in out if t != tok}
        rebound = _rebound_names(stmt)
        alias = _alias_pair(stmt)
        if rebound:
            out = {(t, k) for t, k in out if k not in rebound}
        if alias is not None:
            new, old = alias
            for t, k in list(out):
                if k == old:
                    out.add((t, new))
        got = by_stmt.get(id(stmt))
        none_guard = _none_test(stmt)
        outs: dict[str, object] = {"*": frozenset(out),
                                   "exc": frozenset(exc_out)}
        if got is not None:
            for kind in _gen_edges(stmt, got):
                base = outs.get(kind, outs["*"])
                outs[kind] = frozenset(set(base) | {(id(got), got.key)})
        if none_guard is not None:
            name, none_edge = none_guard
            # `if x is None:` — on the None edge the acquire failed and
            # there is nothing to release
            dead = {t for t, k in out if k == name}
            outs[none_edge] = frozenset(
                {(t, k) for t, k in out if t not in dead})
        return outs

    IN = solve_forward(cfg, frozenset(), flow, lambda a, b: a | b)
    out: list[Finding] = []
    norm = IN.get(cfg.exit, frozenset())
    exc = IN.get(cfg.raise_exit, frozenset())
    for site in sites.values():
        tok = id(site)
        on_norm = any(t == tok for t, _ in norm)
        on_exc = any(t == tok for t, _ in exc)
        if not (on_norm or on_exc):
            continue
        rule = "RES002" if site.kind == "lock" else "RES001"
        how = ("on an exception path — release it in a finally: "
               "(or switch to `with`)") if not on_norm else \
            "on a normal path (no release or ownership handoff reaches exit)"
        fix = ("annotate the handoff with `# lfkt: transfers[...] -- why` "
               "if ownership genuinely moves elsewhere")
        out.append(Finding(
            rule, path, site.line,
            f"{site.what} may leak {how}; {fix}"))

    # ---- RES003: must-analysis of definitely-released values -----------
    # findings are derived from the FINAL fixpoint states, never inside
    # the transfer: on a must-analysis the first visit of a node sees one
    # predecessor's over-approximate state, and a finding emitted there
    # would be order-dependent and unretractable
    def flow_rel(node, state):
        stmt = node.stmt
        if stmt is None:
            return {"*": state}
        new = set(state)
        rebound = _rebound_names(stmt)
        if rebound:
            new = {(t, k) for t, k in new if k not in rebound}
        got = by_stmt.get(id(stmt))
        if got is not None and got.kind != "lock":
            new = {(t, k) for t, k in new if t != id(got)}
        # a `with f:` / `with closing(f):` header DISCHARGES the leak
        # obligation (RES001) but the close only happens at with-EXIT —
        # body reads are fine, so it must not gen "released" here
        if not isinstance(stmt, (ast.With, ast.AsyncWith)):
            for tok, site in site_ids.items():
                if site.kind == "lock":
                    continue
                if _released_here(src, stmt, site, {site.key},
                                  revoking_only=True):
                    new.add((tok, site.key))
        return {"*": frozenset(new), "exc": state}

    IN_rel = solve_forward(cfg, frozenset(), flow_rel, lambda a, b: a & b)
    reported: set[tuple] = set()
    for node, state in IN_rel.items():
        stmt = node.stmt
        if stmt is None or not state:
            continue
        for tok, key in state:
            hit = any(
                isinstance(sub, ast.Name) and sub.id == key
                and isinstance(sub.ctx, ast.Load)
                for root in eval_roots(stmt) for sub in ast.walk(root))
            if hit:
                mark = ("RES003", path, stmt.lineno, tok)
                if mark not in reported:
                    reported.add(mark)
                    out.append(Finding(
                        "RES003", path, stmt.lineno,
                        f"{site_ids[tok].what} (released before this "
                        f"point on every path) is used here — "
                        f"use-after-release"))
    return out


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for src in ctx.sources:
        path = ctx.display_path(src)
        # the transfers grammar is audited output exactly like noqa's:
        # a reason-less annotation still discharges (parallel to a
        # reason-less noqa still suppressing) but is itself a LINT000
        # finding — ownership-handoff claims must carry justification
        for lineno, line in enumerate(src.lines, start=1):
            m = _TRANSFERS_RE.search(line)
            if m is not None and not m.group(2):
                out.append(Finding(
                    "LINT000", path, lineno,
                    "transfers annotation without a reason: write "
                    "`# lfkt: transfers[<name>] -- why`"))
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_check_function(ctx, src, node))
    return out

"""DEAD001-002: module-level functions and exports nobody references.

Dead code in a serving repo is not free: it keeps compiling, keeps
importing, shows up in grep results as if load-bearing, and silently
drifts out of date with the invariants the live code maintains.  This
checker indexes every ``Name``/``Attribute`` reference across the package
AND its consumers (tests/, tools/, bench.py, bench_server.py, the graft
entrypoint) and flags:

- DEAD001 — a module-level function (public or private) with no reference
  anywhere beyond its own definition.  Import statements and ``__all__``
  strings do NOT count as uses — re-exporting a function nobody calls is
  still dead.  Decorated functions are exempt (decorators register them:
  route handlers, custom_partitioning callees, ...), as are ``main`` and
  dunder names.
- DEAD002 — an ``__all__`` entry naming something the module never
  defines or imports (an export lie: ``from m import *`` raises).

Functions used only via ``getattr``/strings need a
``# lfkt: noqa[DEAD001] -- reason`` on their def line.
"""

from __future__ import annotations

import ast

from .core import Context, Finding, Source, str_seq

RULES = {
    "DEAD001": "module-level function never referenced in package, tests, "
               "tools, or bench entrypoints",
    "DEAD002": "__all__ entry that the module never defines or imports",
}

_EXEMPT = {"main"}   # script entrypoints; checker check() functions are
#                      kept alive by core.py's `mod.check` references


def _module_defs(src: Source):
    """(module-level FunctionDefs, names defined/imported at module level,
    __all__ entries with their node)."""
    fns: list[ast.FunctionDef] = []
    defined: set[str] = set()
    all_entries: list[tuple[str, ast.AST]] = []
    for stmt in src.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.append(stmt)
            defined.add(stmt.name)
        elif isinstance(stmt, ast.ClassDef):
            defined.add(stmt.name)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                defined.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    defined.add(t.id)
                    if t.id == "__all__":
                        vals = str_seq(stmt.value)
                        if vals is not None:
                            all_entries.extend((v, stmt) for v in vals)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                            ast.Name):
            defined.add(stmt.target.id)
    return fns, defined, all_entries


def _references(sources) -> dict[str, int]:
    """name -> count of Name/Attribute references (imports and __all__
    strings excluded; a function's own def line excluded by the caller)."""
    refs: dict[str, int] = {}
    for src in sources:
        for node in ast.walk(src.tree):
            # import aliases are not expression nodes, so imports naturally
            # contribute no references — exactly the intended semantics
            if isinstance(node, ast.Name):
                refs[node.id] = refs.get(node.id, 0) + 1
            elif isinstance(node, ast.Attribute):
                refs[node.attr] = refs.get(node.attr, 0) + 1
    return refs


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    everything = list(ctx.sources) + list(ctx.ref_sources)
    refs = _references(everything)

    for src in ctx.sources:
        path = ctx.display_path(src)
        fns, defined, all_entries = _module_defs(src)

        for name, node in all_entries:
            if name not in defined:
                out.append(Finding(
                    "DEAD002", path, node.lineno,
                    f"__all__ exports {name!r}, which this module never "
                    "defines or imports (star-imports would raise)"))

        for fn in fns:
            name = fn.name
            if fn.decorator_list or name in _EXEMPT \
                    or (name.startswith("__") and name.endswith("__")):
                continue
            # own definition contributes 0 Name refs (a def is not a Name
            # node); any genuine call/reference anywhere counts
            if refs.get(name, 0) == 0:
                out.append(Finding(
                    "DEAD001", path, fn.lineno,
                    f"module-level function {name}() is never referenced "
                    "in the package, tests, tools, or bench entrypoints — "
                    "delete it or wire it up"))
    return out

"""OBS001-002: the metric catalog is the single source of truth.

The /metrics surface grew past thirty families; before this checker a
typo'd metric name (``m.inc("request_rejected_total")``) would silently
mint a new, never-alerted series — or, with the strict runtime registry,
crash the first request that hit the site.  The contract (the CFG knob
registry's pattern, applied to metrics):

- every metric is declared once, as a :class:`Metric` entry in
  ``obs/catalog.py`` (name, type, help, buckets, labels, prefix
  families);
- every *literal* metric name passed to a ``Metrics`` recording call
  (``inc``/``observe``/``set_gauge``) resolves against that catalog —
  exactly, or via a declared ``prefix=True`` family (OBS001; f-string
  names are covered by the runtime ``KeyError`` in utils/metrics.py);
- the catalog is the source for the generated metrics table in the docs:
  every cataloged metric is documented somewhere under README.md/docs/
  (OBS002; tests/test_obs.py additionally pins the docs table to the
  generator's output byte-for-byte).

Repo-level docs coverage (OBS002) skips itself outside a checkout.
"""

from __future__ import annotations

import ast
import os

from .core import Context, Finding, const_str, dotted

RULES = {
    "OBS001": "metric name recorded via inc/observe/set_gauge is missing "
              "from the obs/catalog.py metric catalog",
    "OBS002": "cataloged metric is documented nowhere under README/docs",
    "OBS003": "memory-ledger register_component name is missing from the "
              "obs/catalog.py MEM_COMPONENTS catalog",
}

CATALOG_REL = "obs/catalog.py"
_RECORDERS = ("inc", "observe", "set_gauge")
_LEDGER_REGISTRAR = "register_component"


def _catalog(ctx: Context) -> tuple[dict[str, dict], bool]:
    """(name -> {"prefix": bool}, found): parsed statically from the
    ``Metric(...)`` literals in obs/catalog.py."""
    metrics: dict[str, dict] = {}
    for src in ctx.sources:
        if src.rel != CATALOG_REL:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                f = dotted(node.func)
                if f and f.split(".")[-1] == "Metric" and node.args:
                    name = const_str(node.args[0])
                    if name:
                        prefix = any(
                            kw.arg == "prefix"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True
                            for kw in node.keywords)
                        metrics[name] = {"prefix": prefix}
        return metrics, True
    return metrics, False


def _components(ctx: Context) -> tuple[set, bool]:
    """(component names, found): parsed statically from the
    ``MemComponent(...)`` literals in obs/catalog.py — the OBS003 twin of
    :func:`_catalog` (memory ledger, obs/memledger.py)."""
    names: set = set()
    for src in ctx.sources:
        if src.rel != CATALOG_REL:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                f = dotted(node.func)
                if f and f.split(".")[-1] == "MemComponent" and node.args:
                    name = const_str(node.args[0])
                    if name:
                        names.add(name)
        return names, True
    return names, False


def _covered(name: str, metrics: dict[str, dict]) -> bool:
    if name in metrics:
        return True
    return any(meta["prefix"] and name.startswith(prefix)
               for prefix, meta in metrics.items())


def _read_text(path: str) -> str:
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def check(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    metrics, have_catalog = _catalog(ctx)
    if not have_catalog:
        return out

    # -- OBS001: literal recorder calls resolve against the catalog --------
    for src in ctx.sources:
        if src.rel == CATALOG_REL:
            continue
        path = ctx.display_path(src)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = dotted(node.func)
            if f is None or f.split(".")[-1] not in _RECORDERS:
                continue
            # only Metrics-shaped receivers: a bare inc()/observe() name or
            # a counter-ish helper on another class must not be dragged in
            recv = f.rsplit(".", 1)[0] if "." in f else ""
            if not recv:
                continue
            name = const_str(node.args[0])
            if name is None:                # dynamic name: runtime KeyError
                continue
            if not _covered(name, metrics):
                out.append(Finding(
                    "OBS001", path, node.lineno,
                    f"metric {name!r} is not in the obs/catalog.py metric "
                    "catalog; register it (typo'd names mint silent "
                    "series)"))

    # -- OBS003: ledger registrations resolve against MEM_COMPONENTS -------
    components, have_components = _components(ctx)
    if have_components:
        for src in ctx.sources:
            if src.rel == CATALOG_REL:
                continue
            path = ctx.display_path(src)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                f = dotted(node.func)
                if f is None or f.split(".")[-1] != _LEDGER_REGISTRAR:
                    continue
                name = const_str(node.args[0])
                if name is None:        # dynamic name: runtime KeyError
                    continue
                if name not in components:
                    out.append(Finding(
                        "OBS003", path, node.lineno,
                        f"memory component {name!r} is not in the "
                        "obs/catalog.py MEM_COMPONENTS catalog; register "
                        "it (unknown components KeyError at runtime)"))

    # -- OBS002: catalog -> docs coverage ----------------------------------
    if not ctx.repo_root:
        return out
    cat_src = next(s for s in ctx.sources if s.rel == CATALOG_REL)
    cat_path = ctx.display_path(cat_src)
    docs_text = _read_text(os.path.join(ctx.repo_root, "README.md"))
    docs_dir = os.path.join(ctx.repo_root, "docs")
    if os.path.isdir(docs_dir):
        for dirpath, _, filenames in os.walk(docs_dir):
            for fn in sorted(filenames):
                if fn.endswith(".md"):
                    docs_text += _read_text(os.path.join(dirpath, fn))
    if not docs_text:
        return out
    for name in sorted(metrics):
        if name not in docs_text:
            out.append(Finding(
                "OBS002", cat_path, 1,
                f"cataloged metric {name} is documented nowhere under "
                "README.md/docs/ (regenerate the docs/OBSERVABILITY.md "
                "table: python -m llama_fastapi_k8s_gpu_tpu.obs.catalog)"))
    return out

"""WIRE — the wire-surface registry cross-checks (lfkt-lint v4).

serving/wiresurface.py declares every ``x-lfkt-*`` HTTP header and every
page-wire / migration frame-header field with a direction and a TRUST
class, plus the ingress points that accept client bytes.  This checker
enforces the registry three ways (the OBS-catalog / CFG-knob pattern —
declare once, cross-check everywhere):

- **WIRE001** — an undeclared surface: an ``x-lfkt-*`` string literal
  anywhere in the package, a frame-header dict key handed to
  ``send_frame``/``put``/``encode_frame``, or a ``hdr.get(...)`` /
  ``hello.get(...)`` field read whose name the registry does not know.
  A new header or frame field must land in the registry (and pick a
  trust class) in the same commit that introduces it.
- **WIRE002** — a declared ingress that can forward client bytes
  upstream without first stripping every ``internal-stamped-must-strip``
  header.  Proved over the ingress function's CFG with a MUST dataflow
  (forward solve, intersection join): a strip event (a membership test
  against the header name, a ``.pop(HEADER)``, a ``del d[HEADER]``)
  GENerates the header; every node whose statement calls the declared
  forward tail must have all internal-stamped headers in its in-state.
  Strips inside a loop are attributed to the loop header node — an
  empty iteration means there was nothing to strip, so the loop
  vacuously covers them.  Deleting the fleet router's strip loop fires
  this (the PR-17 regression pin).
- **WIRE003** — drift between the registry and the generated table in
  docs/WIRESURFACE.md (pinned byte-for-byte between the
  ``wire-surface:begin``/``end`` markers; regenerate with ``python -m
  llama_fastapi_k8s_gpu_tpu.serving.wiresurface``).  Skips itself
  outside a repo checkout, like every docs rule.

The registry file is parsed statically (``ast`` over the declaration
literals) — the lint never imports the package under analysis.  When
the package has no ``serving/wiresurface.py`` at all, every WIRE rule
skips itself: the registry is the opt-in.
"""

from __future__ import annotations

import ast
import os
import re

from .callgraph import build_graph
from .cfg import build_cfg, eval_roots, solve_forward
from .core import Context, Finding, Source, const_str, dotted

RULES = {
    "WIRE001": "x-lfkt-* header or wire frame-header field used but not "
               "declared in serving/wiresurface.py",
    "WIRE002": "declared ingress point can forward client bytes without "
               "stripping every internal-stamped header (CFG must-"
               "analysis)",
    "WIRE003": "wire-surface registry and the generated docs table have "
               "drifted (regenerate docs/WIRESURFACE.md)",
}

#: the registry module, package-relative
REGISTRY_REL = "serving/wiresurface.py"

#: a header-shaped token (WIRE001 only fires on literals that could BE a
#: header — prose mentioning the prefix, like rule descriptions or error
#: messages with globs, is not a wire surface)
_HEADER_TOKEN_RE = re.compile(r"^x-lfkt-[a-z0-9-]*[a-z0-9]$")

_DOCS_BEGIN = "<!-- wire-surface:begin (generated - do not hand-edit) -->"
_DOCS_END = "<!-- wire-surface:end -->"

#: call tails whose 2nd positional argument is a frame-header dict
_FRAME_CTORS = ("send_frame", "put", "encode_frame")

#: receiver names conventionally bound to a decoded frame header /
#: HELLO geometry doc (the package-wide consumption idiom)
_FRAME_RECEIVERS = ("hdr", "hello", "theirs", "mine", "geometry")

_TRUST_STRIP = "internal-stamped-must-strip"


# ---------------------------------------------------------------------------
# static registry parse
# ---------------------------------------------------------------------------

class _Registry:
    """The declarations, read off the registry file's AST."""

    def __init__(self, src: Source):
        self.src = src
        self.headers: dict[str, dict] = {}      # name -> row
        self.fields: dict[str, dict] = {}
        self.ingresses: list[dict] = []
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            args = [const_str(a) for a in node.args]
            if node.func.id == "WireHeader" and len(args) >= 4 \
                    and all(a is not None for a in args[:4]):
                self.headers[args[0]] = {
                    "name": args[0], "direction": args[1],
                    "trust": args[2], "summary": args[3],
                    "line": node.lineno}
            elif node.func.id == "WireField" and len(args) >= 4 \
                    and all(a is not None for a in args[:4]):
                self.fields[args[0]] = {
                    "name": args[0], "frames": args[1],
                    "trust": args[2], "summary": args[3],
                    "line": node.lineno}
            elif node.func.id == "WireIngress" and len(args) >= 3 \
                    and all(a is not None for a in args[:3]):
                self.ingresses.append({
                    "function": args[0], "forward": args[1],
                    "summary": args[2], "line": node.lineno})

    def internal_stamped(self) -> list[str]:
        return sorted(name for name, row in self.headers.items()
                      if row["trust"] == _TRUST_STRIP)

    def markdown_table(self) -> str:
        """Byte-identical re-render of serving.wiresurface.markdown_table
        from the static declarations (WIRE003's comparison side; the
        tier-1 test pins runtime output to the docs, closing the
        static == runtime loop)."""
        rows = ["### HTTP headers", "",
                "| header | direction | trust | summary |",
                "|---|---|---|---|"]
        for h in self.headers.values():
            rows.append(f"| `{h['name']}` | {h['direction']} | "
                        f"{h['trust']} | {h['summary']} |")
        rows += ["", "### Frame-header fields", "",
                 "| field | frames | trust | summary |",
                 "|---|---|---|---|"]
        for f in self.fields.values():
            rows.append(f"| `{f['name']}` | {f['frames']} | {f['trust']} | "
                        f"{f['summary']} |")
        rows += ["", "### Ingress points", "",
                 "| function | forwards via | summary |",
                 "|---|---|---|"]
        for i in self.ingresses:
            rows.append(f"| `{i['function']}` | `{i['forward']}` | "
                        f"{i['summary']} |")
        return "\n".join(rows)


def _is_docstring_slot(parents: dict, node: ast.Constant) -> bool:
    expr = parents.get(id(node))
    if not isinstance(expr, ast.Expr):
        return False
    holder = parents.get(id(expr))
    if isinstance(holder, (ast.Module, ast.ClassDef, ast.FunctionDef,
                           ast.AsyncFunctionDef)):
        return holder.body and holder.body[0] is expr
    return False


def _parent_map(tree: ast.AST) -> dict:
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


# ---------------------------------------------------------------------------
# WIRE002: the ingress strip proof
# ---------------------------------------------------------------------------

def _header_refs(node: ast.AST, aliases: dict[str, str],
                 declared: set[str]) -> set[str]:
    """Declared header names referenced anywhere inside ``node`` — as a
    string/bytes literal or through a module-level NAME alias (possibly
    ``.encode()``-wrapped; the AST walk sees through that for free)."""
    out: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in aliases:
            out.add(aliases[sub.id])
        elif isinstance(sub, ast.Constant):
            v = sub.value
            if isinstance(v, bytes):
                try:
                    v = v.decode("ascii")
                except UnicodeDecodeError:
                    continue
            if isinstance(v, str) and v.lower() in declared:
                out.add(v.lower())
    return out


def _strip_events(fn_node, aliases: dict[str, str],
                  declared: set[str]) -> dict[int, set[str]]:
    """id(statement) -> header names that statement strips.  A strip is
    a membership Compare naming the header, a ``.pop(HEADER)``, or a
    ``del d[HEADER]``.  Events inside a loop attach to the OUTERMOST
    enclosing loop statement (the loop node dominates the post-loop
    path even on zero iterations)."""
    events: dict[int, set[str]] = {}

    def found(stmt, loop, names):
        if not names:
            return
        anchor = loop if loop is not None else stmt
        events.setdefault(id(anchor), set()).update(names)

    def scan_stmt(stmt, loop):
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Compare) \
                    and any(isinstance(op, (ast.In, ast.NotIn))
                            for op in sub.ops):
                found(stmt, loop, _header_refs(sub, aliases, declared))
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "pop" and sub.args:
                found(stmt, loop,
                      _header_refs(sub.args[0], aliases, declared))
            elif isinstance(sub, ast.Delete):
                found(stmt, loop, _header_refs(sub, aliases, declared))

    def walk(stmts, loop):
        for stmt in stmts:
            is_loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
            inner_loop = loop if loop is not None else (
                stmt if is_loop else None)
            if _has_body(stmt):
                # only the header executes at this statement's CFG node
                # (a membership test in an If header covers BOTH branches
                # — the false edge means the header was absent, which is
                # vacuously stripped)
                for root in eval_roots(stmt):
                    _scan_expr(root, stmt, loop)
            else:
                scan_stmt(stmt, loop)
            for field in ("body", "orelse", "finalbody"):
                walk(getattr(stmt, field, []) or [], inner_loop)
            for h in getattr(stmt, "handlers", []) or []:
                walk(h.body, inner_loop)

    def _scan_expr(root, stmt, loop):
        for sub in ast.walk(root):
            if isinstance(sub, ast.Compare) \
                    and any(isinstance(op, (ast.In, ast.NotIn))
                            for op in sub.ops):
                found(stmt, loop, _header_refs(sub, aliases, declared))
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "pop" and sub.args:
                found(stmt, loop,
                      _header_refs(sub.args[0], aliases, declared))

    def _has_body(stmt):
        return isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While,
                                 ast.With, ast.AsyncWith, ast.Try,
                                 ast.FunctionDef, ast.AsyncFunctionDef))

    walk(fn_node.body, None)
    return events


def _forward_nodes(cfg, forward_tail: str):
    """CFG nodes whose statement calls the declared forward tail."""
    out = []
    for node in cfg.stmt_nodes():
        for root in eval_roots(node.stmt):
            for sub in ast.walk(root):
                if isinstance(sub, ast.Call):
                    d = dotted(sub.func)
                    tail = (sub.func.attr
                            if isinstance(sub.func, ast.Attribute)
                            else d)
                    if tail == forward_tail:
                        out.append(node)
                        break
    return out


def _check_ingress(ctx: Context, graph, reg: _Registry, ingress: dict,
                   aliases: dict[str, str], dpath) -> list[Finding]:
    must_strip = set(reg.internal_stamped())
    if not must_strip:
        return []
    try:
        module, qual = ingress["function"].split(":", 1)
    except ValueError:
        return [Finding(
            "WIRE002", dpath(reg.src.rel), ingress["line"],
            f"ingress declaration {ingress['function']!r} is not "
            "module:qualname")]
    fn = graph.index.fns.get((module, qual))
    if fn is None:
        return [Finding(
            "WIRE002", dpath(reg.src.rel), ingress["line"],
            f"declared ingress {ingress['function']!r} does not resolve "
            "to a package function (stale registry entry?)")]

    cfg = build_cfg(fn.node)
    events = _strip_events(fn.node, aliases, set(reg.headers))
    gen = {}
    for node in cfg.nodes:
        if node.stmt is not None and id(node.stmt) in events:
            gen[node] = frozenset(events[id(node.stmt)])

    def flow(node, state):
        add = gen.get(node)
        return {"*": state | add if add else state}

    states = solve_forward(cfg, frozenset(), flow,
                           lambda a, b: a & b)
    out: list[Finding] = []
    reported: set[str] = set()
    for node in _forward_nodes(cfg, ingress["forward"]):
        state = states.get(node)
        if state is None:
            continue        # unreachable forward: no such path
        missing = sorted(h for h in must_strip if h not in state)
        for h in missing:
            if h in reported:
                continue
            reported.add(h)
            out.append(Finding(
                "WIRE002", dpath(fn.src.rel), node.stmt.lineno,
                f"ingress {qual} reaches {ingress['forward']}() on a "
                f"path that never strips inbound {h!r} "
                f"(trust class {_TRUST_STRIP}) — a client could forge "
                "the internal stamp; filter it out of the forwarded "
                "headers first (serving/wiresurface.py declares the "
                "must-strip set)"))
    if not _forward_nodes(cfg, ingress["forward"]):
        out.append(Finding(
            "WIRE002", dpath(reg.src.rel), ingress["line"],
            f"declared ingress {qual} never calls its declared forward "
            f"tail {ingress['forward']!r} (stale registry entry?)"))
    return out


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------

def check(ctx: Context) -> list[Finding]:
    reg_src = next((s for s in ctx.sources if s.rel == REGISTRY_REL), None)
    if reg_src is None:
        return []            # no registry: the package has not opted in
    reg = _Registry(reg_src)
    out: list[Finding] = []

    def dpath(rel: str) -> str:
        src = next((s for s in ctx.sources if s.rel == rel), None)
        return ctx.display_path(src) if src is not None else rel

    # module-level NAME = "x-lfkt-..." aliases, package-wide
    aliases: dict[str, str] = {}
    for src in ctx.sources:
        for stmt in src.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                v = const_str(stmt.value)
                if v is not None and v.lower().startswith("x-lfkt-"):
                    aliases[stmt.targets[0].id] = v.lower()

    # -- WIRE001: every use is declared -----------------------------------
    for src in ctx.sources:
        if src.rel == REGISTRY_REL:
            continue
        # built only when the file actually holds an x-lfkt-* literal —
        # a full parent map per file would dominate this checker's cost
        parents: dict | None = None
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant):
                v = node.value
                if isinstance(v, bytes):
                    try:
                        v = v.decode("ascii")
                    except UnicodeDecodeError:
                        continue
                if not (isinstance(v, str)
                        and _HEADER_TOKEN_RE.match(v.lower())):
                    continue
                if parents is None:
                    parents = _parent_map(src.tree)
                if _is_docstring_slot(parents, node):
                    continue
                if v.lower() not in reg.headers:
                    out.append(Finding(
                        "WIRE001", dpath(src.rel), node.lineno,
                        f"header {v!r} is not declared in "
                        "serving/wiresurface.py — every x-lfkt-* header "
                        "needs a registry row with a trust class"))
            elif isinstance(node, ast.Call):
                func = node.func
                tail = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else None)
                if tail in _FRAME_CTORS and len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Dict):
                    for k in node.args[1].keys:
                        name = const_str(k)
                        if name is not None and name not in reg.fields:
                            out.append(Finding(
                                "WIRE001", dpath(src.rel), k.lineno,
                                f"frame-header field {name!r} is not "
                                "declared in serving/wiresurface.py — "
                                "every wire field needs a registry row "
                                "with a trust class"))
                elif tail == "get" and isinstance(func, ast.Attribute) \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id in _FRAME_RECEIVERS \
                        and node.args:
                    name = const_str(node.args[0])
                    if name is not None and name not in reg.fields:
                        out.append(Finding(
                            "WIRE001", dpath(src.rel), node.lineno,
                            f"frame-header field {name!r} read off "
                            f"`{func.value.id}` is not declared in "
                            "serving/wiresurface.py"))

    # -- WIRE002: the ingress strip proof ----------------------------------
    graph = build_graph(ctx)
    for ingress in reg.ingresses:
        out.extend(_check_ingress(ctx, graph, reg, ingress, aliases,
                                  dpath))

    # -- WIRE003: registry <-> generated docs table ------------------------
    if ctx.repo_root:
        docs_path = os.path.join(ctx.repo_root, "docs", "WIRESURFACE.md")
        expected = reg.markdown_table()
        block = None
        try:
            with open(docs_path, encoding="utf-8") as f:
                text = f.read()
            lo = text.index(_DOCS_BEGIN) + len(_DOCS_BEGIN)
            hi = text.index(_DOCS_END)
            block = text[lo:hi].strip("\n")
        except (OSError, ValueError):
            block = None
        if block != expected:
            out.append(Finding(
                "WIRE003", dpath(reg_src.rel), 1,
                "the generated wire-surface table in docs/WIRESURFACE.md "
                "does not match the registry — regenerate it: python -m "
                "llama_fastapi_k8s_gpu_tpu.serving.wiresurface"))
    return out

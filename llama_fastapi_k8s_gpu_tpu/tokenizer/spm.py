"""SentencePiece-style tokenizer (GGUF ``tokenizer.ggml.model == "llama"``).

The Mistral / Llama-2 family tokenizer: pieces carry scores
(``tokenizer.ggml.scores``); encoding greedily merges the adjacent pair with
the highest-scoring concatenation (ties broken leftmost), with per-byte
``<0xXX>`` fallback for anything outside the vocab.  Whitespace is escaped to
U+2581 and a dummy space prefix is added, matching sentencepiece defaults.
"""

from __future__ import annotations

import heapq
from typing import Iterable, Sequence

from .base import Tokenizer, TokenType

_SPACE = "▁"  # ▁


class SPMTokenizer(Tokenizer):
    def __init__(
        self,
        tokens: Sequence[str],
        scores: Sequence[float],
        token_types: Sequence[int] | None = None,
        bos_id: int | None = 1,
        eos_id: int | None = 2,
        add_bos: bool = True,
        add_space_prefix: bool = True,
    ):
        super().__init__(tokens, token_types, bos_id, eos_id, add_bos)
        self.scores = list(scores)
        self.add_space_prefix = add_space_prefix
        self._byte_ids = {}
        for i, t in enumerate(self.tokens):
            if self.token_types[i] == TokenType.BYTE and len(t) == 6 and t.startswith("<0x"):
                self._byte_ids[int(t[3:5], 16)] = i

    # ------------------------------------------------------------------
    def _encode_fragment(self, text: str) -> list[int]:
        if not text:
            return []
        if self.add_space_prefix:
            text = " " + text
        text = text.replace(" ", _SPACE)
        symbols: list[str] = list(text)  # start from single characters
        # neighbor links: alive[i] is None if merged away
        prev = list(range(-1, len(symbols) - 1))
        nxt = list(range(1, len(symbols) + 1))
        alive = [True] * len(symbols)

        def score_of(s: str):
            tid = self.token_to_id.get(s)
            if tid is None:
                return None
            return self.scores[tid] if tid < len(self.scores) else 0.0

        heap: list[tuple[float, int, int, str]] = []

        def push(i: int):
            j = nxt[i]
            if j >= len(symbols):
                return
            merged = symbols[i] + symbols[j]
            sc = score_of(merged)
            if sc is not None:
                # max score first; ties → leftmost (llama.cpp llm_symbol_bigram)
                heapq.heappush(heap, (-sc, i, j, merged))

        for i in range(len(symbols) - 1):
            push(i)

        while heap:
            _, i, j, merged = heapq.heappop(heap)
            if not alive[i] or not alive[j] or symbols[i] + symbols[j] != merged:
                continue
            symbols[i] = merged
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] < len(symbols):
                prev[nxt[j]] = i
            if prev[i] >= 0:
                push(prev[i])
            push(i)

        ids: list[int] = []
        i = 0
        while i < len(symbols):
            if not alive[i]:
                i = nxt[i]
                continue
            sym = symbols[i]
            tid = self.token_to_id.get(sym)
            if tid is not None:
                ids.append(tid)
            else:
                for b in sym.encode("utf-8"):
                    if b in self._byte_ids:
                        ids.append(self._byte_ids[b])
                    elif self.token_to_id.get("<unk>") is not None:
                        ids.append(self.token_to_id["<unk>"])
            i = nxt[i]
        return ids

    def decode_bytes(self, ids: Iterable[int], skip_special: bool = True) -> bytes:
        buf = bytearray()
        first_real = True
        for tid in ids:
            ttype = self.token_types[tid]
            piece = self.tokens[tid]
            if ttype == TokenType.CONTROL:
                if not skip_special:
                    buf.extend(piece.encode("utf-8"))
                continue
            if ttype == TokenType.BYTE:
                buf.append(int(piece[3:5], 16))
                first_real = False
                continue
            text = piece.replace(_SPACE, " ")
            if first_real and self.add_space_prefix and text.startswith(" "):
                text = text[1:]  # drop the dummy prefix space
            first_real = False
            buf.extend(text.encode("utf-8"))
        return bytes(buf)

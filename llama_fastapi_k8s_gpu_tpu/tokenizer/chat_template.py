"""Chat template rendering.

The reference passes OpenAI-style ``[{'role', 'content'}]`` message lists to
``create_chat_completion`` (reference api.py:56-57, built at api.py:122-147);
llama.cpp renders them with the GGUF-embedded jinja template.  Rather than
evaluating jinja, the known template families are implemented directly and
selected by fingerprinting the template string — the same approach llama.cpp's
``llama_chat_apply_template`` takes.

Supported: ``llama3`` (<|start_header_id|>…), ``mistral`` ([INST] …),
``chatml`` (<|im_start|>…).
"""

from __future__ import annotations

from typing import Sequence

from .base import Tokenizer


def detect_chat_template(template: str | None, tokenizer: Tokenizer) -> str:
    if template:
        if "<|start_header_id|>" in template:
            return "llama3"
        if "[INST]" in template:
            return "mistral"
        if "<|im_start|>" in template:
            return "chatml"
    # fall back on vocab fingerprints
    if "<|start_header_id|>" in tokenizer.token_to_id:
        return "llama3"
    if "<|im_start|>" in tokenizer.token_to_id:
        return "chatml"
    return "mistral"


def render_llama3(messages: Sequence[dict]) -> str:
    out = []
    for m in messages:
        out.append(
            f"<|start_header_id|>{m['role']}<|end_header_id|>\n\n"
            f"{m['content'].strip()}<|eot_id|>"
        )
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


def render_chatml(messages: Sequence[dict]) -> str:
    out = []
    for m in messages:
        out.append(f"<|im_start|>{m['role']}\n{m['content'].strip()}<|im_end|>\n")
    out.append("<|im_start|>assistant\n")
    return "".join(out)


def render_mistral(messages: Sequence[dict], eos_piece: str = "</s>") -> str:
    """[INST] blocks; system text is folded into the first user message
    (mistral templates have no system role)."""
    system = ""
    out = []
    pending_system = ""
    for m in messages:
        role, content = m["role"], m["content"].strip()
        if role == "system":
            pending_system = content
            continue
        if role == "user":
            if pending_system:
                content = pending_system + "\n\n" + content
                pending_system = ""
            out.append(f"[INST] {content} [/INST]")
        else:  # assistant
            out.append(f" {content}{eos_piece}")
    if pending_system and not out:
        out.append(f"[INST] {pending_system} [/INST]")
    return "".join(out)


def apply_chat_template(
    tokenizer: Tokenizer,
    messages: Sequence[dict],
    template: str | None = None,
    kind: str | None = None,
) -> list[int]:
    """Messages → prompt token ids, ending with the assistant header so the
    model's next token begins the reply."""
    kind = kind or detect_chat_template(template, tokenizer)
    if kind == "llama3":
        text = render_llama3(messages)
    elif kind == "chatml":
        text = render_chatml(messages)
    elif kind == "mistral":
        text = render_mistral(messages)
    else:
        raise ValueError(f"unknown chat template kind: {kind}")
    return tokenizer.encode(text, add_bos=True, parse_special=True)

"""Tokenizer interface + shared machinery.

The reference delegates tokenization entirely to the native engine
(``llm.create_chat_completion(messages=...)``, reference api.py:55-63); the
TPU framework implements the two tokenizer families GGUF models carry:
byte-level BPE ("gpt2" model key — Llama-3) and SentencePiece-style
("llama" model key — Mistral/Llama-2).  Vocabulary, merges, scores and
special-token metadata all come from GGUF KV pairs, never from network.
"""

from __future__ import annotations

import enum
from typing import Iterable, Sequence


class TokenType(enum.IntEnum):
    """tokenizer.ggml.token_type values (llama.cpp llama_token_type)."""

    UNDEFINED = 0
    NORMAL = 1
    UNKNOWN = 2
    CONTROL = 3
    USER_DEFINED = 4
    UNUSED = 5
    BYTE = 6


class Tokenizer:
    """Common base: id↔piece tables, special-token splitting, decode glue."""

    def __init__(
        self,
        tokens: Sequence[str],
        token_types: Sequence[int] | None,
        bos_id: int | None,
        eos_id: int | None,
        add_bos: bool = True,
    ):
        self.tokens = list(tokens)
        self.token_types = (
            [TokenType(t) for t in token_types]
            if token_types is not None
            else [TokenType.NORMAL] * len(self.tokens)
        )
        self.token_to_id = {t: i for i, t in enumerate(self.tokens)}
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.add_bos = add_bos
        # Tokens that must be matched literally before pre-tokenization when
        # parse_special=True (CONTROL and USER_DEFINED types).
        self.special_tokens = {
            t: i
            for i, t in enumerate(self.tokens)
            if self.token_types[i] in (TokenType.CONTROL, TokenType.USER_DEFINED)
        }
        self._special_sorted = sorted(self.special_tokens, key=len, reverse=True)

    # -- interface -----------------------------------------------------------
    @property
    def vocab_size(self) -> int:
        return len(self.tokens)

    def encode(self, text: str, add_bos: bool | None = None,
               parse_special: bool = False) -> list[int]:
        ids: list[int] = []
        if add_bos is None:
            add_bos = self.add_bos
        if add_bos and self.bos_id is not None:
            ids.append(self.bos_id)
        for segment, special_id in self._split_special(text, parse_special):
            if special_id is not None:
                ids.append(special_id)
            elif segment:
                ids.extend(self._encode_fragment(segment))
        return ids

    def decode_bytes(self, ids: Iterable[int], skip_special: bool = True) -> bytes:
        """Raw UTF-8 byte stream for ``ids``.  Unlike the decoded *text*,
        the byte stream is append-only across incremental decodes — the
        property streaming emission relies on (engines feed the new bytes
        through an incremental UTF-8 decoder so streamed text always equals
        the batch decode, even mid-multibyte-sequence)."""
        raise NotImplementedError

    def decode(self, ids: Iterable[int], skip_special: bool = True) -> str:
        return self.decode_bytes(ids, skip_special).decode("utf-8", errors="replace")

    def _encode_fragment(self, text: str) -> list[int]:
        raise NotImplementedError

    def id_to_piece(self, token_id: int) -> str:
        return self.tokens[token_id]

    def is_control(self, token_id: int) -> bool:
        return self.token_types[token_id] == TokenType.CONTROL

    @property
    def stop_ids(self) -> set[int]:
        """End-of-generation ids: eos plus any control token llama.cpp treats
        as end-of-generation (eot/eom variants)."""
        out = set()
        if self.eos_id is not None:
            out.add(self.eos_id)
        for name in ("<|eot_id|>", "<|end_of_text|>", "<|eom_id|>", "</s>",
                     "<|im_end|>", "<|endoftext|>"):
            if name in self.token_to_id:
                out.add(self.token_to_id[name])
        return out

    # -- helpers -------------------------------------------------------------
    def _split_special(self, text: str, parse_special: bool):
        """Yield (fragment, None) or ("", special_token_id) in order."""
        if not parse_special or not self.special_tokens:
            yield text, None
            return
        rest = text
        while rest:
            best_pos, best_tok = None, None
            for tok in self._special_sorted:
                pos = rest.find(tok)
                if pos != -1 and (best_pos is None or pos < best_pos or
                                  (pos == best_pos and len(tok) > len(best_tok))):
                    best_pos, best_tok = pos, tok
            if best_pos is None:
                yield rest, None
                return
            if best_pos:
                yield rest[:best_pos], None
            yield "", self.special_tokens[best_tok]
            rest = rest[best_pos + len(best_tok):]

"""Build a tokenizer from GGUF metadata (the keys llama.cpp reads when the
reference constructs ``Llama(model_path=...)``, reference api.py:24-28)."""

from __future__ import annotations

from ..gguf import GGUFFile
from .base import Tokenizer
from .bpe import BPETokenizer
from .spm import SPMTokenizer


def tokenizer_from_gguf(gf: GGUFFile) -> Tokenizer:
    md = gf.metadata
    model = md.get("tokenizer.ggml.model", "gpt2")
    tokens = md["tokenizer.ggml.tokens"]
    token_types = md.get("tokenizer.ggml.token_type")
    bos_id = md.get("tokenizer.ggml.bos_token_id")
    eos_id = md.get("tokenizer.ggml.eos_token_id")
    add_bos = bool(md.get("tokenizer.ggml.add_bos_token", True))

    if model == "gpt2":
        return BPETokenizer(
            tokens=tokens,
            merges=md.get("tokenizer.ggml.merges", []),
            token_types=token_types,
            bos_id=bos_id,
            eos_id=eos_id,
            add_bos=add_bos,
            pre=md.get("tokenizer.ggml.pre", "llama-bpe"),
        )
    if model in ("llama", "spm"):
        return SPMTokenizer(
            tokens=tokens,
            scores=md.get("tokenizer.ggml.scores", [0.0] * len(tokens)),
            token_types=token_types,
            bos_id=bos_id if bos_id is not None else 1,
            eos_id=eos_id if eos_id is not None else 2,
            add_bos=add_bos,
            add_space_prefix=bool(md.get("tokenizer.ggml.add_space_prefix", True)),
        )
    raise NotImplementedError(f"tokenizer model {model!r}")

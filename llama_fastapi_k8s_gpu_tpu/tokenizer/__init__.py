from .base import Tokenizer, TokenType  # noqa: F401
from .bpe import BPETokenizer  # noqa: F401
from .spm import SPMTokenizer  # noqa: F401
from .chat_template import apply_chat_template, detect_chat_template  # noqa: F401
from .loader import tokenizer_from_gguf  # noqa: F401

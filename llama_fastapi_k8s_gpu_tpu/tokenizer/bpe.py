"""Byte-level BPE tokenizer (GGUF ``tokenizer.ggml.model == "gpt2"``).

This is the Llama-3 family tokenizer: raw UTF-8 bytes are mapped to printable
unicode code points (the GPT-2 byte table), text is pre-split by a regex, and
each pre-token is merged bottom-up by merge rank.  Vocab and merges come from
GGUF metadata (``tokenizer.ggml.tokens`` / ``tokenizer.ggml.merges``).
"""

from __future__ import annotations

import functools
import heapq
from typing import Iterable, Sequence

import regex  # third-party 'regex' module: supports \p{L} classes

from .base import Tokenizer, TokenType

# Pre-tokenizer patterns keyed by GGUF `tokenizer.ggml.pre`.
# llama-bpe is the Llama-3 pattern; default matches GPT-2.
_PRE_PATTERNS = {
    "llama-bpe": (
        r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}"
        r"| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
    ),
    "llama3": None,  # alias, filled below
    "default": (
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+"
        r"|\s+(?!\S)|\s+"
    ),
}
_PRE_PATTERNS["llama3"] = _PRE_PATTERNS["llama-bpe"]


@functools.lru_cache(maxsize=8)
def _compiled_pattern(pre: str):
    pat = _PRE_PATTERNS.get(pre) or _PRE_PATTERNS["default"]
    return regex.compile(pat)


@functools.lru_cache(maxsize=1)
def bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte→unicode map (printable stand-ins for all 256)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


@functools.lru_cache(maxsize=1)
def unicode_to_bytes() -> dict[str, int]:
    return {v: k for k, v in bytes_to_unicode().items()}


class BPETokenizer(Tokenizer):
    def __init__(
        self,
        tokens: Sequence[str],
        merges: Sequence[str],
        token_types: Sequence[int] | None = None,
        bos_id: int | None = None,
        eos_id: int | None = None,
        add_bos: bool = True,
        pre: str = "llama-bpe",
    ):
        super().__init__(tokens, token_types, bos_id, eos_id, add_bos)
        self.merge_ranks: dict[tuple[str, str], int] = {}
        for rank, merge in enumerate(merges):
            left, _, right = merge.partition(" ")
            self.merge_ranks[(left, right)] = rank
        self.pre = pre
        self._pattern = _compiled_pattern(pre)
        self._byte_enc = bytes_to_unicode()
        self._byte_dec = unicode_to_bytes()

    # ------------------------------------------------------------------
    def _bpe_merge(self, symbols: list[str]) -> list[str]:
        """Merge adjacent symbol pairs in rank order until no merge applies.

        Heap + neighbor links (the same O(n log n) bigram queue llama.cpp's
        ``llm_tokenizer_bpe`` uses, and that :mod:`spm` uses score-ordered):
        pop the lowest-rank pair (ties → leftmost), splice, and only re-rank
        the two pairs the splice created.  Stale heap entries are detected by
        comparing the recorded symbols against the current ones.  The round-2
        version rescanned the whole fragment per merge — O(n²) per fragment,
        which a 280k-merge real vocab turns into a latency cliff on long
        unbroken fragments."""
        n = len(symbols)
        if n < 2:
            return symbols
        ranks = self.merge_ranks
        prev = list(range(-1, n - 1))
        nxt = list(range(1, n + 1))
        alive = [True] * n
        heap: list[tuple[int, int, int, str, str]] = []

        def push(i: int):
            j = nxt[i]
            if j >= n:
                return
            rank = ranks.get((symbols[i], symbols[j]))
            if rank is not None:
                heapq.heappush(heap, (rank, i, j, symbols[i], symbols[j]))

        for i in range(n - 1):
            push(i)

        while heap:
            _, i, j, si, sj = heapq.heappop(heap)
            if not alive[i] or not alive[j] or symbols[i] != si or symbols[j] != sj:
                continue
            symbols[i] = si + sj
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] < n:
                prev[nxt[j]] = i
            if prev[i] >= 0:
                push(prev[i])
            push(i)
        return [s for s, a in zip(symbols, alive) if a]

    def _encode_fragment(self, text: str) -> list[int]:
        ids: list[int] = []
        for piece in self._pattern.findall(text):
            mapped = "".join(self._byte_enc[b] for b in piece.encode("utf-8"))
            for sym in self._bpe_merge(list(mapped)):
                tid = self.token_to_id.get(sym)
                if tid is not None:
                    ids.append(tid)
                else:
                    # unmergeable symbol: fall back to per-byte tokens
                    for ch in sym:
                        bid = self.token_to_id.get(ch)
                        if bid is not None:
                            ids.append(bid)
        return ids

    def decode_bytes(self, ids: Iterable[int], skip_special: bool = True) -> bytes:
        buf = bytearray()
        for tid in ids:
            ttype = self.token_types[tid]
            piece = self.tokens[tid]
            if ttype == TokenType.CONTROL:
                if not skip_special:
                    buf.extend(piece.encode("utf-8"))
                continue
            if ttype == TokenType.USER_DEFINED:
                # user-defined pieces are stored as raw text, not byte-mapped
                buf.extend(piece.encode("utf-8"))
                continue
            for ch in piece:
                b = self._byte_dec.get(ch)
                if b is None:
                    buf.extend(ch.encode("utf-8"))
                else:
                    buf.append(b)
        return bytes(buf)

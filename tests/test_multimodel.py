"""Multi-model, multi-tenant serving (ISSUE 9; docs/MULTIMODEL.md).

Two tiny random-weight GGUFs (same geometry, different seeds — so their
KV for identical token ids DIFFERS, making cross-namespace leakage
observable) drive the registry through every acceptance surface:

- manifest grammar + weight-budget refusal (serving/manifest.py,
  serving/registry.py);
- bit-identical greedy parity per model vs single-model baselines, on
  the serial engine and the continuous scheduler;
- a SHARED paged KV pool with per-model radix namespaces: cross-model
  page occupancy, zero phantom prefix hits across tenants;
- the OpenAI-compatible facade (/v1/models, /v1/chat/completions
  streaming + non-streaming + usage counts) through the real server,
  with /response + /health single-model behavior untouched.
"""

from __future__ import annotations

import asyncio
import json

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import ContinuousEngine, Engine, FakeEngine
from llama_fastapi_k8s_gpu_tpu.serving import (
    ModelRegistry,
    ModelSpec,
    UnknownModelError,
    WeightBudgetError,
    parse_manifest,
    pick_default,
)
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.config import Settings

MSGS = [{"role": "user", "content": "hello there"}]
MSGS2 = [{"role": "user", "content": "something else"}]


@pytest.fixture(scope="module")
def ggufs(tmp_path_factory):
    d = tmp_path_factory.mktemp("mm")
    pa, pb = str(d / "a.gguf"), str(d / "b.gguf")
    write_tiny_llama_gguf(pa, seed=0)
    write_tiny_llama_gguf(pb, seed=7)
    return pa, pb


def _serial(path, **kw):
    return Engine(path, n_ctx=128, prefill_buckets=(32,), **kw)


def _greedy(engine, messages=MSGS, n=8, **kw):
    out = engine.create_chat_completion(messages, max_tokens=n,
                                        temperature=0.0, **kw)
    return out["choices"][0]["message"]["content"], out


# ---------------------------------------------------------------------------
# manifest grammar
# ---------------------------------------------------------------------------

def test_manifest_grammar_roundtrip():
    specs = parse_manifest(
        "llama=models/a.gguf:n_ctx=2048;kv_dtype=int8, mistral=/abs/b.gguf")
    assert specs == [
        ModelSpec("llama", "models/a.gguf",
                  {"n_ctx": 2048, "kv_dtype": "int8"}),
        ModelSpec("mistral", "/abs/b.gguf", {}),
    ]
    assert pick_default(specs) == "llama"
    assert pick_default(specs, "mistral") == "mistral"
    assert specs[1].resolved_path("models") == "/abs/b.gguf"
    assert specs[0].resolved_path("md") == "md/models/a.gguf" or \
        specs[0].resolved_path("md").endswith("a.gguf")


@pytest.mark.parametrize("bad", [
    "noequals",                      # no path
    "a=x.gguf:bogus=1",              # unknown override key
    "a=x.gguf:n_ctx=abc",            # uncastable override
    "a=x.gguf,a=y.gguf",             # duplicate alias
    "bad name=x.gguf",               # illegal alias chars
    "a=",                            # empty path
    " , ",                           # nothing at all
])
def test_manifest_grammar_rejects(bad):
    with pytest.raises(ValueError) as ei:
        parse_manifest(bad)
    assert "LFKT_MODELS" in str(ei.value)


def test_default_model_must_be_in_manifest():
    specs = parse_manifest("a=x.gguf")
    with pytest.raises(ValueError):
        pick_default(specs, "zzz")


# ---------------------------------------------------------------------------
# weight budget
# ---------------------------------------------------------------------------

def test_weight_budget_refusal_names_the_offender(ggufs):
    pa, pb = ggufs
    specs = [ModelSpec("alpha", pa), ModelSpec("beta", pb)]
    one_model = _serial(pa)
    per_model = one_model.weight_bytes
    assert per_model > 0

    def build(spec, path, shared_pool):
        return _serial(path)

    # budget fits exactly one model: loading the second must refuse with
    # per-model attribution, not OOM at first traffic
    with pytest.raises(WeightBudgetError) as ei:
        ModelRegistry.from_specs(
            specs, build, default_model="alpha",
            weight_budget_bytes=int(per_model * 1.5))
    msg = str(ei.value)
    assert "beta" in msg and "alpha" in msg and "LFKT_HBM_WEIGHT_BUDGET_MB" in msg

    # a budget that fits the set loads it
    reg = ModelRegistry.from_specs(
        specs, build, default_model="alpha",
        weight_budget_bytes=int(per_model * 3))
    rows = reg.models()
    assert [r["name"] for r in rows] == ["alpha", "beta"]
    assert all(r["weight_bytes"] == per_model for r in rows)
    assert all(r["state"] == "ready" for r in rows)


# ---------------------------------------------------------------------------
# routing + serial greedy parity
# ---------------------------------------------------------------------------

def test_serial_registry_parity_and_routing(ggufs):
    pa, pb = ggufs
    base_a, _ = _greedy(_serial(pa))
    base_b, _ = _greedy(_serial(pb))
    assert base_a != base_b          # different weights actually differ

    reg = ModelRegistry({"alpha": _serial(pa), "beta": _serial(pb)}, "alpha")
    got_a, out_a = _greedy(reg, model="alpha")
    got_b, out_b = _greedy(reg, model="beta")
    got_default, _ = _greedy(reg)    # no model= -> default alias
    assert got_a == base_a           # bit-identical greedy per model
    assert got_b == base_b
    assert got_default == base_a
    # responses echo the manifest alias, not the GGUF's embedded name
    assert out_a["model"] == "alpha" and out_b["model"] == "beta"
    assert out_a["lfkt_timings"]["model"] == "alpha"

    with pytest.raises(UnknownModelError):
        reg.resolve("gamma")


# ---------------------------------------------------------------------------
# shared paged pool: cross-model occupancy, zero cross-namespace hits
# ---------------------------------------------------------------------------

def test_shared_pool_namespace_isolation(ggufs):
    pa, pb = ggufs
    specs = [ModelSpec("alpha", pa), ModelSpec("beta", pb)]

    def build(spec, path, shared_pool):
        return _serial(path, kv_paged=True, kv_page_tokens=8,
                       kv_pool_pages=32, prefix_cache=True, prefix_min=8,
                       kv_pool=shared_pool, kv_namespace=spec.name)

    reg = ModelRegistry.from_specs(specs, build, default_model="alpha")
    ea, eb = reg.resolve("alpha"), reg.resolve("beta")
    pool = ea._kvpool
    assert pool is eb._kvpool        # ONE arena shared by both models

    # a long-ish prompt so the whole-page prefix is committable
    msgs = [{"role": "user", "content": "the quick brown fox jumps over"}]
    first_a, _ = _greedy(ea, msgs, n=6)
    occ_after_a = pool.occupancy()
    assert occ_after_a["pages_used"] > 0
    ids = ea.tokenize_messages(msgs)

    # beta sees NOTHING of alpha's identical token prefix (namespace
    # isolation: its KV for the same ids would be wrong)
    assert pool.match_len(ids, namespace="beta") == 0
    assert pool.match_len(ids, namespace="alpha") > 0
    hits_before = pool.stats()["hits"]
    first_b, _ = _greedy(eb, msgs, n=6)
    assert pool.stats()["hits"] == hits_before   # no phantom cross-hit

    # cross-model page occupancy: both models' pages resident in one arena
    occ_after_b = pool.occupancy()
    assert occ_after_b["pages_used"] > occ_after_a["pages_used"]
    assert occ_after_b["namespaces"] == 2

    # alpha's re-run takes a radix hit and stays bit-identical
    again_a, out = _greedy(ea, msgs, n=6)
    assert again_a == first_a
    assert pool.stats()["hits"] > hits_before
    assert out["lfkt_timings"]["prefix_reused_tokens"] > 0

    # and beta's generation was untouched by alpha's cache
    base_b, _ = _greedy(_serial(pb), msgs, n=6)
    assert first_b == base_b


def test_incompatible_geometry_degrades_to_private_pool(ggufs):
    pa, _ = ggufs
    ea = _serial(pa, kv_paged=True, kv_page_tokens=8, kv_pool_pages=16)
    # int8 KV has a different page layout: sharing must degrade (private
    # pool + attribution), never serve wrong bytes
    eb = _serial(pa, kv_paged=True, kv_page_tokens=8, kv_pool_pages=16,
                 kv_dtype="int8", kv_pool=ea._kvpool, kv_namespace="b")
    assert eb._kvpool is not ea._kvpool

    # the merged occupancy over split pools sums only the additive
    # fields; page geometry is listed per pool, never summed
    reg = ModelRegistry({"alpha": ea, "beta": eb}, "alpha")
    occ = reg.kv_pool_occupancy()
    assert occ["pools"] == 2
    assert occ["pages_total"] == 32                  # additive: 16 + 16
    assert "page_tokens" not in occ                  # non-additive
    assert [p["page_tokens"] for p in occ["per_pool"]] == [8, 8]
    assert all("page_bytes" in p for p in occ["per_pool"])


def test_registry_factory_mirrors_single_model_semantics(ggufs):
    """A 1-entry LFKT_MODELS manifest must keep the single-model
    factory's serving shape: cycle scheduler still builds a MeshEngine
    (no silent scheduler swap), and sp×batch refuses identically."""
    from llama_fastapi_k8s_gpu_tpu.server.app import _registry_factory

    pa, _ = ggufs
    reg = _registry_factory(Settings(
        models=f"solo={pa}", scheduler="cycle", batch_size=2,
        max_context_tokens=128, prefill_buckets="32"))
    assert type(reg.resolve(None)).__name__ == "MeshEngine"
    assert reg.model_names() == ["solo"]

    with pytest.raises(ValueError) as ei:
        _registry_factory(Settings(models=f"solo={pa}", mesh_sp=2,
                                   batch_size=2))
    assert "LFKT_BATCH_SIZE" in str(ei.value)


# ---------------------------------------------------------------------------
# continuous scheduler: interleaved multi-model lanes, greedy parity
# ---------------------------------------------------------------------------

def _continuous(path, **kw):
    return ContinuousEngine(path, n_ctx=128, prefill_buckets=(32,),
                            batch_size=2, prefill_chunk=16, **kw)


def test_continuous_registry_interleaves_models(ggufs):
    pa, pb = ggufs
    single_a = _continuous(pa)
    single_b = _continuous(pb)
    try:
        base_a = single_a.submit(MSGS, max_tokens=8,
                                 temperature=0.0).result(timeout=120)
        base_b = single_b.submit(MSGS2, max_tokens=8,
                                 temperature=0.0).result(timeout=120)
    finally:
        single_a.shutdown()
        single_b.shutdown()

    reg = ModelRegistry({"alpha": _continuous(pa),
                         "beta": _continuous(pb)}, "alpha")
    try:
        # both models' lanes in flight concurrently from one process:
        # the schedulers interleave their waves on the device queue
        futs = [
            reg.submit(MSGS, max_tokens=8, temperature=0.0, model="alpha"),
            reg.submit(MSGS2, max_tokens=8, temperature=0.0, model="beta"),
            reg.submit(MSGS, max_tokens=8, temperature=0.0, model="alpha"),
            reg.submit(MSGS2, max_tokens=8, temperature=0.0, model="beta"),
        ]
        outs = [f.result(timeout=240) for f in futs]
        want_a = base_a["choices"][0]["message"]["content"]
        want_b = base_b["choices"][0]["message"]["content"]
        assert outs[0]["choices"][0]["message"]["content"] == want_a
        assert outs[2]["choices"][0]["message"]["content"] == want_a
        assert outs[1]["choices"][0]["message"]["content"] == want_b
        assert outs[3]["choices"][0]["message"]["content"] == want_b
        assert outs[0]["model"] == "alpha" and outs[1]["model"] == "beta"

        # merged scheduler stats: per-model keys + the fleet-level HPA
        # gauges (admission budget, idle lane-seconds)
        stats = reg.scheduler_stats()
        assert stats["models"] == 2
        assert "alpha_lanes_live" in stats and "beta_lanes_live" in stats
        assert "adm_budget_tokens" in stats and "lane_idle_seconds" in stats
    finally:
        reg.shutdown()


# ---------------------------------------------------------------------------
# the OpenAI facade through the real server
# ---------------------------------------------------------------------------

def _client(engine, **settings_kw):
    app = create_app(engine=engine, settings=Settings(**settings_kw))
    return app, httpx.ASGITransport(app=app)


@pytest.fixture(scope="module")
def served_registry(ggufs):
    pa, pb = ggufs
    return ModelRegistry({"alpha": _serial(pa), "beta": _serial(pb)},
                         "alpha")


@pytest.mark.anyio
async def test_v1_models_lists_manifest(served_registry):
    app, transport = _client(served_registry)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.get("/v1/models")
            assert r.status_code == 200
            doc = r.json()
            assert doc["object"] == "list"
            assert [m["id"] for m in doc["data"]] == ["alpha", "beta"]
            assert all(m["object"] == "model" for m in doc["data"])
        await app.router.shutdown()


@pytest.mark.anyio
async def test_v1_chat_completion_non_streaming_usage(served_registry):
    app, transport = _client(served_registry)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.post("/v1/chat/completions", json={
                "model": "beta", "max_tokens": 6, "temperature": 0.0,
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 200
            doc = r.json()
            assert doc["object"] == "chat.completion"
            assert doc["model"] == "beta"
            assert "lfkt_timings" not in doc
            u = doc["usage"]
            # usage counts come from the engine's own tokenize/decode
            assert u["prompt_tokens"] > 0
            assert 1 <= u["completion_tokens"] <= 6
            assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]
            assert doc["choices"][0]["message"]["role"] == "assistant"
            assert doc["choices"][0]["finish_reason"] in ("stop", "length")
        await app.router.shutdown()


@pytest.mark.anyio
async def test_v1_chat_completion_streaming_schema(served_registry):
    app, transport = _client(served_registry)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.post("/v1/chat/completions", json={
                "model": "alpha", "max_tokens": 6, "temperature": 0.0,
                "stream": True,
                "stream_options": {"include_usage": True},
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 200
            assert r.headers["content-type"].startswith("text/event-stream")
            events = [e for e in r.text.split("\n\n") if e.startswith("data: ")]
            assert events[-1] == "data: [DONE]"
            chunks = [json.loads(e[6:]) for e in events[:-1]]
            # final usage chunk (stream_options.include_usage), empty choices
            usage = chunks[-1]
            assert usage["choices"] == [] and "usage" in usage
            assert usage["usage"]["total_tokens"] == (
                usage["usage"]["prompt_tokens"]
                + usage["usage"]["completion_tokens"])
            body = chunks[:-1]
            assert all(ch["object"] == "chat.completion.chunk" for ch in body)
            assert all(ch["model"] == "alpha" for ch in body)
            assert body[0]["choices"][0]["delta"] == {"role": "assistant"}
            assert body[-1]["choices"][0]["finish_reason"] in ("stop", "length")
            assert all("lfkt_timings" not in ch for ch in body)
        await app.router.shutdown()


@pytest.mark.anyio
async def test_v1_unknown_model_openai_error_body(served_registry):
    app, transport = _client(served_registry)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.post("/v1/chat/completions", json={
                "model": "gamma",
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 400
            err = r.json()["error"]
            assert err["type"] == "invalid_request_error"
            assert err["code"] == "model_not_found"
            assert "gamma" in err["message"] and "alpha" in err["message"]

            # n>1 and empty messages are structured 400s too
            r = await c.post("/v1/chat/completions", json={
                "n": 2, "messages": [{"role": "user", "content": "x"}]})
            assert r.status_code == 400
            assert r.json()["error"]["type"] == "invalid_request_error"
            r = await c.post("/v1/chat/completions", json={"messages": []})
            assert r.status_code == 400
        await app.router.shutdown()


@pytest.mark.anyio
async def test_response_model_field_routes_and_400s(served_registry):
    """/response accepts the optional model field (existing JSON error
    shape on an unknown alias) while the default body stays unchanged."""
    body = {
        "bot_profile": {"name": "Ada", "appearance": "a,b,c,d",
                        "system_prompt": "be brief"},
        "user_profile": {"name": "Sam"},
        "context": [{"turn": "user", "message": "hi"}],
    }
    app, transport = _client(served_registry)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.post("/response", json={**body, "model": "beta"})
            assert r.status_code == 200 and "response" in r.json()
            r = await c.post("/response", json=body)      # default model
            assert r.status_code == 200
            r = await c.post("/response", json={**body, "model": "gamma"})
            assert r.status_code == 400
            assert "unknown model" in r.json()["detail"]  # legacy shape
        await app.router.shutdown()


@pytest.mark.anyio
async def test_health_models_block_and_metrics_labels(served_registry):
    app, transport = _client(served_registry)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            h = await c.get("/health")
            eng = h.json()["engine"]
            rows = eng["models"]
            assert [r["name"] for r in rows] == ["alpha", "beta"]
            assert all(r["weight_bytes"] > 0 for r in rows)
            assert all(r["state"] == "ready" for r in rows)
            assert all(r["quant"] for r in rows)
            assert eng["default_model"] == "alpha"

            await c.post("/v1/chat/completions", json={
                "model": "beta", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "hi"}]})
            m = (await c.get("/metrics")).text
            assert "models_loaded 2" in m
            assert 'model_weight_bytes{model="alpha"}' in m
            assert 'model_weight_bytes{model="beta"}' in m
            assert 'engine_ttft_seconds_count{bucket="32",model="beta"}' in m
            assert 'engine_decode_tokens_per_sec' in m
        await app.router.shutdown()


@pytest.mark.anyio
async def test_v1_single_model_engine_still_serves():
    """The facade works on single-model pods too: the engine's own name
    is the one listed/accepted model; other names 400."""
    engine = FakeEngine(reply="hey")
    engine.model_name = "solo"
    app, transport = _client(engine)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.get("/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["solo"]
            r = await c.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 200
            assert r.json()["choices"][0]["message"]["content"] == "hey"
            r = await c.post("/v1/chat/completions", json={
                "model": "other",
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 400
            assert r.json()["error"]["code"] == "model_not_found"
        await app.router.shutdown()


@pytest.mark.anyio
async def test_v1_oversized_prompt_is_400_not_500(ggufs):
    pa, _ = ggufs
    reg = ModelRegistry({"alpha": _serial(pa)}, "alpha")
    app, transport = _client(reg)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "x" * 2000}]})
            assert r.status_code == 400
            err = r.json()["error"]
            assert err["type"] == "invalid_request_error"
            assert "context window" in err["message"]
        await app.router.shutdown()


@pytest.mark.anyio
async def test_debug_requests_rows_carry_model(ggufs):
    """/debug/requests rows gain the model name: the trace meta carries
    it from the engine's identity attrs."""
    pa, _ = ggufs
    slow = FakeEngine(reply="z" * 50, chunk_delay=0.05)
    reg = ModelRegistry({"alpha": slow}, "alpha")
    app, transport = _client(reg)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            task = asyncio.create_task(c.post("/response/stream", json={
                "bot_profile": {"name": "A", "appearance": "a,b",
                                "system_prompt": "s"},
                "user_profile": {"name": "U"},
                "context": [{"turn": "user", "message": "hi"}],
                "model": "alpha",
            }))
            rows = []
            for _ in range(100):
                await asyncio.sleep(0.02)
                rows = (await c.get("/debug/requests")).json()["requests"]
                if any(r.get("model") == "alpha" for r in rows):
                    break
            assert any(r.get("model") == "alpha" for r in rows), rows
            await task
        await app.router.shutdown()

"""Bit-exactness tests for the GGML quant codecs.

Strategy (SURVEY.md §4 "Unit"): each vectorized numpy dequant in
``gguf/quants.py`` is checked against an *independent scalar* re-implementation
of the llama.cpp block layouts written here with explicit loops, over random
raw blocks (valid by construction).  Quantize→dequantize round-trips are
checked against analytic error bounds.
"""

import numpy as np
import pytest

from llama_fastapi_k8s_gpu_tpu.gguf import GGMLType, quants
from llama_fastapi_k8s_gpu_tpu.gguf.constants import GGML_BLOCK_SIZES

rng = np.random.default_rng(0)


def _f16(lo, hi):
    return np.frombuffer(bytes([lo, hi]), dtype=np.float16)[0].astype(np.float32)


def _rand_f16_bytes(n):
    # random but finite/small half-precision scales
    vals = rng.uniform(-2, 2, size=n).astype(np.float16)
    return vals.view(np.uint8).reshape(n, 2)


def _get_scale_min_k4(j, q):
    if j < 4:
        return q[j] & 63, q[j + 4] & 63
    return (
        (q[j + 4] & 0x0F) | ((q[j - 4] >> 6) << 4),
        (q[j + 4] >> 4) | ((q[j] >> 6) << 4),
    )


def scalar_dequant_q8_0(raw):
    out = []
    for blk in raw.reshape(-1, 34):
        d = _f16(blk[0], blk[1])
        q = blk[2:].view(np.int8)
        out.extend(float(d) * float(x) for x in q)
    return np.array(out, dtype=np.float32)


def scalar_dequant_q4_0(raw):
    out = []
    for blk in raw.reshape(-1, 18):
        d = _f16(blk[0], blk[1])
        qs = blk[2:]
        vals = [0.0] * 32
        for l in range(16):
            vals[l] = float(d) * ((int(qs[l]) & 0x0F) - 8)
            vals[l + 16] = float(d) * ((int(qs[l]) >> 4) - 8)
        out.extend(vals)
    return np.array(out, dtype=np.float32)


def scalar_dequant_q4_1(raw):
    out = []
    for blk in raw.reshape(-1, 20):
        d = _f16(blk[0], blk[1])
        m = _f16(blk[2], blk[3])
        qs = blk[4:]
        vals = [0.0] * 32
        for l in range(16):
            vals[l] = float(d) * (int(qs[l]) & 0x0F) + float(m)
            vals[l + 16] = float(d) * (int(qs[l]) >> 4) + float(m)
        out.extend(vals)
    return np.array(out, dtype=np.float32)


def scalar_dequant_q5_0(raw):
    # transcribed from llama.cpp dequantize_row_q5_0: xh_0 = bit j of qh at
    # position 4, xh_1 = bit (j+16)
    out = []
    for blk in raw.reshape(-1, 22):
        d = _f16(blk[0], blk[1])
        qh = int.from_bytes(bytes(blk[2:6]), "little")
        qs = blk[6:]
        vals = [0.0] * 32
        for j in range(16):
            xh_0 = ((qh >> j) << 4) & 0x10
            xh_1 = (qh >> (j + 12)) & 0x10
            vals[j] = float(d) * (((int(qs[j]) & 0x0F) | xh_0) - 16)
            vals[j + 16] = float(d) * (((int(qs[j]) >> 4) | xh_1) - 16)
        out.extend(vals)
    return np.array(out, dtype=np.float32)


def scalar_dequant_q5_1(raw):
    out = []
    for blk in raw.reshape(-1, 24):
        d = _f16(blk[0], blk[1])
        m = _f16(blk[2], blk[3])
        qh = int.from_bytes(bytes(blk[4:8]), "little")
        qs = blk[8:]
        vals = [0.0] * 32
        for j in range(16):
            xh_0 = ((qh >> j) << 4) & 0x10
            xh_1 = (qh >> (j + 12)) & 0x10
            vals[j] = float(d) * ((int(qs[j]) & 0x0F) | xh_0) + float(m)
            vals[j + 16] = float(d) * ((int(qs[j]) >> 4) | xh_1) + float(m)
        out.extend(vals)
    return np.array(out, dtype=np.float32)


def scalar_dequant_q4_k(raw):
    out = []
    for blk in raw.reshape(-1, 144):
        d = _f16(blk[0], blk[1])
        dmin = _f16(blk[2], blk[3])
        scales = blk[4:16]
        qs = blk[16:]
        is_ = 0
        q_off = 0
        for _ in range(4):  # 64 elements per iteration
            sc1, m1 = _get_scale_min_k4(is_, scales)
            sc2, m2 = _get_scale_min_k4(is_ + 1, scales)
            d1, mm1 = float(d) * sc1, float(dmin) * m1
            d2, mm2 = float(d) * sc2, float(dmin) * m2
            for l in range(32):
                out.append(d1 * (qs[q_off + l] & 0x0F) - mm1)
            for l in range(32):
                out.append(d2 * (qs[q_off + l] >> 4) - mm2)
            q_off += 32
            is_ += 2
    return np.array(out, dtype=np.float32)


def scalar_dequant_q5_k(raw):
    out = []
    for blk in raw.reshape(-1, 176):
        d = _f16(blk[0], blk[1])
        dmin = _f16(blk[2], blk[3])
        scales = blk[4:16]
        qh = blk[16:48]
        ql = blk[48:]
        is_ = 0
        u1, u2 = 1, 2
        q_off = 0
        for _ in range(4):
            sc1, m1 = _get_scale_min_k4(is_, scales)
            sc2, m2 = _get_scale_min_k4(is_ + 1, scales)
            d1, mm1 = float(d) * sc1, float(dmin) * m1
            d2, mm2 = float(d) * sc2, float(dmin) * m2
            for l in range(32):
                out.append(d1 * ((ql[q_off + l] & 0x0F) + (16 if qh[l] & u1 else 0)) - mm1)
            for l in range(32):
                out.append(d2 * ((ql[q_off + l] >> 4) + (16 if qh[l] & u2 else 0)) - mm2)
            q_off += 32
            is_ += 2
            u1 <<= 2
            u2 <<= 2
    return np.array(out, dtype=np.float32)


_KV_IQ4NL = [-127, -104, -83, -65, -49, -35, -22, -10,
             1, 13, 25, 38, 53, 69, 89, 113]


def scalar_dequant_iq4_nl(raw):
    out = []
    for blk in raw.reshape(-1, 18):
        d = _f16(blk[0], blk[1])
        qs = blk[2:]
        vals = [0.0] * 32
        for j in range(16):
            vals[j] = float(d) * _KV_IQ4NL[int(qs[j]) & 0x0F]
            vals[j + 16] = float(d) * _KV_IQ4NL[int(qs[j]) >> 4]
        out.extend(vals)
    return np.array(out, dtype=np.float32)


def scalar_dequant_iq4_xs(raw):
    # transcribed from llama.cpp dequantize_row_iq4_xs
    out = []
    for blk in raw.reshape(-1, 136):
        d = _f16(blk[0], blk[1])
        scales_h = int(blk[2]) | (int(blk[3]) << 8)
        scales_l = blk[4:8]
        qs = blk[8:]
        for ib in range(8):
            ls = (((int(scales_l[ib // 2]) >> (4 * (ib % 2))) & 0xF)
                  | (((scales_h >> (2 * ib)) & 3) << 4))
            dl = float(d) * (ls - 32)
            for j in range(16):
                out.append(dl * _KV_IQ4NL[int(qs[16 * ib + j]) & 0x0F])
            for j in range(16):
                out.append(dl * _KV_IQ4NL[int(qs[16 * ib + j]) >> 4])
    return np.array(out, dtype=np.float32)


def scalar_dequant_q2_k(raw):
    # transcribed from llama.cpp dequantize_row_q2_K (explicit loops)
    out = []
    for blk in raw.reshape(-1, 84):
        scales = blk[:16]
        d = _f16(blk[80], blk[81])
        dmin = _f16(blk[82], blk[83])
        q_off = 16
        is_ = 0
        for _n in range(2):          # two 128-element halves
            shift = 0
            for _j in range(4):
                sc = scales[is_]
                is_ += 1
                dl, ml = float(d) * (sc & 0xF), float(dmin) * (sc >> 4)
                for l in range(16):
                    out.append(dl * ((int(blk[q_off + l]) >> shift) & 3) - ml)
                sc = scales[is_]
                is_ += 1
                dl, ml = float(d) * (sc & 0xF), float(dmin) * (sc >> 4)
                for l in range(16):
                    out.append(dl * ((int(blk[q_off + 16 + l]) >> shift) & 3) - ml)
                shift += 2
            q_off += 32
    return np.array(out, dtype=np.float32)


def scalar_dequant_q3_k(raw):
    # transcribed from llama.cpp dequantize_row_q3_K, incl. the kmask aux
    # munging done on the original aux words before reassignment
    kmask1, kmask2 = 0x03030303, 0x0F0F0F0F
    out = []
    for blk in raw.reshape(-1, 110):
        hm = blk[:32]
        d_all = _f16(blk[108], blk[109])
        aux = [int.from_bytes(bytes(blk[96 + 4 * i:100 + 4 * i]), "little")
               for i in range(3)]
        tmp = aux[2]
        aux2 = ((aux[0] >> 4) & kmask2) | (((tmp >> 4) & kmask1) << 4)
        aux3 = ((aux[1] >> 4) & kmask2) | (((tmp >> 6) & kmask1) << 4)
        aux0 = (aux[0] & kmask2) | (((tmp >> 0) & kmask1) << 4)
        aux1 = (aux[1] & kmask2) | (((tmp >> 2) & kmask1) << 4)
        sc_bytes = b"".join(a.to_bytes(4, "little")
                            for a in (aux0, aux1, aux2, aux3))
        scales = np.frombuffer(sc_bytes, dtype=np.int8)
        q_off = 32
        m = 1
        is_ = 0
        for _n in range(2):
            shift = 0
            for _j in range(4):
                dl = float(d_all) * (int(scales[is_]) - 32)
                is_ += 1
                for l in range(16):
                    q = (int(blk[q_off + l]) >> shift) & 3
                    out.append(dl * (q - (0 if hm[l] & m else 4)))
                dl = float(d_all) * (int(scales[is_]) - 32)
                is_ += 1
                for l in range(16):
                    q = (int(blk[q_off + 16 + l]) >> shift) & 3
                    out.append(dl * (q - (0 if hm[16 + l] & m else 4)))
                shift += 2
                m <<= 1
            q_off += 32
    return np.array(out, dtype=np.float32)


def scalar_dequant_q6_k(raw):
    out = []
    for blk in raw.reshape(-1, 210):
        ql = blk[0:128].astype(int)
        qh = blk[128:192].astype(int)
        sc = blk[192:208].view(np.int8).astype(int)
        d = _f16(blk[208], blk[209])
        y = [0.0] * 256
        for n in range(0, 256, 128):
            half = n // 128
            for l in range(32):
                is_ = l // 16
                base_ql = 64 * half
                base_qh = 32 * half
                base_sc = 8 * half
                q1 = ((ql[base_ql + l] & 0x0F) | (((qh[base_qh + l] >> 0) & 3) << 4)) - 32
                q2 = ((ql[base_ql + l + 32] & 0x0F) | (((qh[base_qh + l] >> 2) & 3) << 4)) - 32
                q3 = ((ql[base_ql + l] >> 4) | (((qh[base_qh + l] >> 4) & 3) << 4)) - 32
                q4 = ((ql[base_ql + l + 32] >> 4) | (((qh[base_qh + l] >> 6) & 3) << 4)) - 32
                y[n + l] = float(d) * sc[base_sc + is_] * q1
                y[n + l + 32] = float(d) * sc[base_sc + is_ + 2] * q2
                y[n + l + 64] = float(d) * sc[base_sc + is_ + 4] * q3
                y[n + l + 96] = float(d) * sc[base_sc + is_ + 6] * q4
        out.extend(y)
    return np.array(out, dtype=np.float32)


def _random_blocks(gtype: GGMLType, nb: int) -> np.ndarray:
    """Random valid raw blocks: random payload bytes, sane f16 scales."""
    _, bsize = GGML_BLOCK_SIZES[gtype]
    raw = rng.integers(0, 256, size=(nb, bsize), dtype=np.uint8)
    if gtype in (GGMLType.Q8_0, GGMLType.Q4_0, GGMLType.Q5_0,
                 GGMLType.IQ4_NL, GGMLType.IQ4_XS):
        raw[:, 0:2] = _rand_f16_bytes(nb)
    elif gtype in (GGMLType.Q4_K, GGMLType.Q5_K, GGMLType.Q4_1,
                   GGMLType.Q5_1):
        raw[:, 0:2] = _rand_f16_bytes(nb)
        raw[:, 2:4] = _rand_f16_bytes(nb)
    elif gtype == GGMLType.Q6_K:
        raw[:, 208:210] = _rand_f16_bytes(nb)
    elif gtype == GGMLType.Q2_K:
        raw[:, 80:82] = _rand_f16_bytes(nb)
        raw[:, 82:84] = _rand_f16_bytes(nb)
    elif gtype == GGMLType.Q3_K:
        raw[:, 108:110] = _rand_f16_bytes(nb)
    return raw.reshape(-1)


SCALAR = {
    GGMLType.Q8_0: scalar_dequant_q8_0,
    GGMLType.Q4_0: scalar_dequant_q4_0,
    GGMLType.Q4_1: scalar_dequant_q4_1,
    GGMLType.Q5_0: scalar_dequant_q5_0,
    GGMLType.Q5_1: scalar_dequant_q5_1,
    GGMLType.Q2_K: scalar_dequant_q2_k,
    GGMLType.Q3_K: scalar_dequant_q3_k,
    GGMLType.Q4_K: scalar_dequant_q4_k,
    GGMLType.Q5_K: scalar_dequant_q5_k,
    GGMLType.Q6_K: scalar_dequant_q6_k,
    GGMLType.IQ4_NL: scalar_dequant_iq4_nl,
    GGMLType.IQ4_XS: scalar_dequant_iq4_xs,
}


@pytest.mark.parametrize("gtype", list(SCALAR))
def test_dequant_matches_scalar_reference(gtype):
    block_elems, _ = GGML_BLOCK_SIZES[gtype]
    nb = 7
    raw = _random_blocks(gtype, nb)
    fast = quants.dequantize(raw, gtype, nb * block_elems)
    slow = SCALAR[gtype](raw)
    np.testing.assert_allclose(fast, slow, rtol=0, atol=0)


@pytest.mark.parametrize(
    "gtype,rel_bound",
    [
        (GGMLType.Q8_0, 0.02),
        (GGMLType.Q4_0, 0.20),
        (GGMLType.Q4_1, 0.15),
        (GGMLType.Q5_0, 0.10),
        (GGMLType.Q5_1, 0.08),
        (GGMLType.Q2_K, 0.45),
        (GGMLType.Q3_K, 0.25),
        (GGMLType.Q4_K, 0.15),
        (GGMLType.Q5_K, 0.08),
        (GGMLType.Q6_K, 0.05),
        (GGMLType.IQ4_NL, 0.15),
        # tightened with the signed max-magnitude scale fit (the q3_k-style
        # fit: sub-block scales use the full −32..31 range; was 0.15)
        (GGMLType.IQ4_XS, 0.10),
    ],
)
def test_quant_roundtrip_error(gtype, rel_bound):
    block_elems, _ = GGML_BLOCK_SIZES[gtype]
    x = rng.standard_normal(block_elems * 16).astype(np.float32)
    raw = quants.quantize(x, gtype)
    y = quants.dequantize(raw, gtype, x.size)
    rms = np.sqrt(np.mean((x - y) ** 2)) / np.sqrt(np.mean(x**2))
    assert rms < rel_bound, f"{gtype.name} round-trip rms {rms:.4f}"


def test_iq4_xs_signed_scale_fit_uses_full_range():
    """quant_iq4_xs fits d against the SIGNED max-magnitude element (as
    quant_q3_k does), so sub-blocks whose extreme element is positive get a
    negative scale (ls < 32) — the unsigned fit could only emit 32..63,
    wasting half the 6-bit field.  Also pins that the max-magnitude element
    of each sub-block survives the round trip near-exactly (it maps onto
    the kvalue table's −127 end by construction)."""
    x = rng.standard_normal(256 * 16).astype(np.float32)
    raw = quants.quantize(x, GGMLType.IQ4_XS)
    blocks = raw.reshape(-1, 136)
    sh = blocks[:, 2:4].copy().view(np.uint16).reshape(-1)
    sl = blocks[:, 4:8]
    ib = np.arange(8)
    ls = (((sl[:, ib // 2] >> (4 * (ib % 2))) & 0x0F)
          | (((sh[:, None] >> (2 * ib)) & 3) << 4))
    assert (ls < 32).any(), "no negative sub-block scales emitted"
    assert (ls >= 32).any()
    y = quants.dequantize(raw, GGMLType.IQ4_XS, x.size)
    sub_x = x.reshape(-1, 32)
    sub_y = y.reshape(-1, 32)
    j = np.abs(sub_x).argmax(axis=1)
    mx = np.take_along_axis(sub_x, j[:, None], axis=1)[:, 0]
    my = np.take_along_axis(sub_y, j[:, None], axis=1)[:, 0]
    # d is f16-rounded and ls integer-rounded; the −127 anchor keeps the
    # extreme element within a few percent (sign always preserved)
    np.testing.assert_allclose(my, mx, rtol=0.05, atol=1e-6)


@pytest.mark.parametrize("gtype", [GGMLType.F16, GGMLType.BF16, GGMLType.F32])
def test_float_formats_roundtrip(gtype):
    x = rng.standard_normal(256).astype(np.float32)
    raw = quants.quantize(x, gtype)
    y = quants.dequantize(raw, gtype, x.size)
    atol = {GGMLType.F32: 0, GGMLType.F16: 1e-3, GGMLType.BF16: 1e-2}[gtype]
    np.testing.assert_allclose(x, y, atol=atol, rtol=atol)


def test_scale_min_pack_unpack_roundtrip():
    sc = rng.integers(0, 64, size=(5, 8), dtype=np.uint8)
    mn = rng.integers(0, 64, size=(5, 8), dtype=np.uint8)
    packed = quants.pack_scale_min_k4(sc, mn)
    sc2, mn2 = quants.unpack_scale_min_k4(packed)
    np.testing.assert_array_equal(sc, sc2)
    np.testing.assert_array_equal(mn, mn2)

"""lfkt-lint tier-1 gates (ISSUE 3).

Three layers:

1. **Tree gates** — one test per rule asserting ZERO unsuppressed findings
   on the real package.  These are the machine-checked invariants: lock
   discipline, jit purity, the config registry three-way cross-check, the
   Pallas kernel contract, no dead code.  A failure names the file:line
   and the rule's fix.
2. **Self-tests** — the checkers run against a planted-violation fixture
   tree (tests/lint_fixtures/) and every rule must FIRE where planted;
   suppressions must suppress; a reasonless or unknown-rule noqa is
   itself an error.  These prove the gates can't rot into always-green.
3. **Registry/runtime** — the knob accessors enforce registration at
   runtime; the registry↔Settings mapping is total; helm's explicit env
   plumbing and probe paths cross-check against the live registry/routes
   (the ISSUE's satellite cross-check, asserted directly — not only via
   the CFG rules).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

import pytest

from llama_fastapi_k8s_gpu_tpu.lint import all_rules, run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


# ---------------------------------------------------------------------------
# layer 1: the tree is clean, rule by rule
# ---------------------------------------------------------------------------

_tree_findings_cache: list | None = None
_tree_findings_seconds: float | None = None


def _tree_findings():
    global _tree_findings_cache, _tree_findings_seconds
    if _tree_findings_cache is None:
        t0 = time.monotonic()
        _tree_findings_cache = run_lint(
            package_dir=os.path.join(REPO, "llama_fastapi_k8s_gpu_tpu"),
            repo_root=REPO)
        _tree_findings_seconds = time.monotonic() - t0
    return _tree_findings_cache


@pytest.mark.parametrize("rule", sorted(all_rules()))
def test_tree_clean(rule):
    live = [f for f in _tree_findings()
            if f.rule == rule and not f.suppressed]
    assert not live, "unsuppressed findings:\n" + "\n".join(
        f.render() for f in live)


def test_every_suppression_has_a_reason():
    # acceptance criterion: every `# lfkt: noqa[...]` carries a reason.
    # LINT000 covers this, but assert it explicitly so the criterion has a
    # named test.
    sup = [f for f in _tree_findings() if f.suppressed]
    assert sup, "expected at least one audited suppression in the tree"
    for f in sup:
        assert f.reason and f.reason.strip(), f.render()


# ---------------------------------------------------------------------------
# layer 2: fixture self-tests — every rule fires where planted
# ---------------------------------------------------------------------------

_fix_findings_cache: list | None = None


def _fix_findings():
    global _fix_findings_cache
    if _fix_findings_cache is None:
        _fix_findings_cache = run_lint(
            package_dir=os.path.join(FIXTURES, "fixpkg"), repo_root=FIXTURES)
    return _fix_findings_cache


def _fired(rule, path_part, suppressed=False):
    return [f for f in _fix_findings()
            if f.rule == rule and path_part in f.path
            and f.suppressed == suppressed]


@pytest.mark.parametrize("rule,path_part,min_hits", [
    ("LOCK001", "lockbad.py", 2),   # bad_write + entry-path write
    ("LOCK002", "lockbad.py", 2),   # undeclared entry write + off-thread
    ("LOCK003", "lockbad.py", 1),   # holds-marked call without the lock
    ("LOCK004", "lockbad.py", 2),   # unknown lock + unknown entry method
    ("JIT001", "jitbad.py", 4),     # time, env, np.random, print
    ("JIT002", "jitbad.py", 1),     # global in reachable helper
    ("JIT003", "jitbad.py", 2),     # block_until_ready + .item()
    ("CFG001", "cfgbad.py", 3),     # get, getenv, subscript
    ("CFG005", "cfgbad.py", 1),     # unregistered accessor name
    ("CFG002", "utils/config.py", 1),   # undocumented registered knob
    ("CFG003", "", 2),              # helm typo'd knob + unplumbed serving
    ("CFG004", "helm/deployment.yaml", 1),  # phantom probe path
    ("OBS001", "obsbad.py", 2),     # typo'd inc + phantom observe
    ("OBS002", "obs/catalog.py", 1),    # undocumented cataloged metric
    ("OBS003", "obsbad.py", 1),     # phantom memledger component
    ("KER001", "kernbad.py", 1),    # pallas_call without interpret=
    ("KER002", "kernbad.py", 1),    # no probe, no fallback
    ("KER002", "loopbad.py", 1),    # unprobed layer-looped decode variant
    ("KER003", "kernbad.py", 1),    # call inside a block shape
    ("PERF001", "perfbad.py", 3),   # decorator + jit-call + pallas_call forms
    ("PERF002", "obs/slo.py", 1),   # SLO over a phantom metric family
    ("RES001", "resbad.py", 3),     # raise-path + early-return + PR-6 shape
    ("RES002", "resbad.py", 1),     # lock.acquire without guaranteed release
    ("RES003", "resbad.py", 1),     # use-after-release
    ("DON001", "donbad.py", 1),     # read of donated attr after dispatch
    ("DON002", "donbad.py", 2),     # stale alias read + stash-on-self exit
    ("EXC001", "excbad.py", 2),     # swallowing handler + ghost annotation
    ("DEAD001", "deadbad.py", 1),   # totally_unused
    ("DEAD002", "deadbad.py", 1),   # phantom __all__ export
    ("LOCK005", "lockorderbad.py", 3),  # in-class cycle + re-acquire +
                                        # interprocedural 2-cycle
    ("LOCK006", "blockunderbad.py", 5),  # direct sleep + helper chain +
                                         # PR-10 scan (inline AND via a
                                         # helper) + unknown-lock site
    ("ASY001", "asyncbad.py", 2),   # PR-10 incident read + direct sleep
    ("ASY002", "asyncbad.py", 1),   # awaited coroutine blocks
    ("LINT000", "noqabad.py", 1),   # noqa without reason
    ("LINT000", "resbad.py", 1),    # transfers[] without reason
    ("LINT000", "blockunderbad.py", 1),  # blocks-under[] without reason
    ("LINT001", "noqabad.py", 2),   # unknown rule id + empty rule list
    ("LINT001", "blockunderbad.py", 1),  # blocks-under unknown lock
    ("TAINT001", "taintbad.py", 3),  # addr sink + CR/LF f-string + two-hop
    ("TAINT002", "taintbad.py", 3),  # path sink + argv + ModelSpec.path
    ("TAINT003", "taintbad.py", 3),  # frame log + peer-http log +
                                     # unknown-tag audit doesn't discharge
    ("WIRE001", "wirebad.py", 3),    # literal + frame-ctor key + hdr.get
    ("WIRE002", "wirebad.py", 1),    # BadProxy: the strip-removed twin
    ("WIRE003", "serving/wiresurface.py", 1),  # no fixture docs table
    ("LINT000", "taintbad.py", 1),   # sanitizes[] without reason
    ("LINT001", "taintbad.py", 1),   # sanitizes[] unknown source tag
])
def test_rule_fires_on_fixture(rule, path_part, min_hits):
    hits = _fired(rule, path_part)
    assert len(hits) >= min_hits, (
        f"{rule} fired {len(hits)}x in {path_part or 'tree'}, "
        f"expected >= {min_hits}:\n"
        + "\n".join(f.render() for f in _fix_findings() if f.rule == rule))


def test_fixture_contract_conforming_kernel_is_clean():
    assert not [f for f in _fix_findings()
                if "kerngood.py" in f.path and f.rule.startswith("KER")]


def test_host_only_code_not_flagged_by_jit_rules():
    # jitbad.host_only commits the same sins as the traced path; it must
    # produce zero JIT findings (reachability, not grep)
    jit_lines = [f for f in _fix_findings() if f.rule.startswith("JIT")]
    host_span = range(29, 34)   # host_only's body in jitbad.py
    assert not [f for f in jit_lines if f.line in host_span], jit_lines


@pytest.mark.parametrize("rule,path_part", [
    ("LOCK001", "lockbad.py"),      # suppressed_write
    ("CFG001", "cfgbad.py"),        # suppressed_read
    ("JIT001", "jitbad.py"),        # def-line noqa covers the body
    ("OBS001", "obsbad.py"),        # audited_total suppression
    ("OBS003", "obsbad.py"),        # audited_component suppression
    ("PERF001", "perfbad.py"),      # suppressed_builder's audited noqa
    ("RES001", "resbad.py"),        # suppressed_leak's audited noqa
    ("DON001", "donbad.py"),        # suppressed_read's audited noqa
    ("DEAD001", "deadbad.py"),      # registry_hook getattr exemption
    ("TAINT003", "taintbad.py"),    # suppressed_log's audited noqa
])
def test_noqa_suppresses(rule, path_part):
    sup = _fired(rule, path_part, suppressed=True)
    assert sup, f"expected a suppressed {rule} finding in {path_part}"
    for f in sup:
        assert f.reason and f.reason.strip(), f.render()


def _fixture_line(fname: str, marker: str) -> int:
    src = open(os.path.join(FIXTURES, "fixpkg", fname)).read()
    return next(i for i, ln in enumerate(src.splitlines(), 1) if marker in ln)


def test_pr6_leak_shape_caught_and_hardened_twin_clean():
    """ISSUE 8 acceptance: disabling a PR-6 hardening fix (the
    `finally: unpin`) makes RES001 fire — demonstrated on the fixture twin
    pair, while the hardened shape stays clean."""
    res1 = {f.line for f in _fired("RES001", "resbad.py")}
    broken = _fixture_line("resbad.py", "RES001: PR-6 leak shape")
    hardened = _fixture_line("resbad.py", "fine: finally releases")
    assert broken in res1, "the unpin-removed twin must fire RES001"
    assert hardened not in res1, "the try/finally twin must stay clean"


def test_use_after_donate_shape_caught():
    """ISSUE 8 acceptance: a read of the donated cache after dispatch is
    caught (DON001), while the engines' rebind idioms stay clean."""
    don1 = {f.line for f in _fired("DON001", "donbad.py")}
    assert _fixture_line("donbad.py", "DON001: use-after-donate") in don1
    res_all = [f for f in _fix_findings()
               if f.rule.startswith("DON") and "donbad.py" in f.path
               and not f.suppressed]
    clean_lines = {_fixture_line("donbad.py", m) for m in
                   ("fine: rebound", "fine: donate-and-rebind")}
    assert not {f.line for f in res_all} & clean_lines


def test_res_clean_shapes_not_flagged():
    """The sanctioned idioms — with-block, conditional acquire +
    try/finally, self-store handoff, tuple-return handoff, None-guard,
    transfers annotation — must produce no RES findings."""
    res = [f for f in _fix_findings()
           if f.rule.startswith("RES") and "resbad.py" in f.path
           and not f.suppressed]
    lines = {f.line for f in res}
    for marker in ("fine: conditional acquire", "fine: with manages it",
                   "fine: stored on self", "fine: returned in a tuple",
                   "fine: None branch exits", "fine: with closes it",
                   "fine: not released on EVERY path",
                   "lfkt: transfers[lease]"):
        ln = _fixture_line("resbad.py", marker)
        span = set(range(ln - 2, ln + 3))   # the acquire sits near the marker
        assert not lines & span, (marker, sorted(lines))


def test_exc001_good_shapes_not_flagged():
    exc = [f for f in _fix_findings() if f.rule == "EXC001"
           and not f.suppressed]
    lines = {f.line for f in exc}
    for marker in ("fine: every swallowing path",
                   "fine: the failure is not swallowed"):
        ln = _fixture_line("excbad.py", marker)
        assert not lines & set(range(ln - 6, ln + 2)), (marker, lines)


def test_good_lock_paths_not_flagged():
    # with-block, acquire/release region, and holds-marker paths in the
    # fixture must produce no LOCK001
    lock1 = {f.line for f in _fired("LOCK001", "lockbad.py")}
    lock1 |= {f.line for f in _fired("LOCK001", "lockbad.py",
                                     suppressed=True)}
    src = open(os.path.join(FIXTURES, "fixpkg", "lockbad.py")).read()
    for marker in ("# guarded: fine", "# fine: acquire region",
                   "# fine: holds marker"):
        line = next(i for i, ln in enumerate(src.splitlines(), 1)
                    if marker in ln)
        assert line not in lock1, f"false positive on line {line} ({marker})"


def test_pr10_regression_fixtures_fire():
    """ISSUE 15 acceptance: the two PR-10 hand-fixed bugs, re-created as
    fixture twins, are machine-caught — re-inlining the KVPool
    fragmentation scan under the pool lock fires LOCK006; moving the
    incident read back onto the event loop fires ASY001."""
    scan = _fixture_line("blockunderbad.py",
                         "PR-10 regression — fragmentation scan")
    assert scan in {f.line for f in _fired("LOCK006", "blockunderbad.py")}
    read = _fixture_line("asyncbad.py", "PR-10 regression — incident read")
    assert read in {f.line for f in _fired("ASY001", "asyncbad.py")}


def test_lock005_reports_both_witness_paths():
    """A cross-class cycle report must carry a witness call path for
    EVERY leg — an operator reads the two paths, picks the global order,
    and fixes one of them (docs/LINT.md 'Reading a lock-order cycle
    report')."""
    cyc = [f for f in _fired("LOCK005", "lockorderbad.py")
           if "CrossB" in f.message and "cycle over 2 locks" in f.message]
    assert cyc, [f.render() for f in _fired("LOCK005", "lockorderbad.py")]
    msg = cyc[0].message
    assert "hold_and_cross" in msg and "grab_then_call" in msg
    assert msg.count("->") >= 2     # one held->acquired arrow per leg


def test_concurrency_clean_twins_silent():
    """The sanctioned idioms — copy-then-release scan, the def-line
    blocks-under audit, the asyncio.to_thread hop (and awaiting through
    it), consistent lock order — must produce no LOCK005/006/ASY
    findings."""
    conc = [f for f in _fix_findings()
            if f.rule in ("LOCK005", "LOCK006", "ASY001", "ASY002")]
    for fname, marker in (
            ("blockunderbad.py", "fine: scan off the lock"),
            ("blockunderbad.py", "fine: discharged by the def-line audit"),
            ("asyncbad.py", "fine: the to_thread hop"),
            ("asyncbad.py", "fine: the awaited coroutine never blocks"),
            ("lockorderbad.py", "fine: consistent order"),
    ):
        ln = _fixture_line(fname, marker)
        near = [f for f in conc if fname in f.path
                and abs(f.line - ln) <= 1]
        assert not near, (marker, [f.render() for f in near])


def test_taint_clean_twins_silent():
    """The sanctioned declassifications — allowlist guard, realpath
    containment guard, the registered sanitizer, the def-line
    `sanitizes[...]` validator, the line-level audit — must produce no
    unsuppressed TAINT findings on their fixture twins."""
    taint = [f for f in _fix_findings()
             if f.rule.startswith("TAINT") and "taintbad.py" in f.path
             and not f.suppressed]
    lines = {f.line for f in taint}
    for marker in ("fine: allowlist guard", "fine: containment guard",
                   "fine: sanitized upstream", "fine: validator output",
                   "fixture: line-level audit"):
        ln = _fixture_line("taintbad.py", marker)
        span = set(range(ln - 1, ln + 4))   # the sink sits at/below it
        assert not lines & span, (marker, [f.render() for f in taint])


def test_wire_strip_twin_clean():
    """GoodProxy (the ingress WITH the strip, via the module-level alias
    and a loop-anchored membership test) must pass the WIRE002
    must-analysis; BadProxy is asserted to fire in the parametrized
    table."""
    wire2 = [f for f in _fix_findings() if f.rule == "WIRE002"]
    good_span = range(_fixture_line("serving/wirebad.py",
                                    "class GoodProxy"),
                      _fixture_line("serving/wirebad.py",
                                    "class BadProxy"))
    assert not [f for f in wire2 if f.line in good_span], (
        [f.render() for f in wire2])


def _copy_pkg(tmp_path):
    import shutil

    pkg = tmp_path / "llama_fastapi_k8s_gpu_tpu"
    shutil.copytree(os.path.join(REPO, "llama_fastapi_k8s_gpu_tpu"),
                    pkg, ignore=shutil.ignore_patterns("__pycache__"))
    return pkg


def test_pr17_strip_removal_fires_wire002(tmp_path):
    """ISSUE 18 acceptance pin: deleting the fleet router's inbound
    stamp strip (the PR-17 hand-fix) must fire WIRE002 on the real
    router — the declared ingress can then forward a client's forged
    x-lfkt-affinity-key / x-lfkt-prior-owner upstream."""
    pkg = _copy_pkg(tmp_path)
    router = pkg / "serving" / "fleet" / "router.py"
    src = router.read_text()
    strip = ('_HOP_HEADERS + (b"content-length", b"host",\n'
             '                                        '
             'b"traceparent",\n'
             '                                        '
             'AFFINITY_KEY_HEADER.encode(),\n'
             '                                        '
             'PRIOR_OWNER_HEADER.encode())')
    assert strip in src, "router strip shape moved; update this pin"
    router.write_text(src.replace(
        strip, '_HOP_HEADERS + (b"content-length", b"host",\n'
               '                                        '
               'b"traceparent")'))
    findings = run_lint(package_dir=str(pkg), rules={"WIRE002"})
    hits = [f for f in findings
            if f.rule == "WIRE002" and "router.py" in f.path
            and not f.suppressed]
    assert len(hits) >= 2, [f.render() for f in findings]  # both stamps
    # and the unedited tree is clean (asserted via the cached full run)
    assert not [f for f in _tree_findings()
                if f.rule == "WIRE002" and not f.suppressed]


def test_manifest_containment_removal_fires_taint002(tmp_path):
    """ISSUE 18 acceptance pin: disabling ModelSpec.resolved_path's
    realpath containment guard must fire TAINT002 — a POSTed manifest
    path could then escape LFKT_MODEL_DIR."""
    pkg = _copy_pkg(tmp_path)
    manifest = pkg / "serving" / "manifest.py"
    src = manifest.read_text()
    guard = "if real != base and not real.startswith(base + os.sep):"
    assert guard in src, "containment guard moved; update this pin"
    manifest.write_text(src.replace(guard, "if False:"))
    findings = run_lint(package_dir=str(pkg), rules={"TAINT002"})
    hits = [f for f in findings
            if f.rule == "TAINT002" and "manifest.py" in f.path
            and not f.suppressed]
    assert hits, [f.render() for f in findings]
    assert not [f for f in _tree_findings()
                if f.rule == "TAINT002" and not f.suppressed]


def test_changed_mode_equals_full_run(tmp_path):
    """Satellite (ISSUE 15): ``--changed`` must produce the IDENTICAL
    finding set to a full run — on a cold cache, on a warm no-op cache
    (everything reused), and after a single-file edit (only that file
    re-derived, cross-file findings still correct)."""
    import json
    import shutil

    work = tmp_path / "lint_fixtures"
    shutil.copytree(FIXTURES, work)
    args = ["--json", "--package", str(work / "fixpkg"),
            "--root", str(work)]

    def run(*extra):
        proc = subprocess.run(
            [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.lint",
             *extra, *args], cwd=REPO, capture_output=True, text=True,
            timeout=300)
        rows = sorted((d["rule"], d["path"], d["line"], d["message"])
                      for d in map(json.loads, proc.stdout.splitlines()))
        return rows, proc.stderr

    full, _ = run()
    cold, _ = run("--changed")                   # no cache yet
    assert cold == full
    cache = work / ".lfkt_lint_cache.json"
    assert cache.exists()
    warm, err = run("--changed")                 # everything reusable
    assert warm == full
    n = int(err.rsplit("reused cached summaries for", 1)[1].split()[0])
    assert n > 0, err

    # edit ONE file's body (symbols unchanged, so the resolution digest
    # holds and every other file's summaries come from the cache), then
    # --changed must match a fresh full run including the NEW finding
    p = work / "fixpkg" / "blockunderbad.py"
    src = p.read_text()
    assert "time.sleep(0.1)         # LOCK006: direct sleep" in src
    p.write_text(src.replace(
        "            time.sleep(0.1)         # LOCK006: direct sleep",
        "            time.sleep(0.1)\n"
        "            time.sleep(0.1)         # LOCK006: direct sleep"))
    full2, _ = run()
    inc2, err2 = run("--changed")
    assert inc2 == full2
    assert inc2 != full                          # the edit IS visible
    n2 = int(err2.rsplit("reused cached summaries for", 1)[1].split()[0])
    assert n2 > 0, err2


def test_resolution_digest_covers_module_instance_bindings():
    """Rebinding a module-level instance (`FAULTS = FaultInjector()` ->
    some other class) changes how UNCHANGED files' calls resolve, so it
    must invalidate the --changed summary cache: module_types is part of
    the resolution digest."""
    from llama_fastapi_k8s_gpu_tpu.lint.callgraph import build_graph
    from llama_fastapi_k8s_gpu_tpu.lint.concurrency import resolution_digest
    from llama_fastapi_k8s_gpu_tpu.lint.core import Context

    ctx = Context(os.path.join(FIXTURES, "fixpkg"), FIXTURES)
    graph = build_graph(ctx)
    before = resolution_digest(graph)
    graph.module_types.setdefault("blockunderbad", {})["PHANTOM"] = (
        "blockunderbad", "BlockUnder")
    assert resolution_digest(graph) != before


def test_lint_runtime_budget():
    """Satellite (ISSUE 15): the full-package lint pass — the
    interprocedural concurrency families included — must finish under a
    fixed wall bound on CPU, so whole-package analysis can never quietly
    make the tier-1 suite unusable.  The bound is ~7x the current cost;
    tighten it if the suite ever gets a faster floor.  Timed on the
    shared full-tree pass (the one the layer-1 tests consume) rather
    than a second derivation — same pass, same machine, half the
    suite cost."""
    _tree_findings()
    assert _tree_findings_seconds is not None
    assert _tree_findings_seconds < 60.0, \
        f"full lint pass took {_tree_findings_seconds:.1f}s (budget 60s)"


def test_concurrency_baseline_ratchet_is_empty_and_green():
    """The committed concurrency baseline is EMPTY (every surviving
    in-tree audit is reason-annotated instead of grandfathered), and the
    ci_gate lint-concurrency check passes against it — i.e. the ratchet
    currently enforces 'no unaudited concurrency finding lands at all'."""
    import json

    doc = json.load(open(os.path.join(REPO,
                                      "lint_baseline_concurrency.json")))
    assert doc["schema"] == 1 and doc["findings"] == []
    proc = subprocess.run(
        [sys.executable, "tools/lint_report.py",
         "--baseline", "lint_baseline_concurrency.json",
         "--rules", "LOCK005", "LOCK006", "ASY001", "ASY002"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ratchet OK" in proc.stdout


def test_taint_baseline_ratchet_is_empty_and_green():
    """The committed trust-boundary baseline is EMPTY (every in-tree
    flow is sanitized, guard-declassified, or reason-audited — nothing
    grandfathered), and the ci_gate lint-taint check passes against it."""
    import json

    doc = json.load(open(os.path.join(REPO, "lint_baseline_taint.json")))
    assert doc["schema"] == 1 and doc["findings"] == []
    proc = subprocess.run(
        [sys.executable, "tools/lint_report.py",
         "--baseline", "lint_baseline_taint.json",
         "--rules", "TAINT001", "TAINT002", "TAINT003",
         "WIRE001", "WIRE002", "WIRE003"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ratchet OK" in proc.stdout


def test_wiresurface_docs_pinned_to_runtime_table():
    """docs/WIRESURFACE.md's generated block is byte-identical to the
    runtime markdown_table() — closing the loop WIRE003 leaves open
    (WIRE003 compares the docs against lint/wire.py's STATIC re-render;
    this pins static == runtime == docs)."""
    from llama_fastapi_k8s_gpu_tpu.serving.wiresurface import (
        internal_stamped_headers, markdown_table)

    assert internal_stamped_headers() == (
        "x-lfkt-affinity-key", "x-lfkt-prior-owner")
    begin = "<!-- wire-surface:begin (generated - do not hand-edit) -->"
    end = "<!-- wire-surface:end -->"
    text = open(os.path.join(REPO, "docs", "WIRESURFACE.md")).read()
    lo = text.index(begin) + len(begin)
    hi = text.index(end)
    assert text[lo:hi].strip("\n") == markdown_table()


# ---------------------------------------------------------------------------
# layer 3: registry runtime enforcement + helm/docs cross-checks
# ---------------------------------------------------------------------------

def test_knob_accessors_enforce_registration(monkeypatch):
    from llama_fastapi_k8s_gpu_tpu.utils.config import env_bool, knob

    with pytest.raises(KeyError):
        knob("LFKT_NOT_A_KNOB")
    with pytest.raises(KeyError):
        env_bool("LFKT_NOT_A_KNOB")
    # non-LFKT names stay unrestricted for env_bool (generic helper)
    assert env_bool("SOME_OTHER_VAR", default=True) is True
    monkeypatch.setenv("LFKT_HBM_GBPS", "512.5")
    assert knob("LFKT_HBM_GBPS") == 512.5
    monkeypatch.delenv("LFKT_HBM_GBPS")
    assert knob("LFKT_HBM_GBPS") == 819.0


def test_registry_settings_mapping_total():
    """Every Settings field is driven by exactly one registered knob and
    every Settings-backed knob maps to a real field (get_settings cannot
    silently drop a knob again)."""
    import dataclasses

    from llama_fastapi_k8s_gpu_tpu.utils.config import KNOBS, Settings

    fields = {f.name for f in dataclasses.fields(Settings)}
    mapped = {k.field for k in KNOBS.values() if k.field is not None}
    assert mapped == fields
    for name, k in KNOBS.items():
        assert name == "LFKT_" + (k.field or name[5:].lower()).upper()


def test_helm_env_names_are_registered():
    """Satellite cross-check, asserted directly: every LFKT_* in the real
    chart exists in the registry (modulo the bench-only allowlist)."""
    from llama_fastapi_k8s_gpu_tpu.lint.configreg import TEST_ONLY_PREFIXES
    from llama_fastapi_k8s_gpu_tpu.utils.config import KNOBS

    names = set()
    for dirpath, _, files in os.walk(os.path.join(REPO, "helm")):
        for fname in files:
            if fname.endswith((".yaml", ".yml", ".tpl")):
                with open(os.path.join(dirpath, fname)) as f:
                    names |= set(re.findall(r"LFKT_[A-Z0-9_]+", f.read()))
    assert names, "expected LFKT_* references in helm/"
    unknown = {n for n in names - set(KNOBS)
               if not n.startswith(TEST_ONLY_PREFIXES)}
    assert not unknown, f"helm references unregistered knobs: {unknown}"


def test_helm_probe_paths_are_registered_routes():
    """Satellite cross-check: /health/ready + /health/live in the chart
    must be actual decorated routes in server/app.py."""
    app_src = open(os.path.join(
        REPO, "llama_fastapi_k8s_gpu_tpu", "server", "app.py")).read()
    routes = set(re.findall(r"@app\.(?:get|post)\(\"([^\"]+)\"\)", app_src))
    dep = open(os.path.join(
        REPO, "helm", "templates", "deployment.yaml")).read()
    probes = set(re.findall(r"^\s*path:\s*(/[^\s{]+)\s*$", dep, re.M))
    assert {"/health/ready", "/health/live"} <= probes
    missing = probes - routes
    assert not missing, f"helm probes at unregistered routes: {missing}"


def test_registered_knobs_documented_in_config_md():
    from llama_fastapi_k8s_gpu_tpu.utils.config import KNOBS

    doc = open(os.path.join(REPO, "docs", "CONFIG.md")).read()
    missing = [n for n in KNOBS if n not in doc]
    assert not missing, f"docs/CONFIG.md missing knobs: {missing}"


# ---------------------------------------------------------------------------
# the CLI (the CI entrypoint) — exit codes and machine output
# ---------------------------------------------------------------------------

def test_cli_exits_zero_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.lint"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_exits_nonzero_on_fixtures_with_json():
    import json

    proc = subprocess.run(
        [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.lint", "--json",
         "--package", os.path.join(FIXTURES, "fixpkg"),
         "--root", FIXTURES],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    findings = [json.loads(line) for line in proc.stdout.splitlines()]
    assert findings and all("rule" in f and "line" in f for f in findings)


def test_lint_report_baseline_ratchet(tmp_path):
    """--write-baseline snapshots the fixture findings; --baseline then
    exits 0 with all of them grandfathered, and exits 1 once the baseline
    is missing one (a 'new' finding for the ratchet)."""
    import json

    bl = str(tmp_path / "baseline.json")
    fix_args = ["--package", os.path.join(FIXTURES, "fixpkg"),
                "--root", FIXTURES]
    wrote = subprocess.run(
        [sys.executable, "tools/lint_report.py", "--write-baseline", bl,
         *fix_args], cwd=REPO, capture_output=True, text=True, timeout=120)
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    doc = json.load(open(bl))
    assert doc["schema"] == 1 and doc["findings"]
    assert all("line" not in e for e in doc["findings"])   # line-agnostic

    ok = subprocess.run(
        [sys.executable, "tools/lint_report.py", "--baseline", bl,
         *fix_args], cwd=REPO, capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "grandfathered" in ok.stdout and "ratchet OK" in ok.stdout

    # drop one grandfathered entry -> that finding is now NEW -> exit 1
    dropped = doc["findings"][0]
    doc["findings"] = doc["findings"][1:]
    json.dump(doc, open(bl, "w"))
    bad = subprocess.run(
        [sys.executable, "tools/lint_report.py", "--baseline", bl,
         *fix_args], cwd=REPO, capture_output=True, text=True, timeout=120)
    assert bad.returncode == 1
    assert "NEW findings" in bad.stdout
    assert dropped["rule"] in bad.stdout


def test_ci_gate_aggregates_lint_and_manifest():
    """tools/ci_gate.py (POST_SUITE_CHECKLIST step 1): one entry point,
    both repo gates, --json machine shape, exit 0 on a clean tree.

    The three pytest-subset checks are --skip'd here: they re-spawn
    tests (decode_loop serial_parity, fleet route_parity, chaos smoke)
    that THIS tier-1 session already ran first-class, and the duplicate
    subprocess runs cost ~35s of suite wall for zero added coverage.
    Their argv targets are asserted below so the check definitions
    cannot rot; standalone `python tools/ci_gate.py` still runs them.
    lfkt-lint and the two ratchets are --skip'd for the same reason:
    the identical commands are test_cli_exits_zero_on_tree and the two
    *_baseline_ratchet_is_empty_and_green tests, a few tests up."""
    import json

    pytest_checks = {"decode-loop-parity", "fleet-route-parity",
                     "chaos-drill", "fleet-trace-continuity"}
    dup_checks = {"lfkt-lint", "lint-concurrency", "lint-taint"}
    proc = subprocess.run(
        [sys.executable, "tools/ci_gate.py", "--json",
         "--skip", ",".join(sorted(pytest_checks | dup_checks))],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    names = {c["name"] for c in doc["checks"]}
    assert names == {"lfkt-lint", "lint-concurrency", "lint-taint",
                     "check-manifest", "incident-schema",
                     "disagg-wire-schema", "decode-loop-parity",
                     "fleet-route-parity", "chaos-drill",
                     "fleet-trace-continuity"}
    assert all(c["exit"] == 0 for c in doc["checks"])
    assert {c["name"] for c in doc["checks"]
            if c.get("skipped")} == pytest_checks | dup_checks
    # the skipped checks' test files + -k markers must not rot: the file
    # exists and the marker matches a test name in it (the substance of
    # each check runs natively in this very tier-1 session)
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_ci_gate", os.path.join(REPO, "tools", "ci_gate.py"))
    ci_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ci_gate)
    for name, argv in ci_gate.CHECKS:
        if name in pytest_checks:
            test_file = next(a for a in argv if a.endswith(".py"))
            marker = argv[argv.index("-k") + 1]
            assert os.path.exists(test_file), f"{name}: {test_file}"
            src = open(test_file, encoding="utf-8").read()
            assert re.search(rf"def test_\w*{re.escape(marker)}", src), \
                f"{name}: -k {marker!r} matches no test in {test_file}"


def test_cli_lists_every_rule():
    proc = subprocess.run(
        [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.lint",
         "--list-rules"], cwd=REPO, capture_output=True, text=True,
        timeout=60)
    assert proc.returncode == 0
    for rule in all_rules():
        assert rule in proc.stdout

"""lfkt-obs tier-1 gates (ISSUE 4): tracing, metrics, structured logging.

Four layers:

1. **Metrics registry** — legal Prometheus exposition (HELP + one TYPE
   per family, cumulative ``_bucket{le=...}`` histograms, derived
   p50/p95/p99), labeled series, and the runtime catalog enforcement
   (unregistered/mis-typed names raise).
2. **Tracer unit behavior** — deterministic sampling, ring eviction
   bounds, W3C ``traceparent`` ingest, idempotent finish, global-event
   fan-in, and the zero-cost guarantee for sampled-out requests.
3. **Engine span trees** — every engine flavor (serial, mesh-batched,
   continuous, sequence-parallel) produces a complete, monotonic,
   nested span tree; concurrent load against a real
   :class:`ContinuousEngine` through the real server yields one complete
   tree per sampled request.
4. **Server surface** — /debug endpoints, response headers, request-id
   stamped JSON access logs, and the generated docs table staying in
   sync with the catalog.
"""

from __future__ import annotations

import asyncio
import importlib.util
import io
import json
import logging
import os
import re

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import ContinuousEngine, Engine, FakeEngine
from llama_fastapi_k8s_gpu_tpu.obs.catalog import METRICS, markdown_table
from llama_fastapi_k8s_gpu_tpu.obs.logctx import (
    JsonFormatter,
    bind_request_id,
    current_request_id,
    setup_json_logging,
)
from llama_fastapi_k8s_gpu_tpu.obs.trace import Tracer, parse_traceparent
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.config import Settings
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MSGS = [{"role": "user", "content": "Say something."}]
BODY = {
    "bot_profile": {"name": "Alice.f",
                    "appearance": "tall,slim,blonde,cats,rain"},
    "user_profile": {"name": "Bob"},
    "context": [{"turn": "user", "message": "hi"}],
}
#: the tiny byte-level test tokenizer spends ~1 token per character, so
#: the real-model tests need a short explicit system prompt to fit the
#: tiny model's 128-token context (the default persona is ~430 chars)
TINY_BODY = {**BODY, "bot_profile": {**BODY["bot_profile"],
                                     "system_prompt": "Be brief."}}

EPS = 0.05   # span timestamp slack (clock reads happen around the work)


# ---------------------------------------------------------------------------
# layer 1: the metrics registry
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? (-?[0-9]+(\.[0-9e+-]+)?)$")


def validate_exposition(text: str) -> dict:
    """Assert ``text`` is legal Prometheus exposition; returns
    family -> type.  A real scraper's constraints: HELP/TYPE once per
    family, every sample attributable to a typed family, no stray
    ``_min/_max/_avg`` pseudo-series."""
    types: dict[str, str] = {}
    helps: set[str] = set()
    for ln in text.rstrip("\n").splitlines():
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps.add(name)
        elif ln.startswith("# TYPE "):
            _, _, name, mtype = ln.split()
            assert mtype in ("counter", "gauge", "histogram"), ln
            assert name not in types, f"duplicate TYPE for {name}"
            assert name in helps, f"TYPE before HELP for {name}"
            types[name] = mtype
        else:
            m = _SAMPLE_RE.match(ln)
            assert m, f"illegal sample line: {ln!r}"
            base = m.group(1)
            fam = base
            for suffix in ("_bucket", "_sum", "_count"):
                stem = base[: -len(suffix)] if base.endswith(suffix) else None
                if stem and types.get(stem) == "histogram":
                    fam = stem
            assert fam in types, f"sample {ln!r} has no TYPE"
    for name in types:
        assert not name.endswith(("_min", "_max", "_avg")), (
            f"summary-hack pseudo-series {name} survived")
    return types


def test_render_is_legal_exposition_with_histograms():
    m = Metrics()
    m.inc("requests_rejected_total")
    m.inc("http_requests_total", route="/response", code="200")
    m.set_gauge("queue_depth", 3)
    for v in (0.004, 0.03, 0.03, 0.2, 0.2, 0.2, 0.7, 3.0, 100.0):
        m.observe("queue_wait_seconds", v)
    text = m.render()
    types = validate_exposition(text)
    assert types["queue_wait_seconds"] == "histogram"
    assert types["queue_depth"] == "gauge"
    # cumulative buckets ending at le="+Inf" == count
    buckets = re.findall(
        r'queue_wait_seconds_bucket\{le="([^"]+)"\} (\d+)', text)
    counts = [int(c) for _, c in buckets]
    assert buckets[-1][0] == "+Inf"
    assert counts == sorted(counts), "buckets must be cumulative"
    assert counts[-1] == 9
    assert "queue_wait_seconds_count 9" in text
    # derived quantiles present, typed as their own gauge families
    assert types["queue_wait_seconds_p50"] == "gauge"
    assert types["queue_wait_seconds_p95"] == "gauge"
    assert types["queue_wait_seconds_p99"] == "gauge"


def test_labeled_series_render_and_quantiles_bracket_observations():
    m = Metrics()
    m.observe("request_seconds", 0.08, route="/response")
    m.observe("request_seconds", 0.08, route="/response")
    m.observe("request_seconds", 22.0, route="/response")
    m.observe("request_seconds", 0.001, route="/health")
    text = m.render()
    assert 'request_seconds_bucket{route="/response",le="0.1"} 2' in text
    assert 'request_seconds_count{route="/response"} 3' in text
    assert 'request_seconds_count{route="/health"} 1' in text
    p50 = float(re.search(
        r'request_seconds_p50\{route="/response"\} ([0-9.]+)', text).group(1))
    p99 = float(re.search(
        r'request_seconds_p99\{route="/response"\} ([0-9.]+)', text).group(1))
    assert 0.05 <= p50 <= 0.1       # inside the 0.08 observation's bucket
    assert 10.0 <= p99 <= 25.0      # inside the 22 s observation's bucket


def test_runtime_catalog_enforcement():
    m = Metrics()
    with pytest.raises(KeyError, match="not in the catalog"):
        m.inc("request_rejected_total")          # typo'd (singular)
    with pytest.raises(KeyError, match="is a counter"):
        m.set_gauge("requests_rejected_total", 1)
    with pytest.raises(KeyError, match="takes labels"):
        m.inc("http_requests_total")             # labels missing
    with pytest.raises(KeyError, match="takes labels"):
        m.observe("queue_wait_seconds", 0.1, route="/x")   # stray label
    # declared prefix family admits runtime-synthesized names
    m.set_gauge("scheduler_lanes_live", 2)
    m.set_gauge("scheduler_spec_drafted", 5)
    assert "scheduler_lanes_live 2" in m.render()


def test_quantile_uses_target_buckets_own_lower_bound():
    """Empty lower buckets must not drag the interpolation floor to 0:
    5 observations all inside (1.0, 2.5] give histogram_quantile p50 of
    exactly 1.75 (code-review regression)."""
    m = Metrics()
    for v in (1.5, 1.8, 2.0, 2.2, 2.4):
        m.observe("queue_wait_seconds", v)
    text = m.render()
    p50 = float(re.search(r"queue_wait_seconds_p50 ([0-9.]+)",
                          text).group(1))
    assert p50 == pytest.approx(1.75)
    assert p50 >= 1.5        # never below the smallest observation's bucket


def test_every_catalog_histogram_declares_buckets():
    for metric in METRICS.values():
        if metric.mtype == "histogram":
            assert metric.buckets, metric.name
            assert list(metric.buckets) == sorted(metric.buckets)


# ---------------------------------------------------------------------------
# layer 2: tracer unit behavior
# ---------------------------------------------------------------------------

def test_sampling_zero_is_disarmed_and_lock_free():
    t = Tracer(sample=0.0, ring=8)
    t._lock = None          # any lock use would AttributeError
    assert t.start() is None
    t.annotate_inflight("watchdog_trip", reason="x")   # no-op, no lock
    t.finish(None)          # None-tolerant


def test_sampling_is_deterministic_by_counter():
    t = Tracer(sample=0.25, ring=64)
    drawn = [t.start() is not None for _ in range(16)]
    assert sum(drawn) == 4                      # exactly every 4th
    assert drawn == [False, False, False, True] * 4


def test_ring_eviction_bounds():
    t = Tracer(sample=1.0, ring=4)
    ids = []
    for _ in range(10):
        tr = t.start()
        ids.append(tr.trace_id)
        t.finish(tr)
    assert t.stats()["ring_used"] == 4
    kept = [s["trace_id"] for s in t.traces()]
    assert kept == list(reversed(ids[-4:]))     # newest first, oldest evicted
    assert t.get(ids[0]) is None                # evicted
    assert t.get(ids[-1]) is not None


def test_traceparent_ingest_and_propagation():
    tp = "00-" + "ab" * 16 + "-" + "12" * 8 + "-01"
    assert parse_traceparent(tp) == ("ab" * 16, "12" * 8)
    for bad in (None, "", "garbage", "01-" + "ab" * 16 + "-" + "12" * 8
                + "-01", "00-" + "0" * 32 + "-" + "12" * 8 + "-01"):
        assert parse_traceparent(bad) is None
    t = Tracer(sample=1.0, ring=4)
    tr = t.start(traceparent=tp)
    assert tr.trace_id == "ab" * 16
    assert tr.parent_span_id == "12" * 8
    out = tr.traceparent()
    assert out.startswith("00-" + "ab" * 16 + "-")
    assert out.split("-")[2] == tr.root.span_id
    # a fresh trace mints valid ids
    tr2 = t.start()
    assert parse_traceparent(tr2.traceparent()) == (tr2.trace_id,
                                                    tr2.root.span_id)


def test_finish_idempotent_and_annotate_targets_only_inflight():
    t = Tracer(sample=1.0, ring=8)
    tr_live, tr_done = t.start(), t.start()
    t.finish(tr_done)
    t.annotate_inflight("watchdog_trip", reason="stall")
    t.finish(tr_live)
    t.finish(tr_live)                            # idempotent
    assert t.stats()["ring_used"] == 2
    live = [e["name"] for e in tr_live.root.events]
    done = [e["name"] for e in tr_done.root.events]
    assert "watchdog_trip" in live and "watchdog_trip" not in done


def test_health_watchdog_and_fault_events_attach_to_inflight_traces():
    """The process-level fan-in: health transitions, watchdog trips and
    fault injections ride the module TRACER (the one the serving stack
    shares) into every in-flight trace as events."""
    from llama_fastapi_k8s_gpu_tpu.engine.watchdog import Watchdog
    from llama_fastapi_k8s_gpu_tpu.obs.trace import TRACER
    from llama_fastapi_k8s_gpu_tpu.utils.faults import FAULTS
    from llama_fastapi_k8s_gpu_tpu.utils.health import (
        DEGRADED,
        READY,
        HealthMonitor,
    )

    tr = TRACER.start("request")
    assert tr is not None, "module tracer must default to sample=1.0"
    try:
        h = HealthMonitor()
        h.transition(READY, "engine loaded")
        h.transition(DEGRADED, "drill")
        eng = FakeEngine()
        wd = Watchdog(eng, h, Metrics())
        wd.handle_trip("stalled_decode: drill")
        FAULTS.arm("decode_step:slow:delay=0")
        try:
            FAULTS.fire("decode_step")
        finally:
            FAULTS.disarm()
    finally:
        TRACER.finish(tr)
    events = [e["name"] for e in tr.root.events]
    assert "health_transition" in events
    assert "watchdog_trip" in events
    assert "fault_fired" in events
    trip = next(e for e in tr.root.events if e["name"] == "watchdog_trip")
    assert "stalled_decode" in trip["reason"]


def test_events_fan_into_private_tracers_too():
    """create_app(tracer=...) installs private tracers; process-level
    events (health/watchdog/faults) must reach their in-flight traces,
    not only the module default's (code-review regression)."""
    from llama_fastapi_k8s_gpu_tpu.utils.health import READY, HealthMonitor

    t = Tracer(sample=1.0, ring=4)
    tr = t.start()
    try:
        HealthMonitor().transition(READY, "fan-in probe")
    finally:
        t.finish(tr)
    assert any(e["name"] == "health_transition" for e in tr.root.events)


def test_finish_sweeps_open_spans_closed():
    """A producer error path that leaves a span open (a prefill that
    raised) must not export end=null: finish closes it at the root's end
    with an ``auto_closed`` stamp, so waterfalls never show a phantom
    still-running phase on a completed request."""
    t = Tracer(sample=1.0, ring=4)
    tr = t.start()
    dangling = tr.span("engine")
    closed = tr.span("queue")
    closed.end()
    t.finish(tr)
    d = tr.to_dict()
    spans = {c["name"]: c for c in d["root"]["children"]}
    assert spans["engine"]["end"] == d["root"]["end"]
    assert spans["engine"]["attrs"].get("auto_closed") is True
    assert "auto_closed" not in spans["queue"]["attrs"]
    assert dangling.t1 is not None


def test_node_cap_counts_drops():
    from llama_fastapi_k8s_gpu_tpu.obs.trace import MAX_NODES_PER_TRACE
    t = Tracer(sample=1.0, ring=2)
    tr = t.start()
    for i in range(MAX_NODES_PER_TRACE + 50):
        tr.span(f"s{i}")
    d = tr.to_dict()
    assert len(d["root"]["children"]) == MAX_NODES_PER_TRACE - 1
    assert d["dropped_nodes"] == 51


# ---------------------------------------------------------------------------
# layer 3: engine span trees (all four engines; ISSUE 4 acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("model") / "tiny.gguf")
    write_tiny_llama_gguf(path)
    return path


@pytest.fixture(scope="module")
def cengine(model_path):
    eng = ContinuousEngine(model_path, dp=2, tp=2, batch_size=4, n_ctx=128,
                           decode_chunk=4, max_gen_tokens=16,
                           prefill_buckets=(32, 64, 128))
    yield eng
    eng.shutdown()


def _spans_by_name(node: dict, out=None) -> dict:
    out = {} if out is None else out
    out.setdefault(node["name"], []).append(node)
    for c in node["children"]:
        _spans_by_name(c, out)
    return out


def _assert_monotonic_nested(node: dict, lo: float, hi: float, path="root"):
    """Every span [start, end] sits inside its parent's window (±EPS) and
    ends after it starts."""
    assert node["start"] >= lo - EPS, f"{path}/{node['name']} starts early"
    assert node["end"] is not None, f"{path}/{node['name']} never ended"
    assert node["end"] >= node["start"], f"{path}/{node['name']} negative"
    assert node["end"] <= hi + EPS, f"{path}/{node['name']} outlives parent"
    for c in node["children"]:
        _assert_monotonic_nested(c, node["start"], node["end"],
                                 f"{path}/{node['name']}")


def _assert_engine_tree(trace_dict: dict, want_decode_chunks: bool = True):
    root = trace_dict["root"]
    names = _spans_by_name(root)
    assert "prefill" in names, sorted(names)
    prefill = names["prefill"][0]
    assert prefill["attrs"]["n_prompt"] > 0
    assert prefill["attrs"].get("ttft_s") is not None
    if want_decode_chunks:
        assert "decode_chunk" in names, sorted(names)
    _assert_monotonic_nested(root, root["start"], root["end"])


def test_serial_engine_span_tree(model_path):
    eng = Engine(model_path, n_ctx=128, decode_chunk=4, max_gen_tokens=16,
                 prefill_buckets=(32, 64, 128))
    t = Tracer(sample=1.0, ring=4)
    tr = t.start()
    out = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=12,
                                     trace=tr)
    t.finish(tr)
    assert out["usage"]["completion_tokens"] >= 1
    d = tr.to_dict()
    _assert_engine_tree(d)
    names = _spans_by_name(d["root"])
    engine_span = names["engine"][0]
    assert engine_span["attrs"]["engine"] == "Engine"
    assert engine_span["attrs"]["completion_tokens"] >= 1
    # streaming rides the same taxonomy
    tr2 = t.start()
    list(eng.create_chat_completion(MSGS, stream=True, temperature=0.0,
                                    max_tokens=8, trace=tr2))
    t.finish(tr2)
    _assert_engine_tree(tr2.to_dict())


def test_mesh_engine_span_tree(model_path):
    from llama_fastapi_k8s_gpu_tpu.engine import MeshEngine

    eng = MeshEngine(model_path, dp=2, tp=2, batch_size=2, n_ctx=128,
                     decode_chunk=4, max_gen_tokens=16,
                     prefill_buckets=(32, 64, 128))
    t = Tracer(sample=1.0, ring=4)
    traces = [t.start(), None]       # entry 1 sampled out: must not trace
    outs = eng.create_chat_completions([MSGS, MSGS], temperature=0.0,
                                       max_tokens=8, traces=traces)
    t.finish(traces[0])
    assert all(o["usage"]["completion_tokens"] >= 1 for o in outs)
    d = traces[0].to_dict()
    _assert_engine_tree(d)
    assert d["meta"]["engine"] == "MeshEngine"
    assert d["meta"]["lane"] == 0


def test_sp_engine_span_tree(model_path):
    from llama_fastapi_k8s_gpu_tpu.engine import SPEngine

    eng = SPEngine(model_path, sp=2, tp=1, n_ctx=128, decode_chunk=4,
                   max_gen_tokens=16, prefill_buckets=(32, 64, 128))
    t = Tracer(sample=1.0, ring=4)
    tr = t.start()
    out = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=8,
                                     trace=tr)
    t.finish(tr)
    assert out["usage"]["completion_tokens"] >= 1
    d = tr.to_dict()
    _assert_engine_tree(d)
    names = _spans_by_name(d["root"])
    assert names["engine"][0]["attrs"]["sp"] == 2   # ring geometry stamped


def test_continuous_engine_span_tree(cengine):
    t = Tracer(sample=1.0, ring=8)
    tr = t.start()
    out = cengine.submit(MSGS, temperature=0.0, max_tokens=8,
                         trace=tr).result(timeout=120)
    t.finish(tr)
    assert out["usage"]["completion_tokens"] >= 1
    d = tr.to_dict()
    names = _spans_by_name(d["root"])
    for want in ("pending", "prefill", "decode"):
        assert want in names, sorted(names)
    assert "decode_chunk" in names
    decode = names["decode"][0]
    assert decode["attrs"]["lane"] in range(4)
    assert decode["attrs"]["finish"] in ("stop", "length")
    _assert_monotonic_nested(d["root"], d["root"]["start"], d["root"]["end"])
    assert d["meta"]["engine"] == "ContinuousEngine"


def test_zero_cost_when_sampled_out(model_path, monkeypatch):
    """LFKT_TRACE_SAMPLE=0 ⇒ the decode path may not construct a single
    span or touch a trace lock: poison Span construction and generate."""
    import llama_fastapi_k8s_gpu_tpu.obs.trace as trace_mod

    eng = Engine(model_path, n_ctx=128, decode_chunk=4, max_gen_tokens=16,
                 prefill_buckets=(32, 64, 128))
    t = Tracer(sample=0.0, ring=4)
    assert t.start() is None

    def boom(*a, **kw):
        raise AssertionError("span constructed for a sampled-out request")

    monkeypatch.setattr(trace_mod.Span, "__init__", boom)
    out = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=8,
                                     trace=t.start())
    assert out["usage"]["completion_tokens"] >= 1


# ---------------------------------------------------------------------------
# layer 3b: concurrent load through the real server on ContinuousEngine
# ---------------------------------------------------------------------------

@pytest.mark.anyio
async def test_concurrent_load_trace_completeness(cengine):
    """N parallel requests against a real ContinuousEngine through the
    real server: every sampled request yields a COMPLETE span tree —
    request → queue → pending → prefill → decode(+chunks) — with
    monotonic, properly nested timestamps (ISSUE 4 acceptance)."""
    tracer = Tracer(sample=1.0, ring=64)
    app = create_app(engine=cengine,
                     settings=Settings(batch_size=4, max_queue_size=32,
                                       timeout_seconds=120),
                     tracer=tracer)
    transport = httpx.ASGITransport(app=app)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://test") as client:
            results = await asyncio.gather(*[
                client.post("/response", json=TINY_BODY) for _ in range(8)])
        await app.router.shutdown()
    assert [r.status_code for r in results] == [200] * 8
    rids = {r.headers["x-request-id"] for r in results}
    assert len(rids) == 8
    stats = tracer.stats()
    assert stats["inflight"] == 0
    for rid in rids:
        tr = tracer.get(rid)
        assert tr is not None, f"request {rid} left no trace"
        d = tr.to_dict()
        assert d["finished"]
        names = _spans_by_name(d["root"])
        for want in ("queue", "pending", "prefill", "decode",
                     "decode_chunk"):
            assert want in names, (rid, sorted(names))
        assert d["root"]["attrs"]["status"] == 200
        assert d["root"]["attrs"]["route"] == "/response"
        _assert_monotonic_nested(d["root"], d["root"]["start"],
                                 d["root"]["end"])
        assert d["meta"]["tokens"] >= 1


# ---------------------------------------------------------------------------
# layer 4: server surface — debug endpoints, headers, logs, docs
# ---------------------------------------------------------------------------

async def _serve(app, calls):
    transport = httpx.ASGITransport(app=app)
    out = []
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://test") as client:
            for method, path, kw in calls:
                out.append(await getattr(client, method)(path, **kw))
        await app.router.shutdown()
    return out


@pytest.mark.anyio
async def test_debug_endpoints_and_headers():
    tracer = Tracer(sample=1.0, ring=8)
    app = create_app(engine=FakeEngine(reply="hey"), tracer=tracer)
    tp = "00-" + "cd" * 16 + "-" + "34" * 8 + "-01"
    r1, listing, missing = await _serve(app, [
        ("post", "/response", {"json": BODY,
                               "headers": {"traceparent": tp}}),
        ("get", "/debug/traces", {}),
        ("get", "/debug/traces/deadbeef", {}),
    ])
    # traceparent ingested: its trace id IS the request id
    assert r1.headers["x-request-id"] == "cd" * 16
    assert r1.headers["traceparent"].startswith("00-" + "cd" * 16 + "-")
    assert missing.status_code == 404
    doc = listing.json()
    ids = [s["trace_id"] for s in doc["traces"]]
    assert "cd" * 16 in ids
    assert doc["stats"]["ring_used"] >= 1
    # the full tree is servable by id
    full, = await _serve(app, [("get", f"/debug/traces/{'cd' * 16}", {})])
    tree = full.json()
    assert tree["parent_span_id"] == "34" * 8
    assert tree["root"]["name"] == "request"
    assert _spans_by_name(tree["root"]).get("queue")


@pytest.mark.anyio
async def test_debug_requests_snapshot_during_flight():
    tracer = Tracer(sample=1.0, ring=8)
    app = create_app(engine=FakeEngine(reply="ok", delay=0.5),
                     tracer=tracer)
    transport = httpx.ASGITransport(app=app)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://test") as client:
            task = asyncio.create_task(client.post("/response", json=BODY))
            await asyncio.sleep(0.15)     # mid-generation
            snap = (await client.get("/debug/requests")).json()["requests"]
            inflight = [s for s in snap if s["name"] == "request"
                        and s.get("route") == "/response"]
            assert inflight, snap
            assert inflight[0]["age_s"] > 0
            assert inflight[0]["deadline_remaining_s"] is not None
            r = await task
            assert r.status_code == 200
        await app.router.shutdown()
    assert tracer.stats()["inflight"] == 0


@pytest.mark.anyio
async def test_request_id_in_json_log_records():
    stream = io.StringIO()
    from llama_fastapi_k8s_gpu_tpu.obs.logctx import access_logger

    handler = setup_json_logging(access_logger, stream)
    access_logger.setLevel(logging.INFO)
    try:
        tracer = Tracer(sample=1.0, ring=8)
        app = create_app(engine=FakeEngine(reply="yo"), tracer=tracer)
        r, = await _serve(app, [("post", "/response", {"json": BODY})])
    finally:
        access_logger.removeHandler(handler)
    records = [json.loads(ln) for ln in stream.getvalue().splitlines()]
    access = [rec for rec in records if rec.get("route") == "/response"]
    assert access, records
    rec = access[-1]
    assert rec["request_id"] == r.headers["x-request-id"]
    assert rec["status"] == 200
    assert rec["logger"] == "lfkt.access"
    assert rec["duration_s"] >= 0


def test_request_id_contextvar_scoping():
    assert current_request_id() == "-"
    with bind_request_id("req-123"):
        assert current_request_id() == "req-123"
        rec = logging.LogRecord("x", logging.INFO, __file__, 1, "m", (), None)
        assert json.loads(JsonFormatter().format(rec))["request_id"] == \
            "req-123"
    assert current_request_id() == "-"


@pytest.mark.anyio
async def test_sampled_out_requests_still_get_request_ids():
    tracer = Tracer(sample=0.0, ring=8)
    app = create_app(engine=FakeEngine(reply="hi"), tracer=tracer)
    r1, r2, listing = await _serve(app, [
        ("post", "/response", {"json": BODY}),
        ("post", "/response", {"json": BODY}),
        ("get", "/debug/traces", {}),
    ])
    assert r1.headers["x-request-id"] != r2.headers["x-request-id"]
    assert "traceparent" not in r1.headers       # no trace to propagate
    assert listing.json()["traces"] == []


def test_docs_metrics_table_is_generated_from_catalog():
    """The docs/OBSERVABILITY.md metrics table IS the catalog generator's
    output (OBS002's docs coverage, pinned byte-for-byte)."""
    doc = open(os.path.join(REPO, "docs", "OBSERVABILITY.md"),
               encoding="utf-8").read()
    begin = "<!-- metrics:begin (generated - do not hand-edit) -->"
    assert begin in doc and "<!-- metrics:end -->" in doc
    block = doc.split(begin)[1].split("<!-- metrics:end -->")[0].strip()
    assert block == markdown_table().strip(), (
        "docs/OBSERVABILITY.md metrics table is stale: regenerate with "
        "python -m llama_fastapi_k8s_gpu_tpu.obs.catalog")


# ---------------------------------------------------------------------------
# tools/trace_report.py — the RUNBOOK waterfall renderer
# ---------------------------------------------------------------------------

def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_report_waterfall(model_path):
    eng = Engine(model_path, n_ctx=128, decode_chunk=4, max_gen_tokens=16,
                 prefill_buckets=(32, 64, 128))
    t = Tracer(sample=1.0, ring=4)
    tr = t.start()
    tr.root.set(route="/response")
    tr.note(route="/response")
    eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=8, trace=tr)
    t.finish(tr)
    mod = _load_trace_report()
    text = mod.render_trace(tr.to_dict())
    assert tr.trace_id in text
    for phase in ("engine", "prefill", "decode_chunk"):
        assert phase in text, text
    assert "phase breakdown:" in text
    assert re.search(r"engine\s+ +[0-9.]+ ms +[0-9.]+%", text)
    assert "█" in text
    listing = mod.render_listing({"traces": t.traces()})
    assert tr.trace_id in listing


@pytest.mark.anyio
async def test_server_installs_metrics_sink_and_slice_histogram(model_path):
    """The app injects its Metrics registry into the engine at startup
    (engine.metrics_sink); a sliced prefill then lands observations in the
    prefill_slice_seconds histogram on /metrics."""
    from tests.test_server import lifespan_client, make_client

    eng = Engine(model_path, n_ctx=128, decode_chunk=4, max_gen_tokens=16,
                 prefill_buckets=(32, 64, 128), prefix_cache=False,
                 prefill_chunk=16, prefill_overlap=2)
    app, transport = make_client(eng)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            assert eng.metrics_sink is app.state.metrics
            body = dict(TINY_BODY)
            body["context"] = [{"turn": "user",
                                "message": "one two three four five " * 2}]
            r = await client.post("/response", json=body)
            assert r.status_code == 200
            m = (await client.get("/metrics")).text
            assert "# TYPE prefill_slice_seconds histogram" in m
            count = re.search(r"prefill_slice_seconds_count (\d+)", m)
            assert count is not None and int(count.group(1)) >= 2
        await app.router.shutdown()


def test_trace_report_renders_prefill_slice_overlap(model_path):
    """A sliced prefill's per-slice events render as ▒ duration bars
    (offset-labeled) tiling the prefill span — the round-6 overlap view."""
    eng = Engine(model_path, n_ctx=128, decode_chunk=4, max_gen_tokens=16,
                 prefill_buckets=(32, 64, 128), prefix_cache=False,
                 prefill_chunk=16, prefill_overlap=2)
    t = Tracer(sample=1.0, ring=4)
    tr = t.start()
    eng.create_chat_completion(
        [{"role": "user", "content": "one two three four five six " * 2}],
        temperature=0.0, max_tokens=4, trace=tr)
    t.finish(tr)
    mod = _load_trace_report()
    text = mod.render_trace(tr.to_dict())
    assert "▒" in text, text
    slices = re.findall(r"slice@(\d+)", text)
    assert len(slices) >= 2, text                 # multi-slice prompt
    assert [int(s) for s in slices] == sorted(int(s) for s in slices)
    assert re.search(r"slice@\d+.*n=\d+", text)   # token count rides along

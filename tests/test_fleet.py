"""Fleet tier: prefix-affinity router + peer table (ISSUE 14;
serving/fleet/).

Layers, all tier-1 on CPU:

1. **Units** — affinity-key extraction (stable per conversation, header
   override, opaque fallback), rendezvous ranking (balance + minimal
   remap on peer loss), peer-table ejection/backoff/re-admission
   against a controllable fake replica.
2. **In-process router** — FakeEngine replicas behind the real router
   over real TCP: affinity stickiness, the round-robin control arm,
   ejection → spill-to-survivor with /health attribution and recovery.
3. **Route parity** (the ci_gate ``fleet-route-parity`` subset) — real
   tiny-GGUF replicas: greedy ``/response`` bytes and ``/v1`` content
   through the router are identical to direct-to-replica serving,
   streaming included.
4. **Two-process acceptance drill** — two real server processes behind
   the router: the multi-turn replay's aggregate prefix-cache hit
   ratio under affinity routing is >= 2x the round-robin control,
   SIGKILLing a replica mid-stream ejects it (attributed, stream
   terminates, fresh traffic spills to the survivor) and restarting it
   re-admits it.
"""

from __future__ import annotations

import asyncio
import http.server
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from llama_fastapi_k8s_gpu_tpu.engine import Engine, FakeEngine
from llama_fastapi_k8s_gpu_tpu.obs import fleettrace
from llama_fastapi_k8s_gpu_tpu.obs.trace import Span, Tracer
from llama_fastapi_k8s_gpu_tpu.server import httpd
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.serving.fleet import FLEET_ROLES, build_router
from llama_fastapi_k8s_gpu_tpu.serving.fleet.affinity import (
    AFFINITY_HEADER,
    affinity_key,
    rendezvous_rank,
)
from llama_fastapi_k8s_gpu_tpu.serving.fleet.peers import PeerTable
from llama_fastapi_k8s_gpu_tpu.serving.fleet.router import FleetRouter
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.config import Settings
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _body(conv: int, history: list | None = None,
          opener: str = "hello") -> bytes:
    return json.dumps({
        "bot_profile": {
            "name": f"Bot{conv}",
            "appearance": "tall, green eyes, red hair, calm voice",
            "system_prompt": f"You are concise assistant #{conv}.",
        },
        "user_profile": {"name": "Sam"},
        "context": history or [{"turn": "user",
                                "message": f"{opener} {conv}"}],
    }).encode()


def _post(port: int, body: bytes, path: str = "/response",
          timeout: float = 60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get_json(port: int, path: str, timeout: float = 30.0) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _wait_http(port: int, path: str = "/health",
               deadline_s: float = 180.0) -> None:
    deadline = time.time() + deadline_s
    while True:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5)
            return
        except Exception:  # noqa: BLE001 — booting
            if time.time() > deadline:
                raise
            time.sleep(0.2)


# ---------------------------------------------------------------------------
# in-process serving helpers (graceful-stoppable httpd + router threads)
# ---------------------------------------------------------------------------

class _Served:
    """One asyncio server (httpd app or router) on its own loop thread,
    stoppable from the test thread."""

    def __init__(self, coro_factory):
        self._loop = None
        self._stop = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        args=(coro_factory,), daemon=True)
        self._thread.start()
        assert self._started.wait(10)

    def _run(self, coro_factory):
        async def main():
            self._loop = asyncio.get_running_loop()
            self._stop = asyncio.Event()
            self._started.set()
            await coro_factory(self._stop)
        asyncio.run(main())

    def stop(self, join_s: float = 15.0):
        if self._loop is not None and self._stop is not None:
            try:
                self._loop.call_soon_threadsafe(self._stop.set)
            except RuntimeError:
                pass
        self._thread.join(timeout=join_s)


def _serve_app(engine, port: int, tracer=None, **settings_kw) -> _Served:
    settings_kw.setdefault("watchdog", False)
    settings_kw.setdefault("temperature", 0.0)
    app = create_app(engine=engine, settings=Settings(**settings_kw),
                     tracer=tracer)
    srv = _Served(lambda stop: httpd.serve(app, "127.0.0.1", port,
                                           stop_event=stop))
    _wait_http(port)
    return srv


def _serve_router(router: FleetRouter, port: int) -> _Served:
    srv = _Served(lambda stop: router.serve("127.0.0.1", port,
                                            stop_event=stop))
    _wait_http(port, path="/health")
    return srv


def _table(ports, **kw) -> PeerTable:
    kw.setdefault("probe_seconds", 0.3)
    kw.setdefault("backoff_seconds", 0.3)
    kw.setdefault("probe_timeout", 2.0)
    return PeerTable(peers=[f"127.0.0.1:{p}" for p in ports], **kw)


# ---------------------------------------------------------------------------
# layer 1: units
# ---------------------------------------------------------------------------

def test_affinity_key_sources():
    # explicit header wins over everything
    k, src = affinity_key("/response", {AFFINITY_HEADER: "conv-42"},
                          _body(0))
    assert (k, src) == ("h:conv-42", "header")

    # /response: stable across turns of one conversation (the persona +
    # the FIRST user message key it), distinct across conversations
    k1, src1 = affinity_key("/response", {}, _body(1))
    grown = [{"turn": "user", "message": "hello 1"},
             {"turn": "bot", "message": "hi!"},
             {"turn": "user", "message": "tell me more"}]
    k1b, _ = affinity_key("/response", {}, _body(1, history=grown))
    assert src1 == "prefix" and k1 == k1b
    k2, _ = affinity_key("/response", {}, _body(2))
    assert k2 != k1

    # /v1: the OpenAI user field is the conversation id when present
    v1 = {"model": "m", "user": "u-7",
          "messages": [{"role": "user", "content": "x"}]}
    k3, src3 = affinity_key("/v1/chat/completions", {},
                            json.dumps(v1).encode())
    assert (k3, src3) == ("u:u-7", "conversation")
    # ... else the stable message prefix
    v2 = {"model": "m", "messages": [
        {"role": "system", "content": "be terse"},
        {"role": "user", "content": "first question"}]}
    k4, src4 = affinity_key("/v1/chat/completions", {},
                            json.dumps(v2).encode())
    v2["messages"].append({"role": "assistant", "content": "answer"})
    v2["messages"].append({"role": "user", "content": "follow-up"})
    k4b, _ = affinity_key("/v1/chat/completions", {},
                          json.dumps(v2).encode())
    assert src4 == "prefix" and k4 == k4b

    # unparseable body: deterministic opaque digest (retries co-locate)
    k5, src5 = affinity_key("/response", {}, b"\xff not json")
    k5b, _ = affinity_key("/response", {}, b"\xff not json")
    assert src5 == "opaque" and k5 == k5b
    # bodyless GET: keyed on the path
    k6, src6 = affinity_key("/v1/models", {}, b"")
    assert src6 == "opaque" and k6 == affinity_key("/v1/models", {}, b"")[0]


def test_rendezvous_rank_balance_and_minimal_remap():
    peers = ["10.0.0.1:8000", "10.0.0.2:8000", "10.0.0.3:8000"]
    keys = [f"conv-{i}" for i in range(300)]
    owners = {k: rendezvous_rank(k, peers)[0] for k in keys}
    counts = {p: sum(1 for o in owners.values() if o == p) for p in peers}
    # roughly balanced: every peer owns a healthy share
    assert all(c > 50 for c in counts.values()), counts
    # stability: ranking is deterministic
    assert owners == {k: rendezvous_rank(k, peers)[0] for k in keys}
    # removing one peer remaps ONLY its keys (the HRW property the
    # warm-cache story depends on: a dead pod must not reshuffle every
    # conversation in the fleet)
    survivors = peers[:2]
    for k in keys:
        if owners[k] in survivors:
            assert rendezvous_rank(k, survivors)[0] == owners[k]
    # spill order: dropping the owner promotes exactly rank-2
    for k in keys[:50]:
        full = rendezvous_rank(k, peers)
        assert rendezvous_rank(
            k, [p for p in peers if p != full[0]])[0] == full[1]


class _FlagReplica:
    """A controllable /health/ready endpoint: 200 while .ready, else 503."""

    def __init__(self):
        self.ready = True
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):           # noqa: N802 — stdlib contract
                code = 200 if outer.ready else 503
                body = b'{"ready": true}' if outer.ready else b'{}'
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()


def test_router_shutdown_joins_prober_off_loop():
    """ISSUE 15 regression (lfkt-lint ASY001): FleetRouter.serve joins
    the prober thread at shutdown.  The join must ride a worker thread
    (``asyncio.to_thread``) so the event loop keeps scheduling — a
    prober wedged in a probe_timeout-long socket wait must not freeze
    in-flight proxied streams.  Re-inlining ``self.peers.stop()`` makes
    the measured loop stall jump to the full wedge duration and fails
    this test (and fires ASY001)."""
    table = _table([_free_port()])
    real_stop = table.stop

    def wedged_stop():
        # a prober mid-probe against a dead peer: stop() blocks in join
        time.sleep(0.5)
        real_stop()

    table.stop = wedged_stop
    router = FleetRouter(table, policy="affinity")
    port = _free_port()

    async def main() -> float:
        ready, stop = asyncio.Event(), asyncio.Event()
        task = asyncio.create_task(
            router.serve("127.0.0.1", port, ready_event=ready,
                         stop_event=stop))
        await ready.wait()
        stop.set()
        # serve() proceeds into the peers.stop() join; with the worker
        # hop the loop stays live and this sleep completes on time
        t0 = time.monotonic()
        await asyncio.sleep(0.05)
        stall = time.monotonic() - t0
        await task
        return stall

    stall = asyncio.run(main())
    assert stall < 0.3, (
        f"event loop stalled {stall:.3f}s during shutdown — the prober "
        "join is running ON the loop")


def test_peer_table_eject_backoff_readmit():
    rep = _FlagReplica()
    table = _table([rep.port])
    try:
        table.start(probe_now=True)
        addr = f"127.0.0.1:{rep.port}"
        assert table.healthy() == [addr]

        # replica turns not-ready: the next sweep ejects with attribution
        rep.ready = False
        deadline = time.time() + 10
        while table.healthy() and time.time() < deadline:
            time.sleep(0.05)
        assert table.healthy() == []
        snap = table.snapshot()
        assert snap["healthy"] == 0 and snap["replicas"] == 1
        row = snap["peers"][0]
        assert row["healthy"] is False
        assert "503" in row["last_error"]
        assert row["ejections"] >= 1

        # backoff grows while it stays down (bounded probing)
        time.sleep(1.2)
        b1 = table.snapshot()["peers"][0]["backoff_seconds"]
        assert b1 >= 0.3

        # recovery: ready again -> re-admitted without operator action
        rep.ready = True
        deadline = time.time() + 10
        while not table.healthy() and time.time() < deadline:
            time.sleep(0.05)
        assert table.healthy() == [addr]
        assert table.snapshot()["peers"][0]["last_error"] is None
    finally:
        table.stop()
        rep.close()


def test_probe_survives_non_http_peer():
    """A port answering non-HTTP (half-dead process, wrong service) must
    eject with attribution — never crash the sweep (or router startup)
    that the REST of the fleet depends on."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]

    def accept_loop():
        while True:
            try:
                c, _addr = srv.accept()
            except OSError:
                return
            try:
                c.sendall(b"NOT HTTP AT ALL\n")
                c.close()
            except OSError:
                pass

    threading.Thread(target=accept_loop, daemon=True).start()
    table = PeerTable(peers=[f"127.0.0.1:{port}"], probe_seconds=0.2,
                      backoff_seconds=0.2, probe_timeout=1.0)
    try:
        table.start(probe_now=True)          # must not raise
        assert table.healthy() == []
        err = table.snapshot()["peers"][0]["last_error"]
        assert "BadStatusLine" in err, err
    finally:
        table.stop()
        srv.close()


def test_peer_table_validation_and_roles():
    with pytest.raises(ValueError, match="LFKT_FLEET_PEERS"):
        PeerTable(peers=[], dns="")
    assert FLEET_ROLES == ("off", "router")
    with pytest.raises(ValueError, match="LFKT_FLEET_POLICY"):
        FleetRouter(object(), policy="sideways")


def test_build_router_from_settings():
    rep = _FlagReplica()
    try:
        router = build_router(Settings(
            fleet_peers=f"127.0.0.1:{rep.port}", fleet_policy="roundrobin",
            fleet_probe_seconds=0.3, fleet_proxy_timeout_seconds=2.0))
        assert router.policy == "roundrobin"
        assert router.peers.healthy() == [f"127.0.0.1:{rep.port}"]
        router.peers.stop()
    finally:
        rep.close()


# ---------------------------------------------------------------------------
# layer 2: the router over FakeEngine replicas
# ---------------------------------------------------------------------------

def test_router_affinity_sticks_roundrobin_spreads():
    p1, p2, rp, rp2 = (_free_port() for _ in range(4))
    s1 = _serve_app(FakeEngine(reply="alpha"), p1)
    s2 = _serve_app(FakeEngine(reply="beta"), p2)
    table = _table([p1, p2]).start()
    router = FleetRouter(table, policy="affinity", metrics=Metrics())
    rs = _serve_router(router, rp)
    table2 = _table([p1, p2]).start()
    rr = FleetRouter(table2, policy="roundrobin")
    rs2 = _serve_router(rr, rp2)
    try:
        # affinity: each conversation sticks to ONE replica...
        seen = {}
        for conv in range(6):
            answers = set()
            for _ in range(3):
                _status, raw = _post(rp, _body(conv))
                answers.add(json.loads(raw)["response"])
            assert len(answers) == 1, (conv, answers)
            seen[conv] = answers.pop()
        # ... and the keyspace uses BOTH replicas
        assert set(seen.values()) == {"alpha", "beta"}

        # round-robin control: consecutive turns of ONE conversation
        # scatter (the cold-cache failure mode the affinity policy fixes)
        answers = set()
        for _ in range(4):
            _status, raw = _post(rp2, _body(0))
            answers.add(json.loads(raw)["response"])
        assert answers == {"alpha", "beta"}

        # the router /metrics carries the fleet families
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rp}/metrics", timeout=10) as r:
            m = r.read().decode()
        assert "fleet_requests_total" in m
        assert "fleet_peers_healthy 2" in m
        assert 'source="prefix"' in m
    finally:
        rs.stop()
        rs2.stop()
        table.stop()
        table2.stop()
        s1.stop()
        s2.stop()


def test_router_ejects_spills_attributes_and_readmits():
    p1, p2, rp = (_free_port() for _ in range(3))
    s1 = _serve_app(FakeEngine(reply="alpha"), p1)
    s2 = _serve_app(FakeEngine(reply="beta"), p2)
    table = _table([p1, p2]).start()
    router = FleetRouter(table, policy="affinity", metrics=Metrics())
    rs = _serve_router(router, rp)
    try:
        # find a conversation owned by replica 1 (alpha)
        conv = next(c for c in range(64)
                    if json.loads(_post(rp, _body(c))[1])["response"]
                    == "alpha")

        # kill replica 1 (graceful stop: the port refuses connections)
        s1.stop()
        # a fresh request for the SAME conversation must spill to the
        # survivor — never a hang, never a 502/503
        status, raw = _post(rp, _body(conv))
        assert status == 200
        assert json.loads(raw)["response"] == "beta"
        assert router.counters["spills"] >= 1

        # the router's /health attributes the ejected peer by name
        doc = _get_json(rp, "/health")
        assert doc["role"] == "router" and doc["healthy"] == 1
        dead = [p for p in doc["peers"] if not p["healthy"]]
        assert len(dead) == 1
        assert dead[0]["addr"] == f"127.0.0.1:{p1}"
        assert dead[0]["last_error"]
        # /health/ready stays 200 while >= 1 replica lives
        assert _get_json(rp, "/health/ready")["ready"] is True

        # recovery: the replica comes back on the same port -> the
        # prober re-admits it and affinity returns home
        s1b = _serve_app(FakeEngine(reply="alpha"), p1)
        try:
            deadline = time.time() + 15
            while len(table.healthy()) < 2 and time.time() < deadline:
                time.sleep(0.1)
            assert len(table.healthy()) == 2
            _status, raw = _post(rp, _body(conv))
            assert json.loads(raw)["response"] == "alpha"
        finally:
            s1b.stop()
    finally:
        rs.stop()
        table.stop()
        s2.stop()


def test_router_503_with_attribution_when_whole_fleet_down():
    p1, rp = _free_port(), _free_port()
    table = PeerTable(peers=[f"127.0.0.1:{p1}"], probe_seconds=0.3,
                      backoff_seconds=0.3, probe_timeout=1.0)
    table.start()            # nothing listening: probe ejects immediately
    router = FleetRouter(table, policy="affinity",
                         proxy_timeout=1.0)
    rs = _serve_router(router, rp)
    try:
        t0 = time.time()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(rp, _body(0), timeout=15)
        assert ei.value.code == 503
        assert "no healthy replica" in ei.value.read().decode()
        assert time.time() - t0 < 10      # bounded, never a hang
        # the router's OWN readiness flips 503 while the fleet is down,
        # so k8s stops routing clients at it
        with pytest.raises(urllib.error.HTTPError) as rei:
            _get_json(rp, "/health/ready")
        assert rei.value.code == 503
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rp}/health", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["healthy"] == 0
        assert doc["counters"]["no_replica_503s"] >= 1
    finally:
        rs.stop()
        table.stop()


# ---------------------------------------------------------------------------
# layer 3: route parity on real engines (the ci_gate subset)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gguf_path(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("fleet") / "tiny.gguf")
    write_tiny_llama_gguf(p)
    return p


def _tiny_engine(path):
    return Engine(path, n_ctx=256, prefill_buckets=(64, 128),
                  max_gen_tokens=8, decode_chunk=4, kv_paged=True,
                  kv_page_tokens=16)


def test_fleet_route_parity(gguf_path):
    """Greedy output THROUGH the router is byte-identical to direct
    serving — /response raw body bytes, /v1 content + usage, and the
    streamed SSE content — on real engines (same GGUF on both replicas,
    so whichever replica owns the key answers identically)."""
    p1, p2, rp = (_free_port() for _ in range(3))
    s1 = _serve_app(_tiny_engine(gguf_path), p1)
    s2 = _serve_app(_tiny_engine(gguf_path), p2)
    table = _table([p1, p2]).start()
    router = FleetRouter(table, policy="affinity")
    rs = _serve_router(router, rp)
    try:
        body = _body(0, opener="The quick brown fox jumps over")
        _st, direct = _post(p1, body, timeout=300)
        _st, routed = _post(rp, body, timeout=300)
        assert routed == direct          # BYTE identity, whole body

        # /v1 facade: deterministic fields match (id/created are minted
        # per request, so compare the generation, not the envelope)
        v1 = json.dumps({
            "model": None, "temperature": 0.0, "max_tokens": 8,
            "messages": [{"role": "user",
                          "content": "Say something about foxes."}],
        }).encode()
        _st, d_raw = _post(p1, v1, path="/v1/chat/completions",
                           timeout=300)
        _st, r_raw = _post(rp, v1, path="/v1/chat/completions",
                           timeout=300)
        d_doc, r_doc = json.loads(d_raw), json.loads(r_raw)
        assert r_doc["choices"] == d_doc["choices"]
        assert r_doc["usage"] == d_doc["usage"]

        # streaming passthrough: the routed SSE stream concatenates to
        # the same greedy text
        def stream_text(port):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/response/stream", data=body,
                headers={"Content-Type": "application/json"})
            parts = []
            with urllib.request.urlopen(req, timeout=300) as r:
                for raw in r:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == "[DONE]":
                        break
                    evt = json.loads(payload)
                    assert "error" not in evt, evt
                    c = evt["choices"][0]["delta"].get("content")
                    if c:
                        parts.append(c)
            return "".join(parts)

        assert stream_text(rp) == stream_text(p1)
    finally:
        rs.stop()
        table.stop()
        s1.stop()
        s2.stop()


# ---------------------------------------------------------------------------
# layer 4: the two-process acceptance drill
# ---------------------------------------------------------------------------

def _proc_env(port: int, model_dir: str, **extra) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "LFKT_MODEL_DIR": model_dir,
        "LFKT_MODEL_NAME": "tiny.gguf",
        "LFKT_HOST": "127.0.0.1",
        "LFKT_PORT": str(port),
        # buckets sized for 3 turns of growing history (the replay) with
        # 8-token replies: turn-3 prompts land in the 256 bucket
        "LFKT_MAX_CONTEXT_TOKENS": "512",
        "LFKT_PREFILL_BUCKETS": "64,128,256",
        "LFKT_MAX_GEN_TOKENS": "8",
        "LFKT_DECODE_CHUNK": "4",
        "LFKT_TEMPERATURE": "0.0",
        "LFKT_KV_PAGED": "1",
        "LFKT_KV_PAGE_TOKENS": "16",
    })
    env.update({k: str(v) for k, v in extra.items()})
    env.pop("XLA_FLAGS", None)   # one CPU device per serving replica
    return env


def _spawn_replica(port: int, model_dir: str, **extra):
    return subprocess.Popen(
        [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.server"],
        env=_proc_env(port, model_dir, **extra), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _wait_proc_ready(proc, port: int, deadline: float) -> None:
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"server :{port} died:\n"
                f"{proc.stderr.read().decode()[-3000:]}")
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=5) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(1.0)
    raise AssertionError(f"server :{port} not healthy before deadline")


def _metric_sum(port: int, name: str) -> float:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        text = r.read().decode()
    total = 0.0
    for ln in text.splitlines():
        head, _, val = ln.rpartition(" ")
        if head == name or head.startswith(name + "{"):
            total += float(val)
    return total


def _fleet_ratio(ports) -> tuple[float, dict]:
    """(token-weighted prefix hit ratio, raw counters) across replicas:
    reused prompt tokens / submitted prompt tokens — the fraction of
    prompt work served from cached KV pages."""
    raw = {"reused": 0.0, "prompt": 0.0, "hits": 0.0, "misses": 0.0}
    for p in ports:
        raw["reused"] += _metric_sum(p, "prefix_cache_reused_tokens_total")
        raw["prompt"] += _metric_sum(p, "tokens_prompt_total")
        raw["hits"] += _metric_sum(p, "prefix_cache_hits_total")
        raw["misses"] += _metric_sum(p, "prefix_cache_misses_total")
    return (raw["reused"] / raw["prompt"] if raw["prompt"] else 0.0), raw


def _replay(router_port: int, convs: list, turns: int,
            phase: str) -> None:
    """C growing conversations x T turns, round-robin ACROSS
    conversations per turn (the k8s traffic shape: consecutive requests
    belong to different users)."""
    histories = {
        c: [{"turn": "user",
             "message": f"[{phase}] Hello bot {c}! The quick brown fox "
                        "jumps over the lazy dog near the riverbank "
                        "while autumn leaves drift slowly down."}]
        for c in convs
    }
    for _t in range(turns):
        for c in convs:
            _status, raw = _post(router_port,
                                 _body(c, history=histories[c]),
                                 timeout=300)
            reply = json.loads(raw)["response"]
            histories[c].append({"turn": "bot",
                                 "message": (reply or "...")[:400]})
            histories[c].append({"turn": "user",
                                 "message": "Please tell me more."})


def test_two_process_affinity_and_fault_drill(tmp_path):
    """THE acceptance drill: 2 real replica processes behind the router.

    (a) multi-turn replay under affinity routing reaches >= 2x the
        aggregate prefix-cache hit ratio of the round-robin control
        (same processes, fresh conversations, counter deltas);
    (b) greedy output through the router is bit-identical to direct;
    (c) SIGKILL a replica mid-stream: the stream terminates (no hang),
        the router ejects the peer with /health attribution, fresh
        requests land on the survivor;
    (d) restarting the replica re-admits it.
    """
    write_tiny_llama_gguf(str(tmp_path / "tiny.gguf"))
    p1, p2 = 8065, 8066
    rp_aff, rp_rr = _free_port(), _free_port()

    proc1 = _spawn_replica(p1, str(tmp_path))
    proc2 = _spawn_replica(p2, str(tmp_path))
    table = table_rr = rs = rs_rr = None
    try:
        deadline = time.time() + 420
        _wait_proc_ready(proc1, p1, deadline)
        _wait_proc_ready(proc2, p2, deadline)

        table = _table([p1, p2]).start()
        rs = _serve_router(FleetRouter(table, policy="affinity"), rp_aff)
        table_rr = _table([p1, p2]).start()
        rs_rr = _serve_router(FleetRouter(table_rr, policy="roundrobin"),
                              rp_rr)

        # (b) parity first, while both replicas are pristine
        body = _body(99, opener="The quick brown fox jumps over the "
                                "lazy dog near the old riverbank ok")
        _st, direct = _post(p1, body, timeout=300)
        _st, routed = _post(rp_aff, body, timeout=300)
        assert routed == direct

        # (a) affinity replay vs round-robin control, by counter deltas.
        # 3 conversations (ODD: an even count over 2 replicas makes
        # round-robin accidentally affine), 3 turns.
        base = _fleet_ratio((p1, p2))[1]
        _replay(rp_aff, [0, 1, 2], turns=3, phase="aff")
        mid = _fleet_ratio((p1, p2))[1]
        _replay(rp_rr, [10, 11, 12], turns=3, phase="rr")
        end = _fleet_ratio((p1, p2))[1]

        def delta(a, b):
            d = {k: b[k] - a[k] for k in a}
            return (d["reused"] / d["prompt"] if d["prompt"] else 0.0), d

        aff_ratio, aff_raw = delta(base, mid)
        rr_ratio, rr_raw = delta(mid, end)
        assert aff_ratio > 0.3, (aff_ratio, aff_raw)
        assert aff_ratio >= 2.0 * rr_ratio, (
            f"affinity hit ratio {aff_ratio:.3f} not >= 2x round-robin "
            f"control {rr_ratio:.3f} (aff={aff_raw}, rr={rr_raw})")

        # (c) SIGKILL a replica mid-stream through the affinity router
        victim_conv = 0
        # the replica that served conversation 0's turns is its owner;
        # find it from the per-replica request counters
        doc = _get_json(rp_aff, "/health")
        assert doc["healthy"] == 2
        stream_req = urllib.request.Request(
            f"http://127.0.0.1:{rp_aff}/response/stream",
            data=_body(victim_conv, opener="[kill] please tell a story"),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(stream_req, timeout=60)
        first = resp.readline()          # stream is live
        assert first is not None
        # which process owns conv 0? ask the router's rank via affinity
        key, _src = affinity_key(
            "/response/stream", {},
            _body(victim_conv, opener="[kill] please tell a story"))
        owner = rendezvous_rank(key, [f"127.0.0.1:{p1}",
                                      f"127.0.0.1:{p2}"])[0]
        victim, survivor_port = ((proc1, p2)
                                 if owner == f"127.0.0.1:{p1}"
                                 else (proc2, p1))
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        # the stream TERMINATES (error event, truncation, or closed
        # socket) within a bound — never a hang
        t0 = time.time()
        try:
            while resp.readline():
                pass
        except Exception:  # noqa: BLE001 — torn connection is a valid end
            pass
        assert time.time() - t0 < 30
        resp.close()

        # fresh requests for the dead owner's conversations spill to the
        # survivor and answer 200
        status, raw = _post(rp_aff, _body(victim_conv,
                                          opener="[kill] and now?"),
                            timeout=300)
        assert status == 200 and json.loads(raw)["response"]
        # the ejection is attributed on the router's health doc
        doc = _get_json(rp_aff, "/health")
        assert doc["healthy"] == 1
        dead_rows = [p for p in doc["peers"] if not p["healthy"]]
        assert len(dead_rows) == 1 and dead_rows[0]["last_error"]
        assert dead_rows[0]["addr"] == owner

        # (d) recovery: restart the victim on its port -> re-admission
        dead_port = int(owner.rsplit(":", 1)[1])
        revived = _spawn_replica(dead_port, str(tmp_path))
        try:
            _wait_proc_ready(revived, dead_port, time.time() + 420)
            deadline = time.time() + 30
            while _get_json(rp_aff, "/health")["healthy"] < 2 \
                    and time.time() < deadline:
                time.sleep(0.5)
            assert _get_json(rp_aff, "/health")["healthy"] == 2
            # ... and its conversations route home again
            status, _raw = _post(rp_aff,
                                 _body(victim_conv,
                                       opener="[kill] welcome back"),
                                 timeout=300)
            assert status == 200
            assert _metric_sum(survivor_port, "http_requests_total") > 0
        finally:
            if revived.poll() is None:
                revived.terminate()
            try:
                revived.wait(timeout=30)
            except subprocess.TimeoutExpired:
                revived.kill()
    finally:
        for closer in (rs, rs_rr):
            if closer is not None:
                closer.stop()
        for t in (table, table_rr):
            if t is not None:
                t.stop()
        for p in (proc1, proc2):
            if p.poll() is None:
                p.terminate()
        for p in (proc1, proc2):
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


# ---------------------------------------------------------------------------
# layer 5: fleet observability (ISSUE 19) — cross-process trace
# continuity (the ci_gate ``fleet-trace-continuity`` subset matches
# ``-k trace_continuity``), metrics federation, zero-cost sampling
# ---------------------------------------------------------------------------

def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _find_spans(root: dict, name: str) -> list[dict]:
    out = []
    stack = [root]
    while stack:
        sp = stack.pop()
        if sp.get("name") == name:
            out.append(sp)
        stack.extend(sp.get("children", ()))
    return out


def test_fleet_trace_continuity_sse(tmp_path):
    """THE cross-process tracing drill (the ci_gate subset): one traced
    streamed ``/v1`` request through the real router and a REAL replica
    process yields ONE request id end-to-end and ONE stitched span tree
    spanning both processes with zero orphan fragments — including the
    router's ``stream.relay`` span ending at the last relayed byte —
    and the waterfall renderer draws the hop boundary."""
    write_tiny_llama_gguf(str(tmp_path / "tiny.gguf"))
    p1, rp = _free_port(), _free_port()
    proc = _spawn_replica(p1, str(tmp_path), LFKT_TRACE_SAMPLE=1,
                          LFKT_TRACE_RING=16)
    table = rs = None
    try:
        _wait_proc_ready(proc, p1, time.time() + 420)
        table = _table([p1]).start()
        router = FleetRouter(table, policy="affinity", metrics=Metrics(),
                             tracer=Tracer(sample=1.0, ring=16))
        rs = _serve_router(router, rp)

        body = json.dumps({
            "model": None, "temperature": 0.0, "max_tokens": 8,
            "stream": True, "user": "conv-trace-1",
            "messages": [{"role": "user",
                          "content": "Say something about foxes."}],
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{rp}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            rid = r.headers.get("x-request-id")
            tp = r.headers.get("traceparent")
            sse = r.read()
        assert sse and b"data:" in sse and b"[DONE]" in sse

        # ONE request id end-to-end: the replica ingested the router's
        # hop traceparent, so the id the CLIENT sees (relayed replica
        # headers) is the ROUTER's trace id
        assert rid is not None and len(rid) == 32, rid
        assert tp is not None and tp.split("-")[1] == rid
        assert router.tracer.get(rid) is not None

        # the stitched tree: poll until the replica's fragment reports
        # finished (its SSE generator closes the trace at stream end)
        doc = None
        deadline = time.time() + 30
        while time.time() < deadline:
            doc = _get_json(rp, f"/debug/fleet/traces/{rid}")
            if doc.get("fragments", 0) >= 2 and doc.get("finished"):
                break
            time.sleep(0.3)
        assert doc is not None and doc["trace_id"] == rid
        assert doc["stitched"] is True
        assert doc["fragments"] >= 2, doc["processes"]
        assert "router" in doc["processes"]
        assert f"127.0.0.1:{p1}" in doc["processes"]
        assert doc["orphans"] == [], doc["orphans"]

        # the router fragment is primary; the replica fragment grafts
        # under the proxy attempt that carried its hop traceparent
        assert doc["root"]["name"] == "fleet.route"
        attempts = _find_spans(doc["root"], "proxy.attempt")
        assert attempts and attempts[0]["attrs"]["peer"] == \
            f"127.0.0.1:{p1}"
        replica_roots = [sp for sp in _find_spans(doc["root"], "request")
                         if sp.get("attrs", {}).get("process")
                         == f"127.0.0.1:{p1}"]
        assert len(replica_roots) == 1
        assert replica_roots[0]["attrs"].get("hop") is True

        # stream.relay ends AT the last relayed byte, with the byte
        # count — raw wire bytes, so chunked framing makes it >= the
        # decoded body urllib handed back
        relays = _find_spans(doc["root"], "stream.relay")
        assert len(relays) == 1
        assert relays[0]["end"] is not None
        assert not relays[0]["attrs"].get("auto_closed")
        assert relays[0]["attrs"]["bytes"] >= len(sse) > 0

        # the waterfall renderer draws the stitched tree with the hop rule
        text = _load_tool("trace_report").render_trace(doc)
        assert "hop: 127.0.0.1:" in text
        assert "stream.relay" in text
        assert "processes=router,127.0.0.1:" in text

        # routerless assembly (tools/fleet_trace.py path): collecting
        # straight from the pods stitches the same tree minus the router
        # fragment — whose absence makes the replica fragment primary
        frags = fleettrace.collect_fragments(rid, [f"127.0.0.1:{p1}"])
        assert len(frags) == 1
        alone = fleettrace.stitch(frags)
        assert alone["trace_id"] == rid and alone["orphans"] == []
    finally:
        if rs is not None:
            rs.stop()
        if table is not None:
            table.stop()
        if proc.poll() is None:
            proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_fleet_metrics_federation_exact_merge():
    """``GET /metrics/fleet`` merges peer scrapes EXACTLY: every fleet
    counter equals the sum of the per-pod series, every histogram
    bucket/sum/count equals the bucket-wise sum, gauges re-label by
    peer, and the SLO engine's fleet-scope burn gauges ride the body."""
    p1, p2, rp = (_free_port() for _ in range(3))
    s1 = _serve_app(FakeEngine(reply="alpha"), p1)
    s2 = _serve_app(FakeEngine(reply="beta"), p2)
    m = Metrics()      # shared router+prober registry, as build_router wires
    table = _table([p1, p2], metrics=m).start()
    router = FleetRouter(table, policy="roundrobin", metrics=m)
    rs = _serve_router(router, rp)
    try:
        for conv in range(6):
            status, _raw = _post(rp, _body(conv))
            assert status == 200
        # quiesce, then scrape pods and fleet back-to-back (no traffic
        # in between: the merge must reproduce the pod sums exactly)
        def scrape(port, path="/metrics"):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                return r.read().decode()

        pod1 = fleettrace.parse_exposition(scrape(p1))
        pod2 = fleettrace.parse_exposition(scrape(p2))
        body = scrape(rp, "/metrics/fleet")
        fleet = fleettrace.parse_exposition(body)

        # the scrapes themselves hit each pod's /metrics, so that one
        # route's series keeps moving between our reads — every OTHER
        # series is quiescent and must merge EXACTLY
        def moving(key) -> bool:
            return ("route", "/metrics") in key

        # counters: fleet series == sum of pod series
        fam = "http_requests_total"
        compared = 0
        for key, val in fleet[fam]["series"].items():
            if moving(key):
                continue
            compared += 1
            expect = (pod1.get(fam, {}).get("series", {}).get(key, 0.0)
                      + pod2.get(fam, {}).get("series", {}).get(key, 0.0))
            assert val == expect, (key, val, expect)
        assert compared >= 1
        total = sum(v for k, v in fleet[fam]["series"].items()
                    if not moving(k))
        assert total >= 6.0

        # histograms: bucket-wise cumulative counts add exactly
        fam = "request_seconds"
        assert fleet[fam]["type"] == "histogram"
        for key, h in fleet[fam]["hist"].items():
            if moving(key):
                continue
            h1 = pod1.get(fam, {}).get("hist", {}).get(
                key, {"le": {}, "sum": 0.0, "count": 0.0})
            h2 = pod2.get(fam, {}).get("hist", {}).get(
                key, {"le": {}, "sum": 0.0, "count": 0.0})
            assert h["count"] == h1["count"] + h2["count"]
            assert abs(h["sum"] - (h1["sum"] + h2["sum"])) < 1e-9
            for le, cum in h["le"].items():
                assert cum == (h1["le"].get(le, 0.0)
                               + h2["le"].get(le, 0.0)), (key, le)

        # gauges re-label by peer — never summed
        assert f'queue_depth{{peer="127.0.0.1:{p1}"}}' in body
        assert f'queue_depth{{peer="127.0.0.1:{p2}"}}' in body

        # the fleet-scope SLO verdict rides the same body + /debug/slo
        assert 'slo_burn_rate{' in body and 'scope="fleet"' in body
        doc = _get_json(rp, "/debug/slo")
        assert doc["scope"] == "fleet"
        assert set(doc["peers"]) == {f"127.0.0.1:{p1}",
                                     f"127.0.0.1:{p2}"}
        assert doc["slos"]

        # satellite: the router's OWN /metrics carries the probe-latency
        # histogram, labeled per peer (peers.py observes every round trip)
        own = scrape(rp)
        assert f'fleet_probe_seconds_bucket{{peer="127.0.0.1:{p1}"' in own
        assert "fleet_probe_seconds_count" in own
    finally:
        rs.stop()
        table.stop()
        s1.stop()
        s2.stop()


def test_router_relay_sampled_out_builds_no_spans(monkeypatch):
    """The zero-cost contract at fleet scope: with LFKT_TRACE_SAMPLE=0
    on both sides, a routed request (stream relay included) constructs
    ZERO Span objects in either process — pinned by poisoning the Span
    constructor, the test_obs idiom."""
    p1, rp = _free_port(), _free_port()
    s1 = _serve_app(FakeEngine(reply="alpha"), p1,
                    tracer=Tracer(sample=0.0, ring=4))
    table = _table([p1]).start()
    router = FleetRouter(table, policy="affinity", metrics=Metrics(),
                         tracer=Tracer(sample=0.0, ring=4))
    rs = _serve_router(router, rp)
    try:
        def poisoned(self, *a, **kw):
            raise AssertionError(
                "Span constructed on the sampled-out fleet path")

        monkeypatch.setattr(Span, "__init__", poisoned)
        status, raw = _post(rp, _body(0))
        assert status == 200
        assert json.loads(raw)["response"] == "alpha"
        # and the request id still exists for log joining (a uuid, not
        # a trace id — no tracer allocation behind it)
        req = urllib.request.Request(
            f"http://127.0.0.1:{rp}/response", data=_body(1),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers.get("x-request-id")
    finally:
        rs.stop()
        table.stop()
        s1.stop()

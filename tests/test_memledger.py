"""lfkt-mem: the live HBM memory ledger (ISSUE 10).

Layers:

1. **Registry semantics** — component-catalog enforcement (runtime twin
   of lfkt-lint OBS003), weakref pruning, duplicate-row merging, the
   disarmed stub.
2. **Pressure / fit check** — injected device stats drive the admission
   controller's memory signal and the registry's pre-load refusal.
3. **Engine wiring** — all four engines register their surfaces; the
   continuous scheduler cuts its budget, counts the event and stamps
   in-flight traces on the rising edge of memory pressure.
4. **Acceptance** — on a CPU two-model registry with paging on, the
   /debug/memory component sum matches ``jax.live_arrays()`` ground
   truth within 5%, with the residual line carrying the remainder.
5. **Disarmed cost** — ``LFKT_MEM_LEDGER=0`` takes no locks and
   allocates nothing on the decode path (poisoned-ledger pin, the
   ``LFKT_TRACE_SAMPLE=0`` precedent).
"""

from __future__ import annotations

import asyncio
import gc

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import (
    ContinuousEngine,
    Engine,
    FakeEngine,
    MeshEngine,
    SPEngine,
)
from llama_fastapi_k8s_gpu_tpu.engine.continuous import AdmissionController
from llama_fastapi_k8s_gpu_tpu.obs.memledger import MemLedger
from llama_fastapi_k8s_gpu_tpu.obs.trace import Tracer
from llama_fastapi_k8s_gpu_tpu.serving import ModelRegistry, ModelSpec
from llama_fastapi_k8s_gpu_tpu.serving.registry import WeightBudgetError
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

MSGS = [{"role": "user", "content": "Say something."}]
LEDGER_PATH = "llama_fastapi_k8s_gpu_tpu.obs.memledger.MEMLEDGER"


@pytest.fixture(scope="module")
def model_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("memledger") / "tiny.gguf")
    write_tiny_llama_gguf(path)
    return path


@pytest.fixture(scope="module")
def ggufs(tmp_path_factory):
    d = tmp_path_factory.mktemp("memledger-mm")
    pa, pb = str(d / "a.gguf"), str(d / "b.gguf")
    write_tiny_llama_gguf(pa, seed=0)
    write_tiny_llama_gguf(pb, seed=7)
    return pa, pb


@pytest.fixture()
def ledger(monkeypatch):
    """A fresh armed process ledger: engines built inside the test
    register here (module-level MEMLEDGER is resolved at call time), so
    other modules' long-lived fixture engines never pollute the rows."""
    led = MemLedger(armed=True, pressure_fraction=0.05)
    monkeypatch.setattr(LEDGER_PATH, led)
    return led


class _Owner:
    def __init__(self, name=""):
        self.model_name = name


# ---------------------------------------------------------------------------
# layer 1: registry semantics
# ---------------------------------------------------------------------------

def test_tree_nbytes_counts_physical_shards():
    """Byte providers and the device ground truth must speak the same
    unit — PHYSICAL bytes: a replicated array costs one copy per device
    (what memory_stats sees), a sharded one exactly its pieces.  On a
    multi-chip mesh, logical .nbytes would understate replication and
    drive the residual negative by ~(N-1)/N."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from llama_fastapi_k8s_gpu_tpu.obs.memledger import tree_nbytes

    devs = jax.devices()
    assert len(devs) == 8                     # conftest virtual devices
    mesh = Mesh(np.array(devs), ("d",))
    repl = jax.device_put(jnp.ones((8, 4)),
                          NamedSharding(mesh, PartitionSpec()))
    assert tree_nbytes({"x": repl}) == repl.nbytes * 8
    shard = jax.device_put(jnp.ones((8, 4)),
                           NamedSharding(mesh, PartitionSpec("d")))
    assert tree_nbytes({"x": shard}) == shard.nbytes
    assert tree_nbytes(None) == 0
    assert tree_nbytes({"scalar": 3}) == 0    # non-array leaves are free


def test_unknown_component_and_residual_refused(ledger):
    with pytest.raises(KeyError):
        ledger.register_component("phantom_surface", _Owner(), lambda o: 1)
    with pytest.raises(KeyError):        # computed, never registered
        ledger.register_component("residual", _Owner(), lambda o: 1)


def test_rows_merge_prune_and_model_attribution(ledger):
    a, b = _Owner("m1"), _Owner("m1")
    ledger.register_component("weights", a, lambda o: 100)
    ledger.register_component("weights", a, lambda o: 100)   # idempotent
    ledger.register_component("weights", b, lambda o: 50)
    pool = _Owner("")
    ledger.register_component(
        "kv_arena_used", pool, lambda o: {"alpha": 10, "beta": 0, "": 5})
    ledger.register_component("host_spill", pool, lambda o: 7)
    rows = {(r["component"], r["model"]): r for r in ledger._rows()}
    # same (component, model) merges by summing; zero rows are dropped
    assert rows[("weights", "m1")]["bytes"] == 150
    assert rows[("kv_arena_used", "alpha")]["bytes"] == 10
    assert rows[("kv_arena_used", "")]["bytes"] == 5
    assert ("kv_arena_used", "beta") not in rows
    assert rows[("host_spill", "")]["device"] is False
    # a raising provider is skipped, never raises through telemetry
    bad = _Owner("boom")
    ledger.register_component("kv_ring", bad,
                              lambda o: (_ for _ in ()).throw(ValueError()))
    assert ("kv_ring", "boom") not in {
        (r["component"], r["model"]) for r in ledger._rows()}
    # weakref pruning: a collected owner's rows vanish
    del b
    gc.collect()
    assert {(r["component"], r["model"]): r["bytes"]
            for r in ledger._rows()}[("weights", "m1")] == 100


def test_always_component_reports_zero_not_absence(ledger):
    """kv_arena_free at 0 IS the exhaustion alert: always-components keep
    their row (and gauge series) at zero instead of vanishing into
    'no data' at the exact moment the RUNBOOK triage needs them."""
    pool, eng = _Owner(), _Owner("m")
    ledger.register_component("kv_arena_free", pool, lambda o: 0)
    ledger.register_component("weights", eng, lambda o: 0)
    rows = {(r["component"], r["model"]): r["bytes"]
            for r in ledger._rows()}
    assert rows[("kv_arena_free", "")] == 0      # reported at zero
    assert ("weights", "m") not in rows          # ordinary zero row drops


def test_snapshot_residual_and_disarmed_stub(ledger):
    ledger.stats_fn = lambda: {"bytes_in_use": 1000, "bytes_limit": 4000}
    w, s = _Owner("m"), _Owner()     # weakly held: keep them alive
    ledger.register_component("weights", w, lambda o: 600)
    ledger.register_component("host_spill", s, lambda o: 50)
    doc = ledger.snapshot()
    assert doc["armed"] and doc["schema"] == 1
    assert doc["ground_truth"]["source"] == "device.memory_stats"
    assert doc["attributed_bytes"] == 600        # host tier excluded
    assert doc["host_bytes"] == 50
    assert doc["residual_bytes"] == 400          # truth - attributed
    assert doc["headroom"]["bytes"] == 3000
    assert doc["headroom"]["fraction"] == 0.75
    ledger.configure(armed=False)
    assert ledger.snapshot() == {"schema": 1, "armed": False}


# ---------------------------------------------------------------------------
# layer 2: pressure + fit check
# ---------------------------------------------------------------------------

def test_pressure_thresholds_and_latch(ledger):
    assert ledger.pressure() is False          # CPU: no stats, latches
    ledger.stats_fn = lambda: {"bytes_in_use": 98, "bytes_limit": 100}
    assert ledger.pressure() is True           # 2% free < 5%
    assert ledger.last_headroom == (2, 100)
    ledger.stats_fn = lambda: {"bytes_in_use": 10, "bytes_limit": 100}
    assert ledger.pressure() is False
    ledger.configure(armed=False)
    ledger.stats_fn = lambda: (_ for _ in ()).throw(AssertionError("boom"))
    assert ledger.pressure() is False          # disarmed: never touches it


def test_zero_bytes_in_use_does_not_latch_stats_off(ledger, monkeypatch):
    """A device that reports memory stats with ZERO bytes in use (the
    registry's pre-load fit check runs before the first allocation) must
    not be mistaken for a stat-less backend: only the ABSENCE of the
    field latches, or pressure()/fit_check() would be dead for the
    process lifetime on exactly the hardware they target."""
    monkeypatch.setattr(ledger, "_raw_device_stats",
                        lambda: {"bytes_in_use": 0, "bytes_limit": 100})
    assert ledger.fit_check(500, label="big") is not None   # 500 > 100
    assert ledger._no_device_stats is False
    assert ledger.pressure() is False                       # 100% free
    # a genuinely stat-less backend still latches after one probe
    monkeypatch.setattr(ledger, "_raw_device_stats", lambda: None)
    ledger._no_device_stats = False
    assert ledger._device_stats() == {}
    assert ledger._no_device_stats is True


def test_fit_check_refusal_names_label(ledger):
    assert ledger.fit_check(10**9, label="big") is None   # no stats: pass
    ledger.stats_fn = lambda: {"bytes_in_use": 900, "bytes_limit": 1000}
    assert ledger.fit_check(50, label="small") is None
    msg = ledger.fit_check(500, label="bigmodel")
    assert msg is not None and "bigmodel" in msg and "HBM" in msg
    ledger.configure(armed=False)
    assert ledger.fit_check(500, label="bigmodel") is None


def test_registry_preload_fit_check_refuses(ledger, ggufs):
    """serving/registry.py asks the ledger BEFORE build(): a manifest
    that cannot physically fit refuses without paying the load."""
    pa, pb = ggufs
    ledger.stats_fn = lambda: {"bytes_in_use": 999, "bytes_limit": 1000}
    built = []

    def build(spec, path, shared_pool):      # must never run
        built.append(spec.name)
        raise AssertionError("build ran past a failing fit check")

    with pytest.raises(WeightBudgetError) as ei:
        ModelRegistry.from_specs(
            [ModelSpec("alpha", pa), ModelSpec("beta", pb)], build,
            default_model="alpha")
    assert "alpha" in str(ei.value) and "fit check" in str(ei.value)
    assert built == []


# ---------------------------------------------------------------------------
# layer 3: engine wiring
# ---------------------------------------------------------------------------

def _components(ledger):
    return {(r["component"], r["model"]) for r in ledger._rows()}


def test_all_four_engines_register_surfaces(ledger, model_path):
    eng = Engine(model_path, n_ctx=128, prefill_buckets=(32,))
    name = eng.model_name
    assert {("weights", name), ("kv_ring", name)} <= _components(ledger)

    mesh = MeshEngine(model_path, dp=1, tp=1, batch_size=2, n_ctx=128,
                      decode_chunk=4, prefill_buckets=(32,))
    assert ("kv_lanes", name) in _components(ledger)

    sp = SPEngine(model_path, sp=2, tp=2, n_ctx=128, decode_chunk=4,
                  prefill_buckets=(32,))
    # the sp engine's sharded ring reports its GLOBAL logical bytes
    rows = {(r["component"], r["model"]): r["bytes"]
            for r in ledger._rows()}
    assert rows[("kv_ring", name)] > 0

    cont = ContinuousEngine(model_path, dp=1, tp=1, batch_size=2, n_ctx=128,
                            decode_chunk=4, max_gen_tokens=8,
                            prefill_buckets=(32, 64, 128))
    try:
        comps = _components(ledger)
        assert ("kv_scratch", name) in comps
        assert ("kv_lanes", name) in comps
    finally:
        cont.shutdown()
    del eng, mesh, sp


def test_paged_pool_registers_arena_rows(ledger, model_path):
    eng = Engine(model_path, n_ctx=128, prefill_buckets=(32,),
                 kv_paged=True, kv_page_tokens=16, kv_pool_pages=8)
    out = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=4)
    assert out["usage"]["completion_tokens"] >= 1
    rows = {(r["component"], r["model"]): r["bytes"]
            for r in ledger._rows()}
    assert rows[("kv_arena_used", "")] > 0       # default namespace
    assert rows[("kv_arena_free", "")] > 0
    # used + free == the whole arena, always
    assert rows[("kv_arena_used", "")] + rows[("kv_arena_free", "")] == \
        eng._kvpool.arena_nbytes


def test_admission_controller_mem_pressure_forces_cut():
    ctl = AdmissionController(chunk=64, lanes=4, base=512)
    for _ in range(4):                   # idle lanes: budget grows
        ctl.observe_wave(1, 0.0, 0.1)
    grown = ctl.budget
    assert grown > 512
    # memory pressure cuts EVEN under idle-growth conditions
    assert ctl.observe_wave(1, 0.0, 0.1, mem_pressure=True) == \
        max(grown // 2, 64)
    for _ in range(10):
        ctl.observe_wave(1, 0.0, 0.1, mem_pressure=True)
    assert ctl.budget == 64              # floored at one slice, never 0


def test_continuous_wave_consults_ledger_and_annotates(ledger, model_path):
    """The scheduler passes the ledger's verdict into the controller,
    publishes mem_pressure in scheduler_stats, bumps the cataloged
    counter, and stamps in-flight traces ONCE per rising edge."""
    eng = ContinuousEngine(model_path, dp=1, tp=1, batch_size=2, n_ctx=128,
                           decode_chunk=4, max_gen_tokens=16,
                           prefill_buckets=(32, 64, 128))
    m = Metrics()
    eng.metrics_sink = m
    tracer = Tracer(sample=1.0, ring=8)
    try:
        base_budget = eng._adm_budget
        ledger.stats_fn = lambda: {"bytes_in_use": 99, "bytes_limit": 100}
        tr = tracer.start("request")
        out = eng.create_chat_completion(MSGS, temperature=0.0,
                                         max_tokens=8, trace=tr)
        assert out["usage"]["completion_tokens"] >= 1
        # the post-drain bookkeeping wave may have republished stats with
        # no chunk in flight; the edge detector and the cut budget carry
        # the deterministic evidence
        assert eng._mem_hot_prev is True
        assert eng._adm_budget < base_budget      # cut toward the floor
        snap = m.snapshot()
        assert snap["mem_pressure_events_total"][()] == 1.0  # rising edge
        events = [e for e in tr.root.events if e["name"] == "mem_pressure"]
        assert len(events) == 1
        assert events[0]["headroom_bytes"] == 1
        assert events[0]["limit_bytes"] == 100
        tracer.finish(tr)
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# layer 4: acceptance — two-model paged reconciliation through the server
# ---------------------------------------------------------------------------

@pytest.mark.anyio
async def test_two_model_paged_reconciliation_within_5pct(ledger, ggufs):
    """ISSUE 10 acceptance: CPU two-model registry, paging on — the
    /debug/memory component sum explains the registry's allocations to
    within 5% of jax.live_arrays() ground truth, and the residual line
    carries exactly the remainder (the pre-existing process bytes)."""
    gc.collect()
    before = ledger.ground_truth()
    assert before["source"] == "jax.live_arrays"
    pa, pb = ggufs
    specs = [ModelSpec("alpha", pa), ModelSpec("beta", pb)]

    def build(spec, path, shared_pool):
        return Engine(path, n_ctx=128, prefill_buckets=(32,),
                      kv_paged=True, kv_page_tokens=8, kv_pool_pages=32,
                      kv_pool=shared_pool, kv_namespace=spec.name)

    reg = ModelRegistry.from_specs(specs, build, default_model="alpha")
    # populate the shared arena under BOTH namespaces
    msgs = [{"role": "user", "content": "the quick brown fox jumps over"}]
    for model in ("alpha", "beta"):
        out = reg.create_chat_completion(msgs, model=model,
                                         temperature=0.0, max_tokens=6)
        assert out["usage"]["completion_tokens"] >= 1

    app = create_app(engine=reg)
    transport = httpx.ASGITransport(app=app)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as client:
            doc = (await client.get("/debug/memory")).json()
            metrics = (await client.get("/metrics")).text
        await app.router.shutdown()

    assert doc["armed"] and doc["schema"] == 1
    comps = {(r["component"], r["model"]): r["bytes"]
             for r in doc["components"]}
    # per-model weights AND per-namespace arena attribution
    assert comps[("weights", "alpha")] > 0
    assert comps[("weights", "beta")] > 0
    assert comps[("kv_arena_used", "alpha")] > 0
    assert comps[("kv_arena_used", "beta")] > 0
    # the reconciliation: everything the registry added is attributed
    truth = doc["ground_truth"]
    assert truth["source"] == "jax.live_arrays"
    attributed = doc["attributed_bytes"]
    grown = truth["bytes"] - before["bytes"]
    assert attributed > 0
    assert abs(grown - attributed) / attributed < 0.05, (
        f"ledger explains {attributed} bytes but the process grew "
        f"{grown} (pre-existing {before['bytes']})")
    # the residual line carries the remainder, exactly
    assert doc["residual_bytes"] == truth["bytes"] - attributed
    # fragmentation present for the paged pool
    assert doc["fragmentation"]["largest_free_run"] >= 1
    assert 0.0 <= doc["fragmentation"]["ratio"] <= 1.0
    # and the same rows flow as hbm_bytes gauges at /metrics
    assert 'hbm_bytes{component="weights",model="alpha"}' in metrics
    assert 'hbm_bytes{component="kv_arena_used",model="beta"}' in metrics
    assert 'hbm_bytes{component="residual",model=""}' in metrics


def test_ns_page_counters_match_tree_walk(ledger, ggufs):
    """The ledger's per-namespace page counters are maintained
    incrementally (so a scrape never walks the radix tree under the
    allocation lock); they must agree with a fresh DFS after a workload
    that commits, evicts, spills and restores across two namespaces."""
    pa, pb = ggufs
    ea = Engine(pa, n_ctx=128, prefill_buckets=(32,), kv_paged=True,
                kv_page_tokens=8, kv_pool_pages=12, kv_spill_pages=8,
                kv_namespace="alpha")
    eb = Engine(pb, n_ctx=128, prefill_buckets=(32,), kv_paged=True,
                kv_pool=ea._kvpool, kv_page_tokens=8, kv_namespace="beta")
    pool = ea._kvpool
    prompts = ["the quick brown fox jumps over", "a completely different",
               "yet another conversation about", "and one more for luck"]
    for i, text in enumerate(prompts):      # 12-page pool: forces
        eng = (ea, eb)[i % 2]               # eviction + spill traffic
        eng.create_chat_completion([{"role": "user", "content": text}],
                                   temperature=0.0, max_tokens=4)
    # re-run the first prompt: spill-restore path
    ea.create_chat_completion([{"role": "user", "content": prompts[0]}],
                              temperature=0.0, max_tokens=4)
    fast = pool._ledger_used()
    slow = pool._ledger_used_slow()
    fast.pop("(unindexed)", None)
    assert fast == slow, (fast, slow, pool.stats())
    assert pool.counters["evictions"] > 0    # the workload really churned
    pool.reset()
    assert pool._ledger_used() == {}


def test_pool_fragmentation_math(ledger, model_path):
    eng = Engine(model_path, n_ctx=128, prefill_buckets=(32,),
                 kv_paged=True, kv_page_tokens=16, kv_pool_pages=8)
    pool = eng._kvpool
    with pool._lock:
        pool._free = [0, 1, 2, 5, 7]
    occ = pool.occupancy()
    assert occ["largest_free_run"] == 3
    assert occ["pages_free"] == 5


# ---------------------------------------------------------------------------
# layer 5: per-model token metering (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.anyio
async def test_token_usage_counters_per_model():
    app = create_app(engine=FakeEngine(reply="hey"))
    body = {
        "bot_profile": {"name": "Alice.f",
                        "appearance": "tall,slim,blonde,cats,rain",
                        "system_prompt": "Be brief."},
        "user_profile": {"name": "Bob"},
        "context": [{"turn": "user", "message": "hi"}],
    }
    transport = httpx.ASGITransport(app=app)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as client:
            assert (await client.post("/response",
                                      json=body)).status_code == 200
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 200
            metrics = (await client.get("/metrics")).text
        await app.router.shutdown()
    # both served requests metered under the model label, prompt AND
    # completion sides (FakeEngine reports 1/1 usage per request)
    assert 'tokens_prompt_total{model="fake"} 2' in metrics
    assert 'tokens_generated_total{model="fake"} 2' in metrics


@pytest.mark.anyio
async def test_hbm_gauges_drop_vanished_rows(ledger):
    """The hbm_bytes family is rebuilt whole each scrape: a row whose
    source vanished (collected engine, drained tier) must drop its
    series, not freeze at its last value — stale rows would make the
    component sum exceed ground truth."""
    app = create_app(engine=FakeEngine(reply="ok"))
    owner = _Owner("ghost")
    ledger.register_component("weights", owner, lambda o: 12345)
    transport = httpx.ASGITransport(app=app)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as client:
            first = (await client.get("/metrics")).text
            assert 'hbm_bytes{component="weights",model="ghost"} 12345' \
                in first
            del owner
            gc.collect()
            second = (await client.get("/metrics")).text
            assert 'model="ghost"' not in second
        await app.router.shutdown()


# ---------------------------------------------------------------------------
# layer 6: disarmed cost (poisoned-ledger pin)
# ---------------------------------------------------------------------------

def test_disarmed_decode_path_is_poison_proof(ledger, model_path,
                                              monkeypatch):
    """LFKT_MEM_LEDGER=0: the per-wave pressure consult is ONE attribute
    read returning False — a poisoned ledger (every internal raises)
    must never be touched by a full continuous generation."""
    ledger.configure(armed=False)

    def boom(*a, **kw):
        raise AssertionError("disarmed memory ledger was touched")

    monkeypatch.setattr(ledger, "_device_stats", boom)
    monkeypatch.setattr(ledger, "_rows", boom)
    monkeypatch.setattr(ledger, "ground_truth", boom)
    eng = ContinuousEngine(model_path, dp=1, tp=1, batch_size=2, n_ctx=128,
                           decode_chunk=4, max_gen_tokens=16,
                           prefill_buckets=(32, 64, 128))
    try:
        out = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=8)
        assert out["usage"]["completion_tokens"] >= 1
        assert eng.scheduler_stats()["mem_pressure"] == 0
        assert ledger.snapshot() == {"schema": 1, "armed": False}
    finally:
        eng.shutdown()

"""Mixed-K-quant name promotion (models/params.py).

llama.cpp's Q4_K_M recipe (``use_more_bits``) puts roughly half the
ffn_down layers on Q6_K and the rest on Q4_K.  Stacked-scan params need one
layout per name, so a mixed name must be PROMOTED to the highest K-quant
present (minority layers requantized onto the finer grid) rather than
dropped to the int8 per-row fallback.
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp
import numpy as np

from llama_fastapi_k8s_gpu_tpu.gguf import GGMLType, GGUFFile, GGUFWriter
from llama_fastapi_k8s_gpu_tpu.gguf.quants import dequant_q4_k, quant_q4_k
from llama_fastapi_k8s_gpu_tpu.models.params import load_params
from llama_fastapi_k8s_gpu_tpu.testing import (
    TINY_CFG,
    byte_vocab_with_specials,
    write_llama_gguf_meta,
)

# ffn_dim=2048 makes ffn_down (dim, 2048) the one fused-compatible linear
# on the CPU interpret grid (K % 2048 == 0, N % 8 == 0)
CFG = dataclasses.replace(TINY_CFG, ffn_dim=2048, n_layers=2)


def _write_mixed_gguf(path: str, rng) -> np.ndarray:
    tokens, types = byte_vocab_with_specials()
    cfg = dataclasses.replace(CFG, vocab_size=len(tokens))
    w = GGUFWriter(path)
    write_llama_gguf_meta(w, cfg, tokens, types)
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    scale = cfg.dim ** -0.5

    def t(name, shape, gtype):
        w.add_tensor(name, rng.standard_normal(shape).astype(np.float32) * scale,
                     gtype)

    t("token_embd.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    down = rng.standard_normal((2, cfg.dim, cfg.ffn_dim)).astype(np.float32) * 0.05
    for i in range(cfg.n_layers):
        p = f"blk.{i}."
        t(p + "attn_norm.weight", (cfg.dim,), GGMLType.F32)
        t(p + "attn_q.weight", (cfg.dim, cfg.dim), GGMLType.Q8_0)
        t(p + "attn_k.weight", (kv_dim, cfg.dim), GGMLType.Q8_0)
        t(p + "attn_v.weight", (kv_dim, cfg.dim), GGMLType.Q8_0)
        t(p + "attn_output.weight", (cfg.dim, cfg.dim), GGMLType.Q8_0)
        t(p + "ffn_norm.weight", (cfg.dim,), GGMLType.F32)
        t(p + "ffn_gate.weight", (cfg.ffn_dim, cfg.dim), GGMLType.Q8_0)
        t(p + "ffn_up.weight", (cfg.ffn_dim, cfg.dim), GGMLType.Q8_0)
        # the mixed name: layer 0 Q4_K, layer 1 Q6_K
        w.add_tensor(p + "ffn_down.weight", down[i],
                     GGMLType.Q4_K if i == 0 else GGMLType.Q6_K)
    t("output_norm.weight", (cfg.dim,), GGMLType.F32)
    t("output.weight", (cfg.vocab_size, cfg.dim), GGMLType.F16)
    w.write()
    return down


def test_mixed_kquant_name_promotes_to_q6k(tmp_path):
    rng = np.random.default_rng(3)
    path = os.path.join(tmp_path, "mixed.gguf")
    down = _write_mixed_gguf(path, rng)
    gf = GGUFFile(path)
    params = load_params(gf, CFG, fmt="q4k", on_device=False)

    wd = params["layers"]["w_down"]
    assert sorted(wd) == ["q2", "q4", "sm6"], (
        "mixed Q4_K/Q6_K ffn_down must promote to the fused Q6_K layout, "
        f"got keys {sorted(wd)}")
    L, n, half = wd["q4"].shape
    assert (L, n, half) == (2, CFG.dim, CFG.ffn_dim // 2)

    # numeric: the promoted (requantized) layer-0 matmul must match the
    # Q4_K-dequantized original within the small Q6 regrid error; layer 1
    # (native Q6_K) must match its own file values the same way
    from llama_fastapi_k8s_gpu_tpu.gguf.quants import dequant_q6_k, quant_q6_k
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.q6matmul import q6k_matmul

    x = jnp.asarray(rng.standard_normal((2, CFG.ffn_dim)), jnp.bfloat16)
    for layer, codec_ref in ((0, dequant_q4_k(quant_q4_k(down[0].reshape(-1)),
                                              down[0].size)),
                             (1, dequant_q6_k(quant_q6_k(down[1].reshape(-1)),
                                              down[1].size))):
        w_layer = {k: v[layer] for k, v in wd.items()}
        got = np.asarray(q6k_matmul(x, w_layer, interpret=True),
                         dtype=np.float32)
        ref_w = codec_ref.reshape(CFG.dim, CFG.ffn_dim)
        want = np.asarray(x, np.float32) @ ref_w.T
        denom = np.maximum(np.abs(want).max(), 1e-6)
        assert np.abs(got - want).max() / denom < 0.05, (
            f"layer {layer}: promoted matmul deviates from file values")

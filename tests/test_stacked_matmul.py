"""Stacked (scalar-prefetch) fused matmuls vs their unstacked oracles.

The model addresses layer ``i`` of stacked (L, ...) fused weights with
``ops.linear.linear_at`` → ``*_matmul_stacked`` (scalar-prefetch BlockSpec
indexing) instead of slicing per layer — slicing would materialize a copy
of every layer's quantized planes before each pallas_call (measured
+6.3 ms/token on 8B v5e decode, tools/decode_breakdown.py).  These tests
pin: (a) stacked == unstacked for every layer and every fused format,
(b) the decode-loop shape (jit + lax.scan over layer ids), and (c) the
GSPMD rule — tp-sharded stacked weights compute locally and match.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from llama_fastapi_k8s_gpu_tpu.ops.linear import (
    linear,
    linear_at,
    make_linear_int8,
    make_linear_q4k,
    make_linear_q5k,
    make_linear_q6k,
    make_linear_q8,
)
from llama_fastapi_k8s_gpu_tpu.parallel.mesh import make_mesh

MAKERS = {
    "q4k": make_linear_q4k,
    "q5k": make_linear_q5k,
    "q6k": make_linear_q6k,
    "q8": make_linear_q8,
    "int8": make_linear_int8,
}


def _stack(ws):
    return jax.tree_util.tree_map(lambda *a: jnp.stack(a), *ws)


@pytest.mark.parametrize("fmt", list(MAKERS))
def test_stacked_matches_unstacked_per_layer(fmt):
    rng = np.random.default_rng(7)
    L, n, k = 3, 16, 2048
    ws = [MAKERS[fmt](rng.standard_normal((n, k)).astype(np.float32) * 0.02)
          for _ in range(L)]
    stacked = _stack(ws)
    x = jnp.asarray(rng.standard_normal((2, k)), jnp.bfloat16)
    for i in range(L):
        ref = np.asarray(linear(x, ws[i]).astype(jnp.float32))
        got = np.asarray(
            linear_at(x, stacked, jnp.int32(i)).astype(jnp.float32))
        np.testing.assert_allclose(got, ref, rtol=1e-3,
                                   atol=1e-3 * (np.abs(ref).max() + 1e-6))


def test_stacked_under_jit_scan_layer_ids():
    """The model's decode-loop shape: scan over layer ids, weights closed
    over (models/llama.py forward)."""
    rng = np.random.default_rng(8)
    L, n, k = 4, 8, 2048
    ws = [make_linear_q4k(
        rng.standard_normal((n, k)).astype(np.float32) * 0.02)
        for _ in range(L)]
    stacked = _stack(ws)
    x = jnp.asarray(rng.standard_normal((1, k)), jnp.bfloat16)

    @jax.jit
    def f(stacked, x):
        def step(carry, i):
            return carry, linear_at(carry, stacked, i)

        _, ys = jax.lax.scan(step, x, jnp.arange(L, dtype=jnp.int32))
        return ys

    ys = f(stacked, x)
    assert ys.shape == (L, 1, n)
    for i in range(L):
        ref = np.asarray(linear(x, ws[i]).astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(ys[i].astype(jnp.float32)), ref, rtol=1e-3,
            atol=1e-3 * (np.abs(ref).max() + 1e-6))


_PLANE_SPEC = {
    # quantized value planes (L, N, K/x) → N on tp
    "qs": P(None, "tp", None), "q5s": P(None, "tp", None),
    "q5h": P(None, "tp", None), "q5p": P(None, "tp", None),
    "q4": P(None, "tp", None), "q6p": P(None, "tp", None),
    "q2": P(None, "tp", None), "q8": P(None, "tp", None),
    # scale planes (L, kt, N, 128) → N on tp
    "sm": P(None, None, "tp", None), "sm5": P(None, None, "tp", None),
    "sm6": P(None, None, "tp", None), "sm8": P(None, None, "tp", None),
}


@pytest.mark.parametrize("fmt", ["q4k", "q5k", "q6k", "q8"])
def test_vmapped_fused_matmul(fmt):
    """The mesh-batched/continuous engines vmap the model over lanes with
    SHARED fused weights (parallel/batched.py).  custom_partitioning has no
    batching rule in JAX, so without the rows_vmappable custom_vmap rule
    this raised ``NotImplementedError: Batching rule for
    'custom_partitioning' not implemented`` — first seen on hardware,
    because CPU tests' tiny dims always fell back to int8."""
    rng = np.random.default_rng(11)
    L, n, k, lanes = 2, 16, 2048, 3
    ws = [MAKERS[fmt](rng.standard_normal((n, k)).astype(np.float32) * 0.02)
          for _ in range(L)]
    xs = jnp.asarray(rng.standard_normal((lanes, 2, k)), jnp.bfloat16)

    got = jax.vmap(lambda x: linear(x, ws[0]))(xs)
    for b in range(lanes):
        ref = np.asarray(linear(xs[b], ws[0]).astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(got[b].astype(jnp.float32)), ref, rtol=1e-3,
            atol=1e-3 * (np.abs(ref).max() + 1e-6))

    stacked = _stack(ws)
    got = jax.vmap(lambda x: linear_at(x, stacked, jnp.int32(1)))(xs)
    for b in range(lanes):
        ref = np.asarray(linear(xs[b], ws[1]).astype(jnp.float32))
        np.testing.assert_allclose(
            np.asarray(got[b].astype(jnp.float32)), ref, rtol=1e-3,
            atol=1e-3 * (np.abs(ref).max() + 1e-6))


def test_vmapped_fused_matmul_rejects_batched_weights():
    """The rows_vmappable rule only supports a batched activation operand;
    batching the weights (no engine does this) must raise loudly rather
    than silently compute against the wrong layout."""
    rng = np.random.default_rng(12)
    n, k, lanes = 16, 2048, 2
    ws = [make_linear_q4k(
        rng.standard_normal((n, k)).astype(np.float32) * 0.02)
        for _ in range(lanes)]
    wb = _stack(ws)   # leading dim = lanes, used as a vmap axis below
    x = jnp.asarray(rng.standard_normal((1, k)), jnp.bfloat16)
    with pytest.raises(Exception, match="activation operand|batch"):
        jax.vmap(lambda w: linear(x, w))(wb)


@pytest.mark.parametrize("fmt", ["q4k", "q5k", "q6k", "q8"])
def test_stacked_partitioned_matches_unsharded(fmt):
    rng = np.random.default_rng(9)
    L, n, k = 2, 256, 2048
    ws = [MAKERS[fmt](rng.standard_normal((n, k)).astype(np.float32)
                      * k ** -0.5) for _ in range(L)]
    stacked = _stack(ws)
    x = jnp.asarray(rng.standard_normal((3, k)), jnp.bfloat16)
    ref = np.asarray(linear(x, ws[1]).astype(jnp.float32))

    mesh = make_mesh(dp=1, tp=2)
    sharded = {
        key: jax.device_put(v, NamedSharding(mesh, _PLANE_SPEC[key]))
        for key, v in stacked.items()
    }
    got = jax.jit(linear_at)(x, sharded, jnp.int32(1))
    np.testing.assert_allclose(np.asarray(got.astype(jnp.float32)), ref,
                               rtol=2e-2, atol=2e-2 * np.abs(ref).max())

"""Live manifest reload (ISSUE 14; serving/registry.py
``reload_manifest`` + parallel/kvpool.py ``drain_namespace`` +
``POST /admin/models/reload``).

The correctness contract, each leg pinned here:

- add-model under budget: loads, warms, turns routable, rows state
  ``ready``;
- ``WeightBudgetError`` refusal leaves the running set (and its rows)
  untouched — no half-loaded fleet;
- remove-model drains its radix namespace to ZERO pages through the
  pool's drain path with no cross-namespace eviction storm (the
  surviving tenant's warm pages are untouched, the eviction counter
  does not move);
- in-flight requests on a removed model finish; new ones 400 cleanly
  with the live model list;
- ``/v1/models`` and ``/health`` track the live set through the
  transition (``loading|ready|draining`` states).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import Engine, FakeEngine
from llama_fastapi_k8s_gpu_tpu.models.config import ModelConfig
from llama_fastapi_k8s_gpu_tpu.parallel.kvpool import KVPool
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.serving import (
    ModelRegistry,
    UnknownModelError,
    WeightBudgetError,
    parse_manifest,
)
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.config import Settings

from tests.test_kvpool import CFG, T, marked_ring

MSGS = [{"role": "user", "content": "The quick brown fox jumps over the "
                                    "lazy dog near the old riverbank "
                                    "while autumn leaves drift down."}]


@pytest.fixture(scope="module")
def ggufs(tmp_path_factory):
    d = tmp_path_factory.mktemp("reload")
    paths = {}
    for name, seed in (("a", 0), ("b", 7), ("c", 13)):
        p = str(d / f"{name}.gguf")
        write_tiny_llama_gguf(p, seed=seed)
        paths[name] = p
    return paths


def _build(spec, path, shared_pool):
    """The serial-engine twin of server/app.py's registry build: paged,
    shared pool, per-model namespace."""
    return Engine(path, n_ctx=256, prefill_buckets=(64, 128),
                  max_gen_tokens=8, decode_chunk=4, kv_paged=True,
                  kv_page_tokens=16, kv_pool=shared_pool,
                  kv_namespace=spec.name)


def _registry(ggufs, names=("alpha", "beta"), budget_bytes=0):
    manifest = ",".join(f"{n}={ggufs[p]}" for n, p in
                        zip(names, ("a", "b", "c")))
    specs = parse_manifest(manifest)
    return ModelRegistry.from_specs(
        specs, _build, default_model=names[0],
        weight_budget_bytes=budget_bytes)


def _greedy(reg, model, n=8):
    out = reg.create_chat_completion(MSGS, max_tokens=n, temperature=0.0,
                                     model=model)
    return out["choices"][0]["message"]["content"]


# ---------------------------------------------------------------------------
# the pool-level drain primitive
# ---------------------------------------------------------------------------

def test_drain_namespace_pinned_then_released():
    """drain_namespace frees everything unpinned, reports the pinned
    remainder, and converges to zero once the lease releases — without
    touching the OTHER namespace."""
    pool = KVPool(CFG, page_tokens=T, n_pages=8)
    ring = marked_ring()
    ids = list(range(1, 25))                  # 3 pages
    assert pool.commit(ids, ring, namespace="doomed") == 3
    assert pool.commit(list(range(100, 117)), ring,
                       namespace="survivor") == 2
    lease = pool.acquire(ids, 16, namespace="doomed")     # pin 2 pages
    assert lease is not None

    remaining = pool.drain_namespace("doomed")
    # the lease pins 2 of the node's 3 pages; a partially pinned node
    # holds ALL its pages until the lease releases (pages are freed per
    # node, never torn out from under a restore)
    assert remaining == 3
    assert pool._ns_pages.get("survivor") == 2   # untouched
    assert pool.counters["evictions"] == 0    # drain is NOT eviction

    pool.release(lease)
    assert pool.drain_namespace("doomed") == 0
    assert "doomed" not in pool._roots
    assert pool._ns_pages.get("doomed") is None
    # survivor still fully matchable, its bytes never moved
    assert pool.match_len(list(range(100, 117)),
                          namespace="survivor") == 16
    assert pool.counters["drained_pages"] == 3
    # the freed pages are genuinely reusable
    assert pool.occupancy()["pages_free"] == 8 - 2
    assert pool.occupancy()["pages_pinned"] == 0


def test_drain_namespace_spilled_and_absent():
    pool = KVPool(CFG, page_tokens=T, n_pages=4, spill_pages=8)
    ring = marked_ring()
    pool.commit(list(range(1, 33)), ring, namespace="ns")   # fill arena
    # force a spill by committing another namespace's pages
    pool.commit(list(range(200, 217)), ring, namespace="other")
    assert pool.counters["spills"] >= 1
    assert pool.drain_namespace("ns") == 0    # spilled nodes drop too
    assert "ns" not in pool._roots
    assert pool.drain_namespace("never-existed") == 0


# ---------------------------------------------------------------------------
# registry reload: add / refuse / remove
# ---------------------------------------------------------------------------

def test_reload_add_under_budget(ggufs):
    reg = _registry(ggufs)
    try:
        text_a = _greedy(reg, "alpha")
        doc = reg.reload_manifest(
            f"alpha={ggufs['a']},beta={ggufs['b']},gamma={ggufs['c']}")
        assert doc["added"] == ["gamma"]
        assert doc["removed"] == []
        assert reg.model_names() == ["alpha", "beta", "gamma"]
        rows = {r["name"]: r for r in reg.models()}
        assert rows["gamma"]["state"] == "ready"
        assert rows["gamma"]["weight_bytes"] > 0
        # the new model serves; the old ones are bit-unchanged
        assert _greedy(reg, "gamma")
        assert _greedy(reg, "alpha") == text_a
        # the new engine joined the SHARED pool under its own namespace
        assert len(reg._pools()) == 1
    finally:
        reg.shutdown()


def test_reload_budget_refusal_leaves_running_set_untouched(ggufs):
    reg = _registry(ggufs)
    try:
        # budget: just what alpha+beta already use — gamma cannot fit
        budget = sum(r["weight_bytes"] for r in reg.models()) + 1
        reg._weight_budget_bytes = budget
        with pytest.raises(WeightBudgetError, match="gamma"):
            reg.reload_manifest(
                f"alpha={ggufs['a']},beta={ggufs['b']},gamma={ggufs['c']}")
        assert reg.model_names() == ["alpha", "beta"]
        rows = {r["name"]: r for r in reg.models()}
        assert set(rows) == {"alpha", "beta"}   # no leftover loading row
        assert all(r["state"] == "ready" for r in rows.values())
        assert _greedy(reg, "alpha")                # still serving
    finally:
        reg.shutdown()


def test_reload_remove_drains_namespace_to_zero_no_storm(ggufs):
    reg = _registry(ggufs)
    try:
        # serve traffic on BOTH models so both namespaces hold pages
        _greedy(reg, "alpha")
        _greedy(reg, "beta")
        pool = reg._pools()[0]
        alpha_pages = pool._ns_pages.get("alpha", 0)
        beta_pages = pool._ns_pages.get("beta", 0)
        assert alpha_pages > 0 and beta_pages > 0
        evictions_before = pool.counters["evictions"]

        doc = reg.reload_manifest(f"alpha={ggufs['a']}")
        assert [r["name"] for r in doc["removed"]] == ["beta"]
        assert doc["removed"][0]["pages_remaining"] == 0

        # beta's namespace drained to zero pages, nothing pinned behind
        assert pool._ns_pages.get("beta") is None
        assert "beta" not in pool._roots
        assert pool.occupancy()["pages_pinned"] == 0
        # ... with NO cross-namespace eviction storm: alpha's warm pages
        # are exactly where they were and the eviction counter never moved
        assert pool._ns_pages.get("alpha", 0) == alpha_pages
        assert pool.counters["evictions"] == evictions_before
        assert pool.counters["drained_pages"] >= beta_pages

        # routing reflects the removal
        assert reg.model_names() == ["alpha"]
        with pytest.raises(UnknownModelError):
            reg.resolve("beta")
        # alpha still warm: the same prompt reuses its cached prefix
        out = reg.create_chat_completion(MSGS, max_tokens=8,
                                         temperature=0.0, model="alpha")
        assert out["lfkt_timings"].get("prefix_reused_tokens", 0) > 0
    finally:
        reg.shutdown()


def test_reload_default_reresolves_and_changed_spec_refused(ggufs):
    reg = _registry(ggufs)
    try:
        # removing the default alias re-resolves to the new manifest's
        # first entry
        doc = reg.reload_manifest(f"beta={ggufs['b']}")
        assert doc["default_model"] == "beta"
        assert reg.resolve(None).model_name == "beta"

        # changing a KEPT model's spec in place is refused with
        # attribution, set untouched
        with pytest.raises(ValueError, match="beta"):
            reg.reload_manifest(f"beta={ggufs['b']}:n_ctx=128")
        assert reg.model_names() == ["beta"]
    finally:
        reg.shutdown()


def test_reload_inflight_requests_finish_before_release():
    """A removed model's in-flight request completes; the reload blocks
    on it (bounded) and only then releases the engine."""
    slow = FakeEngine(reply="slow-done", delay=0.6)
    reg = ModelRegistry({"alpha": FakeEngine(reply="a"), "beta": slow},
                        "alpha")
    results = {}

    def call():
        results["beta"] = reg.create_chat_completion(
            [{"role": "user", "content": "hi"}], model="beta")

    th = threading.Thread(target=call)
    th.start()
    time.sleep(0.15)                       # the request is in flight
    assert reg.inflight("beta") == 1
    t0 = time.time()
    doc = reg.reload_manifest("alpha=whatever.gguf")
    wall = time.time() - t0
    th.join(timeout=5)
    # reload waited for the in-flight request (not a fixed sleep: the
    # 0.6 s generation minus the 0.15 s head start bounds it below)
    assert wall >= 0.3
    assert doc["removed"][0]["inflight_at_release"] == 0
    assert results["beta"]["choices"][0]["message"]["content"] \
        == "slow-done"
    with pytest.raises(UnknownModelError):
        reg.resolve("beta")


def test_reload_warmup_failure_unwinds_everything():
    """A warmup (compile) failure during reload behaves exactly like a
    budget refusal: EVERY engine this reload built is released — the one
    that failed AND earlier successes — no loading row survives, and the
    running set is untouched."""
    built = {}

    class _Eng:
        def __init__(self, name, explode):
            self.model_name = name
            self.weight_bytes = 10
            self._explode = explode
            self.shutdowns = 0

        def warmup(self):
            if self._explode:
                raise RuntimeError("compile boom")

        def create_chat_completion(self, *a, **kw):
            return {"choices": []}

        def shutdown(self):
            self.shutdowns += 1

    def build(spec, path, pool):
        e = _Eng(spec.name, explode=(spec.name == "bad"))
        built[spec.name] = e
        return e

    reg = ModelRegistry.from_specs(parse_manifest("alpha=x.gguf"), build,
                                   default_model="alpha")
    with pytest.raises(RuntimeError, match="compile boom"):
        reg.reload_manifest("alpha=x.gguf,good=y.gguf,bad=z.gguf")
    assert reg.model_names() == ["alpha"]
    assert {r["name"] for r in reg.models()} == {"alpha"}
    assert built["good"].shutdowns == 1      # installed nothing, leaked
    assert built["bad"].shutdowns == 1       # ... nothing


def test_reload_without_build_cannot_add():
    reg = ModelRegistry({"alpha": FakeEngine()}, "alpha")
    with pytest.raises(ValueError, match="cannot load new ones"):
        reg.reload_manifest("alpha=x.gguf,newbie=y.gguf")
    # remove-only works without a builder (test above) and no-op reloads
    # are clean
    doc = reg.reload_manifest("alpha=x.gguf")
    assert doc["added"] == [] and doc["removed"] == []


# ---------------------------------------------------------------------------
# the HTTP surface: POST /admin/models/reload + /v1/models tracking
# ---------------------------------------------------------------------------

def _client(engine, **settings_kw):
    settings_kw.setdefault("watchdog", False)
    app = create_app(engine=engine, settings=Settings(**settings_kw))
    return app, httpx.ASGITransport(app=app)


@pytest.mark.anyio
async def test_admin_reload_route_roundtrip(ggufs):
    reg = _registry(ggufs)
    app, transport = _client(reg)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t",
                                     timeout=300.0) as c:
            r = await c.get("/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["alpha",
                                                           "beta"]
            # add gamma + drop beta in one reload
            r = await c.post("/admin/models/reload", json={
                "models": f"alpha={ggufs['a']},gamma={ggufs['c']}"})
            assert r.status_code == 200, r.text
            doc = r.json()
            assert doc["added"] == ["gamma"]
            assert [x["name"] for x in doc["removed"]] == ["beta"]
            # /v1/models tracks the live set
            r = await c.get("/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["alpha",
                                                           "gamma"]
            # /health rows carry the states
            h = await c.get("/health")
            rows = h.json()["engine"]["models"]
            assert {x["name"]: x["state"] for x in rows} == {
                "alpha": "ready", "gamma": "ready"}
            # traffic on the removed alias 400s cleanly, naming the set
            r = await c.post("/v1/chat/completions", json={
                "model": "beta", "max_tokens": 4,
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 400
            body = r.json()
            assert body["error"]["code"] == "model_not_found"
            # the new model actually serves through the facade
            r = await c.post("/v1/chat/completions", json={
                "model": "gamma", "max_tokens": 4, "temperature": 0.0,
                "messages": [{"role": "user", "content": "hi"}]})
            assert r.status_code == 200
            # budget refusal -> 409, set untouched
            reg._weight_budget_bytes = 1
            r = await c.post("/admin/models/reload", json={
                "models": (f"alpha={ggufs['a']},gamma={ggufs['c']},"
                           f"beta={ggufs['b']}")})
            assert r.status_code == 409
            assert "budget" in r.json()["detail"]
            r = await c.get("/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["alpha",
                                                           "gamma"]
            # bad grammar -> 400
            r = await c.post("/admin/models/reload",
                             json={"models": "no-path-here"})
            assert r.status_code == 400
        await app.router.shutdown()
    reg.shutdown()


def test_resolved_path_contains_relative_paths(tmp_path):
    """ModelSpec.resolved_path: relative manifest paths must stay under
    the model dir after symlink/..-resolution; absolute paths are the
    operator's explicit choice and pass through."""
    from llama_fastapi_k8s_gpu_tpu.serving.manifest import ModelSpec

    d = tmp_path / "models"
    d.mkdir()
    (d / "ok.gguf").write_bytes(b"x")
    assert ModelSpec("ok", "ok.gguf").resolved_path(str(d)) == str(
        d / "ok.gguf")
    assert ModelSpec("abs", str(d / "ok.gguf")).resolved_path(
        "elsewhere") == str(d / "ok.gguf")
    with pytest.raises(ValueError, match="escapes the"):
        ModelSpec("evil", "../../etc/passwd").resolved_path(str(d))
    with pytest.raises(ValueError, match="escapes the"):
        ModelSpec("dot", "sub/../../outside.gguf").resolved_path(str(d))


@pytest.mark.anyio
async def test_admin_reload_rejects_path_traversal(ggufs):
    """The fix's acceptance pin, through the REAL route: a POSTed
    manifest whose relative path climbs out of the model dir gets a 400
    naming the escape, and the running set is untouched."""
    reg = _registry(ggufs)
    app, transport = _client(reg)
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t",
                                     timeout=300.0) as c:
            r = await c.post("/admin/models/reload", json={
                "models": (f"alpha={ggufs['a']},"
                           "evil=../../../../etc/passwd")})
            assert r.status_code == 400, r.text
            assert "escapes the" in r.json()["detail"]
            r = await c.get("/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["alpha",
                                                           "beta"]
        await app.router.shutdown()
    reg.shutdown()


@pytest.mark.anyio
async def test_admin_reload_refused_on_single_engine():
    app, transport = _client(FakeEngine())
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://t") as c:
            r = await c.post("/admin/models/reload",
                             json={"models": "a=x.gguf"})
            assert r.status_code == 400
            assert "LFKT_MODELS" in r.json()["detail"]
        await app.router.shutdown()


def test_reload_metrics_emitted(ggufs):
    """model_reloads_total{action} rides the injected metrics sink."""
    from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

    reg = _registry(ggufs)
    m = Metrics()
    reg.metrics_sink = m
    try:
        reg.reload_manifest(
            f"alpha={ggufs['a']},beta={ggufs['b']},gamma={ggufs['c']}")
        reg.reload_manifest(f"alpha={ggufs['a']}")
        reg._weight_budget_bytes = 1
        with pytest.raises(WeightBudgetError):
            reg.reload_manifest(f"alpha={ggufs['a']},beta={ggufs['b']}")
        text = m.render()
        assert 'model_reloads_total{action="add"} 1' in text
        assert 'model_reloads_total{action="remove"} 2' in text
        assert 'model_reloads_total{action="refused"} 1' in text
    finally:
        reg.shutdown()

"""LFKT_KV_PAGED=1 serving contracts (parallel/kvpool.py).

The load-bearing invariant mirrors the chunked-prefill rollout (PR 5):
paging changes WHERE prefix KV comes from, never WHAT a greedy request
produces.  With no cache hit the paged engines dispatch exactly the
dense-ring programs, so greedy decode is bit-identical on all four
engine flavors — pinned here against a dense serial reference.  On top
of that: radix reuse across turns and across conversations sharing a
system prompt, cross-lane reuse on the continuous scheduler, explicit
seeds bypassing reuse (the reproducibility contract), pool-exhaustion
backpressure at the engine level, and watchdog-recovery pool reset.
"""

from __future__ import annotations

import pytest

from llama_fastapi_k8s_gpu_tpu.engine import (
    ContinuousEngine,
    Engine,
    MeshEngine,
    SPEngine,
)
from llama_fastapi_k8s_gpu_tpu.models.config import ModelConfig
from llama_fastapi_k8s_gpu_tpu.testing import TINY_CFG, write_tiny_llama_gguf

BUCKETS = (32, 64, 128)

#: distinct prompts (only the few-token chat-template header is shared —
#: under a full page, so the radix index can never grant them reuse and
#: parity compares identical dispatch sequences)
PROMPTS = [
    [{"role": "user", "content": "Say something."}],
    [{"role": "user", "content": "alpha bravo charlie delta echo " * 4}],
    [{"role": "user", "content": "one two three four five six seven " * 8}],
]

#: the paged configuration under test: 16-token pages, a 64-page pool
#: (2 full 512-token contexts), a 16-page host spill tier
PAGED_KW = dict(kv_paged=True, kv_page_tokens=16, kv_pool_pages=64,
                kv_spill_pages=16, prefix_min=16)
BASE_KW = dict(n_ctx=512, decode_chunk=4, max_gen_tokens=16,
               prefill_buckets=BUCKETS, prefill_chunk=16, prefill_overlap=2)


@pytest.fixture(scope="module")
def model_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("model") / "tiny.gguf")
    write_tiny_llama_gguf(path, cfg=ModelConfig(
        **{**TINY_CFG.__dict__, "n_ctx": 512}))
    return path


def _texts(eng, prompts=PROMPTS, max_tokens=8):
    out = []
    for p in prompts:
        r = eng.create_chat_completion(p, temperature=0.0,
                                       max_tokens=max_tokens)
        assert r["lfkt_timings"]["prefix_reused_tokens"] == 0, \
            "distinct prompts must not hit the prefix cache"
        out.append(r["choices"][0]["message"]["content"])
    return out


@pytest.fixture(scope="module")
def dense_texts(model_path):
    """The reference outputs: serial engine, dense ring, no reuse."""
    eng = Engine(model_path, prefix_cache=False, **BASE_KW)
    return _texts(eng)


def _convo(turn2: str = "And another one."):
    msgs = [{"role": "system", "content": "You answer carefully. " * 8},
            {"role": "user", "content": "Tell me something interesting."}]
    return msgs, turn2


# ---------------------------------------------------------------------------
# paged-vs-dense greedy bit-parity, all four engines
# ---------------------------------------------------------------------------

def test_serial_paged_matches_dense(model_path, dense_texts):
    eng = Engine(model_path, **BASE_KW, **PAGED_KW)
    assert eng._kv_paged and eng._prefix_cache is False
    assert _texts(eng) == dense_texts
    # misses were counted (the index WAS consulted), commits banked pages
    stats = eng._kvpool.stats()
    assert stats["misses"] >= len(PROMPTS) - 1
    assert stats["stored_pages"] > 0


def test_mesh_paged_matches_dense(model_path, dense_texts):
    """MeshEngine under paging: the serial (stream) path consults the
    radix index; the batched-cycle path keeps its lane rings untouched —
    both must stay greedy-identical to the dense serial reference."""
    eng = MeshEngine(model_path, dp=2, tp=2, batch_size=2,
                     **BASE_KW, **PAGED_KW)
    assert _texts(eng) == dense_texts
    got = [eng.create_chat_completions([p], temperature=0.0, max_tokens=8)[0]
           ["choices"][0]["message"]["content"] for p in PROMPTS]
    assert got == dense_texts


def test_continuous_paged_matches_dense(model_path, dense_texts):
    eng = ContinuousEngine(model_path, dp=1, tp=1, batch_size=2,
                           **BASE_KW, **PAGED_KW)
    try:
        assert eng._lane_prefix is False       # folded behind the radix
        assert _texts(eng) == dense_texts
    finally:
        eng.shutdown()


def test_sp_paged_gates_off_and_matches(model_path, dense_texts):
    """SPEngine shards the ring's n_ctx dim: paging must gate itself off
    (with attribution) and serve the identical dense path."""
    eng = SPEngine(model_path, sp=2, tp=1, prefix_cache=False,
                   **BASE_KW, **PAGED_KW)
    assert eng._kv_paged is False and eng._kvpool is None
    assert _texts(eng) == dense_texts


# ---------------------------------------------------------------------------
# radix reuse behavior (serial)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def paged_serial(model_path):
    return Engine(model_path, **BASE_KW, **PAGED_KW)


def test_serial_multi_turn_resumes_from_pages(paged_serial):
    eng = paged_serial
    msgs, turn2 = _convo()
    t1 = eng.create_chat_completion(msgs, temperature=0.0, max_tokens=8)
    assert t1["lfkt_timings"]["prefix_reused_tokens"] == 0
    msgs2 = msgs + [
        {"role": "assistant",
         "content": t1["choices"][0]["message"]["content"]},
        {"role": "user", "content": turn2}]
    t2 = eng.create_chat_completion(msgs2, temperature=0.0, max_tokens=8)
    reused = t2["lfkt_timings"]["prefix_reused_tokens"]
    assert reused > 0
    assert reused % eng._kvpool.page_tokens == 0   # page-aligned restore
    assert t2["choices"][0]["message"]["content"]
    assert eng._kvpool.stats()["hits"] >= 1
    assert eng._kvpool.occupancy()["pages_pinned"] == 0   # lease released


def test_shared_system_prompt_across_conversations(paged_serial):
    """The headline behavior the per-request claim could never give: a
    DIFFERENT conversation with the same system prompt reuses its pages
    — the system prompt prefills once per process."""
    eng = paged_serial
    sys_msg = {"role": "system", "content": "Be brief and precise. " * 10}
    a = [sys_msg, {"role": "user", "content": "First question here."}]
    b = [sys_msg, {"role": "user", "content": "Unrelated other ask."}]
    ra = eng.create_chat_completion(a, temperature=0.0, max_tokens=8)
    rb = eng.create_chat_completion(b, temperature=0.0, max_tokens=8)
    assert ra["lfkt_timings"]["prefix_reused_tokens"] == 0
    assert rb["lfkt_timings"]["prefix_reused_tokens"] > 0


def test_explicit_seed_bypasses_radix(paged_serial):
    """Same-seed calls must be bit-identical, so they always take the
    full prefill — the serial engine's reproducibility contract extends
    to the paged index."""
    eng = paged_serial
    msgs = [{"role": "user", "content": "Deterministic seeds please. " * 6}]
    r1 = eng.create_chat_completion(msgs, temperature=0.0, max_tokens=8,
                                    seed=7)
    r2 = eng.create_chat_completion(msgs, temperature=0.0, max_tokens=8,
                                    seed=7)
    assert r1["lfkt_timings"]["prefix_reused_tokens"] == 0
    assert r2["lfkt_timings"]["prefix_reused_tokens"] == 0
    assert (r1["choices"][0]["message"]["content"]
            == r2["choices"][0]["message"]["content"])


def test_recover_resets_pool(paged_serial):
    eng = paged_serial
    assert eng._kvpool.occupancy()["pages_used"] > 0
    assert eng.recover()
    occ = eng._kvpool.occupancy()
    assert occ["pages_used"] == 0 and occ["pages_pinned"] == 0


# ---------------------------------------------------------------------------
# radix reuse behavior (continuous scheduler)
# ---------------------------------------------------------------------------

def test_continuous_cross_lane_reuse_and_exhaustion(model_path):
    """One engine, two stories: (1) a follow-up turn reuses its pages no
    matter which lane admits it; (2) with the pool squeezed to 4 pages,
    a burst of distinct conversations completes normally — stores skip
    or evict, requests never fail (backpressure, not OOM)."""
    kw = dict(PAGED_KW, kv_pool_pages=4, kv_spill_pages=0)
    eng = ContinuousEngine(model_path, dp=1, tp=1, batch_size=2,
                           **BASE_KW, **kw)
    try:
        msgs, turn2 = _convo()
        r1 = eng.submit(msgs, temperature=0.0, max_tokens=8).result()
        msgs2 = msgs + [
            {"role": "assistant",
             "content": r1["choices"][0]["message"]["content"]},
            {"role": "user", "content": turn2}]
        r2 = eng.submit(msgs2, temperature=0.0, max_tokens=8).result()
        # 4 pages x 16 tokens: the commit degrades to the conversation
        # HEAD (where the system prompt lives), and the follow-up still
        # hits that partial prefix
        assert r2["lfkt_timings"]["prefix_reused_tokens"] > 0
        assert eng._kvpool.stats()["hits"] >= 1
        # realized reuse publishes under the PAGED stat name, and the
        # dense lane-prefix stat shows no phantom activity
        sstats = eng.scheduler_stats()
        assert sstats["radix_prefix_hits"] >= 1
        assert "lane_prefix_hits" not in sstats
        # exhaustion burst: distinct prompts, every one must complete
        futs = [eng.submit([{"role": "user",
                             "content": f"burst number {i} " * 6}],
                           temperature=0.0, max_tokens=8)
                for i in range(6)]
        for f in futs:
            out = f.result(timeout=120)
            assert out["choices"][0]["message"]["content"]
        stats = eng._kvpool.stats()
        assert stats["store_skips"] + stats["evictions"] > 0
        assert eng._kvpool.occupancy()["pages_pinned"] == 0
    finally:
        eng.shutdown()


def test_paged_prefill_span_attribution(model_path, paged_serial):
    """A traced paged-reuse prefill carries reused_pages/matched_tokens
    and a kv_restore event — the waterfall's spill/restore visibility."""
    from llama_fastapi_k8s_gpu_tpu.obs.trace import Tracer

    eng = paged_serial
    msgs = [{"role": "system", "content": "Trace me carefully now. " * 10},
            {"role": "user", "content": "warm the cache"}]
    eng.create_chat_completion(msgs, temperature=0.0, max_tokens=8)
    tracer = Tracer(sample=1.0, ring=4)
    tr = tracer.start()
    msgs2 = [msgs[0], {"role": "user", "content": "different follow-up"}]
    r = eng.create_chat_completion(msgs2, temperature=0.0, max_tokens=8,
                                   trace=tr)
    tracer.finish(tr)
    assert r["lfkt_timings"]["prefix_reused_tokens"] > 0
    doc = tr.to_dict()
    prefill = None
    stack = [doc["root"]]
    while stack:
        s = stack.pop()
        if s["name"] == "prefill":
            prefill = s
        stack.extend(s["children"])
    assert prefill is not None
    assert prefill["attrs"]["reused"] > 0
    assert prefill["attrs"]["reused_pages"] >= 1
    assert prefill["attrs"]["matched_tokens"] >= prefill["attrs"]["reused"]
    events = [e["name"] for e in prefill["events"]]
    assert "kv_restore" in events


def test_serial_restore_failure_does_not_poison_cache(model_path,
                                                      monkeypatch):
    """The ring is donated into the restore copy: a failed dispatch must
    not leave the dead donated buffer as the engine's cache (the next
    request would trip over it) — the engine rebuilds cold, releases the
    lease, and the request after the failure serves normally."""
    from llama_fastapi_k8s_gpu_tpu.parallel import kvpool

    eng = Engine(model_path, **BASE_KW, **PAGED_KW)
    msgs, turn2 = _convo()
    t1 = eng.create_chat_completion(msgs, temperature=0.0, max_tokens=8)
    msgs2 = msgs + [
        {"role": "assistant",
         "content": t1["choices"][0]["message"]["content"]},
        {"role": "user", "content": turn2}]

    def boom(*_a, **_k):
        raise RuntimeError("injected restore failure")

    monkeypatch.setattr(kvpool, "_restore_pages_jit", boom)
    with pytest.raises(RuntimeError, match="injected restore"):
        eng.create_chat_completion(msgs2, temperature=0.0, max_tokens=8)
    assert eng._kvpool.occupancy()["pages_pinned"] == 0   # lease released
    monkeypatch.undo()
    r = eng.create_chat_completion(msgs2, temperature=0.0, max_tokens=8)
    assert r["choices"][0]["message"]["content"]
    assert r["lfkt_timings"]["prefix_reused_tokens"] > 0


def test_continuous_reuse_survives_poisoned_span(model_path):
    """lfkt-lint RES001 regression (ISSUE 8): a raising span setter inside
    ``_paged_admission_reuse`` sat between ``pool.acquire`` and the lease
    handoff — the one statement whose failure would leak the pinned pages
    for the life of the process (``_begin_admission``'s cleanup releases
    its own ``lease`` local, still None while the helper is on the stack).
    The span set is now guarded: the hit proceeds, nothing stays pinned."""
    eng = ContinuousEngine(model_path, dp=1, tp=1, batch_size=2,
                           **BASE_KW, **PAGED_KW)
    try:
        msgs, _ = _convo()
        eng.submit(msgs, temperature=0.0, max_tokens=8).result()
        ids = eng.tokenize_messages(msgs)
        assert eng._kvpool.match_len(ids) >= eng._paged_align

        class PoisonedSpan:
            def set(self, **kw):
                raise RuntimeError("poisoned span setter")

        r, lease = eng._paged_admission_reuse(ids, PoisonedSpan())
        assert r > 0 and lease is not None, \
            "the radix hit must survive a failing span setter"
        eng._kvpool.release(lease)
        assert eng._kvpool.occupancy()["pages_pinned"] == 0
    finally:
        eng.shutdown()

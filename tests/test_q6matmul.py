"""Fused Q6_K dequant-matmul kernel vs the dequant-then-matmul oracle.

Same contract as tests/test_qmatmul.py: the kernel must agree with an XLA
matmul against ``dequant_ref6`` (bf16-folded scales) and, end to end, with
the numpy Q6_K codec within quantization-noise tolerance.  Q6_K is what
Q4_K_M files use for ffn_down / attn_v / output (the reference's served
artifact mixes both types), so this is the second half of "serve Q4_K_M
fully fused"."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llama_fastapi_k8s_gpu_tpu.gguf.quants import dequant_q6_k, quant_q6_k
from llama_fastapi_k8s_gpu_tpu.ops.linear import linear, make_linear_q6k
from llama_fastapi_k8s_gpu_tpu.ops.pallas.q6matmul import (
    dequant_ref6,
    permute_x6,
    prep_q6k,
    q6k_matmul,
)


def _rand_weights(rng, n, k):
    return (rng.standard_normal((n, k)).astype(np.float32) * (k ** -0.5))


@pytest.mark.parametrize("n,k,b", [
    (8, 2048, 1),       # minimum interpret-mode N tile, decode matvec
    (128, 2048, 4),     # TPU-shaped single k-tile
    (256, 4096, 2),     # full-size tiles, 2 k-steps
    (24, 6144, 3),      # non-power-of-two N (TN=8), 3 k-tiles
])
def test_kernel_matches_dequant_ref6(n, k, b):
    rng = np.random.default_rng(n + k)
    w = make_linear_q6k(_rand_weights(rng, n, k))
    x = jnp.asarray(rng.standard_normal((b, k)), jnp.float32)

    ref = permute_x6(x).astype(jnp.bfloat16).astype(jnp.float32) @ dequant_ref6(w).T
    got = q6k_matmul(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2 * float(jnp.abs(ref).max()))


def test_end_to_end_vs_numpy_codec():
    rng = np.random.default_rng(0)
    n, k = 64, 2048
    wf = _rand_weights(rng, n, k)
    raw = quant_q6_k(wf.reshape(-1))
    w = prep_q6k(raw, n, k)
    w_deq = dequant_q6_k(raw, n * k).reshape(n, k)

    x = rng.standard_normal((2, k)).astype(np.float32)
    ref = x @ w_deq.T
    got = np.asarray(q6k_matmul(jnp.asarray(x), w))
    np.testing.assert_allclose(got, ref, rtol=3e-2,
                               atol=3e-2 * float(np.abs(ref).max()))


def test_prep_roundtrips_exact_values():
    """prep_q6k's repack must preserve every 6-bit value and scale exactly:
    dequant_ref6 (over the packed layout) == numpy codec dequant up to the
    bf16 scale fold, in the permuted column order."""
    rng = np.random.default_rng(1)
    n, k = 16, 2048
    raw = quant_q6_k(_rand_weights(rng, n, k).reshape(-1))
    w = prep_q6k(raw, n, k)
    ref = dequant_q6_k(raw, n * k).reshape(n, k)
    ref_p = np.asarray(permute_x6(jnp.asarray(ref)))
    got = np.asarray(dequant_ref6(w))
    np.testing.assert_allclose(got, ref_p, rtol=8e-3,
                               atol=8e-3 * float(np.abs(ref).max()))


def test_linear_dispatch_routes_q6k():
    rng = np.random.default_rng(2)
    w = make_linear_q6k(_rand_weights(rng, 16, 2048))
    x = jnp.asarray(rng.standard_normal((3, 2048)), jnp.bfloat16)
    y = linear(x, w)
    assert y.shape == (3, 16) and y.dtype == jnp.bfloat16


def test_permute_x6_is_a_permutation():
    x = jnp.arange(2048, dtype=jnp.float32)
    p = np.asarray(permute_x6(x))
    assert sorted(p.tolist()) == list(range(2048))
    # column c = e*128 + s holds original element (s//16)*256 + (s%16)*16 + e
    for c in (0, 1, 15, 16, 17, 127, 128, 129, 2047):
        s, e = c % 128, c // 128
        assert p[c] == (s // 16) * 256 + (s % 16) * 16 + e, c


def test_under_jit_and_scan():
    rng = np.random.default_rng(3)
    L, n, kdim = 3, 16, 2048
    ws = [make_linear_q6k(_rand_weights(rng, n, kdim)) for _ in range(L)]
    stacked = {key: jnp.stack([w[key] for w in ws]) for key in ws[0]}
    x = jnp.asarray(rng.standard_normal((1, kdim)), jnp.bfloat16)

    @jax.jit
    def f(stacked, x):
        def step(carry, wl):
            return carry, linear(carry, wl)

        _, ys = jax.lax.scan(step, x, stacked)
        return ys

    ys = f(stacked, x)
    assert ys.shape == (L, 1, n)
    np.testing.assert_allclose(np.asarray(ys[0]), np.asarray(linear(x, ws[0])),
                               rtol=1e-2, atol=1e-2)


def test_load_params_q4km_fuses_both_types(tmp_path):
    """A Q4_K_M-style file (attn Q4_K, ffn Q6_K): Q4_K names load the fused
    Q4_K layout, Q6_K names load the fused **Q6_K** layout (round 2 sent
    them to int8), and forward logits agree with a bf16 load."""
    from llama_fastapi_k8s_gpu_tpu.gguf import GGMLType, GGUFFile
    from llama_fastapi_k8s_gpu_tpu.models.config import ModelConfig
    from llama_fastapi_k8s_gpu_tpu.models.llama import init_cache, prefill
    from llama_fastapi_k8s_gpu_tpu.models.params import load_params
    from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf

    cfg = ModelConfig(vocab_size=263, dim=2048, n_layers=1, n_heads=16,
                      n_kv_heads=8, ffn_dim=2048, n_ctx=32)
    path = str(tmp_path / "q4km.gguf")
    cfg = write_tiny_llama_gguf(path, cfg=cfg, quant=GGMLType.Q4_K,
                                ffn_quant=GGMLType.Q6_K)
    gf = GGUFFile(path)
    params = load_params(gf, cfg, fmt="q4k", on_device=False)
    assert "qs" in params["layers"]["wq"]
    assert "q4" in params["layers"]["w_gate"]          # fused Q6_K now

    ref = load_params(gf, cfg, fmt="bf16", on_device=False)
    toks = jnp.arange(1, 9, dtype=jnp.int32)
    lg_q, _ = prefill(params, cfg, toks, jnp.int32(8), init_cache(cfg))
    lg_r, _ = prefill(ref, cfg, toks, jnp.int32(8), init_cache(cfg))
    a, b = np.asarray(lg_q), np.asarray(lg_r)
    denom = np.abs(b).max() + 1e-6
    assert np.abs(a - b).max() / denom < 0.08, np.abs(a - b).max() / denom


def test_q6k_params_shard_over_mesh():
    """param_shardings must cover {'q4','q2','sm6'} dicts."""
    import numpy as np

    from llama_fastapi_k8s_gpu_tpu.parallel.mesh import make_mesh, shard_params

    rng = np.random.default_rng(4)
    w = make_linear_q6k(_rand_weights(rng, 256, 2048))
    params = {
        "tok_emb": jnp.zeros((64, 32), jnp.bfloat16),
        "layers": {"attn_norm": jnp.ones((1, 32)),
                   "wq": {k: v[None] for k, v in w.items()},
                   "wk": {k: v[None] for k, v in w.items()},
                   "wv": {k: v[None] for k, v in w.items()},
                   "wo": {k: v[None] for k, v in w.items()},
                   "ffn_norm": jnp.ones((1, 32)),
                   "w_gate": {k: v[None] for k, v in w.items()},
                   "w_up": {k: v[None] for k, v in w.items()},
                   "w_down": {k: v[None] for k, v in w.items()}},
        "out_norm": jnp.ones(32),
        "output": {"w": jnp.zeros((64, 32), jnp.bfloat16)},
    }
    mesh = make_mesh(dp=2, tp=2, sp=2)
    sharded = shard_params(params, mesh)
    assert sharded["layers"]["wq"]["q4"].shape == params["layers"]["wq"]["q4"].shape


def test_parfloor_variant_bit_identical(monkeypatch):
    """LFKT_Q6K_KERNEL=parfloor must produce BIT-identical output: its
    independent floors compute the same exact f32 integers as the serial
    remainder chain."""
    import numpy as np

    from llama_fastapi_k8s_gpu_tpu.gguf.quants import quant_q6_k
    from llama_fastapi_k8s_gpu_tpu.ops.pallas import q6matmul as qm
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.q6matmul import prep_q6k, q6k_matmul

    rng = np.random.default_rng(1)
    n, k = 64, 2048
    w = (rng.standard_normal((n, k)) * 0.05).astype(np.float32)
    wd = prep_q6k(quant_q6_k(w.reshape(-1)), n, k)
    x = jnp.asarray(rng.standard_normal((4, k)), jnp.bfloat16)
    # the variant is part of the builder cache key, so flipping the env
    # between calls re-traces without any cache_clear choreography.
    # Compare cur vs parfloor EXPLICITLY so the assertion is immune to
    # which of the two bit-identical variants leads the tuple default.
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "cur")
    a = np.asarray(q6k_matmul(x, wd, interpret=True))
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "parfloor")
    b = np.asarray(q6k_matmul(x, wd, interpret=True))
    assert np.array_equal(a, b)


def test_vbf32_variant_beats_default_accuracy(monkeypatch):
    """LFKT_Q6K_KERNEL=vbf32 (activation-side recombination, f32 planes,
    telescoped crumb digits) must show no cancellation blowup: at least as
    close to the f32 dequant_ref6 oracle as the bf16-plane default, and
    inside the default's own quantization tolerance."""
    from llama_fastapi_k8s_gpu_tpu.gguf.quants import quant_q6_k
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.q6matmul import prep_q6k, q6k_matmul

    rng = np.random.default_rng(7)
    n, k = 64, 4096
    w = (rng.standard_normal((n, k)) * 0.05).astype(np.float32)
    wd = prep_q6k(quant_q6_k(w.reshape(-1)), n, k)
    x = jnp.asarray(rng.standard_normal((4, k)), jnp.float32)
    ref = np.asarray(
        permute_x6(x).astype(jnp.bfloat16).astype(jnp.float32) @ dequant_ref6(wd).T)
    monkeypatch.delenv("LFKT_Q6K_KERNEL", raising=False)
    cur = np.asarray(q6k_matmul(x, wd, interpret=True))
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "vbf32")
    got = np.asarray(q6k_matmul(x, wd, interpret=True))
    err_cur = np.abs(cur - ref).max()
    err_vb = np.abs(got - ref).max()
    assert err_vb <= err_cur * 1.05, (err_vb, err_cur)
    np.testing.assert_allclose(got, ref, rtol=2e-2,
                               atol=2e-2 * float(np.abs(ref).max()))


def test_pre_layout_matches_oracle_and_split(monkeypatch):
    """LFKT_Q6K_KERNEL=pre (pre-combined int8 q6 plane, ~3 VPU ops/weight)
    must agree with the f32 dequant oracle at least as tightly as the
    split `cur` path: its plane q6*eff is the exact f32 value the split
    path reaches via nib*eff + crumb*(16 eff) before the same bf16 cast,
    and it ROUNDS ONE FEWER corr term (the +8 hi-nibble bias rides the
    exact plane instead of a bf16 corr column)."""
    from llama_fastapi_k8s_gpu_tpu.ops.pallas import q6matmul as qm

    rng = np.random.default_rng(11)
    n, k = 64, 4096
    raw = quant_q6_k(_rand_weights(rng, n, k).reshape(-1))
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "cur")
    w_split = prep_q6k(raw, n, k)
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "pre")
    w_pre = prep_q6k(raw, n, k)
    assert set(w_pre) == {"q6p", "sm6"}
    assert w_pre["q6p"].dtype == jnp.int8
    q6p = np.asarray(w_pre["q6p"])
    assert q6p.min() >= 0 and q6p.max() < 64

    x = jnp.asarray(rng.standard_normal((4, k)), jnp.float32)
    ref = np.asarray(
        permute_x6(x).astype(jnp.bfloat16).astype(jnp.float32)
        @ dequant_ref6(w_split).T)
    got_pre = np.asarray(q6k_matmul(x, w_pre, interpret=True))
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "cur")
    got_cur = np.asarray(q6k_matmul(x, w_split, interpret=True))

    scale = np.abs(ref).max()
    err_pre = np.abs(got_pre - ref).max()
    err_cur = np.abs(got_cur - ref).max()
    # pre rounds a strict subset of cur's terms; allow bf16-noise slack
    assert err_pre <= err_cur + 2e-3 * scale, (err_pre, err_cur, scale)
    np.testing.assert_allclose(got_pre, got_cur, atol=4e-3 * scale)


def test_pre_layout_stacked_matches_plain(monkeypatch):
    """Stacked scalar-prefetch path == plain path for the pre layout."""
    from llama_fastapi_k8s_gpu_tpu.ops.pallas.q6matmul import (
        q6k_matmul_stacked,
    )

    rng = np.random.default_rng(12)
    n, k = 32, 2048
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "pre")
    w0 = prep_q6k(quant_q6_k(_rand_weights(rng, n, k).reshape(-1)), n, k)
    w1 = prep_q6k(quant_q6_k(_rand_weights(rng, n, k).reshape(-1)), n, k)
    ws = {key: jnp.stack([w0[key], w1[key]]) for key in w0}
    x = jnp.asarray(rng.standard_normal((2, k)), jnp.bfloat16)
    for i, w in enumerate((w0, w1)):
        plain = np.asarray(q6k_matmul(x, w, interpret=True))
        stacked = np.asarray(q6k_matmul_stacked(x, ws, i, interpret=True))
        np.testing.assert_array_equal(plain, stacked)


def test_pre_layout_shards_on_mesh(monkeypatch):
    """The q6p plane must ride the full shard_params path: tp over N when
    the per-shard N keeps the kernel tiling, and — the fused-GROUP guard
    (`_FUSED_MAIN_KEY`) — whole-leaf replication when it would not (the
    Llama-3 output head's 128256/tp=4 = 32064 is not 128-aligned; in
    interpret mode the granularity is 8, so N=24 over tp=2 → 12 models
    the same violation)."""
    from llama_fastapi_k8s_gpu_tpu.parallel.mesh import (
        make_mesh, param_shardings, shard_params,
    )

    rng = np.random.default_rng(13)
    monkeypatch.setenv("LFKT_Q6K_KERNEL", "pre")
    n, k = 256, 2048
    w = prep_q6k(quant_q6_k(_rand_weights(rng, n, k).reshape(-1)), n, k)
    ws = {key: jnp.stack([w[key], w[key]]) for key in w}
    n_bad = 24                      # 24/tp=12, not a multiple of gran=8
    w_bad = prep_q6k(
        quant_q6_k(_rand_weights(rng, n_bad, k).reshape(-1)), n_bad, k)
    params = {"tok_emb": jnp.zeros((8, 8)), "out_norm": jnp.zeros((8,)),
              "layers": {"w_down": ws, "attn_norm": jnp.zeros((2, 8))},
              "output": w_bad}
    mesh = make_mesh(dp=2, tp=2, sp=2)
    sh = param_shardings(params, mesh)
    assert sh["layers"]["w_down"]["q6p"] is not None
    sharded = shard_params(params, mesh)
    assert sharded["layers"]["w_down"]["q6p"].shape == ws["q6p"].shape
    # the ill-fitting head leaf must come back REPLICATED, not half-sharded
    head_spec = sharded["output"]["q6p"].sharding.spec
    assert all(a is None for a in head_spec), head_spec

"""lfkt-mem: the incident flight recorder (ISSUE 10).

Layers:

1. **Recorder unit** — atomic schema-valid bundles, the bounded on-disk
   ring, per-kind debounce, cross-process sequence continuation, the
   log-tail ring, schema-drift detection.
2. **Trigger points** — watchdog trip / DEAD escalation
   (engine/watchdog.py), device OOM via the heartbeat
   (utils/health.py), SLO breach (obs/slo.py).
3. **Tools** — tools/incident_report.py rendering + the ``--validate``
   schema gate wired into tools/ci_gate.py.
4. **Acceptance drill** — an injected decode fault on a real
   ContinuousEngine trips the watchdog and produces EXACTLY ONE bundle
   carrying the tripping request's trace, the memory ledger and the
   health transition — readable back through ``/debug/incidents/{id}``
   after the engine recovered.
5. **Disarmed cost** — no ``LFKT_INCIDENT_DIR`` = a single attribute
   read; poisoned-recorder pin.
"""

from __future__ import annotations

import importlib.util
import json
import logging
import os
import time

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import ContinuousEngine, FakeEngine
from llama_fastapi_k8s_gpu_tpu.engine.watchdog import Watchdog
from llama_fastapi_k8s_gpu_tpu.obs.devtime import DevtimeRegistry
from llama_fastapi_k8s_gpu_tpu.obs.flightrec import (
    KINDS,
    SCHEMA,
    FlightRecorder,
    validate_bundle,
)
from llama_fastapi_k8s_gpu_tpu.obs.slo import SLOEngine
from llama_fastapi_k8s_gpu_tpu.obs.trace import Tracer
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.faults import FAULTS, FaultError, SimulatedOOM
from llama_fastapi_k8s_gpu_tpu.utils.health import (
    DEGRADED,
    READY,
    Heartbeat,
    HealthMonitor,
)
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLIGHTREC_PATH = "llama_fastapi_k8s_gpu_tpu.obs.flightrec.FLIGHTREC"
MSGS = [{"role": "user", "content": "Say something."}]


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _wait(pred, timeout=30.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture()
def recorder(tmp_path, monkeypatch):
    """A fresh ARMED process recorder on a tmp ring dir, installed as the
    module global (trigger points resolve it at call time); the log-ring
    handler is detached on teardown."""
    rec = FlightRecorder(directory=str(tmp_path / "ring"), ring=8,
                         debounce_s=0.0, log_lines=50)
    monkeypatch.setattr(FLIGHTREC_PATH, rec)
    yield rec
    rec.configure(directory="")          # removes the root log handler


@pytest.fixture(autouse=True)
def _disarm_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


# ---------------------------------------------------------------------------
# layer 1: recorder unit
# ---------------------------------------------------------------------------

def test_record_writes_schema_valid_atomic_bundle(recorder):
    rid = recorder.record("watchdog_trip", "drill reason",
                          extra={"k": "v"})
    assert rid == "inc-000001-watchdog_trip"
    files = os.listdir(recorder._dir)
    assert files == [rid + ".json"]          # no .tmp left behind
    doc = recorder.get(rid)
    assert validate_bundle(doc) == []
    assert doc["kind"] == "watchdog_trip"
    assert doc["reason"] == "drill reason"
    assert doc["extra"] == {"k": "v"}
    assert doc["memory"]["schema"] == 1      # the live ledger rides along
    assert isinstance(doc["traces"], list)
    assert recorder.recorded_total == 1
    # summaries list newest first
    recorder.record("slo_breach", "second")
    assert [s["id"] for s in recorder.list()] == [
        "inc-000002-slo_breach", rid]
    # id grammar enforced: no path escape through get()
    assert recorder.get("../../etc/passwd") is None
    assert recorder.get("inc-zzz-nope") is None


def test_ring_prunes_oldest_and_seq_survives_restart(recorder):
    recorder.configure(ring=2)
    for i, kind in enumerate(("watchdog_trip", "slo_breach",
                              "resource_exhausted")):
        assert recorder.record(kind, f"r{i}") is not None
    names = sorted(os.listdir(recorder._dir))
    assert len(names) == 2                       # oldest pruned
    assert names[0].startswith("inc-000002-")
    # a NEW recorder on the same dir (post-restart process) continues the
    # sequence instead of overwriting the previous crash's evidence
    rec2 = FlightRecorder(directory=recorder._dir, ring=8, debounce_s=0.0,
                          log_lines=10)
    try:
        assert rec2.record("dead_escalation", "after restart") \
            == "inc-000004-dead_escalation"
    finally:
        rec2.configure(directory="")


def test_debounce_per_kind(recorder):
    recorder.configure(debounce_s=60.0)
    assert recorder.record("watchdog_trip", "first") is not None
    assert recorder.record("watchdog_trip", "burst repeat") is None
    assert recorder.debounced_total == 1
    # a DIFFERENT kind is not debounced by the first
    assert recorder.record("resource_exhausted", "oom") is not None


def test_failed_write_rolls_back_debounce(recorder, monkeypatch):
    """A write failure (disk full during the very incident being
    recorded) must not burn the debounce window: the next trigger of the
    same kind retries instead of being silently suppressed."""
    recorder.configure(debounce_s=600.0)
    real_write = recorder._write
    calls = {"n": 0}

    def flaky(incident_id, bundle):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        real_write(incident_id, bundle)

    monkeypatch.setattr(recorder, "_write", flaky)
    assert recorder.record("watchdog_trip", "first attempt") is None
    assert recorder.record("watchdog_trip", "retry") is not None
    assert recorder.recorded_total == 1


def test_failed_write_leaves_no_tmp_file(recorder, monkeypatch):
    """A write that fails at the atomic rename removes its temp file:
    disk-full retries mint new ids, and leaked .tmp files would compound
    the very disk pressure that failed the write."""
    def no_replace(src, dst):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", no_replace)
    assert recorder.record("watchdog_trip", "r") is None
    monkeypatch.undo()
    assert [n for n in os.listdir(recorder._dir)
            if n.startswith(".tmp-")] == []
    # ...a stray tmp from a previous crash is swept at the first WRITE of
    # an arming — never by merely (re)arming, which is what a read-only
    # tool (incident_report / ci_gate) does by importing the module with
    # LFKT_INCIDENT_DIR set: a reader must not delete a live recorder's
    # in-progress temp file
    stray = os.path.join(recorder._dir, ".tmp-inc-000009-slo_breach.json")
    open(stray, "w").close()
    recorder.configure(directory=recorder._dir)
    recorder.list()
    assert os.path.exists(stray)
    assert recorder.record("slo_breach", "sweep trigger") is not None
    assert not os.path.exists(stray)


def test_install_never_pins_unweakrefable_engine(recorder):
    """install()'s contract is WEAK references: an engine that cannot be
    weakly referenced is dropped (bundles go without scheduler stats),
    never pinned for the process lifetime by the global recorder."""
    recorder.install(engine=(1, 2, 3))     # tuples are un-weakref-able
    assert recorder._engine_ref is None
    doc = recorder.get(recorder.record("watchdog_trip", "r"))
    assert doc["scheduler"] is None


def test_log_tail_rides_the_bundle(recorder):
    logging.getLogger("lfkt.test").warning("breadcrumb %d", 42)
    doc = recorder.get(recorder.record("slo_breach", "r"))
    assert any("breadcrumb 42" in line["message"]
               for line in doc["log_tail"])


def test_validate_bundle_catches_drift(recorder):
    doc = recorder.get(recorder.record("watchdog_trip", "r"))
    assert validate_bundle(doc) == []
    assert any("drift" in v for v in validate_bundle(
        {**doc, "schema": SCHEMA + 1}))
    assert any("kind" in v for v in validate_bundle(
        {**doc, "kind": "novel_kind"}))
    assert any("'traces'" in v for v in validate_bundle(
        {k: v for k, v in doc.items() if k != "traces"}))
    assert validate_bundle([1, 2]) == ["bundle is not a JSON object"]


# ---------------------------------------------------------------------------
# layer 2: trigger points
# ---------------------------------------------------------------------------

def test_watchdog_trip_and_dead_escalation_record(recorder):
    eng = FakeEngine()
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), poll_seconds=10.0,
                  backoff_seconds=0.01, max_recoveries=1)
    wd.handle_trip("stalled_decode: drill")
    docs = [recorder.get(s["id"]) for s in recorder.list()]
    trips = [d for d in docs if d["kind"] == "watchdog_trip"]
    assert len(trips) == 1
    assert "stalled_decode" in trips[0]["reason"]
    assert trips[0]["extra"]["watchdog"]["trips"] == 1
    # health rides the bundle's top-level field via the refs the watchdog
    # installed at construction — captured mid-trip, i.e. DEGRADED
    assert trips[0]["health"]["state"] == DEGRADED
    # exhaust the budget: the DEAD escalation writes its own bundle kind
    wd.handle_trip("stalled_decode: again")
    docs = [recorder.get(s["id"]) for s in recorder.list()]
    assert [d["kind"] for d in docs].count("dead_escalation") == 1


def test_heartbeat_oom_signature_records(recorder):
    hb = Heartbeat()
    hb.record_error(ValueError("ordinary bug"))
    assert recorder.recorded_total == 0          # only the OOM signature
    hb.record_error(SimulatedOOM("RESOURCE_EXHAUSTED: simulated OOM"))
    docs = [recorder.get(s["id"]) for s in recorder.list()]
    assert [d["kind"] for d in docs] == ["resource_exhausted"]
    assert "RESOURCE_EXHAUSTED" in docs[0]["reason"]


def test_slo_breach_records_with_verdict(recorder):
    m = Metrics()
    s = SLOEngine(m, windows=[60.0, 600.0],
                  thresholds={"ttft_p95": 1.0, "decode_floor": 10.0,
                              "error_rate": 0.01, "queue_p95": 0.5},
                  devtime=DevtimeRegistry(armed=True, budget=32))
    s.evaluate(now=0.0)                          # realize both baselines
    for _ in range(8):
        m.observe("engine_decode_tokens_per_sec", 50.0, model="m")
    for _ in range(2):                           # under the 10 tok/s floor
        m.observe("engine_decode_tokens_per_sec", 2.0, model="m")
    doc = s.evaluate(now=700.0)
    assert doc["verdict"] == "breach"
    # the capture+write runs on a short worker thread (the evaluate call
    # sites are async handlers): wait for the bundle, not for luck
    _wait(lambda: recorder.recorded_total == 1, timeout=10,
          what="breach bundle write")
    docs = [recorder.get(x["id"]) for x in recorder.list()]
    assert [d["kind"] for d in docs] == ["slo_breach"]
    assert "decode_floor" in docs[0]["reason"]
    assert docs[0]["extra"]["slo"]["verdict"] == "breach"
    # one bundle per breach EPISODE: the persisting breach re-evaluated
    # on later scrapes must not flood the bounded ring (recorder debounce
    # is 0 here — the edge detector alone holds the line)
    for t in (710.0, 720.0, 730.0):
        assert s.evaluate(now=t)["verdict"] == "breach"
        time.sleep(0.05)
    assert recorder.recorded_total == 1
    # recovery re-arms the detector: a NEW episode records a new bundle
    for _ in range(400):
        m.observe("engine_decode_tokens_per_sec", 50.0, model="m")
    assert s.evaluate(now=1500.0)["verdict"] != "breach"
    for _ in range(3):
        m.observe("engine_decode_tokens_per_sec", 2.0, model="m")
    assert s.evaluate(now=2200.0)["verdict"] == "breach"
    _wait(lambda: recorder.recorded_total == 2, timeout=10,
          what="second-episode bundle write")


# ---------------------------------------------------------------------------
# layer 3: tools — incident_report + the ci_gate schema step
# ---------------------------------------------------------------------------

def test_incident_report_validate_and_render(recorder, capsys):
    rid = recorder.record("watchdog_trip", "drill")
    tool = _load_tool("incident_report")
    assert tool.SCHEMA == SCHEMA                 # tool pins the package
    assert tool.validate(recorder._dir) == 0
    # plant drift: the gate must fail loudly
    bad = recorder.get(rid)
    bad["schema"] = 99
    with open(os.path.join(recorder._dir, rid + ".json"), "w") as f:
        json.dump(bad, f)
    assert tool.validate(recorder._dir) == 1
    out = capsys.readouterr().out
    assert "drift" in out and "FAIL" in out
    # no dir configured = trivially OK (the common CI case)
    assert tool.validate("") == 0
    assert tool.validate(str(recorder._dir) + "-nonexistent") == 0
    # renderers run on a real bundle
    good = {**bad, "schema": SCHEMA}
    text = tool.render_bundle(good)
    assert "watchdog_trip" in text and "memory ledger" in text
    assert "drill" in tool.render_listing(recorder._dir)


def test_ci_gate_includes_incident_schema_check():
    gate = _load_tool("ci_gate")
    assert "incident-schema" in [name for name, _ in gate.CHECKS]


# ---------------------------------------------------------------------------
# layer 4: the acceptance drill (ISSUE 10)
# ---------------------------------------------------------------------------

@pytest.mark.anyio
async def test_fault_drill_one_bundle_readable_after_recovery(
        recorder, tmp_path):
    """Injected decode fault → watchdog trip → EXACTLY ONE bundle with
    the tripping request's trace, the memory ledger and the health
    transition — readable through /debug/incidents/{id} after the
    engine recovered in place."""
    path = str(tmp_path / "tiny-drill.gguf")
    write_tiny_llama_gguf(path)
    eng = ContinuousEngine(path, dp=1, tp=1, batch_size=2, n_ctx=128,
                           decode_chunk=4, max_gen_tokens=16,
                           prefill_buckets=(32, 64, 128))
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), stall_seconds=30,
                  poll_seconds=0.05, backoff_seconds=0.05,
                  max_recoveries=3)
    tracer = Tracer(sample=1.0, ring=8)
    try:
        # the tripping request rides a real trace, still in flight when
        # the scheduler loop dies
        FAULTS.arm("decode_step:error:times=1")
        tr = tracer.start("request")
        tr.note(route="/response")
        fut = eng.submit(MSGS, temperature=0.0, max_tokens=8, trace=tr)
        with pytest.raises(Exception):
            fut.result(timeout=60)
        assert isinstance(eng.failure(), FaultError)

        wd.start()
        _wait(lambda: wd.recoveries >= 1 and health.state == READY,
              what="trip + in-process recovery")

        # exactly one bundle: the trip's (no DEAD, no OOM signature)
        summaries = recorder.list()
        assert len(summaries) == 1
        doc = recorder.get(summaries[0]["id"])
        assert validate_bundle(doc) == []
        assert doc["kind"] == "watchdog_trip"
        assert "scheduler_died" in doc["reason"]
        # the tripping request's trace rides the bundle
        assert tr.trace_id in [t.get("trace_id") for t in doc["traces"]]
        # the memory ledger at capture time
        assert doc["memory"]["armed"] is True
        assert any(r["component"] == "weights"
                   for r in doc["memory"]["components"])
        # the health transition that shed the traffic
        trail = [t["to"] for t in doc["health"]["transitions"]]
        assert DEGRADED in trail
        # and the live scheduler stats via the same installed refs
        assert "lanes_live" in doc["scheduler"]
        tracer.finish(tr)

        # same engine object, recovered: serving again...
        out = eng.create_chat_completion(MSGS, temperature=0.0,
                                         max_tokens=4)
        assert out["usage"]["completion_tokens"] >= 1

        # ...and the bundle reads back through the server surface
        app = create_app(engine=eng)
        transport = httpx.ASGITransport(app=app)
        async with transport:
            await app.router.startup()
            async with httpx.AsyncClient(transport=transport,
                                         base_url="http://t") as client:
                listing = (await client.get("/debug/incidents")).json()
                assert listing["armed"] is True
                assert [s["id"] for s in listing["incidents"]] == \
                    [doc["id"]]
                one = await client.get(f"/debug/incidents/{doc['id']}")
                assert one.status_code == 200
                got = one.json()
                assert got["kind"] == "watchdog_trip"
                assert got["id"] == doc["id"]
                missing = await client.get(
                    "/debug/incidents/inc-999999-watchdog_trip")
                assert missing.status_code == 404
            await app.router.shutdown()
    finally:
        FAULTS.disarm()
        wd.stop()
        eng.shutdown()


# ---------------------------------------------------------------------------
# layer 5: disarmed cost (poisoned-recorder pin)
# ---------------------------------------------------------------------------

def test_disarmed_recorder_is_poison_proof(monkeypatch):
    """No LFKT_INCIDENT_DIR: record() keys off one attribute read — a
    poisoned recorder must never capture, list files, or touch disk,
    even when every trigger point fires."""
    rec = FlightRecorder(directory="", ring=8, debounce_s=0.0,
                         log_lines=10)
    assert rec.armed is False

    def boom(*a, **kw):
        raise AssertionError("disarmed flight recorder was touched")

    monkeypatch.setattr(rec, "_capture", boom)
    monkeypatch.setattr(rec, "_write", boom)
    monkeypatch.setattr(rec, "_list_files", boom)
    monkeypatch.setattr(FLIGHTREC_PATH, rec)
    assert rec.record("watchdog_trip", "r") is None
    # the heartbeat OOM hook fires through the same guard
    hb = Heartbeat()
    hb.record_error(SimulatedOOM("RESOURCE_EXHAUSTED: simulated"))
    assert rec.recorded_total == 0
    # no log handler was ever installed while disarmed
    assert rec._log_handler is None


def test_kinds_are_closed_set(recorder):
    assert recorder.record("made_up_kind", "r") is None
    assert set(KINDS) == {"watchdog_trip", "dead_escalation",
                          "resource_exhausted", "slo_breach",
                          "disagg_peer_dead", "fleet_peer_ejected"}

"""Tokenizer unit tests with hand-built vocabularies (SURVEY.md §4 "Unit":
tokenizer vs known vectors).  Vocabs are synthetic but exercise the real
algorithms: byte-level BPE merge ranks, SPM score-greedy merging, byte
fallback, special-token parsing, and GGUF metadata loading."""

import numpy as np
import pytest

from llama_fastapi_k8s_gpu_tpu.gguf import GGUFWriter, GGUFFile
from llama_fastapi_k8s_gpu_tpu.tokenizer import (
    BPETokenizer,
    SPMTokenizer,
    apply_chat_template,
    detect_chat_template,
    tokenizer_from_gguf,
)
from llama_fastapi_k8s_gpu_tpu.tokenizer.base import TokenType
from llama_fastapi_k8s_gpu_tpu.tokenizer.bpe import bytes_to_unicode


def make_bpe(extra_tokens=(), merges=(), pre="llama-bpe"):
    byte_tokens = [bytes_to_unicode()[b] for b in range(256)]
    merged_tokens = []
    for m in merges:
        left, _, right = m.partition(" ")
        merged_tokens.append(left + right)
    specials = ["<|begin_of_text|>", "<|start_header_id|>", "<|end_header_id|>",
                "<|eot_id|>"]
    tokens = byte_tokens + merged_tokens + list(extra_tokens) + specials
    types = (
        [int(TokenType.NORMAL)] * (len(byte_tokens) + len(merged_tokens) + len(extra_tokens))
        + [int(TokenType.CONTROL)] * len(specials)
    )
    bos = tokens.index("<|begin_of_text|>")
    eot = tokens.index("<|eot_id|>")
    return BPETokenizer(tokens, list(merges), types, bos_id=bos, eos_id=eot, pre=pre)


MERGES = ["h e", "l l", "he ll", "hell o", "Ġ hello"]


def test_bpe_merge_order():
    tok = make_bpe(merges=MERGES)
    ids = tok.encode("hello hello", add_bos=False)
    assert [tok.id_to_piece(i) for i in ids] == ["hello", "Ġhello"]


def test_bpe_roundtrip_unicode():
    tok = make_bpe(merges=MERGES)
    rng = np.random.default_rng(3)
    samples = [
        "hello world",
        "héllo wörld — ‘quotes’ & €",
        "日本語のテキスト",
        "tabs\tand\nnewlines\r\n  spaces",
        "emoji 🤖🔥",
        "".join(chr(int(c)) for c in rng.integers(32, 0x2FFF, size=64)),
    ]
    for s in samples:
        ids = tok.encode(s, add_bos=False)
        assert tok.decode(ids) == s, repr(s)


def test_bpe_llama3_pretokenizer_splits():
    tok = make_bpe(merges=MERGES)
    # digits grouped ≤3; contractions split; punctuation grabs leading space
    assert tok._pattern.findall("12345") == ["123", "45"]
    assert tok._pattern.findall("I'm fine") == ["I", "'m", " fine"]
    assert tok._pattern.findall("a ,b") == ["a", " ,", "b"]


def test_bpe_special_token_parsing():
    tok = make_bpe(merges=MERGES)
    text = "hello<|eot_id|>"
    with_special = tok.encode(text, add_bos=False, parse_special=True)
    assert with_special[-1] == tok.token_to_id["<|eot_id|>"]
    without = tok.encode(text, add_bos=False, parse_special=False)
    # literal "<|eot_id|>" chars, not the control id
    assert tok.token_to_id["<|eot_id|>"] not in without
    assert tok.decode(without) == text
    # control tokens skipped on decode by default, kept when asked
    assert tok.decode(with_special) == "hello"
    assert tok.decode(with_special, skip_special=False) == text


def test_bpe_add_bos():
    tok = make_bpe(merges=MERGES)
    ids = tok.encode("hello")  # add_bos defaults True
    assert ids[0] == tok.bos_id


SPM_TOKENS = [
    ("<unk>", TokenType.UNKNOWN, 0.0),
    ("<s>", TokenType.CONTROL, 0.0),
    ("</s>", TokenType.CONTROL, 0.0),
    ("▁", TokenType.NORMAL, -1.0),
    ("▁h", TokenType.NORMAL, 1.0),
    ("▁he", TokenType.NORMAL, 2.0),
    ("ll", TokenType.NORMAL, 1.5),
    ("lo", TokenType.NORMAL, 0.5),
    ("llo", TokenType.NORMAL, 3.0),
    ("▁hello", TokenType.NORMAL, 5.0),
    ("h", TokenType.NORMAL, -2.0),
    ("e", TokenType.NORMAL, -2.0),
    ("l", TokenType.NORMAL, -2.0),
    ("o", TokenType.NORMAL, -2.0),
    ("<0xE2>", TokenType.BYTE, 0.0),
    ("<0x82>", TokenType.BYTE, 0.0),
    ("<0xAC>", TokenType.BYTE, 0.0),
]


def make_spm():
    tokens = [t for t, _, _ in SPM_TOKENS]
    types = [int(ty) for _, ty, _ in SPM_TOKENS]
    scores = [s for _, _, s in SPM_TOKENS]
    return SPMTokenizer(tokens, scores, types, bos_id=1, eos_id=2)


def test_spm_score_greedy_merge():
    tok = make_spm()
    ids = tok.encode("hello", add_bos=False)
    assert [tok.id_to_piece(i) for i in ids] == ["▁hello"]
    assert tok.decode(ids) == "hello"


def test_spm_partial_merge_and_decode():
    tok = make_spm()
    ids = tok.encode("he llo", add_bos=False)
    pieces = [tok.id_to_piece(i) for i in ids]
    assert pieces == ["▁he", "▁", "llo"]
    assert tok.decode(ids) == "he llo"


def test_spm_byte_fallback():
    tok = make_spm()
    ids = tok.encode("€", add_bos=False)  # only via <0xE2><0x82><0xAC>
    pieces = [tok.id_to_piece(i) for i in ids]
    assert pieces[-3:] == ["<0xE2>", "<0x82>", "<0xAC>"]
    assert tok.decode(ids) == "€"


def test_spm_bos_and_controls():
    tok = make_spm()
    ids = tok.encode("hello")
    assert ids[0] == 1
    assert tok.decode(ids) == "hello"


def test_chat_template_detection():
    bpe = make_bpe(merges=MERGES)
    spm = make_spm()
    assert detect_chat_template("{{...<|start_header_id|>...}}", spm) == "llama3"
    assert detect_chat_template("{% [INST] %}", bpe) == "mistral"
    assert detect_chat_template(None, bpe) == "llama3"  # vocab fingerprint
    assert detect_chat_template(None, spm) == "mistral"


def test_llama3_chat_template_structure():
    tok = make_bpe(merges=MERGES)
    msgs = [
        {"role": "system", "content": "be nice"},
        {"role": "user", "content": "hello"},
    ]
    ids = apply_chat_template(tok, msgs, kind="llama3")
    sh = tok.token_to_id["<|start_header_id|>"]
    eh = tok.token_to_id["<|end_header_id|>"]
    eot = tok.token_to_id["<|eot_id|>"]
    assert ids[0] == tok.bos_id
    assert ids.count(sh) == 3  # system, user, assistant header
    assert ids.count(eot) == 2
    # ends with assistant header then "\n\n" (no trailing eot)
    assert ids[-1] != eot
    text = tok.decode(ids, skip_special=False)
    assert text.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")
    assert "<|start_header_id|>user<|end_header_id|>\n\nhello<|eot_id|>" in text


def test_mistral_chat_template_structure():
    tok = make_spm()
    msgs = [
        {"role": "system", "content": "sys"},
        {"role": "user", "content": "hello"},
        {"role": "assistant", "content": "hey"},
        {"role": "user", "content": "again"},
    ]
    from llama_fastapi_k8s_gpu_tpu.tokenizer.chat_template import render_mistral
    text = render_mistral(msgs)
    assert text == "[INST] sys\n\nhello [/INST] hey</s>[INST] again [/INST]"


def test_tokenizer_from_gguf_roundtrip(tmp_path):
    p = str(tmp_path / "tok.gguf")
    w = GGUFWriter(p)
    w.add_metadata("general.architecture", "llama")
    byte_tokens = [bytes_to_unicode()[b] for b in range(256)]
    merged = ["he", "ll", "hell", "hello", "Ġhello"]
    specials = ["<|begin_of_text|>", "<|eot_id|>"]
    tokens = byte_tokens + merged + specials
    types = [1] * (len(byte_tokens) + len(merged)) + [3] * 2
    w.add_metadata("tokenizer.ggml.model", "gpt2")
    w.add_metadata("tokenizer.ggml.tokens", tokens)
    w.add_metadata("tokenizer.ggml.token_type", types)
    w.add_metadata("tokenizer.ggml.merges", MERGES)
    w.add_metadata("tokenizer.ggml.bos_token_id", tokens.index("<|begin_of_text|>"))
    w.add_metadata("tokenizer.ggml.eos_token_id", tokens.index("<|eot_id|>"))
    w.add_metadata("tokenizer.ggml.pre", "llama-bpe")
    w.write()

    tok = tokenizer_from_gguf(GGUFFile(p))
    ids = tok.encode("hello hello", add_bos=False)
    assert [tok.id_to_piece(i) for i in ids] == ["hello", "Ġhello"]
    assert tok.decode(ids) == "hello hello"
    assert tok.stop_ids == {tok.token_to_id["<|eot_id|>"]}


# ---------------------------------------------------------------------------
# at-scale: Llama-3-sized merge table (VERDICT r2 #4 — the reference's
# tokenizer behavior is fixed by a real 128k-token/~280k-merge vocab inside
# llama.cpp, reference api.py:56-57; these tests pin correctness AND latency
# of the heap-based merge loop at that scale)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def big_bpe():
    from llama_fastapi_k8s_gpu_tpu.testing import synth_bpe_vocab

    tokens, merges, types = synth_bpe_vocab(n_merges=280_000, seed=0)
    bos = tokens.index("<|begin_of_text|>")
    eot = tokens.index("<|eot_id|>")
    return BPETokenizer(tokens, merges, types, bos_id=bos, eos_id=eot,
                        pre="llama-bpe")


def _bpe_merge_quadratic(ranks, symbols):
    """The round-2 reference algorithm (scan-per-merge): the oracle the heap
    version must agree with exactly."""
    if len(symbols) < 2:
        return symbols
    while True:
        best_rank, best_i = None, -1
        for i in range(len(symbols) - 1):
            r = ranks.get((symbols[i], symbols[i + 1]))
            if r is not None and (best_rank is None or r < best_rank):
                best_rank, best_i = r, i
        if best_rank is None:
            return symbols
        symbols = (symbols[:best_i]
                   + [symbols[best_i] + symbols[best_i + 1]]
                   + symbols[best_i + 2:])


def test_big_vocab_heap_matches_quadratic_oracle(big_bpe):
    rng = np.random.default_rng(7)
    letters = "abcdefghijklmnopqrstuvwxyz"
    for trial in range(25):
        n = int(rng.integers(2, 240))
        s = "".join(letters[int(i)] for i in rng.integers(0, 26, n))
        got = big_bpe._bpe_merge(list(s))
        want = _bpe_merge_quadratic(big_bpe.merge_ranks, list(s))
        assert got == want, (trial, s[:40])


def test_big_vocab_merge_depth(big_bpe):
    # the doubling chain collapses a 2^k run of "ab" into one symbol
    ids = big_bpe.encode("ab" * 2048, add_bos=False)
    assert len(ids) == 1
    assert big_bpe.tokens[ids[0]] == "ab" * 2048


def test_big_vocab_10kb_under_50ms(big_bpe):
    import time

    # worst-ish case: one unbroken 10 KiB letter fragment (no pre-split),
    # deep cascading merges.  The round-2 quadratic loop takes seconds here.
    def best_of(text, n=3):
        """best-of-n: immune to CI scheduling noise, still pins the
        algorithmic bound (the quadratic loop took seconds here)"""
        best = float("inf")
        ids = None
        for _ in range(n):
            t0 = time.perf_counter()
            ids = big_bpe.encode(text, add_bos=False)
            best = min(best, time.perf_counter() - t0)
        assert ids
        return best

    text = "ab" * 5120  # 10 KiB, single \p{L}+ fragment
    big_bpe.encode(text, add_bos=False)  # warm caches
    dt = best_of(text)
    # 80 ms: the quadratic loop this pins took SECONDS, so the bound keeps
    # >12x headroom against the regression while no longer flaking at the
    # 66.3 ms a contended full-suite box measures (isolated runs: ~4-30 ms;
    # widened 50->60->80 as suite size grew — the bound is algorithmic, not
    # a wall-clock SLO)
    assert dt < 0.080, f"10KB encode took {dt*1e3:.1f} ms"

    # and a mixed, space-separated 10 KiB text
    rng = np.random.default_rng(3)
    words = ["".join("abcdefgh"[int(c)] for c in rng.integers(0, 8, int(w)))
             for w in rng.integers(2, 12, 2000)]
    text2 = " ".join(words)[:10240]
    dt2 = best_of(text2)
    assert dt2 < 0.050, f"10KB mixed encode took {dt2*1e3:.1f} ms"


def test_big_vocab_roundtrip(big_bpe):
    text = "the quick brown fox jumps over the lazy dog " * 40
    ids = big_bpe.encode(text, add_bos=False)
    assert big_bpe.decode(ids) == text

"""Model-core tests on the XLA-CPU backend (SURVEY.md §4 "Device tests").

The load-bearing check is teacher-forcing consistency: a full-sequence
forward (return_all) must match incremental prefill+decode through the KV
cache at every position — this pins RoPE positions, cache indexing, masking,
and GQA head grouping all at once.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_fastapi_k8s_gpu_tpu.models import ModelConfig, init_cache, prefill, forward
from llama_fastapi_k8s_gpu_tpu.models.llama import decode_step
from llama_fastapi_k8s_gpu_tpu.models.params import synth_params
from llama_fastapi_k8s_gpu_tpu.ops import linear, make_linear_int8, make_linear_bf16

CFG = ModelConfig(
    vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=64, n_ctx=32, rope_theta=10000.0,
)


@pytest.fixture(scope="module")
def params():
    return synth_params(CFG, fmt="bf16", seed=0)


def test_full_vs_incremental_consistency(params):
    rng = np.random.default_rng(0)
    T = 10
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, T), dtype=jnp.int32)

    # full pass, all logits
    full_logits, _ = forward(
        params, CFG, tokens, jnp.int32(0), init_cache(CFG), return_all=True
    )

    # incremental: prefill 4, then decode the rest one at a time
    P = 4
    cache = init_cache(CFG)
    logits_p, cache = prefill(params, CFG, tokens[:P], jnp.int32(P), cache)
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(full_logits[P - 1]), rtol=0.05, atol=0.05
    )
    for t in range(P, T):
        logits_t, cache = decode_step(params, CFG, tokens[t], jnp.int32(t), cache)
        np.testing.assert_allclose(
            np.asarray(logits_t), np.asarray(full_logits[t]), rtol=0.05, atol=0.05,
            err_msg=f"position {t}",
        )


def test_padded_prefill_matches_exact(params):
    rng = np.random.default_rng(1)
    T = 5
    tokens = rng.integers(0, CFG.vocab_size, T)
    exact = jnp.asarray(tokens, dtype=jnp.int32)
    padded = jnp.asarray(list(tokens) + [0] * 11, dtype=jnp.int32)  # bucket 16

    l1, _ = prefill(params, CFG, exact, jnp.int32(T), init_cache(CFG))
    l2, _ = prefill(params, CFG, padded, jnp.int32(T), init_cache(CFG))
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=0.05, atol=0.05)


def test_decode_after_padded_prefill_ignores_pad(params):
    """Pad slots beyond the prompt must not leak into later decode steps."""
    rng = np.random.default_rng(2)
    T = 5
    tokens = rng.integers(0, CFG.vocab_size, T)
    nxt = int(rng.integers(0, CFG.vocab_size))

    cache = init_cache(CFG)
    _, cache = prefill(params, CFG, jnp.asarray(tokens, jnp.int32), jnp.int32(T), cache)
    la, _ = decode_step(params, CFG, jnp.int32(nxt), jnp.int32(T), cache)

    padded = jnp.asarray(list(tokens) + [7] * 11, dtype=jnp.int32)
    cache2 = init_cache(CFG)
    _, cache2 = prefill(params, CFG, padded, jnp.int32(T), cache2)
    lb, _ = decode_step(params, CFG, jnp.int32(nxt), jnp.int32(T), cache2)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=0.05, atol=0.05)


def test_sliding_window_masks_old_tokens(params):
    rng = np.random.default_rng(3)
    T = 12
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, T), dtype=jnp.int32)

    cfg_full = CFG
    cfg_big_win = ModelConfig(**{**CFG.__dict__, "sliding_window": 64})
    cfg_small_win = ModelConfig(**{**CFG.__dict__, "sliding_window": 4})

    lf, _ = forward(params, cfg_full, tokens, jnp.int32(0), init_cache(cfg_full), return_all=True)
    lb, _ = forward(params, cfg_big_win, tokens, jnp.int32(0), init_cache(cfg_big_win), return_all=True)
    ls, _ = forward(params, cfg_small_win, tokens, jnp.int32(0), init_cache(cfg_small_win), return_all=True)

    # window ≥ seq behaves exactly like full attention
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lb), rtol=1e-5, atol=1e-5)
    # a small window must change late-position logits
    assert not np.allclose(np.asarray(lf[-1]), np.asarray(ls[-1]), rtol=0.05, atol=0.05)
    # ...but not the first position (window covers it)
    np.testing.assert_allclose(np.asarray(lf[0]), np.asarray(ls[0]), rtol=1e-5, atol=1e-5)


def test_int8_linear_close_to_bf16():
    rng = np.random.default_rng(4)
    w = rng.standard_normal((64, 32)).astype(np.float32) * 0.1
    x = jnp.asarray(rng.standard_normal((5, 32)), dtype=jnp.bfloat16)
    y_ref = np.asarray(linear(x, make_linear_bf16(w)), dtype=np.float32)
    y_q = np.asarray(linear(x, make_linear_int8(w)), dtype=np.float32)
    rel = np.abs(y_q - y_ref).max() / (np.abs(y_ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_int8_model_close_to_bf16_model():
    p16 = synth_params(CFG, fmt="bf16", seed=0)
    p8 = synth_params(CFG, fmt="int8", seed=0)
    tokens = jnp.arange(6, dtype=jnp.int32)
    l16, _ = forward(p16, CFG, tokens, jnp.int32(0), init_cache(CFG), return_all=True)
    l8, _ = forward(p8, CFG, tokens, jnp.int32(0), init_cache(CFG), return_all=True)
    # logits drift but top-1 should rarely flip on a random tiny model;
    # require high overlap rather than exact match
    top16 = np.asarray(jnp.argmax(l16, -1))
    top8 = np.asarray(jnp.argmax(l8, -1))
    assert (top16 == top8).mean() >= 0.5


def test_tied_embeddings():
    cfg = ModelConfig(**{**CFG.__dict__, "tie_embeddings": True})
    p = synth_params(cfg, fmt="bf16", seed=5)
    assert p["output"]["w"] is p["tok_emb"]
    logits, _ = forward(p, cfg, jnp.arange(4, dtype=jnp.int32), jnp.int32(0),
                        init_cache(cfg))
    assert logits.shape == (cfg.vocab_size,)
    assert np.isfinite(np.asarray(logits)).all()

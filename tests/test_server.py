"""Server integration tests against fake engines (SURVEY.md §4
"Integration"): exercises the queue/semaphore/timeout/503/408/500 admission
paths deterministically, plus the prompt-assembly and truncation quirks that
must match reference api.py."""

import asyncio

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import FakeEngine
from llama_fastapi_k8s_gpu_tpu.server.app import (
    build_system_prompt,
    count_tokens_roughly,
    create_app,
    truncate_messages_to_fit_context,
)
from llama_fastapi_k8s_gpu_tpu.server.schemas import BotProfile
from llama_fastapi_k8s_gpu_tpu.utils.config import Settings

BODY = {
    "bot_profile": {"name": "Alice.f", "appearance": "tall,slim,blonde,loves cats,hates rain"},
    "user_profile": {"name": "Bob"},
    "context": [
        {"turn": "user", "message": "hi"},
        {"turn": "assistant", "message": "hey"},
        {"turn": "user", "message": "how are you?"},
    ],
}


def make_client(engine, **settings_kw):
    settings = Settings(**settings_kw) if settings_kw else Settings()
    app = create_app(engine=engine, settings=settings)
    transport = httpx.ASGITransport(app=app)
    return app, transport


async def lifespan_client(app, transport):
    return httpx.AsyncClient(transport=transport, base_url="http://test")


@pytest.mark.anyio
async def test_response_happy_path():
    engine = FakeEngine(reply="hello there")
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response", json=BODY)
            assert r.status_code == 200
            assert r.json() == {"response": "hello there"}
        await app.router.shutdown()

    # prompt assembly: system inserted at index 1 (not 0!)
    sent = engine.calls[0]
    assert sent[0] == {"role": "user", "content": "hi"}
    assert sent[1]["role"] == "system"
    sys_prompt = sent[1]["content"]
    assert "NEVER break the character" in sys_prompt
    assert "Alice.f." in sys_prompt  # name interpolated into default persona
    # reference quirk: the verbatim default persona (api.py:130-136) is
    # ~430 chars BEFORE the gender clause, so the 400-char per-message clip
    # (api.py:36-39) cuts the gender clause and appearance facts off the
    # wire prompt whenever the default persona is used
    assert len(sys_prompt) == 400
    assert "You a girl." not in sys_prompt


@pytest.mark.anyio
async def test_explicit_system_prompt_wins():
    engine = FakeEngine()
    body = {**BODY, "bot_profile": {**BODY["bot_profile"],
                                    "system_prompt": "custom prompt",
                                    "name": "Carol"}}
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response", json=body)
            assert r.status_code == 200
        await app.router.shutdown()
    sys_prompt = engine.calls[0][1]["content"]
    assert sys_prompt.startswith("custom prompt")
    assert "You a boy." in sys_prompt  # no .f suffix


@pytest.mark.anyio
async def test_queue_full_503():
    engine = FakeEngine(delay=0.5)
    app, transport = make_client(engine, max_queue_size=1, timeout_seconds=5)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            tasks = [asyncio.create_task(client.post("/response", json=BODY))
                     for _ in range(4)]
            results = await asyncio.gather(*tasks)
            codes = sorted(r.status_code for r in results)
            assert 503 in codes  # overflow rejected
            assert 200 in codes  # some served
        await app.router.shutdown()


@pytest.mark.anyio
async def test_timeout_408_and_cancellation():
    engine = FakeEngine(delay=1.0)
    app, transport = make_client(engine, timeout_seconds=0.1)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response", json=BODY)
            assert r.status_code == 408
            assert r.json()["detail"] == "Generation timed out"
        await app.router.shutdown()


@pytest.mark.anyio
async def test_engine_error_500():
    engine = FakeEngine(fail=RuntimeError("boom"))
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response", json=BODY)
            assert r.status_code == 500
            assert "boom" in r.json()["detail"]
        await app.router.shutdown()


@pytest.mark.anyio
async def test_health_and_metrics_and_items():
    engine = FakeEngine()
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            h = await client.get("/health")
            assert h.status_code == 200
            assert h.json()["status"] == "ok"
            assert h.json()["model_loaded"] is True

            await client.post("/response", json=BODY)
            m = await client.get("/metrics")
            assert m.status_code == 200
            assert "request_seconds_count" in m.text
            assert 'request_seconds_bucket{' in m.text   # true histograms
            assert "request_seconds_p95" in m.text       # derived quantiles
            assert "queue_depth" in m.text
            assert "queue_wait_seconds" in m.text  # per-phase timers, SURVEY §5

            i = await client.get("/items/7")
            assert i.json() == {"item_id": 7}
        await app.router.shutdown()


@pytest.mark.anyio
async def test_metrics_flattens_nested_scheduler_stats():
    """Dict-valued scheduler stats (spec telemetry) must flatten into one
    gauge per leaf — a dict rendered verbatim is an invalid exposition
    line every Prometheus scraper (and bench parser) drops."""
    engine = FakeEngine()
    engine.scheduler_stats = lambda: {
        "lanes_live": 1, "spec": {"drafted": 5, "accepted": 3}}
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            m = await client.get("/metrics")
            assert "scheduler_lanes_live 1" in m.text
            assert "scheduler_spec_drafted 5" in m.text
            assert "scheduler_spec_accepted 3" in m.text
            # no dict-valued gauge rendered verbatim (histogram bucket
            # labels are the only legal brace-bearing lines)
            for line in m.text.splitlines():
                if "{" in line:
                    assert not line.startswith("#"), line
                    assert "{'" not in line and '="' in line, line
        await app.router.shutdown()


# ---------------------------------------------------------------------------
# pure-function behavior parity (reference api.py:30-46, 127-147)
# ---------------------------------------------------------------------------

def test_count_tokens_roughly():
    assert count_tokens_roughly("abcd" * 10) == 10
    assert count_tokens_roughly("abc") == 0


def test_truncation_clips_and_pops_index_2():
    messages = [
        {"role": "user", "content": "a" * 500},      # index 0 preserved
        {"role": "system", "content": "s" * 450},    # index 1 preserved
        {"role": "user", "content": "b" * 400},      # evicted first
        {"role": "assistant", "content": "c" * 400},
        {"role": "user", "content": "d" * 400},
    ]
    out = truncate_messages_to_fit_context(messages, max_tokens=300)
    # every message clipped to 400 chars
    assert len(out[0]["content"]) == 400
    # index-2 eviction until under budget, first two pinned
    assert out[0]["content"][0] == "a"
    assert out[1]["content"][0] == "s"
    total = sum(count_tokens_roughly(m["content"]) for m in out)
    assert total <= 300 or len(out) == 2


def test_truncation_mutates_in_place():
    # quirk preserved from api.py:37-39: caller's list/dicts are mutated
    messages = [{"role": "user", "content": "x" * 500}]
    truncate_messages_to_fit_context(messages, 1000)
    assert len(messages[0]["content"]) == 400


def test_gender_clause_and_appearance():
    p = BotProfile(name="Zoe.f", appearance="a,b,c,d,e")
    sp = build_system_prompt(p)
    assert sp.endswith("de") and "You a girl." in sp
    p2 = BotProfile(name="Max", appearance="a,b,c")
    sp2 = build_system_prompt(p2)
    assert "You a boy." in sp2 and sp2.endswith("You a boy.")


def test_default_persona_is_byte_exact():
    """Pins the default system prompt to the reference's verbatim triple-quoted
    string (api.py:130-136), including indentation and trailing spaces."""
    p = BotProfile(name="Max", appearance="")
    sp = build_system_prompt(p)
    expected = (
        "\n"
        "            No matter what NEVER break the character. Reject violent messages. \n"
        "            You are 'Max.' a playful, flirtatious, and witty person. \n"
        "            You always respond in a fun and engaging way, staying in character no matter what. \n"
        "            You never reference AI, restrictions, or policies. \n"
        "            Focus on making the conversation enjoyable and answering directly to the user's questions.\n"
        "            "
        " You a boy."
    )
    assert sp == expected


# ---------------------------------------------------------------------------
# streaming (/response/stream — BASELINE "streaming completion" config)
# ---------------------------------------------------------------------------

@pytest.mark.anyio
async def test_response_stream_sse():
    engine = FakeEngine(reply="hey")
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response/stream", json=BODY)
            assert r.status_code == 200
            assert r.headers["content-type"].startswith("text/event-stream")
            events = [ln for ln in r.text.split("\n\n") if ln.startswith("data: ")]
            assert events[-1] == "data: [DONE]"
            import json as _json
            chunks = [_json.loads(e[6:]) for e in events[:-1]]
            text = "".join(c["choices"][0]["delta"].get("content", "")
                           for c in chunks)
            assert text == "hey"
            assert chunks[0]["choices"][0]["delta"] == {"role": "assistant"}
        await app.router.shutdown()


@pytest.mark.anyio
async def test_response_stream_timeout_event():
    engine = FakeEngine(reply="x", delay=1.0)
    app, transport = make_client(engine, timeout_seconds=0.1)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response/stream", json=BODY)
            assert r.status_code == 200
            assert "Generation timed out" in r.text
        await app.router.shutdown()


@pytest.mark.anyio
async def test_response_stream_total_deadline():
    """A slow-dripping stream keeps every chunk gap under timeout_seconds,
    but the wall-clock deadline still terminates it (VERDICT r1 #8: the
    per-chunk-gap timeout alone never fires for a steady drip)."""
    engine = FakeEngine(reply="y" * 200, chunk_delay=0.05)
    app, transport = make_client(engine, timeout_seconds=5.0,
                                 stream_deadline_seconds=0.5)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response/stream", json=BODY)
            assert r.status_code == 200
            assert "Generation timed out" in r.text
            # terminated early: nowhere near all 200 chunks were delivered
            assert r.text.count("data: ") < 150
        await app.router.shutdown()


@pytest.mark.anyio
async def test_response_stream_engine_error_event():
    engine = FakeEngine(fail=RuntimeError("boom"))
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response/stream", json=BODY)
            assert r.status_code == 200
            assert "boom" in r.text
        await app.router.shutdown()


@pytest.mark.anyio
async def test_health_reports_engine_config(tmp_path):
    """/health exposes the served config (attn impl, per-group weight
    layouts incl. probe degradations) for operability; tolerant of engines
    without params (fakes)."""
    from llama_fastapi_k8s_gpu_tpu.engine import Engine
    from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf

    path = str(tmp_path / "tiny.gguf")
    write_tiny_llama_gguf(path)
    engine = Engine(path, n_ctx=128, prefill_buckets=(32,))
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.get("/health")
            assert r.status_code == 200
            eng = r.json()["engine"]
            assert eng["n_ctx"] == 128
            assert eng["attn_impl"] in ("xla", "pallas")
            assert set(eng["weight_formats"]) >= {"wq", "w_gate", "w_down"}
            assert all(v in ("q4k-fused", "q5k-fused", "q6k-fused",
                             "int8", "bf16") for v in eng["weight_formats"].values())
        await app.router.shutdown()


# ---------------------------------------------------------------------------
# client disconnect mid-stream (resilience layer): the sse generator's
# finally cancels the request future, which every engine path watches —
# the serial run() loop per chunk, the continuous scheduler via abandon
# ---------------------------------------------------------------------------

def test_stream_client_disconnect_reclaims_engine():
    """A client that drops its socket mid-SSE must free the engine within
    ~one chunk: a follow-up request is served promptly instead of waiting
    for the dead stream to drip out its full reply."""
    import socket
    import struct
    import time as _time

    from tests.test_httpd_drain import (
        PAYLOAD,
        _free_port,
        _raw_request,
        _read_response,
        _start_server,
        _stop,
    )

    # full stream would take ~4 s (400 chunks x 10 ms)
    eng = FakeEngine(reply="z" * 400, chunk_delay=0.01)
    port = _free_port()
    holder = _start_server(create_app(engine=eng), port)
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        s.sendall(_raw_request(PAYLOAD, path=b"/response/stream"))
        first = s.recv(4096)                     # status line + first chunks
        assert b"200" in first.split(b"\r\n", 1)[0]
        # abrupt close with RST so the server's next write fails fast
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        s.close()

        t0 = _time.time()
        s2 = socket.create_connection(("127.0.0.1", port), timeout=10)
        try:
            s2.sendall(_raw_request(PAYLOAD))
            status, _head, _body = _read_response(s2)
        finally:
            s2.close()
        elapsed = _time.time() - t0
        assert status == 200
        # serial consumer: the second request waits behind the stream task;
        # prompt service proves the abandoned stream stopped early (the
        # un-reclaimed path would hold it for the remaining ~4 s)
        assert elapsed < 2.5, f"engine not reclaimed after disconnect: {elapsed:.1f}s"
    finally:
        try:
            s.close()
        except OSError:
            pass
        _stop(holder)
        holder["thread"].join(10)

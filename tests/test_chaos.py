"""Chaos drills for fleet KV survivability (ISSUE 17;
serving/fleet/migrate.py + tools/chaos_drill.py).

Layers, all tier-1 on CPU:

1. **Smoke** (the ci_gate ``chaos-drill`` subset, ``-k smoke``) — real
   pools + the real wire, no engines: pull round trips bitwise, every
   fault point (``migrate_pull``/``migrate_push``/``drain_push``)
   degrades with attribution and zero pinned pages, graceful drain is
   a commanded pull on the successor, the router stamps
   ``x-lfkt-prior-owner``/``x-lfkt-affinity-key`` itself (stripping
   inbound forgeries) and answers 503 + Retry-After at the spill
   budget.
2. **In-process drain drill** — two real tiny-GGUF engines with
   migration armed: stopping replica A runs the httpd drain sequence,
   whose drain-push hands A's hottest pages to B; B's first post-drain
   turn is warm.
3. **Multi-process SIGKILL drill** — real server processes behind the
   affinity router: kill the owner mid-stream (bounded client-visible
   errors, attributed pull failures while the owner is down), restart
   it (re-admission makes it "fresh", the router stamps the interim
   owner, the restarted pod pulls its conversations back) and pin the
   token-weighted prefix hit ratio of the warm restart at >= 2x the
   cold spill-over control — plus greedy parity and fleet-wide
   ``pages_pinned == 0`` at the end.
"""

from __future__ import annotations

import http.server
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from llama_fastapi_k8s_gpu_tpu.models.config import ModelConfig
from llama_fastapi_k8s_gpu_tpu.models.llama import init_cache
from llama_fastapi_k8s_gpu_tpu.parallel.kvpool import KVPool
from llama_fastapi_k8s_gpu_tpu.serving.fleet.affinity import (
    AFFINITY_KEY_HEADER,
    PRIOR_OWNER_HEADER,
    affinity_key,
    rendezvous_rank,
)
from llama_fastapi_k8s_gpu_tpu.serving.fleet.migrate import (
    MigrationManager,
    MigrationServer,
)
from llama_fastapi_k8s_gpu_tpu.serving.fleet.peers import PeerTable
from llama_fastapi_k8s_gpu_tpu.serving.fleet.router import FleetRouter
from llama_fastapi_k8s_gpu_tpu.utils.config import Settings
from llama_fastapi_k8s_gpu_tpu.utils.faults import FAULTS
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

from tests.test_fleet import (  # noqa: F401 — shared fleet drill helpers
    _body,
    _free_port,
    _get_json,
    _metric_sum,
    _post,
    _proc_env,
    _serve_app,
    _serve_router,
    _spawn_replica,
    _table,
    _wait_proc_ready,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = ModelConfig(vocab_size=263, dim=16, n_layers=2, n_heads=4,
                  n_kv_heads=2, ffn_dim=32, n_ctx=64)
T = 8


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _marked_ring(cfg=CFG):
    from tests.test_kvpool import marked_ring
    return marked_ring(cfg)


def _mgr(pool, *, peers: str = "", self_addr: str = "",
         timeout: float = 2.0, drain: float = 3.0, top_k: int = 8,
         metrics=None):
    """A served MigrationServer + its manager, on an ephemeral port."""
    server = MigrationServer(pool, host="127.0.0.1", port=0,
                             metrics=metrics)
    settings = Settings(fleet_peers=peers, migrate_self=self_addr,
                        migrate_timeout_seconds=timeout,
                        migrate_drain_seconds=drain, migrate_top_k=top_k)
    return MigrationManager(pool, settings, metrics=metrics, server=server)


def _assert_prefix_equal(got, want, tokens):
    from tests.test_kvpool import assert_prefix_equal
    assert_prefix_equal(got, want, tokens)


# ---------------------------------------------------------------------------
# layer 1: smoke (the ci_gate chaos-drill subset)
# ---------------------------------------------------------------------------

def test_smoke_pull_round_trip_bitwise_and_warm_skip():
    """A pull over the real wire lands bit-identical pages, a re-pull
    dedups locally (skipped_warm, no wire traffic), and nothing stays
    pinned on either side."""
    ring = _marked_ring()
    src = _mgr(KVPool(CFG, page_tokens=T, n_pages=8))
    dst = _mgr(KVPool(CFG, page_tokens=T, n_pages=8), metrics=Metrics())
    try:
        ids = list(range(1, 26))                   # 25 ids: 3 whole pages
        assert src._pool.commit(ids, ring, namespace="m") == 3
        got = dst.pull(src.wire_addr, ids, namespace="m")
        assert got == 24                           # remap: (25-1)//8*8
        lease = dst._pool.acquire(ids[:24], 24, namespace="m")
        assert lease is not None
        _assert_prefix_equal(dst._pool.restore(lease, init_cache(CFG)),
                             ring, 24)
        dst._pool.release(lease)

        assert dst.pull(src.wire_addr, ids, namespace="m") == 24
        assert dst.counters["skipped_warm"] == 1
        assert dst.counters["pulls"] == 1          # the warm skip was free

        # a cold miss on the far side is honest: 0, no failure attributed
        assert dst.pull(src.wire_addr, list(range(900, 930)),
                        namespace="m") == 0
        assert dst.counters["failures"] == 0
        assert src._pool.occupancy()["pages_pinned"] == 0
        assert dst._pool.occupancy()["pages_pinned"] == 0
        assert src.server.status()["pulls_served"] == 1
        assert src.server.status()["pulls_cold"] == 1
        assert dst.metrics.render().count("kv_migration_") > 0
    finally:
        src.close()
        dst.close()


def test_smoke_geometry_mismatch_refused_with_attribution():
    """Two pools that cannot exchange pages bit-exactly refuse at the
    handshake — attributed, never corrupted KV."""
    other = ModelConfig(vocab_size=263, dim=16, n_layers=2, n_heads=4,
                        n_kv_heads=4, ffn_dim=32, n_ctx=64)
    src = _mgr(KVPool(CFG, page_tokens=T, n_pages=8))
    dst = _mgr(KVPool(other, page_tokens=T, n_pages=8))
    try:
        src._pool.commit(list(range(1, 17)), _marked_ring())
        assert dst.pull(src.wire_addr, list(range(1, 18))) == 0
        assert dst.counters["failures"] == 1
        assert dst.last_error.startswith("geometry")
        assert src.server.status()["handshake_refusals"] == 1
    finally:
        src.close()
        dst.close()


def test_smoke_fault_points_degrade_attributed():
    """Every migration fault point degrades to a 0-token pull (or an
    attributed drain skip) without raising, hanging, or leaking pins."""
    ring = _marked_ring()
    src = _mgr(KVPool(CFG, page_tokens=T, n_pages=8))
    dst = _mgr(KVPool(CFG, page_tokens=T, n_pages=8))
    try:
        ids = list(range(1, 26))
        src._pool.commit(ids, ring, namespace="m")

        # migrate_pull error: the hop dies inside the client
        FAULTS.arm("migrate_pull:error:times=1")
        assert dst.pull(src.wire_addr, ids, namespace="m") == 0
        assert dst.counters["failures"] == 1
        assert dst.last_error.startswith("wire")

        # migrate_push error: the SERVER dies between page groups — the
        # puller sees a torn stream, attributed, bounded
        FAULTS.arm("migrate_push:error:times=1")
        t0 = time.time()
        assert dst.pull(src.wire_addr, ids, namespace="m") == 0
        assert time.time() - t0 < dst.timeout + 2.0
        assert dst.counters["failures"] == 2

        # migrate_pull slow: the deadline clips the hop — never a hang
        FAULTS.arm("migrate_pull:slow:delay=1.0:times=1")
        t0 = time.time()
        assert dst.pull(src.wire_addr, ids, namespace="m",
                        deadline=time.time() + 0.3) == 0
        assert time.time() - t0 < 3.0
        assert dst.counters["failures"] == 3
        assert dst.last_error.startswith("deadline")

        # the wire recovers once the faults are spent
        FAULTS.disarm()
        assert dst.pull(src.wire_addr, ids, namespace="m") == 24
        assert src._pool.occupancy()["pages_pinned"] == 0
        assert dst._pool.occupancy()["pages_pinned"] == 0
    finally:
        src.close()
        dst.close()


class _SuccessorStub:
    """A successor replica's HTTP surface, minus the engine: /health
    advertises the migration wire addr, POST /admin/migrate/pull runs a
    real pull into a real pool — exactly what a DRAINING pod commands."""

    def __init__(self, mgr: MigrationManager):
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def _reply(self, doc, code=200):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("content-type", "application/json")
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):           # noqa: N802 — stdlib contract
                self._reply({"migration": {"addr": mgr.wire_addr}})

            def do_POST(self):          # noqa: N802 — stdlib contract
                n = int(self.headers.get("content-length") or 0)
                req = json.loads(self.rfile.read(n))
                covered = mgr.pull(
                    req["peer"], [int(t) for t in req["ids"]],
                    namespace=str(req.get("namespace") or ""),
                    reason="drain", deadline=req.get("deadline"))
                self._reply({"covered": covered})

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()


def test_smoke_drain_push_is_a_commanded_pull():
    """Graceful drain: the DRAINING pod commands its successor to pull
    each recorded conversation — pages land bitwise on the successor,
    keyless fallback ships the pool's hottest runs, and a drain_push
    fault degrades to an attributed skip without delaying anything."""
    ring = _marked_ring()
    succ = _mgr(KVPool(CFG, page_tokens=T, n_pages=8))
    stub = _SuccessorStub(succ)
    me = "127.0.0.1:59999"
    src = _mgr(KVPool(CFG, page_tokens=T, n_pages=8),
               peers=f"127.0.0.1:{stub.port},{me}", self_addr=me,
               drain=3.0, top_k=4)
    try:
        a = list(range(1, 17))
        b = list(range(100, 125))
        src._pool.commit(a, ring, namespace="m")
        src._pool.commit(b, ring, namespace="m")
        src.record_prompt("conv-a", "m", a)
        src.record_prompt("conv-b", "m", b)

        assert src.drain_push() == 2
        assert src.counters["drain_pushes"] == 2
        assert succ.counters["pulls"] == 2
        assert succ._pool.match_len(a, namespace="m") == 16
        assert succ._pool.match_len(b, namespace="m") == 24
        lease = succ._pool.acquire(a, 16, namespace="m")
        _assert_prefix_equal(succ._pool.restore(lease, init_cache(CFG)),
                             ring, 16)
        succ._pool.release(lease)
        assert succ._pool.occupancy()["pages_pinned"] == 0
        assert src._pool.occupancy()["pages_pinned"] == 0
    finally:
        src.close()

    # keyless fallback: no router-stamped traffic, the pool's hottest
    # runs still survive; a drain_push fault skips with attribution
    src2 = _mgr(KVPool(CFG, page_tokens=T, n_pages=8),
                peers=f"127.0.0.1:{stub.port},{me}", self_addr=me,
                drain=3.0, top_k=4)
    try:
        c = list(range(300, 325))
        src2._pool.commit(c, ring, namespace="m")
        FAULTS.arm("drain_push:error:times=1")
        t0 = time.time()
        pushed = src2.drain_push()
        assert time.time() - t0 < src2.drain_budget + 1.0
        assert pushed == 0
        assert src2.counters["drain_failures"] == 1
        assert src2.last_error.startswith("drain_push")
        assert succ._pool.match_len(c, namespace="m") == 0
    finally:
        src2.close()
        succ.close()
        stub.close()


class _CaptureBackend:
    """A raw TCP backend that records each request head and answers a
    minimal HTTP 200 — for asserting exactly what the router forwards."""

    def __init__(self):
        self.heads: list[bytes] = []
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            try:
                c, _ = self._sock.accept()
            except OSError:
                return
            try:
                c.settimeout(5.0)
                buf = b""
                while b"\r\n\r\n" not in buf:
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                head = buf.split(b"\r\n\r\n")[0]
                self.heads.append(head)
                c.sendall(b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n"
                          b"connection: close\r\n\r\nok")
            except OSError:
                pass
            finally:
                try:
                    c.close()
                except OSError:
                    pass

    def close(self):
        self._sock.close()


def _router_on(table, port, **kw):
    router = FleetRouter(table, policy="affinity", metrics=Metrics(), **kw)
    return router, _serve_router(router, port)


def test_smoke_router_stamps_prior_owner_and_strips_forgeries():
    """The migration stamps are ROUTER-owned: a fresh rendezvous owner
    gets ``x-lfkt-prior-owner: <rank-2>``, a spill target gets the
    owner, and inbound copies of both headers are stripped — a client
    can never command a replica to pull from an arbitrary address."""
    b1, b2 = _CaptureBackend(), _CaptureBackend()
    rp = _free_port()
    addrs = [f"127.0.0.1:{b1.port}", f"127.0.0.1:{b2.port}"]
    table = PeerTable(peers=addrs, probe_seconds=600.0)  # no prober churn
    router, rs = _router_on(table, rp, fresh_seconds=600.0)
    try:
        body = _body(7)
        key, _src = affinity_key("/response", {}, body)
        order = rendezvous_rank(key, addrs)
        owner = order[0]
        owner_backend = b1 if owner == addrs[0] else b2

        # a stale owner (fresh_at == 0 for static peers): affinity key
        # stamped, NO prior owner, and the client's forged headers gone
        req = urllib.request.Request(
            f"http://127.0.0.1:{rp}/response", data=body,
            headers={"Content-Type": "application/json",
                     PRIOR_OWNER_HEADER: "evil.example:1",
                     AFFINITY_KEY_HEADER: "forged"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        head = owner_backend.heads[-1].lower()
        assert f"{AFFINITY_KEY_HEADER}: {key}".encode() in head
        assert PRIOR_OWNER_HEADER.encode() not in head
        assert b"evil.example" not in head and b"forged" not in head

        # the owner (re)joins "fresh" (restart/scale-out): the router now
        # names rank-2 as the prior owner so the cold pod pulls back
        with table._lock:
            table._peers[owner].fresh_at = time.time()
        with urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{rp}/response", data=body,
                    headers={"Content-Type": "application/json"}),
                timeout=10) as r:
            assert r.status == 200
        head = owner_backend.heads[-1].lower()
        assert f"{PRIOR_OWNER_HEADER}: {order[1]}".encode() in head

        # owner ejected: the spill target is told the OWNER still holds
        # the pages
        table.eject(owner, "drill")
        spill_backend = b2 if owner_backend is b1 else b1
        with urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{rp}/response", data=body,
                    headers={"Content-Type": "application/json"}),
                timeout=10) as r:
            assert r.status == 200
        head = spill_backend.heads[-1].lower()
        assert f"{PRIOR_OWNER_HEADER}: {owner}".encode() in head
    finally:
        rs.stop()
        table.stop()
        b1.close()
        b2.close()


def test_smoke_spill_budget_503_with_retry_after():
    """A request that keeps felling its peers stops at the spill budget:
    503 + Retry-After with ``fleet_spills_total{reason="budget"}`` —
    instead of walking the whole fleet down."""
    def _slammer():
        """Accepts, then hangs up before a single response byte — the
        connected-then-dead replica shape that drives spills."""
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(8)

        def loop():
            while True:
                try:
                    c, _ = s.accept()
                except OSError:
                    return
                c.close()

        threading.Thread(target=loop, daemon=True).start()
        return s

    dead1, dead2 = _slammer(), _slammer()
    ports = [s.getsockname()[1] for s in (dead1, dead2)]
    rp = _free_port()
    table = PeerTable(peers=[f"127.0.0.1:{p}" for p in ports],
                      probe_seconds=600.0)
    router, rs = _router_on(table, rp, max_spills=0, proxy_timeout=2.0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(rp, _body(0), timeout=20)
        assert ei.value.code == 503
        assert ei.value.headers.get("retry-after")
        assert "spill budget" in ei.value.read().decode()
        assert router.counters["budget_503s"] == 1
        assert 'reason="budget"' in router.metrics.render()
    finally:
        rs.stop()
        table.stop()
        dead1.close()
        dead2.close()


# ---------------------------------------------------------------------------
# layer 2: in-process graceful-drain drill on real engines
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gguf_path(tmp_path_factory):
    from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
    p = str(tmp_path_factory.mktemp("chaos") / "tiny.gguf")
    write_tiny_llama_gguf(p)
    return p


def _migrating_engine(path):
    from llama_fastapi_k8s_gpu_tpu.engine import Engine
    return Engine(path, n_ctx=256, prefill_buckets=(64, 128),
                  max_gen_tokens=8, decode_chunk=4, kv_paged=True,
                  kv_page_tokens=16)


def test_drain_hands_hot_pages_to_successor(gguf_path):
    """SIGTERM-equivalent stop of replica A runs the httpd drain, whose
    migration push lands A's hottest pages on B BEFORE A's page service
    dies — so B's first post-drain turn reuses prompt tokens instead of
    recomputing them, and B ends with zero pinned pages."""
    pa, pb = _free_port(), _free_port()
    fleet = f"127.0.0.1:{pa},127.0.0.1:{pb}"
    common = dict(migrate=True, migrate_bind="127.0.0.1", migrate_port=0,
                  fleet_peers=fleet, migrate_drain_seconds=5.0,
                  migrate_timeout_seconds=10.0, migrate_top_k=4)
    sb = _serve_app(_migrating_engine(gguf_path), pb,
                    migrate_self=f"127.0.0.1:{pb}", **common)
    sa = _serve_app(_migrating_engine(gguf_path), pa,
                    migrate_self=f"127.0.0.1:{pa}", **common)
    try:
        assert _get_json(pa, "/health")["migration"]["addr"]
        body = _body(3, opener="The quick brown fox jumps over the lazy "
                               "dog near the riverbank tonight")
        _status, _raw = _post(pa, body, timeout=300)   # warm A only
        reused_b0 = _metric_sum(pb, "prefix_cache_reused_tokens_total")
        pulls_b0 = _metric_sum(pb, "kv_migration_pulls_total")

        sa.stop(join_s=30)                             # SIGTERM drain path

        # B pulled A's hot pages during the drain window
        assert _metric_sum(pb, "kv_migration_pulls_total") > pulls_b0
        doc = _get_json(pb, "/health")
        assert doc["migration"]["counters"]["pulls"] >= 1
        # ... so B's FIRST turn for A's conversation starts warm
        _status, _raw = _post(pb, body, timeout=300)
        assert _metric_sum(
            pb, "prefix_cache_reused_tokens_total") > reused_b0
        assert _get_json(pb, "/health")["engine"]["kv_pool"][
            "pages_pinned"] == 0
    finally:
        sa.stop()
        sb.stop()


# ---------------------------------------------------------------------------
# layer 3: the multi-process SIGKILL drill
# ---------------------------------------------------------------------------

def _labeled_metric(port: int, name: str, **labels) -> float:
    """Sum of a metric's series whose label set includes ``labels``."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        text = r.read().decode()
    total = 0.0
    want = [f'{k}="{v}"' for k, v in labels.items()]
    for ln in text.splitlines():
        head, _, val = ln.rpartition(" ")
        if head.startswith(name + "{") and all(w in head for w in want):
            total += float(val)
    return total


def _ratio_delta(port: int, before: dict) -> tuple[float, dict]:
    now = {
        "reused": _metric_sum(port, "prefix_cache_reused_tokens_total"),
        "prompt": _metric_sum(port, "tokens_prompt_total"),
    }
    d = {k: now[k] - before.get(k, 0.0) for k in now}
    return (d["reused"] / d["prompt"] if d["prompt"] else 0.0), now


def _turn(rp: int, histories: dict, phase: str) -> None:
    for c, hist in histories.items():
        _status, raw = _post(rp, _body(c, history=hist), timeout=300)
        reply = json.loads(raw)["response"]
        hist.append({"turn": "bot", "message": (reply or "...")[:400]})
        hist.append({"turn": "user",
                     "message": f"[{phase}] Please tell me more."})


def test_sigkill_migration_drill(tmp_path):
    """THE survivability acceptance drill (ISSUE 17), two real replica
    processes with migration armed behind the affinity router:

    (a) greedy parity: routed bytes == direct bytes;
    (b) SIGKILL the owner mid-stream: the stream terminates bounded, the
        next turns spill to the survivor with the pull degrade
        ATTRIBUTED (the stamped prior owner is dead) — this cold
        spill-over batch is the control arm;
    (c) restart the owner: re-admission marks it fresh, the router
        stamps the interim owner, and the restarted pod pulls its
        conversations back (kv_migration_pulls_total{reason=remap}) —
        its first batch's token-weighted prefix hit ratio is >= 2x the
        control's;
    (d) pages_pinned == 0 on every live replica at the end.
    """
    from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
    write_tiny_llama_gguf(str(tmp_path / "tiny.gguf"))
    p1, p2 = 8075, 8076
    rp = _free_port()
    fleet = f"127.0.0.1:{p1},127.0.0.1:{p2}"

    def extra(port):
        return {
            "LFKT_MIGRATE": "1",
            "LFKT_MIGRATE_BIND": "127.0.0.1",
            "LFKT_MIGRATE_PORT": "0",
            "LFKT_MIGRATE_SELF": f"127.0.0.1:{port}",
            "LFKT_FLEET_PEERS": fleet,
            # warm-up covers at most ONE prefix, so the post-restart
            # warmth below is attributable to pull-on-remap
            "LFKT_MIGRATE_TOP_K": "1",
            "LFKT_MIGRATE_TIMEOUT_SECONDS": "10.0",
            "LFKT_MIGRATE_DRAIN_SECONDS": "3.0",
        }

    proc1 = _spawn_replica(p1, str(tmp_path), **extra(p1))
    proc2 = _spawn_replica(p2, str(tmp_path), **extra(p2))
    table = rs = None
    revived = None
    try:
        deadline = time.time() + 420
        _wait_proc_ready(proc1, p1, deadline)
        _wait_proc_ready(proc2, p2, deadline)
        table = _table([p1, p2]).start()
        router = FleetRouter(table, policy="affinity", metrics=Metrics(),
                             fresh_seconds=600.0)
        rs = _serve_router(router, rp)

        # (a) parity while both replicas are pristine
        body = _body(99, opener="The quick brown fox jumps over the lazy "
                                "dog near the old riverbank ok")
        _st, direct = _post(p1, body, timeout=300)
        _st, routed = _post(rp, body, timeout=300)
        assert routed == direct

        # pick 3 conversations OWNED by p1 (the victim-to-be).  The
        # affinity key hashes bot name + system prompt + the FIRST
        # context message, so ownership must be computed with the same
        # opener the replay sends (ctx[0] never changes across turns).
        def _opener(c):
            return [{"turn": "user",
                     "message": f"Hello bot {c}! The quick brown fox "
                                "jumps over the lazy dog near the "
                                "riverbank while autumn leaves drift "
                                "slowly down."}]

        addrs = [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"]
        victim_addr, survivor_port = addrs[0], p2
        convs = []
        for c in range(200, 300):
            key, src = affinity_key(
                "/response", {}, _body(c, history=_opener(c)))
            assert src == "prefix"
            if rendezvous_rank(key, addrs)[0] == victim_addr and \
                    len(convs) < 3:
                convs.append(c)
        assert len(convs) == 3
        histories = {c: _opener(c) for c in convs}
        _turn(rp, histories, "warm")               # victim owns + records

        # (b) SIGKILL the owner mid-stream
        stream_req = urllib.request.Request(
            f"http://127.0.0.1:{rp}/response/stream",
            data=_body(convs[0], history=histories[convs[0]]),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(stream_req, timeout=60)
        assert resp.readline() is not None
        proc1.send_signal(signal.SIGKILL)
        proc1.wait(timeout=30)
        t0 = time.time()
        try:
            while resp.readline():
                pass
        except Exception:  # noqa: BLE001 — a torn stream is a valid end
            pass
        assert time.time() - t0 < 30, "stream did not terminate bounded"
        resp.close()

        # control arm: the survivor serves the next batch COLD (its pull
        # attempt against the dead prior owner degrades, attributed) —
        # and every request still answers 200
        before_b = _ratio_delta(survivor_port, {})[1]
        fails_b0 = _metric_sum(survivor_port, "kv_migration_failures_total")
        _turn(rp, histories, "spill")
        cold_ratio, _ = _ratio_delta(survivor_port, before_b)
        assert _metric_sum(survivor_port,
                           "kv_migration_failures_total") > fails_b0
        surv_doc = _get_json(survivor_port, "/health")
        assert surv_doc["migration"]["last_error"]

        # (c) restart the victim: re-admitted => fresh => the router
        # stamps the interim owner and the pod pulls its pages back
        revived = _spawn_replica(p1, str(tmp_path), **extra(p1))
        _wait_proc_ready(revived, p1, time.time() + 420)
        deadline = time.time() + 30
        while len(table.healthy()) < 2 and time.time() < deadline:
            time.sleep(0.3)
        assert len(table.healthy()) == 2
        before_a = _ratio_delta(p1, {})[1]
        _turn(rp, histories, "back")
        warm_ratio, _ = _ratio_delta(p1, before_a)
        assert _labeled_metric(p1, "kv_migration_pulls_total",
                               reason="remap") >= 1
        assert warm_ratio > 0.3, warm_ratio
        assert warm_ratio >= 2.0 * cold_ratio, (warm_ratio, cold_ratio)

        # (d) nothing stays pinned fleet-wide
        for port in (p1, survivor_port):
            assert _get_json(port, "/health")["engine"]["kv_pool"][
                "pages_pinned"] == 0
    finally:
        if rs is not None:
            rs.stop()
        if table is not None:
            table.stop()
        for p in (proc1, proc2, revived):
            if p is not None and p.poll() is None:
                p.terminate()
        for p in (proc1, proc2, revived):
            if p is not None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()

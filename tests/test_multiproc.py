"""Multi-process serving stance (VERDICT r4 missing #3 / next #10).

The reference's app image runs `gunicorn -w N` (reference
docker/Dockerfile.app:12; BASELINE config #5).  On TPU the scaling axes are
different and deliberate:

- within one chip: in-process lanes (`LFKT_BATCH_SIZE`, continuous
  batching) — N worker processes would load N model copies and fight over
  the chip's single claimant slot, so `LFKT_WORKERS>1` is REFUSED
  (server/__main__.py), pinned here;
- across processes: by ROLE, not by copy — `LFKT_DISAGG_ROLE` splits
  prefill and decode into cooperating processes streaming KV pages
  (serving/disagg/; drilled in tests/test_disagg.py), which the refusal
  message now names as the principled multi-process path;
- across chips: k8s `replicas` of the 1-worker pod (helm/values.yaml) —
  the two-replica analogue is smoke-tested here as two real server
  processes on one host, each with its own engine, both serving the
  reference wire shape concurrently.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from tests.test_server import BODY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(port: int, model_dir: str) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # tiny-model serving knobs: small bucket set so per-process warmup
        # compiles stay in seconds
        "LFKT_MODEL_DIR": model_dir,
        "LFKT_MODEL_NAME": "tiny.gguf",
        "LFKT_HOST": "127.0.0.1",
        "LFKT_PORT": str(port),
        "LFKT_PREFILL_BUCKETS": "64,128",
        "LFKT_MAX_GEN_TOKENS": "8",
        "LFKT_DECODE_CHUNK": "4",
    })
    # the conftest's virtual 8-device mesh flag is per-process; a serving
    # replica needs only one CPU device
    env.pop("XLA_FLAGS", None)
    return env


def test_multi_worker_request_is_refused():
    """`-w 2`'s analogue must fail loudly BEFORE touching the model/device,
    naming the supported scaling axes."""
    proc = subprocess.run(
        [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.server"],
        env={**os.environ, "LFKT_WORKERS": "2", "JAX_PLATFORMS": "cpu"},
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode != 0
    assert "LFKT_WORKERS=2 refused" in proc.stderr
    assert "LFKT_BATCH_SIZE" in proc.stderr      # points at the right axes:
    assert "LFKT_DISAGG_ROLE" in proc.stderr     # lanes within a chip, roles
    assert "replicas" in proc.stderr             # across processes, replicas
    #                                              across chips


def test_two_replica_processes_serve_concurrently(tmp_path):
    """Two 1-worker server processes (the k8s replicas model) on one host:
    both become ready and both answer the reference's `/response` wire
    shape with independent engines."""
    from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf

    write_tiny_llama_gguf(str(tmp_path / "tiny.gguf"))
    ports = (8031, 8032)
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.server"],
            env=_env(port, str(tmp_path)), cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        for port in ports
    ]
    try:
        deadline = time.time() + 420
        ready = set()
        while len(ready) < len(ports) and time.time() < deadline:
            for port in ports:
                if port in ready:
                    continue
                if procs[ports.index(port)].poll() is not None:
                    err = procs[ports.index(port)].stderr.read().decode()
                    raise AssertionError(f"replica :{port} died:\n{err[-2000:]}")
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/health", timeout=5) as r:
                        if r.status == 200:
                            ready.add(port)
                except (urllib.error.URLError, OSError):
                    pass
            time.sleep(1.0)
        assert ready == set(ports), f"ready={ready} before deadline"

        for port in ports:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/response",
                data=json.dumps(BODY).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())
            assert r.status == 200
            assert isinstance(out.get("response"), str)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


def test_golden_transcript_reproducible_across_processes(tmp_path):
    """SURVEY §4 "Golden/e2e": the same tiny GGUF served by two fresh
    server processes must produce byte-identical temp=0 `/response` output
    — the golden transcript is pinned by the model file + seed rather than
    a hardcoded string (stable across jax versions, still catches any
    nondeterminism in load → tokenize → prefill → sample → decode)."""
    import urllib.request

    from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf

    write_tiny_llama_gguf(str(tmp_path / "tiny.gguf"))
    body = json.dumps({**BODY, "context": [
        {"turn": "user", "message": "Tell me a short story."}]}).encode()
    replies = []
    for port in (8033, 8034):
        env = _env(port, str(tmp_path))
        env["LFKT_TEMPERATURE"] = "0.0"
        proc = subprocess.Popen(
            [sys.executable, "-m", "llama_fastapi_k8s_gpu_tpu.server"],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        try:
            deadline = time.time() + 420
            ready = False
            while not ready and time.time() < deadline:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"server died:\n{proc.stderr.read().decode()[-2000:]}")
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/health", timeout=5) as r:
                        ready = r.status == 200
                except OSError:
                    pass
                if not ready:
                    time.sleep(1.0)
            assert ready, f"replica :{port} not healthy before deadline"
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/response", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                replies.append(json.loads(r.read())["response"])
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
    assert replies[0] == replies[1]
    assert isinstance(replies[0], str)

"""Ring attention / sequence parallelism on the 8-virtual-device CPU mesh
(SURVEY.md §4 "Multi-chip logic tested without hardware").

Oracle: the single-device XLA attention path — sp-sharded prefill/decode
must produce the same logits and the same cache contents.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_fastapi_k8s_gpu_tpu.models import ModelConfig, init_cache, prefill
from llama_fastapi_k8s_gpu_tpu.models.llama import decode_step
from llama_fastapi_k8s_gpu_tpu.models.params import synth_params
from llama_fastapi_k8s_gpu_tpu.parallel.mesh import make_mesh, shard_params
from llama_fastapi_k8s_gpu_tpu.parallel.ring import (
    ring_attention,
    ring_context,
    sharded_decode_attention,
    sp_prefill,
    sp_state_shardings,
)

CFG = ModelConfig(
    vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    ffn_dim=128, n_ctx=64, rope_theta=10000.0,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(dp=1, tp=2, sp=4)


@pytest.fixture(scope="module")
def params():
    return synth_params(CFG, fmt="bf16", seed=0)


def _ref_attention(q, k, v, pos_offset, sm_scale, sliding_window=0):
    """k/v head-major (n_kv, n_ctx, hd), matching init_cache."""
    S, H, hd = q.shape
    n_kv, n_ctx, _ = k.shape
    group = H // n_kv
    qg = q.reshape(S, n_kv, group, hd).transpose(1, 2, 0, 3)
    scores = jnp.einsum(
        "ngsh,nch->ngsc", qg, k,
        preferred_element_type=jnp.float32,
    ) * sm_scale
    key_pos = jnp.arange(n_ctx)
    q_pos = pos_offset + jnp.arange(S)
    mask = key_pos[None, :] <= q_pos[:, None]
    if sliding_window:
        mask &= key_pos[None, :] > q_pos[:, None] - sliding_window
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("ngsc,nch->ngsh", probs, v)
    return ctx.transpose(2, 0, 1, 3).reshape(S, H, hd)


@pytest.mark.parametrize("offset,window", [(0, 0), (16, 0), (8, 24)])
def test_ring_attention_matches_reference(mesh, offset, window):
    S, n_ctx, H, n_kv, hd = 32, 64, 4, 2, 32
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(keys[0], (S, H, hd), jnp.float32)
    k = jax.random.normal(keys[1], (n_kv, n_ctx, hd), jnp.float32)
    v = jax.random.normal(keys[2], (n_kv, n_ctx, hd), jnp.float32)
    with ring_context(mesh):
        got = ring_attention(q, k, v, jnp.int32(offset), sm_scale=hd ** -0.5,
                             sliding_window=window)
    want = _ref_attention(q, k, v, jnp.int32(offset), hd ** -0.5, window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_sharded_decode_attention_matches_reference(mesh):
    n_ctx, H, n_kv, hd = 64, 4, 2, 32
    keys = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(keys[0], (1, H, hd), jnp.float32)
    k = jax.random.normal(keys[1], (n_kv, n_ctx, hd), jnp.float32)
    v = jax.random.normal(keys[2], (n_kv, n_ctx, hd), jnp.float32)
    with ring_context(mesh):
        got = sharded_decode_attention(q, k, v, jnp.int32(37),
                                       sm_scale=hd ** -0.5)
    want = _ref_attention(q, k, v, jnp.int32(37), hd ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_sp_prefill_matches_single_device(mesh, params):
    tokens = jnp.arange(1, 33, dtype=jnp.int32)       # S=32, sp=4 → 8/shard
    length = jnp.int32(32)
    ref_logits, ref_cache = prefill(params, CFG, tokens, length, init_cache(CFG))

    sharded = shard_params(params, mesh)
    cache = jax.device_put(init_cache(CFG), sp_state_shardings(CFG, mesh))
    got_logits, got_cache = sp_prefill(sharded, CFG, tokens, length, cache, mesh)
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(
        np.asarray(got_cache["k"][:, :32], np.float32),
        np.asarray(ref_cache["k"][:, :32], np.float32),
        rtol=3e-2, atol=3e-2,
    )


def test_sp_decode_step_matches_single_device(mesh, params):
    from llama_fastapi_k8s_gpu_tpu.parallel.ring import sp_decode_step

    tokens = jnp.arange(1, 33, dtype=jnp.int32)
    length = jnp.int32(32)
    ref_logits, ref_cache = prefill(params, CFG, tokens, length, init_cache(CFG))
    want, _ = decode_step(params, CFG, jnp.int32(5), jnp.int32(32), ref_cache)

    sharded = shard_params(params, mesh)
    cache = jax.device_put(init_cache(CFG), sp_state_shardings(CFG, mesh))
    _, sp_cache = sp_prefill(sharded, CFG, tokens, length, cache, mesh)
    got, _ = sp_decode_step(sharded, CFG, jnp.int32(5), jnp.int32(32),
                            sp_cache, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-2, atol=3e-2)

"""lfkt-perf SLO gates (ISSUE 7): burn-rate math + the /debug surface.

Three layers:

1. **Burn-rate math units** — bucket interpolation exactness, window
   baseline selection with injected clocks (window units: a 60 s window
   diffs against the snapshot ~60 s back, not since boot), all three SLO
   kinds (latency, floor, ratio), per-series worst-bucket reporting, and
   the warn-vs-breach multi-window verdict.
2. **Gauge export** — ``slo_burn_rate{slo=,window=}`` lands in legal
   exposition on the bound registry.
3. **Server surface** — /debug/slo and /debug/compiles schemas over the
   real app, /debug/profile's opt-in gating, and the ISSUE acceptance:
   a recompile storm arising while a request is in flight is visible in
   /metrics, in /debug/slo, AND as an event on the in-flight trace.
"""

from __future__ import annotations

import asyncio

import httpx
import pytest

from llama_fastapi_k8s_gpu_tpu.engine import FakeEngine
from llama_fastapi_k8s_gpu_tpu.obs.devtime import DEVTIME, DevtimeRegistry
from llama_fastapi_k8s_gpu_tpu.obs.slo import SLOEngine, SLOS, _n_at_or_below
from llama_fastapi_k8s_gpu_tpu.obs.trace import Tracer
from llama_fastapi_k8s_gpu_tpu.server.app import create_app
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

BODY = {
    "bot_profile": {"name": "Alice.f",
                    "appearance": "tall,slim,blonde,cats,rain"},
    "user_profile": {"name": "Bob"},
    "context": [{"turn": "user", "message": "hi"}],
}

#: thresholds aligned to engine_ttft_seconds bucket bounds for exactness
THRESHOLDS = {"ttft_p95": 0.25, "decode_floor": 10.0,
              "error_rate": 0.01, "queue_p95": 0.25}


def _engine(m, windows=(60.0, 600.0), devtime=None):
    return SLOEngine(m, windows=list(windows), thresholds=THRESHOLDS,
                     devtime=devtime or DevtimeRegistry(armed=True,
                                                        budget=32))


def _slo(doc, name):
    return next(s for s in doc["slos"] if s["name"] == name)


# ---------------------------------------------------------------------------
# layer 1: burn-rate math
# ---------------------------------------------------------------------------

def test_n_at_or_below_interpolation():
    bounds = (0.1, 0.2, 0.4)
    #           <=0.1  <=0.2  <=0.4  +Inf
    deltas = [4, 2, 2, 2]
    # exact at a bound: cumulative counts
    assert _n_at_or_below(bounds, deltas, 10, 0.2) == 6
    # mid-bucket: linear interpolation inside (0.2, 0.4]
    assert _n_at_or_below(bounds, deltas, 10, 0.3) == pytest.approx(7.0)
    # above the largest finite bound: everything
    assert _n_at_or_below(bounds, deltas, 10, 9.9) == 10
    # empty window
    assert _n_at_or_below(bounds, [0, 0, 0, 0], 0, 0.2) == 0.0


def test_latency_slo_burns_when_tail_exceeds_threshold():
    m = Metrics()
    s = _engine(m)
    s.evaluate(now=0.0)                    # baseline: both windows realize
    for _ in range(18):
        m.observe("engine_ttft_seconds", 0.05, bucket="128", model="m")
    for _ in range(2):                     # 10% of events over the bound
        m.observe("engine_ttft_seconds", 1.8, bucket="128", model="m")
    doc = s.evaluate(now=1_000.0)
    ttft = _slo(doc, "ttft_p95")
    for ev in ttft["windows"].values():
        # bad_frac 0.1 over a 0.05 budget = burn 2.0
        assert ev["burn_rate"] == pytest.approx(2.0, rel=1e-3)
        assert ev["worst_series"] == "128,m"
        assert "truncated" not in ev       # both windows genuinely elapsed
    assert ttft["verdict"] == "breach"     # burning on EVERY window
    assert doc["verdict"] == "breach"


def test_window_units_short_burn_is_warn_not_breach():
    """903 good requests over 10 minutes, then 3 slow ones in the last
    minute: the 60 s window burns hard, the 600 s window stays inside
    budget — verdict 'warn' (fast burn that has not lasted)."""
    m = Metrics()
    s = _engine(m, windows=(60.0, 600.0))
    s.evaluate(now=0.0)                           # baseline A (empty)
    for _ in range(903):
        m.observe("engine_ttft_seconds", 0.05, bucket="128", model="m")
    s.evaluate(now=540.0)                         # baseline B (all good)
    for _ in range(3):
        m.observe("engine_ttft_seconds", 1.8, bucket="128", model="m")
    doc = s.evaluate(now=600.0)
    ttft = _slo(doc, "ttft_p95")
    assert ttft["windows"]["60s"]["burn_rate"] >= 1.0        # 3/3 bad
    assert ttft["windows"]["600s"]["burn_rate"] < 1.0        # 3/906 bad
    assert ttft["verdict"] == "warn"
    assert doc["verdict"] == "warn"


def test_floor_slo_counts_slow_decodes_as_bad():
    m = Metrics()
    s = _engine(m)
    s.evaluate(now=0.0)                    # baseline: both windows realize
    for _ in range(8):
        m.observe("engine_decode_tokens_per_sec", 50.0, model="m")
    for _ in range(2):                     # below the 10 tok/s floor
        m.observe("engine_decode_tokens_per_sec", 2.0, model="m")
    doc = s.evaluate(now=700.0)
    floor = _slo(doc, "decode_floor")
    ev = floor["windows"]["60s"]
    assert ev["burn_rate"] >= 1.0 and ev["bad"] == pytest.approx(2.0)
    assert floor["verdict"] == "breach"


def test_truncated_window_cannot_confirm_breach():
    """A pod restarted into a latency blip must page 'warn', not
    'breach': with process age below the long window both windows hold
    the same evidence, so the long window cannot play its independent
    confirm-the-burn-lasted role."""
    m = Metrics()
    s = _engine(m)                         # windows 60 s / 600 s
    s.evaluate(now=0.0)                    # boot snapshot
    for _ in range(20):
        m.observe("engine_ttft_seconds", 1.8, bucket="128", model="m")  # all bad
    doc = s.evaluate(now=120.0)            # 2 min after boot
    ttft = _slo(doc, "ttft_p95")
    assert ttft["windows"]["60s"]["burn_rate"] >= 1.0
    assert ttft["windows"]["600s"]["burn_rate"] >= 1.0
    assert ttft["windows"]["600s"]["truncated"] is True
    assert "truncated" not in ttft["windows"]["60s"]
    assert ttft["verdict"] == "warn"
    assert doc["verdict"] == "warn"
    # once the burn has genuinely lasted the long window, it breaches
    for _ in range(20):
        m.observe("engine_ttft_seconds", 1.8, bucket="128", model="m")
    doc = s.evaluate(now=650.0)
    assert _slo(doc, "ttft_p95")["verdict"] == "breach"


def test_ratio_slo_5xx_over_total():
    m = Metrics()
    s = _engine(m)
    for _ in range(98):
        m.inc("http_requests_total", route="/response", code="200")
    m.inc("http_requests_total", route="/response", code="503")
    m.inc("http_requests_total", route="/response", code="500")
    doc = s.evaluate(now=7.0)
    err = _slo(doc, "error_rate")
    ev = err["windows"]["60s"]
    # 2/100 over a 0.01 budget = burn 2.0
    assert ev["burn_rate"] == pytest.approx(2.0, rel=1e-3)
    assert ev["bad"] == 2 and ev["total"] == 100


def test_ratio_slo_excludes_self_monitoring_routes():
    """Scrape + probe traffic (guaranteed 200s at a fixed cadence) must
    not dilute the user-facing 5xx ratio: a quiet pod whose only real
    request failed is burning its whole budget, not 1/141 of it."""
    m = Metrics()
    s = _engine(m)
    for _ in range(100):
        m.inc("http_requests_total", route="/metrics", code="200")
        m.inc("http_requests_total", route="/health/ready", code="200")
    m.inc("http_requests_total", route="/debug/slo", code="200")
    m.inc("http_requests_total", route="/response", code="500")
    doc = s.evaluate(now=7.0)
    ev = _slo(doc, "error_rate")["windows"]["60s"]
    assert ev["total"] == 1 and ev["bad"] == 1      # only /response counted
    assert ev["burn_rate"] >= 1.0


def test_worst_bucket_series_wins():
    m = Metrics()
    s = _engine(m)
    for _ in range(10):
        m.observe("engine_ttft_seconds", 0.05, bucket="128", model="m")   # healthy
    for _ in range(10):
        m.observe("engine_ttft_seconds", 1.8, bucket="1024", model="m")   # all bad
    doc = s.evaluate(now=3.0)
    ev = _slo(doc, "ttft_p95")["windows"]["60s"]
    assert ev["worst_series"] == "1024,m"
    assert ev["series"]["128,m"] == 0.0
    assert ev["series"]["1024,m"] == pytest.approx(20.0, rel=1e-3)


def test_no_traffic_is_ok_not_breach():
    m = Metrics()
    doc = _engine(m).evaluate(now=1.0)
    assert doc["verdict"] == "ok"
    for s in doc["slos"]:
        assert s["verdict"] == "ok"


def test_every_cataloged_slo_references_a_real_family():
    from llama_fastapi_k8s_gpu_tpu.obs.catalog import lookup

    for slo in SLOS:
        assert lookup(slo.metric) is not None, slo.name


# ---------------------------------------------------------------------------
# layer 2: gauge export
# ---------------------------------------------------------------------------

def test_export_publishes_burn_rate_gauges():
    m = Metrics()
    s = _engine(m)
    for _ in range(5):
        m.observe("queue_wait_seconds", 5.0)       # way past 0.25 s bound
    s.export(now=2.0)
    text = m.render()
    line = next(ln for ln in text.splitlines()
                if ln.startswith('slo_burn_rate{slo="queue_p95"'
                                 ',window="60s",scope="pod"}'))
    assert float(line.split()[-1]) >= 1.0


# ---------------------------------------------------------------------------
# layer 3: server surface + the storm acceptance criterion
# ---------------------------------------------------------------------------

async def _serve(app, calls):
    transport = httpx.ASGITransport(app=app)
    out = []
    async with transport:
        await app.router.startup()
        async with httpx.AsyncClient(transport=transport,
                                     base_url="http://test") as client:
            for method, path, kw in calls:
                out.append(await getattr(client, method)(path, **kw))
        await app.router.shutdown()
    return out


@pytest.mark.anyio
async def test_debug_slo_and_compiles_schemas():
    app = create_app(engine=FakeEngine(reply="hey"),
                     tracer=Tracer(sample=1.0, ring=8))
    r, slo, compiles, metrics = await _serve(app, [
        ("post", "/response", {"json": BODY}),
        ("get", "/debug/slo", {}),
        ("get", "/debug/compiles", {}),
        ("get", "/metrics", {}),
    ])
    assert r.status_code == 200
    doc = slo.json()
    assert set(doc) == {"now", "windows", "slos", "recompile", "verdict"}
    assert [s["name"] for s in doc["slos"]] == [s.name for s in SLOS]
    for s in doc["slos"]:
        assert set(s["windows"]) == set(doc["windows"])
        for ev in s["windows"].values():
            assert {"burn_rate", "bad", "total",
                    "worst_series", "window_s"} <= set(ev)
    assert {"budget", "storms", "storms_total",
            "verdict"} <= set(doc["recompile"])
    comp = compiles.json()
    assert set(comp) == {"armed", "budget", "storms_total",
                         "events_dropped", "degrades", "programs"}
    for d in comp["degrades"]:   # the kernel-degrade attribution ledger
        assert {"program", "reason", "count"} <= set(d)
    for p in comp["programs"]:
        assert {"name", "kind", "compiles", "dispatches",
                "signatures", "signature_list"} <= set(p)
    # the scrape carries the devtime + slo families
    text = metrics.text
    assert "slo_burn_rate{" in text
    assert "xla_recompile_storms_total" in text


@pytest.mark.anyio
async def test_debug_profile_is_opt_in(monkeypatch):
    monkeypatch.delenv("LFKT_PROFILE_DIR", raising=False)
    app = create_app(engine=FakeEngine(reply="x"))
    r403, rbad, rnan, rinf = await _serve(app, [
        ("get", "/debug/profile", {}),
        ("get", "/debug/profile?seconds=banana", {}),
        ("get", "/debug/profile?seconds=nan", {}),
        ("get", "/debug/profile?seconds=inf", {}),
    ])
    assert r403.status_code == 403
    assert rbad.status_code in (400, 403)     # parse rejects before gating
    # nan/inf parse as floats but slide through min() clamps (nan<x is
    # False) — they must 400, never hold the capture lock for the max
    assert rnan.status_code == 400
    assert rinf.status_code == 400


@pytest.mark.anyio
async def test_debug_profile_captures_when_armed(monkeypatch, tmp_path):
    monkeypatch.setenv("LFKT_PROFILE_DIR", str(tmp_path / "xprof"))
    app = create_app(engine=FakeEngine(reply="x"))
    r, = await _serve(app, [("get", "/debug/profile?seconds=0.05", {})])
    assert r.status_code == 200
    doc = r.json()
    # "seconds" is the clamped capture window (deterministic); "wall_s"
    # additionally counts profiler start/stop, which serializes every
    # retained event and is unbounded on a long-lived process
    assert doc["ok"] is True and doc["seconds"] == 0.05
    assert doc["wall_s"] > 0


@pytest.mark.anyio
async def test_storm_visible_in_metrics_slo_and_inflight_trace():
    """ISSUE 7 acceptance: a recompile storm while a request is in flight
    shows up in /metrics, /debug/slo, and as events on the request's own
    trace — all three surfaces, one storm."""
    tracer = Tracer(sample=1.0, ring=8)
    app = create_app(engine=FakeEngine(reply="ok", delay=0.6),
                     tracer=tracer)
    old_budget = DEVTIME.budget
    DEVTIME.reset()
    DEVTIME.configure(budget=1)
    transport = httpx.ASGITransport(app=app)
    try:
        async with transport:
            await app.router.startup()
            async with httpx.AsyncClient(transport=transport,
                                         base_url="http://test") as client:
                task = asyncio.create_task(client.post("/response",
                                                       json=BODY))
                await asyncio.sleep(0.15)          # request now in flight
                DEVTIME.record_compile("stormy", "f32[1]", 0.2)
                DEVTIME.record_compile("stormy", "f32[2]", 0.2)  # storm
                metrics = (await client.get("/metrics")).text
                slo = (await client.get("/debug/slo")).json()
                r = await task
            await app.router.shutdown()
        assert r.status_code == 200
        assert "xla_recompile_storms_total 1" in metrics
        assert 'xla_compiles_total{program="stormy"} 2' in metrics
        assert 'xla_compile_seconds_count{program="stormy"} 2' in metrics
        assert slo["recompile"]["verdict"] == "storm"
        assert slo["recompile"]["storms"][0]["program"] == "stormy"
        assert slo["verdict"] in ("warn", "breach")
        tr = tracer.get(r.headers["x-request-id"])
        assert tr is not None
        events = [e for e in tr.root.events if e["name"] == "recompile_storm"]
        assert events and events[0]["program"] == "stormy"
    finally:
        DEVTIME.reset()
        DEVTIME.configure(budget=old_budget)

"""Fault-injection-driven resilience suite (tier-1, CPU, deterministic).

Covers the layer ISSUE 2 added over the reference's let-the-pod-die story:
the health state machine and its probe split, the env-armed fault injector,
the engine watchdog (stall / burst / scheduler-death detection, bounded
recovery, DEAD escalation), deadline/abort propagation into every engine's
decode loop, and the flagship in-process lifecycle on a real
ContinuousEngine: fault → trip → DEGRADED (readiness 503, liveness 200) →
recovery → READY, no process restart.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from llama_fastapi_k8s_gpu_tpu.engine import ContinuousEngine, Engine, MeshEngine
from llama_fastapi_k8s_gpu_tpu.engine.fake import FakeEngine
from llama_fastapi_k8s_gpu_tpu.engine.watchdog import Watchdog
from llama_fastapi_k8s_gpu_tpu.testing import write_tiny_llama_gguf
from llama_fastapi_k8s_gpu_tpu.utils.faults import (
    FAULTS,
    FaultError,
    FaultInjector,
    SimulatedOOM,
)
from llama_fastapi_k8s_gpu_tpu.utils.health import (
    DEAD,
    DEGRADED,
    DRAINING,
    READY,
    STARTING,
    DeadlineExceeded,
    EngineUnavailable,
    Heartbeat,
    HealthMonitor,
)
from llama_fastapi_k8s_gpu_tpu.utils.metrics import Metrics

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MSGS = [{"role": "user", "content": "Say something."}]


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No fault armed leaks across tests."""
    FAULTS.disarm()
    yield
    FAULTS.disarm()


# ---------------------------------------------------------------------------
# health state machine
# ---------------------------------------------------------------------------

def test_health_state_machine_lifecycle():
    h = HealthMonitor()
    assert h.state == STARTING
    assert not h.ready() and h.alive()          # starting: not ready, alive
    assert h.transition(READY, "engine loaded")
    assert h.ready() and h.alive()
    assert h.transition(DEGRADED, "watchdog trip")
    assert not h.ready() and h.alive()          # degraded: shed, don't kill
    assert h.transition(READY, "recovered")
    assert h.ready()
    snap = h.snapshot()
    assert snap["state"] == READY
    assert snap["reason"] == "recovered"
    assert [t["to"] for t in snap["transitions"]] == [READY, DEGRADED, READY]


def test_health_dead_is_terminal():
    h = HealthMonitor()
    h.transition(READY, "up")
    h.transition(DEAD, "budget exhausted")
    assert not h.alive() and not h.ready()
    assert not h.transition(READY, "necromancy")       # refused
    assert h.state == DEAD
    assert h.transition(DEAD, "still dead")            # self-transition ok


def test_health_draining_only_yields_to_dead():
    h = HealthMonitor()
    h.transition(READY, "up")
    h.transition(DRAINING, "sigterm")
    assert not h.ready() and h.alive()
    assert not h.transition(READY, "no: draining pod must not re-advertise")
    assert h.transition(DEAD, "drain escalated")
    assert not h.alive()


def test_health_rejects_unknown_state():
    with pytest.raises(ValueError):
        HealthMonitor().transition("ZOMBIE")


# ---------------------------------------------------------------------------
# fault injector
# ---------------------------------------------------------------------------

def test_faults_inert_by_default():
    inj = FaultInjector()
    for _ in range(100):
        inj.fire("decode_step")     # never raises, never sleeps
    assert not inj.armed()


def test_faults_after_times_script():
    inj = FaultInjector()
    inj.arm("decode_step:error:after=2:times=1")
    inj.fire("decode_step")         # hit 1: pass-through
    inj.fire("decode_step")         # hit 2: pass-through
    with pytest.raises(FaultError):
        inj.fire("decode_step")     # hit 3: fires
    inj.fire("decode_step")         # hit 4: budget spent, inert again
    assert inj.stats()["decode_step"]["fired"] == 1


def test_faults_oom_and_slow_modes():
    inj = FaultInjector()
    inj.arm("load:oom")
    with pytest.raises(SimulatedOOM, match="RESOURCE_EXHAUSTED"):
        inj.fire("load")
    inj.arm("prefill:slow:delay=0.1:times=1")
    t0 = time.monotonic()
    inj.fire("prefill")
    assert time.monotonic() - t0 >= 0.1


def test_faults_reject_bad_specs():
    inj = FaultInjector()
    with pytest.raises(ValueError):
        inj.arm("nonsense_point:error")
    with pytest.raises(ValueError):
        inj.arm("decode_step:explode")
    with pytest.raises(ValueError):
        inj.arm("decode_step:error:bogus=1")


# ---------------------------------------------------------------------------
# watchdog against a minimal engine contract
# ---------------------------------------------------------------------------

class _ContractEngine:
    """Smallest thing the watchdog can supervise."""

    def __init__(self, recover_ok=True):
        self.heartbeat = Heartbeat()
        self.recover_ok = recover_ok
        self.recoveries = 0
        self.failed: list = []

    def recover(self):
        self.recoveries += 1
        return self.recover_ok

    def fail_inflight(self, exc):
        self.failed.append(exc)


def _wait(pred, timeout=5.0, what=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def test_watchdog_trips_on_stall_and_recovers():
    eng = _ContractEngine()
    health = HealthMonitor()
    health.transition(READY, "up")
    m = Metrics()
    wd = Watchdog(eng, health, m, stall_seconds=0.05, poll_seconds=0.02,
                  backoff_seconds=0.01, max_recoveries=5).start()
    try:
        eng.heartbeat.enter()       # in-flight work...
        time.sleep(0.06)            # ...with no progress: a stall
        _wait(lambda: wd.recoveries >= 1 and health.state == READY,
              what="stall trip + recovery")
        assert eng.recoveries >= 1
        assert eng.failed and isinstance(eng.failed[0], EngineUnavailable)
        assert "stalled_decode" in wd.last_trip_reason
        trail = [t["to"] for t in health.snapshot()["transitions"]]
        assert DEGRADED in trail and trail[-1] == READY
        assert "watchdog_trips_total" in m.render()
        assert "watchdog_recoveries_total" in m.render()
    finally:
        wd.stop()


def test_watchdog_trips_on_error_burst():
    eng = _ContractEngine()
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), poll_seconds=0.02,
                  error_burst=3, error_window=5.0, backoff_seconds=0.01)
    try:
        for _ in range(3):
            eng.heartbeat.record_error(RuntimeError("step blew up"))
        reason = wd.check()
        assert reason is not None and "exception_burst" in reason
        wd.handle_trip(reason)
        assert health.state == READY        # recovered (recover_ok fake)
        assert eng.recoveries == 1
    finally:
        wd.stop()


def test_watchdog_burst_on_busy_engine_recovers_in_place():
    """A transient exception burst on an engine that is still serving
    (recover() refuses: loop alive / lock held) must NOT walk to DEAD —
    the trip consumes the burst evidence and, with no remaining fault
    signature, the watchdog re-readies in place (code-review finding:
    the old behavior re-tripped on the same stale errors every poll and
    deterministically killed a healthy pod)."""
    eng = _ContractEngine(recover_ok=False)   # "busy": refuses re-init
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), poll_seconds=0.02,
                  error_burst=3, error_window=30.0, backoff_seconds=0.01,
                  max_recoveries=2)
    try:
        for _ in range(3):
            eng.heartbeat.record_error(RuntimeError("transient device error"))
        reason = wd.check()
        assert reason is not None and "exception_burst" in reason
        wd.handle_trip(reason)
        assert health.state == READY          # re-readied in place, not DEAD
        assert wd.recoveries == 1
        assert wd.check() is None             # evidence consumed: no re-trip
    finally:
        wd.stop()


def test_watchdog_escalates_to_dead_when_recovery_fails():
    eng = _ContractEngine(recover_ok=False)
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), stall_seconds=0.03,
                  poll_seconds=0.02, backoff_seconds=0.01,
                  max_recoveries=2).start()
    try:
        eng.heartbeat.enter()       # permanent wedge, recovery always fails
        _wait(lambda: health.state == DEAD, what="escalation to DEAD")
        assert not health.alive()
        assert wd.trips == 3        # 2 failed recoveries + the fatal trip
        assert "max_recoveries_exceeded" in health.snapshot()["reason"]
    finally:
        wd.stop()


def test_watchdog_forgets_trips_after_healthy_window():
    """The DEAD escalation budget is per incident, not per process
    lifetime: after trip_forget_seconds of trip-free READY serving the
    window resets, so isolated transient incidents days apart can never
    accumulate into a needless pod restart."""
    eng = _ContractEngine()
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), stall_seconds=0.05,
                  poll_seconds=0.02, backoff_seconds=0.01,
                  max_recoveries=1, trip_forget_seconds=0.2).start()
    try:
        for incident in range(3):     # each would escalate if accumulated
            eng.heartbeat.enter()
            time.sleep(0.06)          # stall → trip → recover (fake resets)
            _wait(lambda: health.state == READY and eng.heartbeat.busy_count() == 0,
                  what=f"recovery from incident {incident}")
            _wait(lambda: wd.trips_window == 0, timeout=5,
                  what=f"trip window forgotten after incident {incident}")
        assert health.state == READY
        assert wd.trips == 3 and wd.recoveries == 3
    finally:
        wd.stop()


def test_failed_mid_recovery_does_not_go_zombie_ready(tmp_path):
    """If the device re-init inside ContinuousEngine.recover() fails (the
    likely condition recovery runs under — OOM), the fault signature must
    survive: the engine keeps refusing submissions and the watchdog must
    NOT declare an in-place recovery over a scheduler-less zombie."""
    path = str(tmp_path / "tiny-zombie.gguf")
    write_tiny_llama_gguf(path)
    eng = ContinuousEngine(path, dp=1, tp=1, batch_size=2, n_ctx=64,
                           decode_chunk=2, max_gen_tokens=8,
                           prefill_buckets=(32, 64))
    health = HealthMonitor()
    health.transition(READY, "up")
    wd = Watchdog(eng, health, Metrics(), poll_seconds=0.05,
                  backoff_seconds=0.01, max_recoveries=10)
    try:
        FAULTS.arm("decode_step:error:times=1")
        fut = eng.submit(MSGS, temperature=0.0, max_tokens=8)
        with pytest.raises(Exception):
            fut.result(timeout=60)
        assert eng.failure() is not None

        def broken_recover_locked():
            raise RuntimeError("RESOURCE_EXHAUSTED: re-init OOM")

        eng._recover_locked = broken_recover_locked
        reason = wd.check()
        assert reason is not None
        wd.handle_trip(reason)
        # recovery failed mid re-init: fault signature intact, still shed
        assert eng.failure() is not None
        assert health.state == DEGRADED
        with pytest.raises(EngineUnavailable):
            eng.submit(MSGS, max_tokens=4)
    finally:
        FAULTS.disarm()
        wd.stop()
        eng.shutdown()


# ---------------------------------------------------------------------------
# deadline / abort propagation per engine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serial_engine(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("model") / "tiny-res.gguf")
    write_tiny_llama_gguf(path)
    return Engine(path, n_ctx=256, decode_chunk=4, max_gen_tokens=128,
                  prefill_buckets=(32, 64, 128, 256))


def test_serial_engine_deadline_stops_decode(serial_engine):
    out = serial_engine.create_chat_completion(
        MSGS, temperature=0.0, max_tokens=100, deadline=time.time())
    assert out["choices"][0]["finish_reason"] == "deadline"
    # at most the prefill token + the already-dispatched first chunk
    assert out["usage"]["completion_tokens"] <= 1 + serial_engine.decode_chunk


def test_serial_engine_abort_stops_within_one_chunk(serial_engine):
    calls = {"decode": 0, "abort": 0}
    orig = serial_engine._decode_chunk_call

    def counting(*a, **kw):
        calls["decode"] += 1
        return orig(*a, **kw)

    def abort():
        calls["abort"] += 1
        return calls["abort"] > 2      # let ~2 chunks run, then disconnect

    serial_engine._decode_chunk_call = counting
    try:
        out = serial_engine.create_chat_completion(
            MSGS, temperature=0.0, max_tokens=100, abort=abort)
    finally:
        serial_engine._decode_chunk_call = orig
    assert out["choices"][0]["finish_reason"] == "deadline"
    assert out["usage"]["completion_tokens"] < 100
    # the loop checks abort before each dispatch: after it fires, no
    # further chunk is dispatched
    assert calls["decode"] <= 4, calls


def test_serial_engine_no_deadline_is_unchanged(serial_engine):
    """Default path (no deadline/abort) must be byte-identical."""
    a = serial_engine.create_chat_completion(MSGS, temperature=0.0,
                                             max_tokens=12, seed=7)
    b = serial_engine.create_chat_completion(MSGS, temperature=0.0,
                                             max_tokens=12, seed=7,
                                             deadline=None, abort=None)
    assert a["choices"][0]["message"] == b["choices"][0]["message"]
    assert a["choices"][0]["finish_reason"] == b["choices"][0]["finish_reason"]


def test_mesh_engine_per_lane_deadline(tmp_path):
    path = str(tmp_path / "tiny-mesh-res.gguf")
    write_tiny_llama_gguf(path)
    eng = MeshEngine(path, dp=2, tp=2, batch_size=2, n_ctx=128,
                     decode_chunk=4, max_gen_tokens=64,
                     prefill_buckets=(32, 64, 128))
    outs = eng.create_chat_completions(
        [MSGS, MSGS], temperature=0.0, max_tokens=24,
        deadlines=[time.time(), None], aborts=[None, None])
    # entry 0 expired immediately; entry 1 unaffected by its neighbor
    assert outs[0]["choices"][0]["finish_reason"] == "deadline"
    assert outs[0]["usage"]["completion_tokens"] <= 1 + eng.decode_chunk
    assert outs[1]["usage"]["completion_tokens"] > \
        outs[0]["usage"]["completion_tokens"]


def test_mesh_engine_abort_frees_cycle(tmp_path):
    path = str(tmp_path / "tiny-mesh-ab.gguf")
    write_tiny_llama_gguf(path)
    eng = MeshEngine(path, dp=1, tp=1, batch_size=2, n_ctx=128,
                     decode_chunk=4, max_gen_tokens=64,
                     prefill_buckets=(32, 64, 128))
    # both entries abort after a couple of chunks: the cycle must end long
    # before the 60-token budget (one timed-out batch no longer pins the
    # consumer for the full budget)
    state = {"n": 0}

    def abort():
        state["n"] += 1
        return state["n"] > 4

    outs = eng.create_chat_completions(
        [MSGS, MSGS], temperature=0.0, max_tokens=60,
        aborts=[abort, abort])
    for o in outs:
        assert o["choices"][0]["finish_reason"] == "deadline"
        assert o["usage"]["completion_tokens"] < 60


@pytest.fixture(scope="module")
def cont_engine(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("model") / "tiny-cont-res.gguf")
    write_tiny_llama_gguf(path)
    eng = ContinuousEngine(path, dp=1, tp=1, batch_size=2, n_ctx=128,
                           decode_chunk=4, max_gen_tokens=32,
                           prefill_buckets=(32, 64, 128))
    yield eng
    eng.shutdown()


def test_continuous_deadline_expired_in_queue(cont_engine):
    fut = cont_engine.submit(MSGS, temperature=0.0, max_tokens=8,
                             deadline=time.time() - 1)
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=60)
    # the engine keeps serving afterwards (no lane leaked)
    ok = cont_engine.create_chat_completion(MSGS, temperature=0.0,
                                            max_tokens=4)
    assert ok["usage"]["completion_tokens"] >= 1


def test_continuous_deadline_mid_generation_frees_lane(cont_engine):
    t0 = time.time()
    fut = cont_engine.submit(MSGS, temperature=0.0, max_tokens=32,
                             deadline=time.time() + 0.2)
    try:
        out = fut.result(timeout=60)
        # fast box: finished inside the deadline — a legal outcome
        assert out["object"] == "chat.completion"
    except DeadlineExceeded:
        # the deadline path must resolve promptly, not at token budget
        assert time.time() - t0 < 30
    _wait(lambda: cont_engine.scheduler_stats()["lanes_live"] == 0,
          timeout=30, what="lane freed after deadline")
    ok = cont_engine.create_chat_completion(MSGS, temperature=0.0,
                                            max_tokens=4)
    assert ok["usage"]["completion_tokens"] >= 1


# ---------------------------------------------------------------------------
# the flagship: fault → trip → DEGRADED → bounded recovery → READY, one
# process, a real scheduler engine
# ---------------------------------------------------------------------------

def test_continuous_watchdog_full_lifecycle(tmp_path):
    path = str(tmp_path / "tiny-lifecycle.gguf")
    write_tiny_llama_gguf(path)
    eng = ContinuousEngine(path, dp=1, tp=1, batch_size=2, n_ctx=128,
                           decode_chunk=4, max_gen_tokens=16,
                           prefill_buckets=(32, 64, 128))
    health = HealthMonitor()
    health.transition(READY, "up")
    m = Metrics()
    wd = Watchdog(eng, health, m, stall_seconds=30, poll_seconds=0.05,
                  backoff_seconds=0.05, max_recoveries=3)
    try:
        # healthy baseline
        ok = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=4)
        assert ok["usage"]["completion_tokens"] >= 1

        # one injected decode-step fault kills the scheduler loop; the
        # in-flight future must fail loudly, not hang
        FAULTS.arm("decode_step:error:times=1")
        fut = eng.submit(MSGS, temperature=0.0, max_tokens=8)
        with pytest.raises(Exception):
            fut.result(timeout=60)
        assert isinstance(eng.failure(), FaultError)

        # submissions during the outage get the 503-mapped taxonomy error
        with pytest.raises(EngineUnavailable):
            eng.submit(MSGS, max_tokens=4)

        # the watchdog detects the death, degrades, recovers, re-readies.
        # (Wait on recoveries, not trips: trips increments before the
        # DEGRADED transition, so "trips>=1 and READY" can race with the
        # still-initial READY state; recoveries increments only after the
        # recovered-READY transition is next.)
        wd.start()
        _wait(lambda: wd.recoveries >= 1 and health.state == READY,
              timeout=30, what="trip + in-process recovery")
        trail = [t["to"] for t in health.snapshot()["transitions"]]
        assert DEGRADED in trail and trail[-1] == READY
        assert "scheduler_died" in wd.last_trip_reason
        rendered = m.render()
        assert "watchdog_trips_total 1" in rendered
        assert "watchdog_recoveries_total 1" in rendered

        # same process, same engine object: serving again
        assert eng.failure() is None
        out = eng.create_chat_completion(MSGS, temperature=0.0, max_tokens=4)
        assert out["usage"]["completion_tokens"] >= 1
    finally:
        FAULTS.disarm()
        wd.stop()
        eng.shutdown()


def test_continuous_recover_refused_after_deliberate_shutdown(tmp_path):
    path = str(tmp_path / "tiny-shut.gguf")
    write_tiny_llama_gguf(path)
    eng = ContinuousEngine(path, dp=1, tp=1, batch_size=2, n_ctx=64,
                           decode_chunk=2, max_gen_tokens=8,
                           prefill_buckets=(32, 64))
    eng.shutdown()
    assert eng.recover() is False      # a deliberate stop is not a fault


# ---------------------------------------------------------------------------
# server integration: taxonomy mapping + probe routes
# ---------------------------------------------------------------------------

@pytest.mark.anyio
async def test_engine_unavailable_maps_to_503():
    from tests.test_server import BODY, lifespan_client, make_client

    engine = FakeEngine(fail=EngineUnavailable("recovery in progress"))
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.post("/response", json=BODY)
            assert r.status_code == 503
            assert "Engine unavailable" in r.json()["detail"]
            m = await client.get("/metrics")
            assert "engine_unavailable_total 1" in m.text
        await app.router.shutdown()


@pytest.mark.anyio
async def test_probe_routes_follow_state_machine():
    from tests.test_server import lifespan_client, make_client

    engine = FakeEngine()
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        async with await lifespan_client(app, transport) as client:
            r = await client.get("/health/ready")
            assert r.status_code == 200 and r.json()["state"] == READY
            assert (await client.get("/health/live")).status_code == 200

            app.state.health.transition(DEGRADED, "watchdog trip: test")
            r = await client.get("/health/ready")
            assert r.status_code == 503          # shed traffic...
            assert r.json()["state"] == DEGRADED
            assert (await client.get("/health/live")).status_code == 200  # ...but live
            h = await client.get("/health")
            assert h.status_code == 200
            assert h.json()["state"] == DEGRADED
            assert h.json()["resilience"]["health"]["reason"] \
                == "watchdog trip: test"
            m = await client.get("/metrics")
            assert "health_state 2" in m.text    # DEGRADED code

            app.state.health.transition(DEAD, "budget exhausted")
            assert (await client.get("/health/ready")).status_code == 503
            assert (await client.get("/health/live")).status_code == 503
        await app.router.shutdown()


@pytest.mark.anyio
async def test_watchdog_started_and_stopped_by_app_lifecycle():
    from tests.test_server import lifespan_client, make_client

    engine = FakeEngine()
    app, transport = make_client(engine)
    async with transport:
        await app.router.startup()
        assert app.state.watchdog is not None       # FakeEngine has a heartbeat
        assert app.state.engine_kw["deadline"] is True
        async with await lifespan_client(app, transport) as client:
            assert (await client.get("/health/ready")).status_code == 200
        await app.router.shutdown()
        assert app.state.watchdog is None            # stopped and cleared


# ---------------------------------------------------------------------------
# the drill script (tools/fault_drill.py) stays green in the tier-1 gate
# ---------------------------------------------------------------------------

def test_fault_drill_script():
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fault_drill.py")],
        capture_output=True, text=True, cwd=ROOT, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PASS: READY → DEGRADED → READY" in r.stdout
